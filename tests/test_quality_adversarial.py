"""Adversarial quality suite (VERDICT round-2 task 2).

The north-star quality claim (BASELINE.md: free >=95% as many on-demand
nodes as an ILP oracle) must survive contention: high spot utilization,
taints, selector-pinned pools — the regime where one-pass greedy
(first-fit, the reference's rescheduler.go:334-370 semantics, or
best-fit) demonstrably loses drains. These tests pin:

- the contended configs DO discriminate: pure first-fit achieves < 0.95
  of the oracle;
- the shipped solver stack (first-fit ∪ best-fit ∪ local-search repair,
  solver/repair.py) recovers to >= 0.95 on the same clusters;
- the LP/Hall relaxation (bench/quality.lp_upper_bound) is a true upper
  bound on the ILP at small scale (where both are computable) and scales
  to config-2-size packs;
- planner placement hints route evicted pods by the drain plan's proof.
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.bench.quality import (
    drain_to_exhaustion,
    ilp_max_drains,
    lp_upper_bound,
    pack_quality,
)
from k8s_spot_rescheduler_tpu.io.synthetic import (
    QUALITY_CONFIGS,
    ContendedSpec,
    SyntheticSpec,
    generate_quality_cluster,
)
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

SMALL = ContendedSpec("quality-contended-test", n_groups=6)


def _exhaust(spec, seed, **cfg_kwargs):
    cfg = ReschedulerConfig(
        solver="numpy", resources=spec.resources, **cfg_kwargs
    )
    client = generate_quality_cluster(spec, seed, reschedule_evicted=True)
    return drain_to_exhaustion(client, cfg)


@pytest.mark.parametrize("seed", [0, 1])
def test_contended_discriminates_and_repair_recovers(seed):
    packed = pack_quality(SMALL, seed)
    ilp = ilp_max_drains(packed)
    assert ilp and ilp > 0
    ffd = _exhaust(SMALL, seed, fallback_best_fit=False, repair_rounds=0)
    shipped = _exhaust(SMALL, seed)
    assert ffd / ilp < 0.95, "config no longer stresses pure first-fit"
    assert shipped / ilp >= 0.95, "shipped solver lost the contended regime"


def test_best_fit_alone_insufficient_on_contended():
    # the swap pools are built so best-fit misroutes exactly like
    # first-fit — only the repair phase recovers them
    packed = pack_quality(SMALL, 0)
    ilp = ilp_max_drains(packed)
    bf_only = _exhaust(SMALL, 0, repair_rounds=0)
    assert bf_only / ilp < 0.95


@pytest.mark.parametrize(
    "spec,seed",
    [(SMALL, 0), (SMALL, 3), (SyntheticSpec("q", 8, 8, 120), 0)],
)
def test_lp_bound_dominates_ilp_small_scale(spec, seed):
    packed = pack_quality(spec, seed)
    ilp = ilp_max_drains(packed)
    lp = lp_upper_bound(packed)
    assert lp is not None and ilp is not None
    assert lp >= ilp


def test_lp_bound_scales_to_config2():
    from bench import build_problem

    packed, _, _ = build_problem(2, 0)
    lp = lp_upper_bound(packed)
    assert lp is not None
    assert 0 <= lp <= int(np.asarray(packed.cand_valid).sum())


def test_shipped_configs_registered():
    assert {"balanced", "contended", "contended-zipf"} <= set(QUALITY_CONFIGS)


def test_placement_hints_route_by_plan():
    """A hinted eviction lands on the plan's node even when first-fit
    dict order would strand a later pod."""
    client = generate_quality_cluster(SMALL, 0, reschedule_evicted=True)
    swap_pods = [p for p in client.pods.values() if p.name.startswith("tol-")]
    assert swap_pods
    pod = swap_pods[0]
    g = pod.node_selector["pool"]
    target = f"spot-z-{g[1:]}"
    client.placement_hints[pod.uid] = target
    client.evict_pod(pod, 0)
    client.clock.advance(5.0)
    moved = client.pods[pod.uid]
    assert moved.node_name == target


def test_hint_ignored_when_inadmissible():
    """A stale/invalid hint falls back to the scheduler scan."""
    client = generate_quality_cluster(SMALL, 0, reschedule_evicted=True)
    intol = [p for p in client.pods.values() if p.name.startswith("intol-")][0]
    g = intol.node_selector["pool"]
    client.placement_hints[intol.uid] = f"spot-z-{g[1:]}"  # tainted: refused
    client.evict_pod(intol, 0)
    client.clock.advance(5.0)
    live = client.pods.get(intol.uid)
    if live is not None:
        assert live.node_name != f"spot-z-{g[1:]}"
