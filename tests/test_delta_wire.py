"""Delta wire (wire v4) tests: the fingerprinted tenant cache and the
batched device scatter on the service (service/server.py), the
delta-shipping agent with per-endpoint fingerprint tracking
(service/agent.py), and the tenant-mesh sharding of the batched
schedule program (parallel/tenant_batch.py).

The byte-level protocol is pinned in tests/test_wire_fixtures.py; the
O(churn)-bytes-per-tick acceptance runs as ``make serve-smoke`` and the
corrupted-delta/failover resync accounting as ``make
fleet-chaos-smoke`` (bench.serve_smoke / bench.fleet_chaos_smoke)."""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.columnar import (
    apply_packed_delta,
    emit_packed_delta,
    pack_fingerprint,
)
from k8s_spot_rescheduler_tpu.service import buckets as bucketing
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.server import (
    PlannerService,
    ResyncRequired,
    ServiceServer,
)
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.test_service import _observation, tiny_packed


def _service(clock=None, **kwargs) -> PlannerService:
    return PlannerService(
        ReschedulerConfig(solver="numpy"),
        clock=clock or FakeClock(),
        batch_window_s=0,
        **kwargs,
    )


def _resync_count():
    return metrics.service_snapshot()["delta_requests"].get("resync", 0)


# ---------------------------------------------------------------------------
# service: cache + apply + resync semantics


def test_delta_applies_bit_identical_to_full_pack():
    """A full pack seeds the cache; subsequent deltas produce replies
    bit-identical to shipping the full new pack — across several ticks
    of churn, host path."""
    svc = _service()
    p = tiny_packed(seed=3)
    fp = pack_fingerprint(p)
    svc.submit("t", p, pack_fingerprint=fp)
    rng = np.random.default_rng(7)
    for tick in range(4):
        new = p._replace(
            spot_free=(rng.random(p.spot_free.shape) * 100).astype(
                np.float32
            ),
            cand_valid=rng.random(2) < 0.8,
        )
        new_fp = pack_fingerprint(new)
        delta = emit_packed_delta(p, new)
        got = svc.submit_delta("t", delta, fp, new_fp)
        want = svc.submit(f"oracle-{tick}", new, pack_fingerprint=new_fp)
        assert (got.found, got.index, got.n_feasible) == (
            want.found, want.index, want.n_feasible,
        ), tick
        np.testing.assert_array_equal(got.row, want.row)
        p, fp = new, new_fp
    # the host mirror converged on exactly the last pack
    entry = svc._tenant_cache["t"]
    padded = bucketing.pad_to_bucket(p, entry.bucket)
    for f in padded._fields:
        np.testing.assert_array_equal(
            getattr(entry.host, f), getattr(padded, f), err_msg=f
        )


def test_delta_mismatch_eviction_and_restart_cause():
    """Every anti-entropy edge answers with a typed resync whose cause
    names the real reason — and the resync metric fires once per
    demand."""
    import dataclasses
    import tempfile

    state_dir = tempfile.mkdtemp(prefix="delta-wire-state-")
    cfg = dataclasses.replace(
        ReschedulerConfig(solver="numpy"), service_state_dir=state_dir
    )
    svc = PlannerService(cfg, clock=FakeClock(), batch_window_s=0)
    p = tiny_packed(seed=5)
    fp = pack_fingerprint(p)
    new = p._replace(spot_count=p.spot_count + 1)
    new_fp = pack_fingerprint(new)
    delta = emit_packed_delta(p, new)

    # unknown tenant (first contact)
    before = _resync_count()
    with pytest.raises(ResyncRequired, match="no cached state"):
        svc.submit_delta("t", delta, fp, new_fp)
    svc.submit("t", p, pack_fingerprint=fp)
    # fingerprint mismatch
    with pytest.raises(ResyncRequired, match="fingerprint mismatch"):
        svc.submit_delta("t", delta, "0" * 64, new_fp)
    # eviction
    assert svc.invalidate_tenant_cache("t") == 1
    with pytest.raises(ResyncRequired, match="no cached state"):
        svc.submit_delta("t", delta, fp, new_fp)
    assert _resync_count() == before + 3
    # warm restart: fingerprints persist, content does not — the new
    # replica's resync names the restart as the cause
    svc.submit("t", p, pack_fingerprint=fp)
    assert svc.save_state()
    svc2 = PlannerService(cfg, clock=FakeClock(), batch_window_s=0)
    svc2.warm_start()
    with pytest.raises(ResyncRequired, match="restart"):
        svc2.submit_delta("t", delta, fp, new_fp)
    # after the full-pack resync the delta path works again
    svc2.submit("t", p, pack_fingerprint=fp)
    reply = svc2.submit_delta("t", delta, fp, new_fp)
    assert reply.n_feasible >= 0


def test_delta_cache_pruned_with_tenant_ttl():
    """The tenant cache rides the tenant-state TTL: a tenant whose
    last plan aged out loses its cached packed state too."""
    from k8s_spot_rescheduler_tpu.service import server as srv

    clock = FakeClock()
    svc = _service(clock)
    p = tiny_packed()
    svc.submit("old", p, pack_fingerprint=pack_fingerprint(p))
    assert "old" in svc._tenant_cache
    clock.advance(srv.TENANT_STATE_TTL_S + 10)
    svc.submit("fresh", p, pack_fingerprint=pack_fingerprint(p))
    assert "old" not in svc._tenant_cache
    assert "fresh" in svc._tenant_cache
    assert metrics.service_snapshot()["tenant_cache_entries"] == 1


def test_delta_request_without_fingerprint_not_cached():
    """A full pack WITHOUT a fingerprint (delta wire off, or an old
    agent) seeds nothing — the cache only ever holds states whose
    content is named."""
    svc = _service()
    svc.submit("plain", tiny_packed())
    assert "plain" not in svc._tenant_cache


def test_delta_malformed_apply_is_resync_not_crash():
    """A delta whose indices are out of the cached bucket's range is
    refused with a resync demand (numpy would WRAP a negative index
    where the device scatter drops it — neither may happen)."""
    svc = _service()
    p = tiny_packed(seed=9)
    fp = pack_fingerprint(p)
    svc.submit("t", p, pack_fingerprint=fp)
    new = p._replace(spot_count=p.spot_count + 1)
    delta = emit_packed_delta(p, new)
    bad = delta._replace(spot_rows=np.array([-1], np.int32))
    with pytest.raises(ResyncRequired, match="out of range"):
        svc.submit_delta("t", bad, fp, pack_fingerprint(new))
    # the cached state is untouched and still serves the honest delta
    reply = svc.submit_delta("t", delta, fp, pack_fingerprint(new))
    assert reply.n_feasible >= 0


def test_host_path_delta_drops_stale_device_twin():
    """A delta applied on the HOST path (sick watchdog) must drop the
    tenant's device-resident twin: a post-recovery scatter would
    otherwise build on a base missing the sick-window churn — wrong
    state under a MATCHING fingerprint, the one corruption the
    resync-on-anything ladder could not catch."""
    svc = PlannerService(
        ReschedulerConfig(solver="jax"), clock=FakeClock(),
        batch_window_s=0,
    )
    p = tiny_packed(seed=50)
    fp = pack_fingerprint(p)
    svc.submit("t", p, pack_fingerprint=fp)

    def churn(prev, row):
        sf = prev.spot_free.copy()
        sf[row] += 1.0 + row
        new = prev._replace(spot_free=sf)
        return new, emit_packed_delta(prev, new), pack_fingerprint(new)

    # healthy delta -> the batched scatter populates the device twin
    new1, d1, fp1 = churn(p, 0)
    svc.submit_delta("t", d1, fp, fp1)
    entry = svc._tenant_cache["t"]
    assert entry.device is not None
    # sick window: the delta applies host-only; the twin must go
    wd = svc._watchdog()
    wd._flip_sick("test", "forced")
    new2, d2, fp2 = churn(new1, 0)
    svc.submit_delta("t", d2, fp1, fp2)
    assert svc._tenant_cache["t"].device is None
    # recovery: the next delta (touching a DIFFERENT row, so a stale
    # twin could not be healed by overwrite) rebuilds the twin from
    # the authoritative host mirror — device == host, field for field,
    # and the reply matches an oracle tenant shipping the full pack
    wd.sick = False
    new3, d3, fp3 = churn(new2, 1)
    got = svc.submit_delta("t", d3, fp2, fp3)
    want = svc.submit("oracle", new3, pack_fingerprint=fp3)
    assert (got.found, got.index, got.n_feasible) == (
        want.found, want.index, want.n_feasible,
    )
    np.testing.assert_array_equal(got.row, want.row)
    entry = svc._tenant_cache["t"]
    assert entry.device is not None
    for f in entry.host._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(entry.device, f)), getattr(entry.host, f),
            err_msg=f,
        )


# ---------------------------------------------------------------------------
# batched device scatter parity


def test_batched_tenant_scatter_matches_host_apply():
    """The jitted batched scatter (parallel/tenant_batch.
    apply_tenant_deltas) applies T tenants' padded deltas exactly as
    the host reference (models/columnar.apply_packed_delta), pad rows
    dropped."""
    from k8s_spot_rescheduler_tpu.models.columnar import (
        empty_packed_delta,
        pad_packed_delta,
    )
    from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
    from k8s_spot_rescheduler_tpu.parallel.tenant_batch import (
        make_tenant_delta_applier,
    )

    rng = np.random.default_rng(11)
    packs, deltas, wants = [], [], []
    for i in range(3):
        p = tiny_packed(seed=20 + i)
        new = p._replace(
            spot_free=(rng.random(p.spot_free.shape) * 50).astype(
                np.float32
            )
        )
        d = emit_packed_delta(p, new)
        if i == 2:
            d = empty_packed_delta(p)  # a zero-churn tenant in the mix
            new = p
        packs.append(p)
        deltas.append(d)
        wants.append(apply_packed_delta(p, d))
    b = bucketing.bucket_for(packs[0])
    stacked = bucketing.stack_bucket(
        [bucketing.pad_to_bucket(p, b) for p in packs], b
    )
    padded = [
        pad_packed_delta(
            d, b.C, b.S, lane_rows=8, cand_rows=8, spot_rows=8, K=b.K
        )
        for d in deltas
    ]
    stacked_delta = type(padded[0])(
        *(
            np.stack([getattr(d, f) for d in padded])
            for f in type(padded[0])._fields
        )
    )
    out = make_tenant_delta_applier()(*stacked, stacked_delta)
    for i, want in enumerate(wants):
        want_padded = bucketing.pad_to_bucket(want, b)
        got = PackedCluster(*(np.asarray(f[i]) for f in out))
        for f in want_padded._fields:
            np.testing.assert_array_equal(
                getattr(got, f), getattr(want_padded, f),
                err_msg=f"tenant {i} field {f}",
            )


# ---------------------------------------------------------------------------
# agent: delta emission, resync retry, failover forces a full pack


def _recording_agent(cfg, urls, tenant="c1"):
    from k8s_spot_rescheduler_tpu.service.agent import RemotePlanner

    agent = RemotePlanner(cfg, urls, tenant=tenant)
    kinds = []
    inner = agent.transport

    def rec(url, body, headers, timeout):
        kinds.append((url, body[5]))
        return inner(url, body, headers, timeout)

    agent.transport = rec
    return agent, kinds


def test_agent_ships_delta_then_resyncs_then_recovers():
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server.start_background()
    try:
        node_map, pdbs = _observation()
        agent, kinds = _recording_agent(cfg, f"http://{server.address}")
        want = agent.plan(node_map, pdbs)
        r2 = agent.plan(node_map, pdbs)
        assert [k for _, k in kinds] == [
            wire.KIND_PLAN_REQUEST, wire.KIND_PACKED_DELTA,
        ]
        assert r2.solver == "remote"
        assert dict(r2.plan.assignments) == dict(want.plan.assignments)
        # forced resync (cache dropped server-side): ONE delta attempt,
        # ONE full-pack retry on the same endpoint, a correct plan, and
        # the next tick ships deltas again
        server.service.invalidate_tenant_cache()
        before = _resync_count()
        r3 = agent.plan(node_map, pdbs)
        assert _resync_count() == before + 1
        assert r3.solver == "remote"
        assert dict(r3.plan.assignments) == dict(want.plan.assignments)
        assert [k for _, k in kinds[2:]] == [
            wire.KIND_PACKED_DELTA, wire.KIND_PLAN_REQUEST,
        ]
        agent.plan(node_map, pdbs)
        assert kinds[-1][1] == wire.KIND_PACKED_DELTA
    finally:
        server.close()


def test_agent_failover_forces_full_pack():
    """Per-endpoint fingerprint tracking: replica B never acknowledged
    the agent's pack, so the failover tick ships it a FULL pack by
    construction — no resync round trip, no wrong base."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    a = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    b = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    a.start_background()
    b.start_background()
    try:
        node_map, pdbs = _observation()
        agent, kinds = _recording_agent(
            cfg, f"http://{a.address},http://{b.address}"
        )
        want = agent.plan(node_map, pdbs)  # full to A
        agent.plan(node_map, pdbs)  # delta to A
        a.close()
        r = agent.plan(node_map, pdbs)  # A dead -> B serves
        assert r.solver == "remote"
        assert dict(r.plan.assignments) == dict(want.plan.assignments)
        b_url = f"http://{b.address}"
        b_kinds = [k for url, k in kinds if url.startswith(b_url)]
        assert b_kinds == [wire.KIND_PLAN_REQUEST]
        # and B, having acknowledged, now gets deltas
        r2 = agent.plan(node_map, pdbs)
        assert r2.solver == "remote"
        assert [k for url, k in kinds if url.startswith(b_url)] == [
            wire.KIND_PLAN_REQUEST, wire.KIND_PACKED_DELTA,
        ]
    finally:
        for srv in (a, b):
            try:
                srv.close()
            except Exception:  # noqa: BLE001 — a may already be closed
                pass


def test_agent_delta_wire_disabled_ships_full_packs():
    import dataclasses

    cfg = dataclasses.replace(
        ReschedulerConfig(solver="numpy", planner_timeout=5.0),
        delta_wire_enabled=False,
    )
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server.start_background()
    try:
        node_map, pdbs = _observation()
        agent, kinds = _recording_agent(cfg, f"http://{server.address}")
        agent.plan(node_map, pdbs)
        agent.plan(node_map, pdbs)
        assert [k for _, k in kinds] == [
            wire.KIND_PLAN_REQUEST, wire.KIND_PLAN_REQUEST,
        ]
        assert len(server.service._tenant_cache) == 0
    finally:
        server.close()


def test_corrupted_delta_over_http_forces_resync_not_wrong_plan():
    """A delta corrupted in flight (one bit flipped ahead of the
    decode — the ServiceChaos hook's fault) fails the digest, the
    service demands a resync, and the agent's SAME-tick full-pack
    retry still produces the correct plan."""
    cfg = ReschedulerConfig(solver="numpy", planner_timeout=5.0)
    server = ServiceServer(cfg, "127.0.0.1:0", batch_window_s=0.01)
    server.start_background()
    try:
        node_map, pdbs = _observation()
        agent, kinds = _recording_agent(cfg, f"http://{server.address}")
        want = agent.plan(node_map, pdbs)

        inner = agent.transport

        def corrupt_deltas_once(url, body, headers, timeout):
            if body[5] == wire.KIND_PACKED_DELTA:
                mutated = bytearray(body)
                mutated[len(mutated) // 2] ^= 0x10
                body = bytes(mutated)
            return inner(url, body, headers, timeout)

        agent.transport = corrupt_deltas_once
        before = _resync_count()
        r = agent.plan(node_map, pdbs)
        assert _resync_count() == before + 1
        assert r.solver == "remote"
        assert dict(r.plan.assignments) == dict(want.plan.assignments)
    finally:
        server.close()


# ---------------------------------------------------------------------------
# tenant-mesh sharding of the batched schedule program (ROADMAP 1 tail)


def test_schedule_batch_shards_over_tenant_mesh_and_matches_vmap():
    """The batched drain-schedule program sharded over the tenant mesh
    (8 virtual CPU devices via conftest) is identical to the plain
    single-device vmap program, tenant for tenant, step for step."""
    import jax

    if len(jax.devices()) <= 1:
        pytest.skip("needs >1 device")
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_tenant_mesh
    from k8s_spot_rescheduler_tpu.parallel.tenant_batch import (
        make_tenant_schedule_planner,
    )

    mesh = make_tenant_mesh()
    n = int(mesh.devices.size)
    packs = [tiny_packed(seed=30 + i) for i in range(n)]
    b = bucketing.bucket_for(packs[0])
    stacked = bucketing.stack_bucket(
        [bucketing.pad_to_bucket(p, b) for p in packs], b
    )
    sharded = np.asarray(
        make_tenant_schedule_planner(mesh, horizon=4, rounds=8)(stacked)
    )
    ref = np.asarray(
        make_tenant_schedule_planner(None, horizon=4, rounds=8)(stacked)
    )
    assert sharded.shape == ref.shape == (n, 4, 3 + b.K)
    np.testing.assert_array_equal(sharded, ref)


def test_service_schedule_batch_pads_tenants_to_mesh_multiple():
    """The service-side schedule solve pads the tenant axis to a
    device multiple (all-invalid problems) and trims the pad back off
    — same contract as the single-plan batch."""
    import jax

    if len(jax.devices()) <= 1:
        pytest.skip("needs >1 device")
    svc = PlannerService(
        ReschedulerConfig(solver="jax"), clock=FakeClock(),
        batch_window_s=0,
    )
    packs = [tiny_packed(seed=40 + i) for i in range(3)]  # 3 % 8 != 0
    b = bucketing.bucket_for(packs[0])
    stacked = bucketing.stack_bucket(
        [bucketing.pad_to_bucket(p, b) for p in packs], b
    )
    svc._ensure_mesh()
    assert svc._mesh is not None
    out = svc._solve_schedule_batch(stacked, horizon=3)
    assert out.shape == (3, 3, 3 + b.K)
    host = svc._solve_schedule_host(stacked, 3)
    np.testing.assert_array_equal(out, host)
