"""Columnar observe-path parity: ``models/columnar.ColumnarStore.pack``
must emit bit-identical ``PackedCluster`` tensors to the object path
(``build_node_map`` → ``models/tensors.pack_cluster``) for the same
cluster, including under churn (adds/removes/taints/readiness flips) —
the incremental mirror may never drift from ground truth.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, SyntheticSpec, generate_cluster
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import (
    PDBSpec,
    Taint,
    build_node_map,
)
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)

RESOURCES4 = ("cpu", "memory", "ephemeral-storage", "pods")


def object_pack(fc, resources, *, pdbs=None, threshold=0, dnr=False, **pads):
    """The reference-faithful path: list → classify/sort → pack."""
    nodes = fc.list_ready_nodes()
    pods_by_node = {n.name: fc.list_pods_on_node(n.name) for n in nodes}
    node_map = build_node_map(
        nodes,
        pods_by_node,
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
        priority_threshold=threshold,
    )
    return pack_cluster(
        node_map,
        pdbs if pdbs is not None else fc.pdbs,
        resources=resources,
        delete_non_replicated=dnr,
        **pads,
    )


def assert_packed_equal(a, b):
    for field in a._fields:
        x, y = getattr(a, field), getattr(b, field)
        np.testing.assert_array_equal(x, y, err_msg=f"field {field}")
        assert x.dtype == y.dtype, field


def columnar(fc, resources):
    return fc.columnar_store(
        resources, on_demand_label=ON_DEMAND_LABEL, spot_label=SPOT_LABEL
    )


@pytest.mark.parametrize("config_id", [1, 2])
def test_pack_parity_synthetic(config_id):
    fc = generate_cluster(CONFIGS[config_id], seed=3)
    spec = CONFIGS[config_id]
    store = columnar(fc, spec.resources)
    obj, _ = object_pack(fc, spec.resources)
    col, _ = store.pack(fc.pdbs)
    assert_packed_equal(obj, col)


def test_pack_parity_taints_affinity_pdbs():
    spec = dataclasses.replace(
        CONFIGS[4], n_on_demand=60, n_spot=60, n_pods=900
    )
    fc = generate_cluster(spec, seed=7)
    store = columnar(fc, spec.resources)
    obj, _ = object_pack(fc, spec.resources)
    col, _ = store.pack(fc.pdbs)
    assert_packed_equal(obj, col)


def test_pack_parity_under_churn():
    spec = dataclasses.replace(
        CONFIGS[3], n_on_demand=40, n_spot=40, n_pods=500
    )
    fc = generate_cluster(spec, seed=11)
    store = columnar(fc, spec.resources)
    rng = np.random.default_rng(0)

    for step in range(12):
        action = step % 4
        if action == 0:  # evict-like pod removals
            uids = list(fc.pods)
            for uid in rng.choice(uids, size=min(15, len(uids)), replace=False):
                pod = fc.pods[str(uid)]
                fc._remove_pod(pod.uid)
        elif action == 1:  # pods appear (reschedule path), randomly
            # carrying every modeled constraint surface — the universe
            # caches must refresh identically on both packers
            nodes = list(fc.nodes)
            for i in range(10):
                node = str(rng.choice(nodes))
                extra = {}
                roll = int(rng.integers(0, 8))
                if roll == 1:
                    extra["node_selector"] = {"pool": f"g{i % 3}"}
                elif roll == 2:
                    extra["node_affinity"] = (
                        (("zone", "In", (f"z{i % 2}",)),),
                    )
                elif roll == 3:
                    extra["node_affinity"] = (
                        (("metadata.name", "FieldIn", (node,)),),
                    )
                elif roll == 4:
                    extra["anti_affinity_match"] = {"churn": f"a{i % 2}"}
                    extra["labels"] = {"churn": f"a{i % 2}"}
                elif roll == 5:
                    extra["anti_affinity_zone_match"] = {"churn": f"z{i % 2}"}
                elif roll == 6:
                    extra["pod_affinity_match"] = {"churn": f"p{i % 2}"}
                elif roll == 7:
                    extra["unmodeled_constraints"] = True
                fc.add_pod(
                    make_pod(
                        f"churn-{step}-{i}", int(rng.integers(50, 800)),
                        node, memory=64 * 1024**2, **extra,
                    )
                )
        elif action == 2:  # spot interruption + replacement (half the
            # replacements land in a zone, exercising zone aggregation)
            spots = [n for n in fc.nodes if n.startswith("spot-")]
            if spots:
                fc.remove_node(str(rng.choice(spots)))
            labels = dict(SPOT_LABELS)
            if step % 2:
                labels["topology.kubernetes.io/zone"] = f"z{step % 3}"
            fc.add_node(make_node(f"spot-new-{step}", labels))
        else:  # actuator-style taint + readiness flips
            names = list(fc.nodes)
            name = str(rng.choice(names))
            fc.add_taint(name, Taint("ToBeDeletedByClusterAutoscaler", "", "NoSchedule"))
            other = str(rng.choice(names))
            fc.nodes[other].ready = not fc.nodes[other].ready
            if step > 4:
                fc.remove_taint(name, "ToBeDeletedByClusterAutoscaler")
        obj, _ = object_pack(fc, spec.resources)
        col, _ = store.pack(fc.pdbs)
        assert_packed_equal(obj, col)


def test_priority_threshold_and_dnr_parity():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("low", 100, "spot-1", priority=-5))
    fc.add_pod(make_pod("hi", 100, "spot-1", priority=5))
    fc.add_pod(make_pod("odlow", 100, "od-1", priority=-5))
    fc.add_pod(make_pod("bare", 100, "od-1", replicated=False))
    store = columnar(fc, ("cpu", "memory"))
    for threshold in (0, -10):
        for dnr in (False, True):
            obj, om = object_pack(
                fc, ("cpu", "memory"), threshold=threshold, dnr=dnr
            )
            col, cm = store.pack(
                fc.pdbs, priority_threshold=threshold, delete_non_replicated=dnr
            )
            assert_packed_equal(obj, col)
            assert (
                {(b.pod.uid, b.reason) for b in om.blocking_pods()}
                == {(b.pod.uid, b.reason) for b in cm.blocking_pods()}
            )


def test_pdb_blocking_parity():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("guarded", 100, "od-1", labels={"app": "db"}))
    fc.add_pod(make_pod("free", 100, "od-2", labels={"app": "web"}))
    fc.pdbs.append(
        PDBSpec(name="db-pdb", match_labels={"app": "db"}, disruptions_allowed=0)
    )
    fc.pdbs.append(
        PDBSpec(name="web-pdb", match_labels={"app": "web"}, disruptions_allowed=3)
    )
    store = columnar(fc, ("cpu", "memory"))
    obj, om = object_pack(fc, ("cpu", "memory"))
    col, cm = store.pack(fc.pdbs)
    assert_packed_equal(obj, col)
    assert [b.reason for b in cm.blocking_pods()] == [
        "not enough pod disruption budget (db-pdb)"
    ]
    # namespace-scoped empty selector blocks everything in that namespace
    fc.pdbs.insert(0, PDBSpec(name="ns-wide", disruptions_allowed=0))
    obj, om = object_pack(fc, ("cpu", "memory"))
    col, cm = store.pack(fc.pdbs)
    assert_packed_equal(obj, col)
    assert (
        {(b.pod.uid, b.reason) for b in om.blocking_pods()}
        == {(b.pod.uid, b.reason) for b in cm.blocking_pods()}
    )


def test_mirror_daemonset_terminal_parity():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(
        make_pod(
            "mirror", 100, "od-1",
            annotations={"kubernetes.io/config.mirror": "x"}, replicated=False,
        )
    )
    from k8s_spot_rescheduler_tpu.models.cluster import OwnerRef

    fc.add_pod(
        PodSpecFactory := make_pod("ds", 100, "od-1")
    )
    PodSpecFactory.owner_refs[:] = [OwnerRef("DaemonSet", "ds-owner")]
    # re-add so the store re-reads the mutated owner_refs
    fc.add_pod(PodSpecFactory)
    fc.add_pod(make_pod("done", 100, "od-1", phase="Succeeded"))
    fc.add_pod(make_pod("mv", 150, "od-1"))
    store = columnar(fc, ("cpu", "memory"))
    obj, _ = object_pack(fc, ("cpu", "memory"))
    col, cm = store.pack(fc.pdbs)
    assert_packed_equal(obj, col)
    # only the movable pod occupies a slot
    assert int(col.slot_valid.sum()) == 1
    assert col.cand_valid[:1].tolist() == [True]


def test_node_delete_before_pod_deletes():
    """A watch can deliver a node delete before its pods' deletes; row
    reuse by a later add_node must not reattach the stale pods."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("p1", 300, "spot-1"))
    store = columnar(fc, ("cpu", "memory"))
    # bypass FakeCluster's remove-pods-first discipline: hit the store raw
    store.remove_node("spot-1")
    store.add_node(make_node("spot-2", SPOT_LABELS))
    packed, _ = store.pack([])
    # the stale pod must not occupy the new node's capacity
    assert packed.spot_free[0, 0] == 2000.0
    assert packed.spot_count[0] == 0
    assert store.n_pods == 0  # stale pod was dropped with its node


def test_pod_move_readd_keeps_one_placement():
    """Re-adding a uid on a different node is a move — the object read
    path and the columnar mirror must both see exactly one placement."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    pod = make_pod("mv", 300, "od-1")
    fc.add_pod(pod)
    store = columnar(fc, ("cpu", "memory"))
    fc.add_pod(dataclasses.replace(pod, node_name="od-2"))
    assert [p.uid for p in fc.list_pods_on_node("od-1")] == []
    assert [p.uid for p in fc.list_pods_on_node("od-2")] == ["default/mv"]
    obj, _ = object_pack(fc, ("cpu", "memory"))
    col, _ = store.pack([])
    assert_packed_equal(obj, col)


def test_same_node_upsert_keeps_slot_order():
    """A watch MODIFIED event (same uid, same node) must not reorder
    equal-CPU slot ties — the object path's dict update keeps position."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    a = make_pod("a", 300, "od-1", memory=64 * 1024**2)
    fc.add_pod(a)
    store = columnar(fc, ("cpu", "memory"))
    fc.add_pod(make_pod("b", 300, "od-1", memory=128 * 1024**2))
    fc.add_pod(dataclasses.replace(a))  # re-add a: position must not move
    obj, _ = object_pack(fc, ("cpu", "memory"))
    col, _ = store.pack([])
    assert_packed_equal(obj, col)
    assert col.slot_req[0, :2, 1].tolist() == [64.0, 128.0]  # a first


def test_node_changing_upsert_keeps_slot_order():
    """A MODIFIED event that moves a uid across nodes keeps the pod's
    dict position on the object path — the mirror must keep its seq."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    a = make_pod("a", 300, "od-1", memory=64 * 1024**2)
    fc.add_pod(a)
    store = columnar(fc, ("cpu", "memory"))
    fc.add_pod(make_pod("b", 300, "od-2", memory=128 * 1024**2))
    fc.add_pod(dataclasses.replace(a, node_name="od-2"))  # move: a first
    obj, _ = object_pack(fc, ("cpu", "memory"))
    col, _ = store.pack([])
    assert_packed_equal(obj, col)


def test_move_to_unseen_node_then_node_appears_keeps_slot_order():
    """A move to a not-yet-observed node parks the pod; when the node
    shows up, the un-parked pod must resume its original slot position
    (the object path's dict never moved it)."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    a = make_pod("a", 300, "od-1", memory=64 * 1024**2)
    b = make_pod("b", 300, "od-1", memory=128 * 1024**2)
    fc.add_pod(a)
    fc.add_pod(b)
    store = columnar(fc, ("cpu", "memory"))
    # watch ordering the fake forbids but a real stream can deliver:
    # both pods move to od-2 (b first, then a) BEFORE od-2 is observed
    od2 = make_node("od-2", ON_DEMAND_LABELS)
    store.add_pod(dataclasses.replace(b, node_name="od-2"))
    store.add_pod(dataclasses.replace(a, node_name="od-2"))
    store.add_node(od2)
    # bring the object truth to the same end state (its dict order is
    # insertion order: a then b, positions unmoved by the updates)
    fc.add_node(od2)
    fc.add_pod(dataclasses.replace(b, node_name="od-2"))
    fc.add_pod(dataclasses.replace(a, node_name="od-2"))
    obj, _ = object_pack(fc, ("cpu", "memory"))
    col, _ = store.pack([])
    assert_packed_equal(obj, col)


def test_loop_parity_columnar_vs_object():
    """Same cluster, same solver: the columnar and object observe paths
    must drain the same nodes tick for tick."""
    drains = {}
    for use_columnar in (False, True):
        clock = FakeClock()
        fc = generate_cluster(
            SyntheticSpec("loop-parity", 6, 6, 60), seed=5,
            clock=clock, reschedule_evicted=True,
        )
        config = ReschedulerConfig(
            solver="numpy", use_columnar=use_columnar, node_drain_delay=0.0
        )
        r = Rescheduler(
            fc, SolverPlanner(config), config, clock=clock, recorder=fc
        )
        drained = []
        for _ in range(10):
            result = r.tick()
            drained.extend(result.drained)
            clock.advance(30.0)
        drains[use_columnar] = drained
    assert drains[True] == drains[False]
    assert drains[True]  # something actually drained


def test_store_plan_materialization():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    for i, cpu in enumerate([300, 200, 100]):
        fc.add_pod(make_pod(f"p{i}", cpu, "od-1", memory=32 * 1024**2))
    store = columnar(fc, ("cpu", "memory"))
    config = ReschedulerConfig(solver="numpy")
    planner = SolverPlanner(config)
    report = planner.plan(store, [])
    assert report.plan is not None
    plan = report.plan
    assert plan.node.node.name == "od-1"
    assert [p.name for p in plan.pods] == ["p0", "p1", "p2"]  # cpu desc
    assert set(plan.assignments.values()) == {"spot-1"}


def test_columnar_counts_match_object_metrics():
    spec = dataclasses.replace(CONFIGS[4], n_on_demand=25, n_spot=25, n_pods=300)
    fc = generate_cluster(spec, seed=2)
    store = columnar(fc, spec.resources)
    od, spot = store.node_pod_counts(fc.pdbs)
    # ground truth via the object evictability filter
    from k8s_spot_rescheduler_tpu.models.evictability import get_pods_for_deletion

    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    want_od = {
        info.node.name: len(get_pods_for_deletion(info.pods, fc.pdbs)[0])
        for info in node_map.on_demand
    }
    want_spot = {
        info.node.name: len(get_pods_for_deletion(info.pods, fc.pdbs)[0])
        for info in node_map.spot
    }
    assert dict(od) == want_od
    assert dict(spot) == want_spot
