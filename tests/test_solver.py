"""Solver tests: the reference's planner fixtures, ported 1:1, plus
oracle↔TPU-solver parity on randomized clusters.

Fixture provenance: reference rescheduler_test.go:40-81
(TestFindSpotNodeForPod) and :102-151 (TestCanDrainNode).
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.models.cluster import NodeInfo, NodeMap
from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster, pack_cluster
from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod


def _spot_info(name: str, capacity: int, used_pods):
    """createTestNodeInfo equivalent: a spot node of given capacity with
    pods already consuming some of it."""
    pods = [make_pod(f"p{i}-{name}", cpu, name) for i, cpu in enumerate(used_pods)]
    return NodeInfo.build(make_node(name, SPOT_LABELS, cpu_millis=capacity), pods)


def _pack_drain_case(spot_infos, pods_for_deletion):
    """One candidate on-demand node holding ``pods_for_deletion``."""
    od = NodeInfo.build(
        make_node("od-1", ON_DEMAND_LABELS, cpu_millis=4000),
        [make_pod(f"d{i}", cpu, "od-1") for i, cpu in enumerate(pods_for_deletion)],
    )
    # NodeMap is normally sorted by build_node_map; here the fixture order
    # is the probe order, matching rescheduler_test.go:119-123.
    nm = NodeMap(on_demand=[od], spot=list(spot_infos))
    return pack_cluster(nm)


# The TestCanDrainNode spot pool: free CPU 700 / 300 / 100, presorted
# most-requested-first (rescheduler_test.go:119-123).
def _test_spot_pool():
    return [
        _spot_info("node3", 2000, [500, 500, 300]),  # free 700
        _spot_info("node2", 1100, [500, 300]),  # free 300
        _spot_info("node1", 500, [100, 300]),  # free 100
    ]


class TestCanDrainNodeFixture:
    def test_feasible_set(self):
        # rescheduler_test.go:126-132 + 142-145: 500,300,100,100,100 fits.
        packed, meta = _pack_drain_case(_test_spot_pool(), [500, 300, 100, 100, 100])
        res = plan_oracle(packed)
        assert bool(res.feasible[0])
        # Placement trace of the reference's first-fit:
        # 500->node3, 300->node3(wait: free 200 after? no -- see below)
        # Actual: 500->node3 (700->200), 300->node2 (300->0),
        #         100->node3 (200->100), 100->node3 (100->0), 100->node1.
        names = [meta.spot[s].node.name for s in res.assignment[0][:5]]
        assert names == ["node3", "node2", "node3", "node3", "node1"]

    def test_infeasible_set_over_capacity(self):
        # rescheduler_test.go:134-150: swap one 300m pod for 400m -> fails.
        packed, _ = _pack_drain_case(_test_spot_pool(), [500, 400, 100, 100, 100])
        res = plan_oracle(packed)
        assert not bool(res.feasible[0])
        assert (res.assignment[0] == -1).all()

    def test_jax_matches_fixture(self):
        for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
            packed, _ = _pack_drain_case(_test_spot_pool(), pods)
            want = plan_oracle(packed)
            got = plan_ffd_jit(packed)
            np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
            np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


class TestFindSpotNodeForPodFixture:
    """rescheduler_test.go:40-81, expressed as single-pod candidates."""

    def _pool(self):
        # free CPU: node1=100, node2=200, node3=700, probe order as listed.
        return [
            _spot_info("node1", 500, [100, 300]),
            _spot_info("node2", 1000, [500, 300]),
            _spot_info("node3", 2000, [500, 500, 300]),
        ]

    @pytest.mark.parametrize(
        "cpu,want",
        [(100, "node1"), (200, "node2"), (700, "node3"), (2200, None)],
    )
    def test_first_fit(self, cpu, want):
        packed, meta = _pack_drain_case(self._pool(), [cpu])
        res = plan_oracle(packed)
        if want is None:
            assert not bool(res.feasible[0])
        else:
            assert bool(res.feasible[0])
            assert meta.spot[res.assignment[0][0]].node.name == want


def _random_packed(rng: np.random.Generator) -> PackedCluster:
    """A randomized PackedCluster exercising every predicate dimension."""
    C = int(rng.integers(1, 6))
    K = int(rng.integers(1, 7))
    S = int(rng.integers(1, 8))
    R = int(rng.integers(1, 5))
    W, A = 1, 2
    return PackedCluster(
        slot_req=rng.integers(0, 900, (C, K, R)).astype(np.float32),
        slot_valid=rng.random((C, K)) < 0.8,
        slot_tol=rng.integers(0, 4, (C, K, W)).astype(np.uint32),
        slot_aff=(
            np.uint32(1)
            << rng.integers(0, 32, (C, K, A)).astype(np.uint32)
        )
        * (rng.random((C, K, A)) < 0.3),
        cand_valid=rng.random((C,)) < 0.9,
        spot_free=rng.integers(-100, 2000, (S, R)).astype(np.float32),
        spot_count=rng.integers(0, 5, (S,)).astype(np.int32),
        spot_max_pods=rng.integers(1, 8, (S,)).astype(np.int32),
        spot_taints=rng.integers(0, 4, (S, W)).astype(np.uint32),
        spot_ok=rng.random((S,)) < 0.9,
        spot_aff=(
            np.uint32(1) << rng.integers(0, 32, (S, A)).astype(np.uint32)
        )
        * (rng.random((S, A)) < 0.3),
    )


def test_config3_packs_four_resources():
    """BASELINE config 3 promises 4 resource dimensions (cpu, memory,
    ephemeral-storage, pods); the generator emits all four and the batched
    solver agrees with the serial oracle on them."""
    import dataclasses

    from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map

    spec = dataclasses.replace(CONFIGS[3], n_on_demand=8, n_spot=8, n_pods=120)
    assert len(spec.resources) == 4
    client = generate_cluster(spec, seed=7)
    nodes = client.list_ready_nodes()
    nm = build_node_map(
        nodes,
        {n.name: client.list_pods_on_node(n.name) for n in nodes},
        on_demand_label="kubernetes.io/role=worker",
        spot_label="kubernetes.io/role=spot-worker",
    )
    packed, _ = pack_cluster(nm, resources=spec.resources)
    assert packed.slot_req.shape[2] == 4
    # every pod carries a pods-count request of exactly 1
    valid = packed.slot_valid
    np.testing.assert_array_equal(packed.slot_req[..., 3][valid], 1.0)
    want = plan_oracle(packed)
    got = plan_ffd_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(40))
def test_oracle_jax_parity_randomized(seed):
    """The batched TPU solver is bit-identical to the serial reference
    semantics on randomized clusters (taints, affinity, pod-count caps,
    invalid lanes/slots, negative free capacity)."""
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    got = plan_ffd_jit(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)
