"""Randomized end-to-end safety over the round-5 widened surface.

The invariant everything rests on (SURVEY.md §7 hard part (e)): a drain
the planner approves must never strand a pod. The fake scheduler
(io/fake.py) independently enforces the full widened semantics — term
scopes (own/cross-namespace/wildcard), the four selector operators,
multi-term families, spread skew math — so on a randomized cluster any
modeling unsoundness (the packers approving a placement the scheduler
refuses) surfaces as a drain-evicted pod stuck pending. Each seed also
pins object-vs-columnar packer bit-parity on its cluster.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.bench.quality import drain_to_exhaustion
from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"
KEYS = ("app", "tier", "track")
VALS = ("db", "web", "cache", "be")
NSS = ("default", "payments", "infra")


def _rand_req(rng):
    key = rng.choice(KEYS)
    op = rng.choice(("In", "In", "NotIn", "Exists", "DoesNotExist"))
    if op in ("Exists", "DoesNotExist"):
        return (key, op, ())
    values = tuple(sorted(set(
        rng.sample(VALS, rng.randint(1, 2))
    )))
    return (key, op, values)


def _rand_selector(rng):
    return tuple(sorted({_rand_req(rng) for _ in range(rng.randint(1, 2))}))


def _rand_scope(rng, own_ns):
    roll = rng.random()
    if roll < 0.6:
        return (own_ns,)
    if roll < 0.8:
        return tuple(sorted({own_ns, rng.choice(NSS)}))
    return ("*",)


def _rand_labels(rng):
    return {
        k: rng.choice(VALS)
        for k in rng.sample(KEYS, rng.randint(0, 2))
    }


def _random_widened_cluster(seed):
    rng = random.Random(seed)
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    zones = ("za", "zb")
    for i in range(rng.randint(2, 3)):
        fc.add_node(make_node(f"od-{i}", ON_DEMAND_LABELS))
    for i in range(rng.randint(4, 7)):
        labels = dict(SPOT_LABELS, **{HOST: f"spot-{i}"})
        if rng.random() < 0.8:
            labels[ZONE] = rng.choice(zones)
        fc.add_node(make_node(f"spot-{i}", labels, cpu_millis=2000))
        # some spot residents with random labels
        for j in range(rng.randint(0, 2)):
            fc.add_pod(make_pod(
                f"res-{i}-{j}", rng.randint(100, 400), f"spot-{i}",
                namespace=rng.choice(NSS), labels=_rand_labels(rng),
            ))
    pod_n = 0
    for i in range(len([n for n in fc.nodes if n.startswith("od-")])):
        for j in range(rng.randint(1, 3)):
            ns = rng.choice(NSS)
            kwargs = {}
            r = rng.random()
            if r < 0.45:
                kwargs["anti_affinity_match"] = tuple(
                    (_rand_scope(rng, ns), _rand_selector(rng))
                    for _ in range(rng.randint(1, 2))
                )
            elif r < 0.6:
                kwargs["anti_affinity_zone_match"] = (
                    (_rand_scope(rng, ns), _rand_selector(rng)),
                )
            elif r < 0.7:
                kwargs["pod_affinity_match"] = (
                    (_rand_scope(rng, ns), _rand_selector(rng)),
                )
            elif r < 0.85:
                kwargs["spread_constraints"] = (
                    (rng.choice((HOST, ZONE, "example.com/rack")),
                     rng.randint(1, 3), _rand_selector(rng)),
                )
            fc.add_pod(make_pod(
                f"mover-{pod_n}", rng.randint(100, 500), f"od-{i}",
                namespace=ns, labels=_rand_labels(rng), **kwargs,
            ))
            pod_n += 1
    return fc


@pytest.mark.parametrize("seed", range(16))
def test_widened_surface_never_strands(seed):
    """Drains proven against random widened constraints must land every
    evicted pod in the independent fake scheduler — a drain-evicted pod
    left pending is a stranding (modeling unsoundness)."""
    fc = _random_widened_cluster(seed)
    drain_to_exhaustion(
        fc, ReschedulerConfig(solver="numpy", resources=("cpu", "memory"))
    )
    # let every graceful termination land
    fc.clock.advance(120.0)
    evicted = set(fc.evictions)
    stranded = {p.uid for p in fc.pending} & evicted
    assert not stranded, (seed, stranded)


@pytest.mark.parametrize("seed", range(16))
def test_widened_surface_packer_parity(seed):
    """Object-vs-columnar tensors stay bit-identical on random widened
    clusters."""
    fc = _random_widened_cluster(seed)
    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = pack_cluster(node_map, fc.pdbs, resources=("cpu", "memory"))
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
