"""Round-5 widened selector operators across the whole stack.

The reference delegates every affinity/spread selector shape to the real
scheduler's predicate checker (reference rescheduler.go:344; predicate
list README.md:103-114) — Exists / NotIn / DoesNotExist / multi-value In
selectors, multiple required terms per family, and explicit cross-
namespace ``namespaces`` lists all come free. Round 5 models them as
canonical terms (predicates/selectors.py); these tests pin, per
operator class: the matching algebra, decode, the oracle's placement
verdicts (both anti-affinity directions), object-vs-columnar packer
bit-parity, and a closed drain loop against the fake scheduler.
namespaceSelector remains conservative and visible to the gauges.
"""

from __future__ import annotations

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import decode_pod
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.predicates.selectors import (
    canon_labels,
    req_matches,
    selector_matches,
    selector_matches_nothing,
    term_matches,
)
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)


# --- the matching algebra --------------------------------------------------

def test_req_matches_k8s_semantics():
    labels = {"app": "db", "tier": "be"}
    assert req_matches(("app", "In", ("db", "web")), labels)
    assert not req_matches(("app", "In", ("web",)), labels)
    assert not req_matches(("gone", "In", ("x",)), labels)  # absent: no
    assert req_matches(("app", "NotIn", ("web",)), labels)
    assert not req_matches(("app", "NotIn", ("db", "x")), labels)
    assert req_matches(("gone", "NotIn", ("x",)), labels)  # absent: yes
    assert req_matches(("tier", "Exists", ()), labels)
    assert not req_matches(("gone", "Exists", ()), labels)
    assert req_matches(("gone", "DoesNotExist", ()), labels)
    assert not req_matches(("app", "DoesNotExist", ()), labels)


def test_selector_matches_is_conjunction():
    sel = (("app", "In", ("db",)), ("v", "NotIn", ("old",)))
    assert selector_matches(sel, {"app": "db"})
    assert selector_matches(sel, {"app": "db", "v": "new"})
    assert not selector_matches(sel, {"app": "db", "v": "old"})
    assert not selector_matches(sel, {"v": "new"})


def test_term_matches_namespace_scope():
    term = (("a", "b"), canon_labels({"app": "db"}))
    assert term_matches(term, "a", {"app": "db"})
    assert term_matches(term, "b", {"app": "db"})
    assert not term_matches(term, "c", {"app": "db"})
    assert not term_matches(term, "a", {"app": "web"})


@pytest.mark.parametrize("sel,nothing", [
    ((("k", "In", ("a",)), ("k", "In", ("b",))), True),
    ((("k", "In", ("a", "b")), ("k", "In", ("b", "c"))), False),
    ((("k", "In", ("a",)), ("k", "NotIn", ("a",))), True),
    ((("k", "In", ("a", "b")), ("k", "NotIn", ("a",))), False),
    ((("k", "In", ("a",)), ("k", "DoesNotExist", ())), True),
    ((("k", "Exists", ()), ("k", "DoesNotExist", ())), True),
    ((("k", "NotIn", ("a",)), ("k", "DoesNotExist", ())), False),
    ((("k", "NotIn", ("a",)),), False),
    ((("k", "Exists", ()), ("k", "NotIn", ("a",))), False),
    ((("k", "In", ("a",)), ("j", "DoesNotExist", ())), False),
])
def test_selector_matches_nothing(sel, nothing):
    assert selector_matches_nothing(tuple(sorted(sel))) == nothing


# --- cluster helpers -------------------------------------------------------

def _pack(fc):
    nodes = fc.list_ready_nodes()
    node_map = build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    return pack_cluster(node_map, fc.pdbs, resources=("cpu", "memory"))


def _placement(fc, pod_name):
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    for c, pods in enumerate(meta.cand_pods):
        for k, p in enumerate(pods):
            if p.name == pod_name:
                if not result.feasible[c]:
                    return None
                return meta.spot[int(result.assignment[c, k])].node.name
    raise AssertionError(f"{pod_name} not in any lane")


def _parity(fc):
    """Object packer vs columnar store: bit-identical tensors."""
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def _anti(reqs, namespaces=None):
    """One canonical hostname anti-affinity term for make_pod."""
    nss = tuple(sorted(namespaces)) if namespaces else ("default",)
    return ((nss, tuple(sorted(reqs))),)


# --- oracle verdicts per operator ------------------------------------------

def _two_spot_cluster(resident_labels, resident_ns="default"):
    """od-1 carries the mover; spot-busy (probed first) hosts the
    resident; spot-free is empty."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-busy", SPOT_LABELS))
    fc.add_node(make_node("spot-free", SPOT_LABELS))
    fc.add_pod(make_pod(
        "resident", 500, "spot-busy", namespace=resident_ns,
        labels=resident_labels,
    ))
    return fc


def test_exists_operator_repels_any_labeled_match():
    fc = _two_spot_cluster({"app": "anything"})
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti([("app", "Exists", ())]),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_notin_operator_repels_non_listed_values():
    # NotIn("web") matches the db resident -> repelled from spot-busy
    fc = _two_spot_cluster({"app": "db"})
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti([("app", "NotIn", ("web",))]),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_notin_operator_admits_listed_value():
    # NotIn("db") does NOT match the db resident -> spot-busy admits
    fc = _two_spot_cluster({"app": "db"})
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti([("app", "NotIn", ("db",))]),
    ))
    assert _placement(fc, "mover") == "spot-busy"


def test_notin_matches_unlabeled_resident():
    # k8s semantics: NotIn matches when the key is ABSENT
    fc = _two_spot_cluster({"other": "x"})
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti([("app", "NotIn", ("db",))]),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_doesnotexist_operator():
    fc = _two_spot_cluster({"other": "x"})  # lacks "app" -> matched
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti([("app", "DoesNotExist", ())]),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_multi_value_in_operator():
    fc = _two_spot_cluster({"app": "cache"})
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti([("app", "In", ("cache", "db"))]),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_symmetric_direction_with_widened_operator():
    """A plain mover matched by a RESIDENT's Exists-selector term must
    avoid that node (the scheduler enforces existing pods' required
    anti-affinity)."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-busy", SPOT_LABELS))
    fc.add_node(make_node("spot-free", SPOT_LABELS))
    fc.add_pod(make_pod(
        "guard", 500, "spot-busy",
        anti_affinity_match=_anti([("app", "Exists", ())]),
    ))
    fc.add_pod(make_pod("mover", 300, "od-1", labels={"app": "db"}))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_cross_namespace_scope_repels_only_listed_namespaces():
    # the resident lives in ns "prod"; a mover in "default" carrying a
    # term scoped to ["prod"] is repelled; scoped to ["staging"] is not
    fc = _two_spot_cluster({"app": "db"}, resident_ns="prod")
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti(
            [("app", "In", ("db",))], namespaces=["prod"]
        ),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)

    fc2 = _two_spot_cluster({"app": "db"}, resident_ns="prod")
    fc2.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=_anti(
            [("app", "In", ("db",))], namespaces=["staging"]
        ),
    ))
    assert _placement(fc2, "mover") == "spot-busy"


def test_multi_term_anti_affinity_every_term_enforced():
    """Two hostname terms: the mover refuses nodes matching EITHER."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-db", SPOT_LABELS))
    fc.add_node(make_node("spot-cache", SPOT_LABELS))
    fc.add_node(make_node("spot-free", SPOT_LABELS))
    fc.add_pod(make_pod("r-db", 600, "spot-db", labels={"app": "db"}))
    fc.add_pod(make_pod("r-cache", 500, "spot-cache",
                        labels={"app": "cache"}))
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=(
            _anti([("app", "In", ("db",))])
            + _anti([("app", "In", ("cache",))])
        ),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)


def test_multi_term_positive_affinity_needs_all_terms():
    """Two positive hostname terms: only a node hosting BOTH a db match
    and a cache match admits the carrier."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-db", SPOT_LABELS))
    fc.add_node(make_node("spot-both", SPOT_LABELS))
    fc.add_pod(make_pod("r-db", 600, "spot-db", labels={"app": "db"}))
    fc.add_pod(make_pod("b-db", 300, "spot-both", labels={"app": "db"}))
    fc.add_pod(make_pod("b-cache", 200, "spot-both",
                        labels={"app": "cache"}))
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        pod_affinity_match=(
            _anti([("app", "In", ("db",))])
            + _anti([("app", "In", ("cache",))])
        ),
    ))
    assert _placement(fc, "mover") == "spot-both"
    _parity(fc)


def test_spread_with_expression_selector_counts_widened_matches():
    """A hostname maxSkew=1 spread whose selector is Exists("app"):
    counting must see every app-labeled pod. spot-1 holds two, spot-2
    holds one — placing on spot-1 (3 vs min 1) breaks skew, spot-2 ok."""
    fc = FakeCluster(FakeClock())
    host1 = dict(SPOT_LABELS, **{"kubernetes.io/hostname": "spot-1"})
    host2 = dict(SPOT_LABELS, **{"kubernetes.io/hostname": "spot-2"})
    hod = dict(ON_DEMAND_LABELS, **{"kubernetes.io/hostname": "od-1"})
    fc.add_node(make_node("od-1", hod))
    fc.add_node(make_node("spot-1", host1))
    fc.add_node(make_node("spot-2", host2))
    fc.add_pod(make_pod("a1", 400, "spot-1", labels={"app": "x"}))
    fc.add_pod(make_pod("a2", 300, "spot-1", labels={"app": "y"}))
    fc.add_pod(make_pod("b1", 500, "spot-2", labels={"app": "z"}))
    fc.add_pod(make_pod(
        "mover", 200, "od-1",
        labels={"app": "m"},
        spread_constraints=(
            ("kubernetes.io/hostname", 1, (("app", "Exists", ()),)),
        ),
    ))
    # after the mover's departure: od-1 0, spot-1 2, spot-2 1; min 0.
    # placing (selfMatch) on spot-1 -> 3-0 > 1 refused; spot-2 -> 2-0 > 1
    # refused too... loosen: skew 2 admits spot-2 only
    fc.pods["default/mover"].spread_constraints = (
        ("kubernetes.io/hostname", 2, (("app", "Exists", ()),)),
    )
    assert _placement(fc, "mover") == "spot-2"
    _parity(fc)


# --- decode + gauge of what stays conservative -----------------------------

def test_namespace_selector_stays_conservative_and_gauged():
    obj = {
        "metadata": {"name": "p", "namespace": "default"},
        "spec": {"nodeName": "od-1", "containers": [], "affinity": {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "namespaceSelector": {"matchLabels": {"team": "x"}},
                     "labelSelector": {"matchLabels": {"app": "db"}}}]}}},
        "status": {"phase": "Running"},
    }
    pod = decode_pod(obj)
    assert pod.unmodeled_constraints
    assert pod.anti_affinity_match == ()
    # the unmodeled pod pins its candidate and is counted by the gauge
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_pod(make_pod("mover", 300, "od-1", unmodeled_constraints=True))
    packed, meta = _pack(fc)
    assert meta.unplaceable_pod_count() == 1
    assert not plan_oracle(packed).feasible[:1].any()


# --- end to end ------------------------------------------------------------

def test_loop_drains_with_widened_operators():
    """Closed loop: drain proven against widened-operator constraints,
    evicted pods land where the independent fake scheduler (which
    enforces the same k8s semantics) accepts them."""
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-busy", SPOT_LABELS))
    fc.add_node(make_node("spot-free", SPOT_LABELS))
    fc.add_pod(make_pod("resident", 500, "spot-busy",
                        labels={"app": "db", "v": "2"}))
    fc.add_pod(make_pod(
        "mover-a", 300, "od-1",
        anti_affinity_match=_anti([("app", "Exists", ())]),
    ))
    fc.add_pod(make_pod(
        "mover-b", 200, "od-1", labels={"q": "1"},
        anti_affinity_match=_anti([("v", "In", ("1", "2"))]),
    ))
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    clock.advance(10.0)
    assert fc.pods["default/mover-a"].node_name == "spot-free"
    assert fc.pods["default/mover-b"].node_name == "spot-free"
    assert fc.pending == []


def test_namespace_selector_empty_means_all_namespaces():
    """Round 5: ``namespaceSelector: {}`` selects EVERY namespace (k8s)
    and is modeled as the wildcard scope; non-empty selectors (matching
    namespace labels we do not observe) stay conservative; null means
    "no selector" and keeps the default scope."""
    import json

    from k8s_spot_rescheduler_tpu.io import native_ingest

    def obj(term_extra, ns="a"):
        term = {"topologyKey": "kubernetes.io/hostname",
                "labelSelector": {"matchLabels": {"app": "db"}}}
        term.update(term_extra)
        return {
            "metadata": {"name": "p", "namespace": ns, "uid": "u1"},
            "spec": {"nodeName": "n1", "containers": [], "affinity": {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution":
                        [term]}}},
            "status": {"phase": "Running"},
        }

    wild = decode_pod(obj({"namespaceSelector": {}}))
    assert wild.anti_affinity_match == (
        (("*",), (("app", "In", ("db",)),)),
    )
    assert not wild.unmodeled_constraints
    # the wildcard subsumes any namespaces list
    both = decode_pod(obj({"namespaceSelector": {},
                           "namespaces": ["x", "y"]}))
    assert both.anti_affinity_match == wild.anti_affinity_match
    # null ≡ absent
    nul = decode_pod(obj({"namespaceSelector": None}))
    assert nul.anti_affinity_match == (
        (("a",), (("app", "In", ("db",)),)),
    )
    # label-matching selectors stay conservative
    lbl = decode_pod(obj({"namespaceSelector": {
        "matchLabels": {"team": "x"}}}))
    assert lbl.unmodeled_constraints

    if native_ingest.available():
        objs = [obj({"namespaceSelector": {}}),
                obj({"namespaceSelector": None}),
                obj({"namespaceSelector": {"matchLabels": {"team": "x"}}})]
        for i, o in enumerate(objs):
            o["metadata"] = dict(o["metadata"], name=f"p{i}", uid=f"u{i}")
        batch = native_ingest.parse_pod_list(
            json.dumps({"items": objs}).encode()
        )
        for i, o in enumerate(objs):
            want = decode_pod(o)
            got = batch.view(i)
            assert got.anti_affinity_match == want.anti_affinity_match, i
            assert (
                got.unmodeled_constraints == want.unmodeled_constraints
            ), i


def test_all_namespaces_scope_repels_across_namespaces():
    """A wildcard-scope anti-affinity term repels matches in ANY
    namespace — and the symmetric presence direction reaches every
    pod, on both pack paths."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-busy", SPOT_LABELS))
    fc.add_node(make_node("spot-free", SPOT_LABELS))
    fc.add_pod(make_pod("resident", 500, "spot-busy",
                        namespace="payments", labels={"app": "db"}))
    fc.add_pod(make_pod(
        "mover", 300, "od-1",
        anti_affinity_match=(
            (("*",), (("app", "In", ("db",)),)),
        ),
    ))
    assert _placement(fc, "mover") == "spot-free"
    _parity(fc)
