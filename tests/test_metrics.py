"""Metrics parity tests (reference metrics/metrics.go:24-96)."""

from prometheus_client import REGISTRY

from k8s_spot_rescheduler_tpu.metrics import registry as metrics


def _value(name, labels=None):
    return REGISTRY.get_sample_value(name, labels or {})


def test_series_names_match_reference():
    metrics.update_nodes_map("od-label", "spot-label", 3, 5)
    assert _value("spot_rescheduler_nodes_count", {"node_type": "od-label"}) == 3
    assert _value("spot_rescheduler_nodes_count", {"node_type": "spot-label"}) == 5

    metrics.update_node_pods_count("od-label", "node-1", 7)
    assert (
        _value(
            "spot_rescheduler_node_pods_count",
            {"node_type": "od-label", "node": "node-1"},
        )
        == 7
    )

    before = _value("spot_rescheduler_evicted_pods_total") or 0
    metrics.update_evictions_count()
    assert _value("spot_rescheduler_evicted_pods_total") == before + 1

    metrics.update_node_drain_count("Success", "node-1")
    assert (
        _value(
            "spot_rescheduler_node_drain_total",
            {"drain_state": "Success", "node": "node-1"},
        )
        >= 1
    )


def test_plan_duration_histogram():
    metrics.observe_plan_duration("jax", 0.042, 17)
    assert _value("spot_rescheduler_plan_candidates") == 17
    assert (
        _value("spot_rescheduler_plan_duration_seconds_count", {"solver": "jax"})
        >= 1
    )


def test_solver_repair_chunks_gauge():
    """solver_repair_chunks mirrors the dispatch decision, and
    repair_unavailable fires ONLY on the repair-dropping 2-D tier (past
    the chunked ceiling) — the cand tier with chunked repair keeps it
    clear."""
    metrics.update_solver_mode(
        "jax", "jax+cand-sharded", False, repair_chunks=4
    )
    assert _value("spot_rescheduler_solver_repair_chunks") == 4
    assert _value("spot_rescheduler_repair_unavailable") == 0
    metrics.update_solver_mode("jax", "jax+sharded", True, repair_chunks=0)
    assert _value("spot_rescheduler_solver_repair_chunks") == 0
    assert _value("spot_rescheduler_repair_unavailable") == 1
    # back on a repair-capable path: both recover
    metrics.update_solver_mode("jax", "jax", False, repair_chunks=1)
    assert _value("spot_rescheduler_solver_repair_chunks") == 1
    assert _value("spot_rescheduler_repair_unavailable") == 0


def test_repair_ceiling_thresholds_feed_the_gauge():
    """The dispatch math behind the gauge: chunked estimates fall
    monotonically, and pick_repair_chunks returns 0 (the only
    repair_unavailable regime) solely when even full chunking cannot
    fit the budget."""
    from k8s_spot_rescheduler_tpu.solver import memory

    shapes = (20480, 32, 20480, 4, 2, 2)  # 8x north star
    e1 = memory.estimate_union_hbm_bytes(*shapes)
    e8 = memory.estimate_union_hbm_bytes(*shapes, repair_spot_chunks=8)
    assert e8 < e1
    assert memory.pick_repair_chunks(*shapes, budget_bytes=(e1 + e8) // 2) > 1
    assert memory.pick_repair_chunks(*shapes, budget_bytes=1) == 0


def test_tick_phase_histogram():
    """Tick phases (observe/plan/actuate) land in the phase histogram."""
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
    from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod

    clock = FakeClock()
    fc = FakeCluster(clock)
    fc.add_node(make_node("od", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot", SPOT_LABELS))
    fc.add_pod(make_pod("a", 100, "od"))
    cfg = ReschedulerConfig(solver="numpy")
    Rescheduler(fc, SolverPlanner(cfg), cfg, clock=clock).tick()
    for phase in ("observe", "plan", "actuate"):
        assert (
            _value(
                "spot_rescheduler_tick_phase_duration_seconds_count",
                {"phase": phase},
            )
            >= 1
        )
