"""Metrics parity tests (reference metrics/metrics.go:24-96)."""

from prometheus_client import REGISTRY

from k8s_spot_rescheduler_tpu.metrics import registry as metrics


def _value(name, labels=None):
    return REGISTRY.get_sample_value(name, labels or {})


def test_series_names_match_reference():
    metrics.update_nodes_map("od-label", "spot-label", 3, 5)
    assert _value("spot_rescheduler_nodes_count", {"node_type": "od-label"}) == 3
    assert _value("spot_rescheduler_nodes_count", {"node_type": "spot-label"}) == 5

    metrics.update_node_pods_count("od-label", "node-1", 7)
    assert (
        _value(
            "spot_rescheduler_node_pods_count",
            {"node_type": "od-label", "node": "node-1"},
        )
        == 7
    )

    before = _value("spot_rescheduler_evicted_pods_total") or 0
    metrics.update_evictions_count()
    assert _value("spot_rescheduler_evicted_pods_total") == before + 1

    metrics.update_node_drain_count("Success", "node-1")
    assert (
        _value(
            "spot_rescheduler_node_drain_total",
            {"drain_state": "Success", "node": "node-1"},
        )
        >= 1
    )


def test_plan_duration_histogram():
    metrics.observe_plan_duration("jax", 0.042, 17)
    assert _value("spot_rescheduler_plan_candidates") == 17
    assert (
        _value("spot_rescheduler_plan_duration_seconds_count", {"solver": "jax"})
        >= 1
    )


def test_solver_repair_chunks_gauge():
    """solver_repair_chunks mirrors the dispatch decision, and
    repair_unavailable fires ONLY on the repair-dropping 2-D tier (past
    the chunked ceiling) — the cand tier with chunked repair keeps it
    clear."""
    metrics.update_solver_mode(
        "jax", "jax+cand-sharded", False, repair_chunks=4
    )
    assert _value("spot_rescheduler_solver_repair_chunks") == 4
    assert _value("spot_rescheduler_repair_unavailable") == 0
    metrics.update_solver_mode("jax", "jax+sharded", True, repair_chunks=0)
    assert _value("spot_rescheduler_solver_repair_chunks") == 0
    assert _value("spot_rescheduler_repair_unavailable") == 1
    # back on a repair-capable path: both recover
    metrics.update_solver_mode("jax", "jax", False, repair_chunks=1)
    assert _value("spot_rescheduler_solver_repair_chunks") == 1
    assert _value("spot_rescheduler_repair_unavailable") == 0


def test_repair_ceiling_thresholds_feed_the_gauge():
    """The dispatch math behind the gauge: chunked estimates fall
    monotonically, and pick_repair_chunks returns 0 (the only
    repair_unavailable regime) solely when even full chunking cannot
    fit the budget."""
    from k8s_spot_rescheduler_tpu.solver import memory

    shapes = (20480, 32, 20480, 4, 2, 2)  # 8x north star
    e1 = memory.estimate_union_hbm_bytes(*shapes)
    e8 = memory.estimate_union_hbm_bytes(*shapes, repair_spot_chunks=8)
    assert e8 < e1
    assert memory.pick_repair_chunks(*shapes, budget_bytes=(e1 + e8) // 2) > 1
    assert memory.pick_repair_chunks(*shapes, budget_bytes=1) == 0


def test_tick_phase_histogram():
    """Tick phases (observe/plan/actuate) land in the phase histogram."""
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
    from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod

    clock = FakeClock()
    fc = FakeCluster(clock)
    fc.add_node(make_node("od", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot", SPOT_LABELS))
    fc.add_pod(make_pod("a", 100, "od"))
    cfg = ReschedulerConfig(solver="numpy")
    Rescheduler(fc, SolverPlanner(cfg), cfg, clock=clock).tick()
    for phase in ("observe", "plan", "actuate"):
        assert (
            _value(
                "spot_rescheduler_tick_phase_duration_seconds_count",
                {"phase": phase},
            )
            >= 1
        )


# ---------------------------------------------------------------------------
# fleet observability plane: windowed waits, fairness, shed/compile series


def test_jain_fairness_index():
    assert metrics.jain_fairness([]) == 1.0
    assert metrics.jain_fairness([0, 0, 0]) == 1.0  # nobody served, nobody starved
    assert metrics.jain_fairness([5, 5, 5, 5]) == 1.0
    n = 8
    one_hot = [1.0] + [0.0] * (n - 1)
    assert abs(metrics.jain_fairness(one_hot) - 1.0 / n) < 1e-9
    mild = metrics.jain_fairness([1, 2, 1, 2])
    assert 1.0 / 4 < mild < 1.0


def test_windowed_wait_percentiles_and_reset():
    metrics.reset_service_window()
    # 200 waits for one tenant: the per-tenant ring keeps the last
    # WAIT_WINDOW only, so the p50 reflects the recent half
    waits = [("t-ring", float(i)) for i in range(200)]
    metrics.update_service_batch(4, 1, waits, occupancy=0.5)
    snap = metrics.service_tenant_wait_snapshot()
    assert snap["t-ring"]["n"] == metrics.WAIT_WINDOW
    # nearest-rank p99 of the 128-deep ring [72..199] is rank 127 -> 198
    assert snap["t-ring"]["p99_ms"] == 198.0
    assert snap["t-ring"]["p50_ms"] >= 100.0  # old half evicted
    summary = metrics.service_queue_wait_summary(top=4)
    assert summary["n"] == 200  # pooled window is wider than one ring
    assert summary["p99_ms"] >= snap["t-ring"]["p50_ms"]
    assert _value("spot_rescheduler_service_queue_wait_p99_ms") > 0
    assert _value("spot_rescheduler_service_batch_occupancy") == 0.5
    metrics.reset_service_window()
    assert metrics.service_tenant_wait_snapshot() == {}
    assert metrics.service_queue_wait_summary()["n"] == 0
    assert _value("spot_rescheduler_service_queue_wait_p99_ms") == 0.0


def test_tenant_wait_snapshot_keeps_worst_tenants():
    metrics.reset_service_window()
    pairs = [(f"t-{i}", float(i * 100)) for i in range(8)]
    metrics.update_service_batch(8, 8, pairs)
    snap = metrics.service_tenant_wait_snapshot(top=3)
    assert set(snap) == {"t-7", "t-6", "t-5"}  # worst p99 win
    metrics.reset_service_window()


def test_tenant_wait_rings_are_lru_bounded():
    metrics.reset_service_window()
    n_over = metrics.WAIT_TENANTS_MAX + 5
    for i in range(n_over):
        metrics.update_service_batch(1, 1, [(f"lru-{i}", 1.0)])
    snap = metrics.service_tenant_wait_snapshot()
    assert len(snap) == metrics.WAIT_TENANTS_MAX
    assert "lru-0" not in snap  # oldest evicted
    assert f"lru-{n_over - 1}" in snap
    metrics.reset_service_window()


def test_admission_shed_reason_labels():
    name = "spot_rescheduler_service_admission_shed_total"
    before = _value(name, {"reason": "queue-timeout"}) or 0
    metrics.update_service_admission_shed("queue-timeout")
    assert _value(name, {"reason": "queue-timeout"}) == before + 1
    other = _value(name, {"reason": "max-inflight"}) or 0
    metrics.update_service_admission_shed("max-inflight")
    assert _value(name, {"reason": "max-inflight"}) == other + 1


def test_bucket_compile_hit_miss_counters():
    hits = "spot_rescheduler_service_bucket_compile_hits_total"
    misses = "spot_rescheduler_service_bucket_compile_misses_total"
    h0, m0 = _value(hits) or 0, _value(misses) or 0
    metrics.update_service_bucket_compile(first=True)
    metrics.update_service_bucket_compile(first=False)
    metrics.update_service_bucket_compile(first=False)
    assert _value(hits) == h0 + 2
    assert _value(misses) == m0 + 1


def test_service_snapshot_carries_fleet_plane():
    metrics.reset_service_window()
    metrics.update_service_batch(
        4, 2, [("snap-a", 10.0), ("snap-b", 30.0)], occupancy=0.25
    )
    snap = metrics.service_snapshot()
    assert snap["batch_occupancy"] == 0.25
    assert snap["queue_wait_p99_ms"] == 30.0
    assert snap["tenant_queue_wait"]["snap-b"]["p99_ms"] == 30.0
    assert 0 < snap["jain_served"] <= 1.0
    assert "admission_shed" in snap and "compile_hits" in snap
    metrics.reset_service_window()
