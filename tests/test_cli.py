"""CLI flag-surface tests (reference rescheduler.go:89-142, 407-417)."""

import pytest

from k8s_spot_rescheduler_tpu.cli.main import build_parser, config_from_args, main
from k8s_spot_rescheduler_tpu.utils.durations import parse_duration


def test_defaults_match_reference():
    args = build_parser().parse_args([])
    cfg = config_from_args(args)
    assert cfg.housekeeping_interval == 10.0  # rescheduler.go:63
    assert cfg.node_drain_delay == 600.0  # rescheduler.go:66
    assert cfg.pod_eviction_timeout == 120.0  # rescheduler.go:69
    assert cfg.max_graceful_termination == 120.0  # rescheduler.go:73
    assert cfg.listen_address == "localhost:9235"  # rescheduler.go:77
    assert cfg.namespace == "kube-system"
    assert cfg.on_demand_node_label == "kubernetes.io/role=worker"
    assert cfg.spot_node_label == "kubernetes.io/role=spot-worker"
    assert cfg.priority_threshold == 0
    assert cfg.delete_non_replicated_pods is False


def test_robustness_defaults():
    cfg = config_from_args(build_parser().parse_args([]))
    assert cfg.kube_retry_max == 4
    assert cfg.kube_retry_base == 0.25
    assert cfg.breaker_threshold == 3
    assert cfg.breaker_max_interval == 300.0  # "5m"
    assert cfg.reconcile_orphaned_taints is True
    assert cfg.chaos_profile == ""  # chaos is strictly opt-in
    assert cfg.chaos_seed == 0


def test_robustness_flags_flow_into_config():
    args = build_parser().parse_args(
        ["--kube-retry-max", "2", "--kube-retry-base", "0.1",
         "--breaker-threshold", "5", "--breaker-max-interval", "2m",
         "--reconcile-orphaned-taints", "false",
         "--chaos-profile", "heavy", "--chaos-seed", "9"]
    )
    cfg = config_from_args(args)
    assert cfg.kube_retry_max == 2
    assert cfg.kube_retry_base == 0.1
    assert cfg.breaker_threshold == 5
    assert cfg.breaker_max_interval == 120.0
    assert cfg.reconcile_orphaned_taints is False
    assert cfg.chaos_profile == "heavy"
    assert cfg.chaos_seed == 9


def test_freshness_defaults():
    cfg = config_from_args(build_parser().parse_args([]))
    assert cfg.watch_progress_deadline == 120.0  # "2m"
    assert cfg.mirror_staleness_budget == 60.0  # "1m"
    assert cfg.resync_interval == 300.0  # "5m"
    assert cfg.chaos_watch_stall_rate == 0.0  # chaos stays opt-in


def test_freshness_flags_flow_into_config():
    args = build_parser().parse_args(
        ["--watch-progress-deadline", "30s",
         "--mirror-staleness-budget", "45s",
         "--resync-interval", "10m",
         "--chaos-watch-stall-rate", "0.25"]
    )
    cfg = config_from_args(args)
    assert cfg.watch_progress_deadline == 30.0
    assert cfg.mirror_staleness_budget == 45.0
    assert cfg.resync_interval == 600.0
    assert cfg.chaos_watch_stall_rate == 0.25


def test_freshness_zero_disables():
    cfg = config_from_args(build_parser().parse_args(
        ["--watch-progress-deadline", "0",
         "--mirror-staleness-budget", "0",
         "--resync-interval", "0"]
    ))
    assert cfg.watch_progress_deadline == 0.0
    assert cfg.mirror_staleness_budget == 0.0
    assert cfg.resync_interval == 0.0


def test_chaos_demo_run():
    """Full binary path under fault injection: the seeded chaos wrapper
    engages and the bounded run still exits cleanly."""
    rc = main(
        ["--cluster", "synthetic:1", "--ticks", "3", "--no-metrics-server",
         "--node-drain-delay", "1s", "--solver", "numpy",
         "--chaos-profile", "light", "--chaos-seed", "3"]
    )
    assert rc == 0


def test_version_flag(capsys):
    assert main(["--version"]) == 0
    assert "k8s-spot-rescheduler-tpu" in capsys.readouterr().out


def test_bad_label_rejected(capsys):
    rc = main(["--on-demand-node-label", "a=b=c", "--no-metrics-server"])
    assert rc == 1
    assert "not correctly formatted" in capsys.readouterr().err


def test_duration_parsing():
    assert parse_duration("10s") == 10.0
    assert parse_duration("10m") == 600.0
    assert parse_duration("2m30s") == 150.0
    assert parse_duration("1.5h") == 5400.0
    assert parse_duration("100ms") == pytest.approx(0.1)
    assert parse_duration(42) == 42.0
    with pytest.raises(ValueError):
        parse_duration("10 parsecs")


def test_synthetic_demo_run():
    """Full binary path: synthetic cluster, 2 ticks, jax solver."""
    rc = main(
        ["--cluster", "synthetic:1", "--ticks", "2", "--no-metrics-server",
         "--node-drain-delay", "1s"]
    )
    assert rc == 0


def test_jax_cache_dir_flag(tmp_path):
    """--jax-cache-dir flows into the config, and building a device
    planner points XLA's persistent compilation cache at it (paid once
    per image, not per process restart)."""
    import jax

    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner

    cache = str(tmp_path / "xla-cache")
    args = build_parser().parse_args(["--jax-cache-dir", cache])
    cfg = config_from_args(args)
    assert cfg.jax_cache_dir == cache
    assert config_from_args(build_parser().parse_args([])).jax_cache_dir == ""

    prev = jax.config.jax_compilation_cache_dir
    try:
        SolverPlanner(cfg)
        assert jax.config.jax_compilation_cache_dir == cache
        import os

        assert os.path.isdir(cache)  # created if absent
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
