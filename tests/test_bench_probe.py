"""Bench backend-probe tests (VERDICT round-2 task 9).

The driver's benchmark run must capture a TPU number automatically the
moment the backend is healthy, and an honest CPU-fallback JSON line when
it is not — with no code changes between the two worlds. These tests pin
both directions of ``acquire_backend`` (unit, via a stubbed probe
subprocess) and both end-to-end dispatch paths (subprocess runs of
bench.py against the only backend tests may assume: CPU).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import bench
import pytest

REPO = Path(__file__).resolve().parent.parent


class _Result:
    def __init__(self, returncode=0, stdout="", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


def test_probe_success_first_attempt(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Result(0, "tpu/TPU v5 lite\n"),
    )
    platform, attempts, err = bench.acquire_backend(
        budget_s=5.0, probe_timeout_s=1.0
    )
    assert platform == "tpu/TPU v5 lite"
    assert attempts == 1
    assert err is None


def test_probe_retries_then_succeeds(monkeypatch):
    calls = []

    def run(*a, **k):
        calls.append(1)
        if len(calls) < 3:
            return _Result(1, "", "RuntimeError: backend not ready")
        return _Result(0, "tpu/TPU v5 lite\n")

    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, attempts, err = bench.acquire_backend(
        budget_s=30.0, probe_timeout_s=1.0
    )
    assert platform == "tpu/TPU v5 lite"
    assert attempts == 3
    assert err is None


def test_probe_hang_is_killed_and_reported(monkeypatch):
    def run(*a, **k):
        raise subprocess.TimeoutExpired("probe", k.get("timeout", 1))

    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, attempts, err = bench.acquire_backend(
        budget_s=0.2, probe_timeout_s=0.1
    )
    assert platform is None
    assert attempts >= 1
    assert "hung" in err


def test_probe_failure_surfaces_last_error(monkeypatch):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Result(1, "", "RuntimeError: no axon backend"),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, attempts, err = bench.acquire_backend(
        budget_s=0.2, probe_timeout_s=0.1
    )
    assert platform is None
    assert "no axon backend" in err


def _run_bench(*args, timeout=600):
    env = dict(os.environ)
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )


def test_e2e_backend_available_emits_device_json():
    """With a healthy backend (CPU here; axon on the driver) the JSON line
    carries the device and no error field — the TPU-capture path. The
    tunneled axon backend goes through sick phases where initialization
    hangs for minutes (memory: tpu-tunnel-quirks); when the probe
    reports exactly that, the HEALTHY-path assertion has no backend to
    run against — skip rather than fail on weather."""
    r = _run_bench("--config", "1", "--repeats", "1", "--watchdog", "500")
    assert r.returncode == 0, r.stderr[-800:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    if "backend unavailable" in out.get("error", ""):
        pytest.skip("axon tunnel currently unavailable: " + out["error"])
    assert out["value"] is not None
    assert out["vs_baseline"] is not None
    assert "device" in out
    assert "error" not in out
    assert "backend ready" in r.stderr


def test_e2e_backend_unavailable_falls_back_honestly():
    """Zero probe budget = backend never acquired: the run still succeeds
    on CPU and says so in the error field."""
    r = _run_bench(
        "--config", "1", "--repeats", "1", "--backend-budget", "0",
        "--watchdog", "500",
    )
    assert r.returncode == 0, r.stderr[-800:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["value"] is not None
    assert "tpu backend unavailable" in out["error"]
    assert "FALLBACK" in r.stderr


def test_e2e_no_cpu_fallback_flag_fails_closed():
    r = _run_bench(
        "--config", "1", "--backend-budget", "0", "--no-cpu-fallback",
        "--watchdog", "300",
    )
    assert r.returncode == 1
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["value"] is None
    assert "no usable jax backend" in out["error"]


def test_probe_attempt_cap(monkeypatch):
    """Total probe spend is capped by max_attempts even with a generous
    wall-clock budget (BENCH_r05 burned 4 x 90 s before every fallback)."""
    calls = []

    def run(*a, **k):
        calls.append(1)
        return _Result(1, "", "RuntimeError: backend not ready")

    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    platform, attempts, err = bench.acquire_backend(
        budget_s=10_000.0, probe_timeout_s=1.0, max_attempts=3
    )
    assert platform is None
    assert attempts == 3 and len(calls) == 3


def test_probe_failed_verdict_cached(monkeypatch):
    """With cache=True a failed acquisition is remembered: the second
    call within the same bench invocation must not probe again."""
    calls = []

    def run(*a, **k):
        calls.append(1)
        return _Result(1, "", "RuntimeError: no backend")

    monkeypatch.setattr(bench.subprocess, "run", run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench, "_probe_verdict", {})
    first = bench.acquire_backend(
        budget_s=5.0, probe_timeout_s=1.0, max_attempts=2, cache=True
    )
    n_probes = len(calls)
    second = bench.acquire_backend(
        budget_s=5.0, probe_timeout_s=1.0, max_attempts=2, cache=True
    )
    assert first[0] is None and second == first
    assert len(calls) == n_probes  # no new probe subprocesses


def test_probe_cache_off_by_default(monkeypatch):
    """Unit callers (these tests) must not leak verdicts between calls."""
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: _Result(1, "", "RuntimeError: down"),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench, "_probe_verdict", {})
    bench.acquire_backend(budget_s=1.0, probe_timeout_s=0.5, max_attempts=1)
    assert bench._probe_verdict == {}


def test_emit_drops_non_finite_fields():
    """NaN/inf never reach the JSON line: dict fields are omitted (a
    strict parser must accept every line bench prints)."""
    scrubbed = bench.drop_non_finite(
        {
            "value": 1.5,
            "device_only_ms": float("nan"),
            "nested": {"ok": 2, "bad": float("inf")},
            "list": [1.0, float("nan")],
        }
    )
    assert scrubbed == {"value": 1.5, "nested": {"ok": 2}, "list": [1.0, None]}
    json.loads(json.dumps(scrubbed))  # round-trips as strict JSON


def test_smoke_mode_emits_delta_fields():
    """`bench.py --smoke` (the make bench-smoke target): delta tick must
    upload fewer bytes than the first full pack, and the JSON line must
    carry the staged/delta fields with no NaN anywhere."""
    r = _run_bench("--smoke", "--watchdog", "500")
    assert r.returncode == 0, r.stderr[-800:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ok"] is True
    assert out["delta_upload_bytes"] < out["first_full_pack_bytes"]
    assert out["chunks_solved"] >= 1
    assert "nan" not in r.stdout.lower()
