"""Pin the bench's device-only estimation protocol (bench/protocol.py).

The 1.03 ms/solve headline rests on (chain - rtt)/N math through a ~65 ms
tunnel; these tests freeze the chain length, the median arithmetic, the
zero clamp, and the chain program's actual iteration count so the
methodology cannot silently change meaning between rounds.
"""

import math
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from k8s_spot_rescheduler_tpu.bench.protocol import (
    N_CHAIN,
    device_only_ms,
    make_chained,
    protocol_record,
)


def test_chain_length_is_pinned():
    # the recorded device-only numbers are quotients by THIS constant;
    # changing it is a deliberate protocol break, not a refactor
    assert N_CHAIN == 50


def test_device_only_math():
    # chain median 80 ms over 50 solves above a 60 ms floor -> 0.4 ms
    chain = [0.081, 0.080, 0.079]
    rtt = [0.060, 0.061, 0.060]
    est = device_only_ms(chain, rtt, 50)
    assert abs(est - (0.080 - 0.060) / 50 * 1e3) < 1e-9


def test_device_only_uses_medians_not_means():
    chain = [0.080, 0.080, 10.0]  # one straggler must not move the estimate
    rtt = [0.060, 0.060, 5.0]
    assert abs(device_only_ms(chain, rtt, 50) - 0.4) < 1e-9


def test_device_only_clamps_negative_to_zero():
    # tunnel variance: chain measured under the floor -> 0, not negative
    assert device_only_ms([0.055], [0.060], 50) == 0.0


def test_device_only_degenerate_inputs_are_nan():
    assert math.isnan(device_only_ms([], [0.06], 50))
    assert math.isnan(device_only_ms([0.08], [], 50))
    assert math.isnan(device_only_ms([0.08], [0.06], 0))


class _P(NamedTuple):
    slot_req: jnp.ndarray


def test_chained_program_runs_n_dependent_solves():
    """The chained program must execute the solver exactly n times (its
    scalar result is n x one solve's reduction) with each iteration
    data-dependent on the last — the stub solver sums slot_req, so any
    dropped or collapsed iteration changes the total."""
    p = _P(slot_req=jnp.arange(6, dtype=jnp.float32).reshape(2, 3))
    fused = lambda q: q.slot_req  # noqa: E731 — reducible output, like the planner's

    for n in (1, 7):
        chained = make_chained(fused, n)
        got = float(np.asarray(chained(p)))
        assert got == n * float(np.asarray(p.slot_req.sum())), n


def test_protocol_record_carries_raw_inputs():
    rec = protocol_record([0.080], [0.060], 50)
    assert rec == {
        "chain_len": 50,
        "chain_ms": 80.0,
        "rtt_ms": 60.0,
        "device_only_ms": 0.4,
    }
