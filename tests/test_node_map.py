"""Node-map builder tests.

Mirrors reference nodes/nodes_test.go:58-298: classification, both node sort
orders, per-node pod sort, the spot-only priority filter, CPU accounting,
AddPod arithmetic, and copy isolation.
"""

from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeInfo,
    build_node_map,
    pods_requested,
)
from tests.fixtures import (
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)


def _build(nodes, pods_by_node, priority_threshold=0):
    return build_node_map(
        nodes,
        pods_by_node,
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
        priority_threshold=priority_threshold,
    )


def test_classification_and_sort_orders():
    # nodes/nodes_test.go:58-124: spot sorted most-requested first,
    # on-demand least-requested first, unlabeled nodes dropped.
    nodes = [
        make_node("od-busy", ON_DEMAND_LABELS),
        make_node("od-idle", ON_DEMAND_LABELS),
        make_node("spot-empty", SPOT_LABELS),
        make_node("spot-full", SPOT_LABELS),
        make_node("other", {"kubernetes.io/role": "master"}),
    ]
    pods = {
        "od-busy": [make_pod("a", 800, "od-busy"), make_pod("b", 400, "od-busy")],
        "od-idle": [make_pod("c", 100, "od-idle")],
        "spot-full": [make_pod("d", 1500, "spot-full")],
        "spot-empty": [make_pod("e", 200, "spot-empty")],
        "other": [make_pod("f", 999, "other")],
    }
    nm = _build(nodes, pods)
    assert [n.node.name for n in nm.on_demand] == ["od-idle", "od-busy"]
    assert [n.node.name for n in nm.spot] == ["spot-full", "spot-empty"]
    assert nm.on_demand[1].requested_cpu == 1200
    assert nm.on_demand[1].free_cpu == 800


def test_pods_sorted_biggest_cpu_first():
    # nodes/nodes.go:76-80
    nodes = [make_node("od", ON_DEMAND_LABELS)]
    pods = {"od": [make_pod("small", 100), make_pod("big", 900), make_pod("mid", 400)]}
    nm = _build(nodes, pods)
    assert [p.name for p in nm.on_demand[0].pods] == ["big", "mid", "small"]


def test_priority_filter_spot_only():
    # nodes/nodes_test.go:144-218: low-priority pods dropped on spot nodes,
    # kept on on-demand nodes.
    nodes = [make_node("spot", SPOT_LABELS), make_node("od", ON_DEMAND_LABELS)]
    mixed = lambda node: [
        make_pod("p1", 100, node),
        make_pod("p2", 100, node, priority=-1),
        make_pod("p3", 100, node, priority=5),
    ]
    nm = _build(nodes, {"spot": mixed("spot"), "od": mixed("od")}, priority_threshold=0)
    assert len(nm.spot[0].pods) == 2  # p2 dropped
    assert len(nm.on_demand[0].pods) == 3
    assert nm.spot[0].requested_cpu == 200
    assert nm.on_demand[0].requested_cpu == 300


def test_node_with_both_labels_is_spot():
    # switch precedence nodes/nodes.go:82-92
    both = dict(SPOT_LABELS)
    nm = _build([make_node("n", both)], {})
    assert len(nm.spot) == 1 and not nm.on_demand


def test_add_pod_updates_accounting():
    # nodes/nodes_test.go:126-142
    info = NodeInfo.build(make_node("n", SPOT_LABELS), [make_pod("a", 300)])
    info.add_pod(make_pod("b", 500))
    assert info.requested_cpu == 800
    assert info.free_cpu == 2000 - 800
    assert len(info.pods) == 2


def test_copy_isolation():
    # nodes/nodes_test.go:256-298 CopyNodeInfos
    info = NodeInfo.build(make_node("n", SPOT_LABELS), [make_pod("a", 300)])
    clone = info.copy()
    clone.add_pod(make_pod("b", 500))
    assert info.requested_cpu == 300
    assert len(info.pods) == 1


def test_cpu_aggregation():
    # nodes/nodes_test.go:220-254
    pods = [make_pod("a", 150), make_pod("b", 250), make_pod("c", 0)]
    assert pods_requested(pods) == 400
