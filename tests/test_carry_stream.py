"""Narrow-int delta carries + spot-streamed greedy kernels (ROADMAP 5).

The carry-streamed tier's whole claim is BIT-identity: the delta-form
narrow carry (solver/carry.CarryLayout) widened on read must reproduce
the wide kernels' every placement, and the spot-streamed first-fit's
leftover flow must reproduce global probe order across any chunk
boundary. These tests pin that claim against the numpy oracles and the
existing fused planner at multiple chunk counts, drive the dtype
saturation edges the layout guard promises (residual exactly at the
int8/int16/uint16 edge — and one past it, where the guard must widen),
and prove the dispatch ladder lands on the carry tier with repair LIVE.
"""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.solver.carry import (
    CarryLayout,
    NARROW_LAYOUT,
    WIDE_LAYOUT,
    carry_layout,
    is_narrow,
    plane_bytes,
)
from k8s_spot_rescheduler_tpu.solver.fallback import (
    with_repair,
    with_repair_streamed,
)
from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd, plan_ffd_streamed
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.solver.repair import (
    plan_repair_chunked,
    plan_repair_oracle,
)
from tests.test_solver import _random_packed

CHUNK_COUNTS = (2, 3, 5)  # >= 3 distinct counts, incl. a non-divisor


def _assert_same(got, want, note=""):
    np.testing.assert_array_equal(
        np.asarray(got.feasible), np.asarray(want.feasible), err_msg=note
    )
    np.testing.assert_array_equal(
        np.asarray(got.assignment), np.asarray(want.assignment), err_msg=note
    )


# --- randomized bit parity --------------------------------------------------

@pytest.mark.parametrize("seed", range(8))
def test_streamed_first_fit_parity(seed):
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    layout = carry_layout(packed)
    for n in CHUNK_COUNTS:
        for lay in (WIDE_LAYOUT, layout):
            got = plan_ffd_streamed(packed, carry_chunks=n, layout=lay)
            _assert_same(got, want, f"seed={seed} chunks={n} layout={lay}")


@pytest.mark.parametrize("seed", range(8))
def test_streamed_best_fit_parity(seed):
    packed = _random_packed(np.random.default_rng(100 + seed))
    want = plan_oracle(packed, best_fit=True)
    layout = carry_layout(packed)
    for n in CHUNK_COUNTS:
        got = plan_ffd_streamed(
            packed, carry_chunks=n, layout=layout, best_fit=True
        )
        _assert_same(got, want, f"seed={seed} chunks={n}")


@pytest.mark.parametrize("seed", range(6))
def test_streamed_union_parity_vs_fused_and_oracle(seed):
    """The whole carry-streamed union (ff ∪ bf ∪ chunked repair on the
    narrow delta carry) against BOTH the existing fused planner's union
    and the host oracle stack — the acceptance bit-identity."""
    packed = _random_packed(np.random.default_rng(200 + seed))
    layout = carry_layout(packed)
    fused = with_repair(plan_ffd, 8)(packed)
    ff = plan_oracle(packed)
    bf = plan_oracle(packed, best_fit=True)
    rp = plan_repair_oracle(packed, rounds=8)
    feasible = ff.feasible | bf.feasible | rp.feasible
    assignment = np.where(
        ff.feasible[:, None],
        ff.assignment,
        np.where(bf.feasible[:, None], bf.assignment, rp.assignment),
    )
    np.testing.assert_array_equal(np.asarray(fused.feasible), feasible)
    for n in CHUNK_COUNTS:
        got = with_repair_streamed(8, n, layout)(packed)
        _assert_same(got, fused, f"seed={seed} chunks={n} (vs fused)")
        np.testing.assert_array_equal(np.asarray(got.feasible), feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), assignment)


@pytest.mark.parametrize("seed", range(4))
def test_streamed_chunked_repair_parity(seed):
    packed = _random_packed(np.random.default_rng(300 + seed))
    layout = carry_layout(packed)
    want = plan_repair_oracle(packed, rounds=6)
    for n in CHUNK_COUNTS:
        got = plan_repair_chunked(
            packed, rounds=6, spot_chunks=n, layout=layout
        )
        _assert_same(got, want, f"seed={seed} chunks={n}")


# --- chunk-boundary leftover flow -------------------------------------------

def _leftover_case() -> PackedCluster:
    """Pod 0 fits nothing in chunk 0 and places in chunk 1 while pod 1
    places in chunk 0 AFTER pod 0 already failed it — the leftover
    interleave a wrong streaming order would scramble. 4 spot nodes so
    every CHUNK_COUNTS split puts a boundary inside the probe order:
    node0 tiny, node1 tiny, node2 big, node3 big."""
    C, K, S, R, W, A = 1, 3, 4, 1, 1, 1
    return PackedCluster(
        slot_req=np.array([[[500.0], [100.0], [400.0]]], np.float32),
        slot_valid=np.ones((C, K), bool),
        slot_tol=np.zeros((C, K, W), np.uint32),
        slot_aff=np.zeros((C, K, A), np.uint32),
        cand_valid=np.ones((C,), bool),
        spot_free=np.array(
            [[150.0], [120.0], [900.0], [450.0]], np.float32
        ),
        spot_count=np.zeros((S,), np.int32),
        spot_max_pods=np.full((S,), 8, np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.ones((S,), bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )


def test_leftover_flows_across_chunk_boundary():
    """Global first-fit: pod0(500)→node2, pod1(100)→node0, pod2(400)→
    node2 again (depleted to exactly 400 by pod0 — the saturating fit).
    Every chunking must agree — pod0 and pod2 are chunk-0 leftovers
    that must see chunk 1 in POD order (pod2 after pod0's depletion),
    while pod1 back-fills chunk 0 in between."""
    packed = _leftover_case()
    want = plan_oracle(packed)
    assert bool(want.feasible[0])
    assert list(want.assignment[0]) == [2, 0, 2]
    for n in (2, 3, 4):
        for lay in (WIDE_LAYOUT, NARROW_LAYOUT):
            got = plan_ffd_streamed(packed, carry_chunks=n, layout=lay)
            _assert_same(got, want, f"chunks={n} layout={lay}")


# --- dtype saturation edges --------------------------------------------------

def _edge_pack(req_each: float, k: int, free: float) -> PackedCluster:
    """One lane, ``k`` identical pods of ``req_each`` against one open
    node of ``free`` capacity (plus a decoy the taints forbid)."""
    C, K, S, R, W, A = 1, k, 2, 1, 1, 1
    return PackedCluster(
        slot_req=np.full((C, K, R), req_each, np.float32),
        slot_valid=np.ones((C, K), bool),
        slot_tol=np.zeros((C, K, W), np.uint32),
        slot_aff=np.zeros((C, K, A), np.uint32),
        cand_valid=np.ones((C,), bool),
        spot_free=np.array([[free], [free]], np.float32),
        spot_count=np.zeros((S,), np.int32),
        spot_max_pods=np.full((S,), k + 1, np.int32),
        spot_taints=np.array([[0], [1]], np.uint32),  # decoy: untolerated
        spot_ok=np.ones((S,), bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )


def test_layout_guard_used_edges():
    """Consumed-sum bounds exactly AT each dtype edge narrow; one past
    widens. The edge packs then solve bit-identically on the narrow
    layout — the saturating residual is representable by construction."""
    at_i16 = _edge_pack(32767.0 / 7, 7, 40000.0)
    at_i16 = at_i16._replace(
        slot_req=np.full((1, 7, 1), 4681.0, np.float32)  # 7*4681 = 32767
    )
    assert carry_layout(at_i16).used == "int16"
    past_i16 = at_i16._replace(
        slot_req=np.full((1, 7, 1), 4682.0, np.float32)  # 32774 > int16
    )
    assert carry_layout(past_i16).used == "uint16"
    at_u16 = _edge_pack(13107.0, 5, 70000.0)  # 5*13107 = 65535 == edge
    assert carry_layout(at_u16).used == "uint16"
    past_u16 = _edge_pack(13108.0, 5, 70000.0)  # 65540 > uint16
    assert carry_layout(past_u16).used == "float32"
    for packed in (at_i16, past_i16, at_u16, past_u16):
        lay = carry_layout(packed)
        want = plan_oracle(packed)
        assert bool(want.feasible[0])  # the full residual is consumed
        for n in (1, 2):
            got = plan_ffd_streamed(packed, carry_chunks=n, layout=lay)
            _assert_same(got, want, f"layout={lay} chunks={n}")
        got = with_repair_streamed(4, 2, lay)(packed)
        _assert_same(got, want, f"union layout={lay}")


def test_layout_guard_count_and_aff_edges():
    small = _random_packed(np.random.default_rng(0))
    # count: K <= 127 -> int8; past -> int16
    k127 = small._replace(
        slot_req=np.zeros((1, 127, 1), np.float32),
        slot_valid=np.ones((1, 127), bool),
        slot_tol=np.zeros((1, 127, 1), np.uint32),
        slot_aff=np.zeros((1, 127, 1), np.uint32),
        cand_valid=np.ones((1,), bool),
    )
    assert carry_layout(k127).count == "int8"
    k128 = k127._replace(
        slot_req=np.zeros((1, 128, 1), np.float32),
        slot_valid=np.ones((1, 128), bool),
        slot_tol=np.zeros((1, 128, 1), np.uint32),
        slot_aff=np.zeros((1, 128, 1), np.uint32),
    )
    assert carry_layout(k128).count == "int16"
    # aff: highest interned dynamic bit decides the word width
    def with_bit(bit):
        aff = np.zeros((1, 2, 1), np.uint32)
        aff[0, 0, 0] = np.uint32(1) << bit
        return k127._replace(
            slot_req=np.zeros((1, 2, 1), np.float32),
            slot_valid=np.ones((1, 2), bool),
            slot_tol=np.zeros((1, 2, 1), np.uint32),
            slot_aff=aff,
        )
    assert carry_layout(with_bit(7)).aff == "uint8"
    assert carry_layout(with_bit(15)).aff == "uint16"  # exactly the edge
    assert carry_layout(with_bit(16)).aff == "uint32"  # one past widens


def test_affinity_edge_bit_parity():
    """A pod whose interned affinity bit sits exactly at the uint16
    edge (bit 15) must conflict identically through the narrow carry —
    the second group member is rejected on the node the first took."""
    C, K, S, R, W, A = 1, 2, 2, 1, 1, 1
    bit15 = np.uint32(1) << 15
    packed = PackedCluster(
        slot_req=np.full((C, K, R), 10.0, np.float32),
        slot_valid=np.ones((C, K), bool),
        slot_tol=np.zeros((C, K, W), np.uint32),
        slot_aff=np.full((C, K, A), bit15, np.uint32),  # anti-affine pair
        cand_valid=np.ones((C,), bool),
        spot_free=np.full((S, R), 100.0, np.float32),
        spot_count=np.zeros((S,), np.int32),
        spot_max_pods=np.full((S,), 8, np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.ones((S,), bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )
    lay = carry_layout(packed)
    assert lay.aff == "uint16"
    want = plan_oracle(packed)
    assert list(want.assignment[0]) == [0, 1]  # split across the nodes
    for n in (1, 2):
        got = plan_ffd_streamed(packed, carry_chunks=n, layout=lay)
        _assert_same(got, want, f"chunks={n}")


# --- layout plumbing ---------------------------------------------------------

def test_plane_bytes_and_narrow_flag():
    assert plane_bytes(WIDE_LAYOUT, 4, 2) == 4 * (4 + 2 + 1)  # 28: history
    assert plane_bytes(NARROW_LAYOUT, 4, 2) == 2 * 4 + 1 + 2 * 2
    assert not is_narrow(WIDE_LAYOUT)
    assert is_narrow(NARROW_LAYOUT)
    assert is_narrow(CarryLayout(used="float32", count="int8", aff="uint8"))


# --- memory sizing + dispatch ladder -----------------------------------------

def test_pick_carry_chunks_ladder():
    from k8s_spot_rescheduler_tpu.solver import memory

    npb = plane_bytes(NARROW_LAYOUT, 4, 2)
    shapes = (6400, 32, 51200, 4, 2, 2)
    fits_plain = memory.estimate_union_hbm_bytes(
        *shapes, repair_spot_chunks=1, carry_chunks=1, carry_plane_bytes=npb
    )
    # generous budget: no streaming needed
    assert memory.pick_carry_chunks(
        *shapes, fits_plain + 1, carry_plane_bytes=npb
    ) == 1
    # the v5e default: streaming must engage with a power-of-two count
    budget = int(memory.DEFAULT_HBM_BYTES * memory.BUDGET_FRACTION)
    n = memory.pick_carry_chunks(*shapes, budget, carry_plane_bytes=npb)
    assert n > 1 and (n & (n - 1)) == 0
    est = memory.estimate_union_hbm_bytes(
        *shapes, repair_spot_chunks=n, carry_chunks=n, carry_plane_bytes=npb
    )
    assert est <= budget
    # below even the stacked narrow carries: the 2-D regime
    carries = memory.estimate_union_hbm_breakdown(
        *shapes, carry_chunks=1, carry_plane_bytes=npb
    )["carries"]
    assert memory.pick_carry_chunks(
        *shapes, carries - 1, carry_plane_bytes=npb
    ) == 0


def test_pick_tier_20x_keeps_repair_live():
    """THE acceptance pin: at the 20x shapes (1M pods / 100k nodes,
    hot_programs.MAX_SHAPES) over an 8-device v5e fleet, the ladder
    must land on the carry-streamed tier with repair live — for the
    fully narrow layout AND the conservative guarded layout of the
    4-resource synthetic config (f32 used, int8 count, uint8 aff) —
    while 16x still fits the WIDE chunked tier (the documented old
    ceiling stays history, not current behavior)."""
    from k8s_spot_rescheduler_tpu.hot_programs import MAX_SHAPES
    from k8s_spot_rescheduler_tpu.solver import memory

    budget = int(memory.DEFAULT_HBM_BYTES * memory.BUDGET_FRACTION)
    s = MAX_SHAPES
    guarded = CarryLayout(used="float32", count="int8", aff="uint8")
    for layout in (NARROW_LAYOUT, guarded):
        tier = memory.pick_tier(
            s.C, s.K, s.S, s.R, s.W, s.A,
            n_devices=8, budget_bytes=budget, wants_repair=True,
            carry_plane_bytes=plane_bytes(layout, s.R, s.A),
        )
        assert tier.kind == "cand-carry", (layout, tier)
        assert not tier.repair_unavailable
        assert tier.repair_chunks > 0 and tier.carry_chunks > 1
        assert tier.est_bytes <= budget
    # 16x: the wide chunked tier still carries it (the old ceiling)
    n16 = 2560 * 16
    tier16 = memory.pick_tier(
        n16, 32, n16, 4, 2, 2,
        n_devices=8, budget_bytes=budget, wants_repair=True,
        carry_plane_bytes=plane_bytes(NARROW_LAYOUT, 4, 2),
    )
    assert tier16.kind == "cand-chunked" and tier16.repair_chunks > 1


def test_planner_dispatches_carry_tier_with_repair_live():
    """End to end on the 8-virtual-device platform: a budget below the
    wide tiers but above the carry tier must land on
    ``jax+cand-carry`` with the SAME drain the host oracle stack
    proves, repair_unavailable 0, and the report/gauges/healthz naming
    the tier."""
    from k8s_spot_rescheduler_tpu.loop import health
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.solver import memory
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
    from tests.test_sharding import _repair_demanding_fake

    node_map = _repair_demanding_fake()
    want = SolverPlanner(ReschedulerConfig(solver="numpy")).plan(node_map, [])
    assert want.plan is not None  # only repair proves this drain

    packed, _ = pack_cluster(node_map, [], resources=("cpu", "memory"))
    C, K, S, R, W, A = memory.packed_shapes(packed)
    pb = plane_bytes(carry_layout(packed), R, A)
    carry_est = memory.estimate_union_hbm_bytes(
        -(-C // 8), K, S, R, W, A,
        repair_spot_chunks=1, carry_chunks=1, carry_plane_bytes=pb,
    )
    cfg = ReschedulerConfig(
        solver="jax", solver_hbm_budget=int(carry_est) + 1, carry_chunks=2
    )
    planner = SolverPlanner(cfg)
    report = planner.plan(node_map, [])
    assert report.solver == "jax+cand-carry"
    assert report.carry_chunks == 2
    assert report.repair_chunks == 2  # repair LIVE, spot-chunked
    assert report.plan is not None
    assert report.plan.node.node.name == want.plan.node.node.name
    assert report.plan.assignments == want.plan.assignments
    assert (
        metrics.repair_unavailable.collect()[0].samples[0].value == 0.0
    )
    assert (
        metrics.solver_carry_chunks.collect()[0].samples[0].value == 2.0
    )
    assert metrics.solver_carry_bytes.collect()[0].samples[0].value > 0
    snap = health.snapshot()
    assert snap["solver_mode"] == "jax+cand-carry"
    assert snap["carry_chunks"] == 2
    assert snap["solver_carry_bytes"] > 0


def test_streamed_union_repairs_greedy_failure():
    """A drain only repair can prove survives the carry-streamed union
    bit-identically (the repair phase genuinely runs on the narrow
    carry, not just the greedy passes)."""
    from tests.test_repair_chunked import _swap_case

    packed = _swap_case()
    assert not plan_oracle(packed).feasible[0]
    want = plan_repair_oracle(packed, rounds=8)
    assert bool(want.feasible[0])
    for n in (2, 3):
        got = with_repair_streamed(8, n, carry_layout(packed))(packed)
        _assert_same(got, want, f"chunks={n}")
