"""Zone-topology anti-affinity via zone-salted affinity-group bits.

Required podAntiAffinity with topologyKey=topology.kubernetes.io/zone
previously collapsed to the unplaceable bit. It is now modeled
statically per tick: a spot node's affinity word ORs in the zone-family
masks of every counted pod in its entire zone (any node class), giving
both scheduler directions — a requirer refuses zones hosting a match,
and a matched pod refuses zones hosting a requirer. The one case static
bits cannot prove safe — two zone-involved pods inside one candidate
lane — is conservatively killed by the shared lane guard
(masks.zone_lane_guard).
"""

import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import decode_pod
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.predicates.masks import ZONE_LABEL
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    own_terms,
    pack_fake,
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)


def _zone_labels(base, zone):
    return dict(base, **{ZONE_LABEL: zone})


# --- decode ----------------------------------------------------------------

def _pod_obj(anti_term):
    return {
        "metadata": {"name": "p", "namespace": "ns1"},
        "spec": {"nodeName": "n1", "containers": [],
                 "affinity": {"podAntiAffinity": {
                     "requiredDuringSchedulingIgnoredDuringExecution":
                         anti_term}}},
        "status": {"phase": "Running"},
    }


def test_decode_zone_topology_modeled():
    pod = decode_pod(_pod_obj([{
        "topologyKey": "topology.kubernetes.io/zone",
        "labelSelector": {"matchLabels": {"app": "db"}},
    }]))
    assert pod.anti_affinity_zone_match == own_terms({"app": "db"}, "ns1")
    assert pod.anti_affinity_match == ()
    assert not pod.unmodeled_constraints


def test_decode_legacy_zone_key_unmodeled():
    pod = decode_pod(_pod_obj([{
        "topologyKey": "failure-domain.beta.kubernetes.io/zone",
        "labelSelector": {"matchLabels": {"app": "db"}},
    }]))
    assert pod.anti_affinity_zone_match == ()
    assert pod.unmodeled_constraints


def test_decode_hostname_still_hostname():
    pod = decode_pod(_pod_obj([{
        "topologyKey": "kubernetes.io/hostname",
        "labelSelector": {"matchLabels": {"app": "db"}},
    }]))
    assert pod.anti_affinity_match == own_terms({"app": "db"}, "ns1")
    assert pod.anti_affinity_zone_match == ()
    assert not pod.unmodeled_constraints


# --- oracle / packer -------------------------------------------------------

def _cluster():
    """Zone A: spot-a1 (hosts app=db), spot-a2. Zone B: spot-b1. One
    zoneless spot node."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-a2", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_node(make_node("spot-nz", SPOT_LABELS))
    fc.add_pod(make_pod("db-0", 100, "spot-a1", labels={"app": "db"}))
    return fc


def _pack(fc):
    return pack_fake(fc)


def _placement(fc, pod_name):
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    for c, pods in enumerate(meta.cand_pods):
        for k, p in enumerate(pods):
            if p.name == pod_name:
                if not result.feasible[c]:
                    return None
                return meta.spot[int(result.assignment[c, k])].node.name
    raise AssertionError(f"{pod_name} not in any lane")


def test_requirer_avoids_zone_hosting_match():
    fc = _cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    target = _placement(fc, "web")
    # zone a hosts app=db -> both zone-a nodes repel; b or zoneless ok
    assert target in ("spot-b1", "spot-nz")


def test_matcher_avoids_zone_hosting_requirer():
    """Symmetric direction: a resident requirer in zone a repels matched
    pods from the WHOLE zone, even from a different node."""
    fc = _cluster()
    fc.add_pod(make_pod("guard", 100, "spot-a2",
                        anti_affinity_zone_match={"tier": "cache"}))
    fc.add_pod(make_pod("cache", 300, "od-1", labels={"tier": "cache"}))
    target = _placement(fc, "cache")
    assert target in ("spot-b1", "spot-nz")


def test_requirer_blocked_when_every_zone_hosts_match():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-a", 100, "spot-a1", labels={"app": "db"}))
    fc.add_pod(make_pod("db-b", 100, "spot-b1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    packed, _ = _pack(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_zoneless_nodes_never_conflict():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-nz1", SPOT_LABELS))
    fc.add_pod(make_pod("db-0", 100, "spot-nz1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    # k8s: a node without the topology key cannot match the term
    assert _placement(fc, "web") == "spot-nz1"


def test_match_on_od_node_repels_same_zone_spot():
    """Zone presence reaches across node classes: a match resident on an
    ON-DEMAND node in zone a repels the requirer from zone-a SPOT
    nodes."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", _zone_labels(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "od-2", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    assert _placement(fc, "web") == "spot-b1"


def test_match_on_unclassified_node_repels_same_zone_spot():
    """Regression (advisor r3, medium): zone presence must span pods on
    UNCLASSIFIED ready nodes — a match resident on e.g. a control-plane
    node in zone a still repels the requirer from every zone-a node in
    the real scheduler. Before the fix this drain planned into zone a
    and the pod stranded."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("cp-1", _zone_labels({}, "a")))  # neither label
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "cp-1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    assert _placement(fc, "web") == "spot-b1"
    _parity(fc)


def test_requirer_on_unclassified_node_repels_matches():
    """Symmetric direction: a REQUIRER on an unclassified zone-a node
    repels matched pods zone-wide — its selector must reach the zone
    universe even though the pod is on no listed node class."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("cp-1", _zone_labels({}, "a")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("guard", 100, "cp-1",
                        anti_affinity_zone_match={"tier": "cache"}))
    fc.add_pod(make_pod("cache", 300, "od-1", labels={"tier": "cache"}))
    assert _placement(fc, "cache") == "spot-b1"
    _parity(fc)


def test_unready_node_presence_visible_both_paths():
    """An UNREADY node's pods are presence-visible (round-4 widening:
    zone conflicts and spread counts still exist to the real scheduler
    on not-ready nodes — NodeMap.unready / columnar presence_extra):
    the zone-a match on the unready node repels the requirer from zone
    a on BOTH paths, bit-identically."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    cp = make_node("cp-1", _zone_labels({}, "a"))
    cp.ready = False
    fc.add_node(cp)
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "cp-1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    assert _placement(fc, "web") == "spot-b1"
    _parity(fc)


def test_unready_spot_node_is_presence_not_capacity():
    """A not-ready SPOT node never joins the placement pool, but its
    resident zone conflicts stay visible."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    dead = make_node("spot-a1", _zone_labels(SPOT_LABELS, "a"))
    dead.ready = False
    fc.add_node(dead)
    fc.add_node(make_node("spot-a2", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "spot-a1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    # the match on the dead zone-a node repels web from ALL of zone a
    assert _placement(fc, "web") == "spot-b1"
    _parity(fc)


def test_lane_guard_two_requirers():
    """Two pods carrying the same zone identity in one lane: static bits
    cannot prove the in-plan interaction safe -> lane conservatively
    infeasible even though two clean zones exist."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("w1", 300, "od-1", labels={"app": "web"},
                        anti_affinity_zone_match={"app": "web"}))
    fc.add_pod(make_pod("w2", 300, "od-1", labels={"app": "web"},
                        anti_affinity_zone_match={"app": "web"}))
    packed, _ = _pack(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_lane_guard_requirer_plus_matcher():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("req", 200, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    fc.add_pod(make_pod("match", 200, "od-1", labels={"app": "db"}))
    packed, _ = _pack(fc)
    assert not plan_oracle(packed).feasible[:1].any()


def test_single_requirer_with_plain_peers_still_drains():
    fc = _cluster()
    fc.add_pod(make_pod("web", 200, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    fc.add_pod(make_pod("plain", 200, "od-1"))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    pods = meta.cand_pods[0]
    k = next(i for i, p in enumerate(pods) if p.name == "web")
    assert meta.spot[int(result.assignment[0, k])].node.name in (
        "spot-b1", "spot-nz"
    )


# --- columnar parity -------------------------------------------------------

def _parity(fc):
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
    return store


def test_columnar_parity_zone_bits():
    fc = _cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    _parity(fc)


def test_columnar_parity_cross_class_zone_presence():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", _zone_labels(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "od-2", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    _parity(fc)


def test_columnar_parity_lane_guard():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("w1", 300, "od-1", labels={"app": "web"},
                        anti_affinity_zone_match={"app": "web"}))
    fc.add_pod(make_pod("w2", 300, "od-1", labels={"app": "web"},
                        anti_affinity_zone_match={"app": "web"}))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    _parity(fc)


def test_columnar_parity_tracks_zone_match_departure():
    fc = _cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    store = _parity(fc)
    # the zone-a match leaves: zone a opens up next tick
    fc.evict_pod(fc.pods["default/db-0"], 0)
    fc.clock.advance(5.0)
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def test_two_term_pair_enforces_both_families():
    """Round-4 widened decode: one pod carrying the hostname+zone
    anti-affinity PAIR (two required terms) enforces both — it refuses
    the zone hosting a match AND any node hosting one."""
    pod = decode_pod({
        "metadata": {"name": "p", "namespace": "default"},
        "spec": {"nodeName": "od-1", "containers": [], "affinity": {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "labelSelector": {"matchLabels": {"app": "db"}}},
                    {"topologyKey": ZONE_LABEL,
                     "labelSelector": {"matchLabels": {"app": "db"}}},
                ]}}},
        "status": {"phase": "Running"},
    })
    assert pod.anti_affinity_match == own_terms({"app": "db"})
    assert pod.anti_affinity_zone_match == own_terms({"app": "db"})
    assert not pod.unmodeled_constraints

    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_node(make_node("spot-nz", SPOT_LABELS))  # zoneless, hosts match
    fc.add_pod(make_pod("db-a", 100, "spot-a1", labels={"app": "db"}))
    fc.add_pod(make_pod("db-nz", 100, "spot-nz", labels={"app": "db"}))
    fc.add_pod(make_pod(
        "web", 300, "od-1",
        anti_affinity_match={"app": "db"},
        anti_affinity_zone_match={"app": "db"},
    ))
    # zone a refused by the zone term; spot-nz refused by the hostname
    # term (hosts a match); only spot-b1 admits
    assert _placement(fc, "web") == "spot-b1"
    _parity(fc)


# --- end to end ------------------------------------------------------------

def test_drain_respects_zone_spread():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zone_labels(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "spot-a1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    assert fc.pods["default/web"].node_name == "spot-b1"


def test_fake_scheduler_enforces_zone_anti_affinity():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a1", _zone_labels(SPOT_LABELS, "a")))
    fc.add_pod(make_pod("db-0", 100, "spot-a1", labels={"app": "db"}))
    pod = make_pod("web", 300, "od-1", anti_affinity_zone_match={"app": "db"})
    fc.add_pod(pod)
    fc.evict_pod(pod, 0)
    fc.clock.advance(5.0)
    assert "default/web" not in fc.pods
    assert any(p.name == "web" for p in fc.pending)


def test_zoneless_node_with_residents_never_acquires_zone_bits():
    """Regression (review finding): a resident's POD-side mask includes
    zone-family bits, but its contribution to its own node must be
    hostname-family only — else a zoneless node hosting a match would
    repel the requirer, diverging from the scheduler (and from the
    columnar/object parity contract). The hostname universe is forced
    non-empty to exercise the object packer's accumulation branch."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-nz1", SPOT_LABELS))
    fc.add_pod(make_pod("db-0", 100, "spot-nz1", labels={"app": "db"}))
    # unrelated hostname-anti pod makes match_universe non-empty
    fc.add_pod(make_pod("spread", 50, "od-1",
                        anti_affinity_match={"tier": "x"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        anti_affinity_zone_match={"app": "db"}))
    assert _placement(fc, "web") == "spot-nz1"
    _parity(fc)
