"""Required POSITIVE pod-affinity as inverted pseudo-taint bits.

The reference gets inter-pod affinity free from the real scheduler's
predicate (reference rescheduler.go:344; predicate list
README.md:103-114); previously any required podAffinity collapsed to the
conservative unplaceable bit, silently pinning such pods' nodes at
zero drains. The modeled shape (one required term, hostname topology,
matchLabels, own namespace — mirroring the anti-affinity canonical form)
now interns as ``PodAffinityBit``: set on every spot node NOT currently
hosting a match, untolerated only by the requiring pod. Conservative
dynamics: only pre-plan residents count as matches.
"""

import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import decode_pod
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    own_terms,
    pack_fake,
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)


# --- decode ----------------------------------------------------------------

def _pod_obj(affinity):
    return {
        "metadata": {"name": "p", "namespace": "ns1"},
        "spec": {"nodeName": "n1", "containers": [], "affinity": affinity},
        "status": {"phase": "Running"},
    }


def _paff(term):
    return {"podAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": term}}


def test_decode_modeled_pod_affinity():
    pod = decode_pod(_pod_obj(_paff([{
        "topologyKey": "kubernetes.io/hostname",
        "labelSelector": {"matchLabels": {"app": "db"}},
    }])))
    assert pod.pod_affinity_match == own_terms({"app": "db"}, "ns1")
    assert not pod.unmodeled_constraints


def test_decode_widened_selector_shapes_modeled():
    """Round 5: the full LabelSelector operator surface, explicit
    namespaces lists (cross-namespace included), and multiple required
    terms are all modeled as canonical terms."""
    # pure matchExpressions selector
    pod = decode_pod(_pod_obj(_paff([{
        "topologyKey": "kubernetes.io/hostname",
        "labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "In", "values": ["db"]}]}}])))
    assert pod.pod_affinity_match == own_terms({"app": "db"}, "ns1")
    assert not pod.unmodeled_constraints
    # mixed matchLabels + expressions
    pod = decode_pod(_pod_obj(_paff([{
        "topologyKey": "kubernetes.io/hostname",
        "labelSelector": {
            "matchLabels": {"tier": "be"},
            "matchExpressions": [
                {"key": "app", "operator": "In", "values": ["db"]}]}}])))
    assert pod.pod_affinity_match == (
        (("ns1",), (("app", "In", ("db",)), ("tier", "In", ("be",)))),
    )
    assert not pod.unmodeled_constraints
    # own-namespace namespaces list (the pod's ns is ns1 in _pod_obj)
    pod = decode_pod(_pod_obj(_paff([{
        "topologyKey": "kubernetes.io/hostname",
        "namespaces": ["ns1"],
        "labelSelector": {"matchLabels": {"app": "db"}}}])))
    assert pod.pod_affinity_match == own_terms({"app": "db"}, "ns1")
    assert not pod.unmodeled_constraints
    # round 5: operators beyond In, multi-value In, cross-namespace
    # scopes, multiple required terms
    pod = decode_pod(_pod_obj(_paff([
        {"topologyKey": "kubernetes.io/hostname",
         "labelSelector": {"matchExpressions": [
             {"key": "app", "operator": "In", "values": ["db", "cache"]},
             {"key": "v", "operator": "NotIn", "values": ["old"]}]}},
        {"topologyKey": "kubernetes.io/hostname",
         "namespaces": ["other", "ns1"],
         "labelSelector": {"matchExpressions": [
             {"key": "tier", "operator": "Exists"},
             {"key": "legacy", "operator": "DoesNotExist"}]}},
    ])))
    assert pod.pod_affinity_match == (
        (("ns1",), (("app", "In", ("cache", "db")),
                    ("v", "NotIn", ("old",)))),
        (("ns1", "other"), (("legacy", "DoesNotExist", ()),
                            ("tier", "Exists", ()))),
    )
    assert not pod.unmodeled_constraints


def test_decode_zone_topology_pod_affinity_modeled():
    """Round 4: required positive pod-affinity with ZONE topology is
    modeled (ZonePodAffinityBit) — the pod may only join a zone already
    hosting a match."""
    pod = decode_pod(_pod_obj(_paff([{
        "topologyKey": "topology.kubernetes.io/zone",
        "labelSelector": {"matchLabels": {"app": "db"}}}])))
    assert pod.pod_affinity_zone_match == own_terms({"app": "db"}, "ns1")
    assert pod.pod_affinity_match == ()
    assert not pod.unmodeled_constraints


def test_decode_unmodeled_pod_affinity_shapes():
    for term in (
        # other topology keys
        [{"topologyKey": "example.com/rack",
          "labelSelector": {"matchLabels": {"app": "db"}}}],
        # namespaceSelector matching namespace LABELS (unobserved)
        [{"topologyKey": "kubernetes.io/hostname",
          "namespaceSelector": {"matchLabels": {"team": "x"}},
          "labelSelector": {"matchLabels": {"app": "db"}}}],
        # malformed: Exists carrying values (k8s validation rejects)
        [{"topologyKey": "kubernetes.io/hostname",
          "labelSelector": {"matchExpressions": [
              {"key": "app", "operator": "Exists", "values": ["x"]}]}}],
        # malformed: In with no values
        [{"topologyKey": "kubernetes.io/hostname",
          "labelSelector": {"matchExpressions": [
              {"key": "app", "operator": "In", "values": []}]}}],
        # unknown operator
        [{"topologyKey": "kubernetes.io/hostname",
          "labelSelector": {"matchExpressions": [
              {"key": "app", "operator": "Gt", "values": ["1"]}]}}],
    ):
        pod = decode_pod(_pod_obj(_paff(term)))
        assert pod.pod_affinity_match == ()
        assert pod.unmodeled_constraints, term


def test_decode_never_matching_positive_term_kept_exactly():
    """Round 5: a positive term whose selector can never match any pod
    is KEPT (not unmodeled) — no node can ever host a match, so the
    carrier is exactly unplaceable through the affinity machinery."""
    pod = decode_pod(_pod_obj(_paff([{
        "topologyKey": "kubernetes.io/hostname",
        "labelSelector": {
            "matchLabels": {"app": "db"},
            "matchExpressions": [
                {"key": "app", "operator": "In", "values": ["web"]}]}}])))
    assert pod.pod_affinity_match == (
        (("ns1",), (("app", "In", ("db",)), ("app", "In", ("web",)))),
    )
    assert not pod.unmodeled_constraints


def test_decode_preferred_only_is_unconstrained():
    pod = decode_pod(_pod_obj({"podAffinity": {
        "preferredDuringSchedulingIgnoredDuringExecution": [{"weight": 1}]}}))
    assert pod.pod_affinity_match == ()
    assert not pod.unmodeled_constraints


# --- oracle / packer -------------------------------------------------------

def _cluster(*, match_on="spot-with-db"):
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-plain", SPOT_LABELS))
    fc.add_node(make_node("spot-with-db", SPOT_LABELS))
    if match_on:
        fc.add_pod(make_pod("db-0", 100, match_on, labels={"app": "db"}))
    return fc


def _pack(fc):
    return pack_fake(fc)


def test_affinity_pod_placed_only_where_match_resides():
    fc = _cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-with-db"


def test_affinity_pod_with_no_resident_match_blocks_drain():
    fc = _cluster(match_on=None)
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    assert not result.feasible[:1].any()


def test_match_on_candidate_node_does_not_count():
    """Conservative dynamics: a match that itself must move (it lives on
    the on-demand node) cannot anchor the affinity pod."""
    fc = _cluster(match_on=None)
    fc.add_pod(make_pod("db-0", 100, "od-1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    assert not result.feasible[:1].any()


def test_namespace_scoping():
    fc = _cluster()  # db-0 resides in namespace "default"
    fc.add_pod(make_pod("web", 300, "od-1", namespace="other",
                        pod_affinity_match={"app": "db"}))
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    assert not result.feasible[:1].any()


def test_plain_pods_unaffected_by_universe():
    fc = _cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    fc.add_pod(make_pod("plain", 200, "od-1"))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    pods = meta.cand_pods[0]
    k = next(i for i, p in enumerate(pods) if p.name == "web")
    assert meta.spot[int(result.assignment[0, k])].node.name == "spot-with-db"


# --- zone-topology positive affinity (round 4) -----------------------------

def _zl(base, zone):
    from k8s_spot_rescheduler_tpu.predicates.masks import ZONE_LABEL

    return dict(base, **{ZONE_LABEL: zone})


def _zone_cluster(db_on="spot-a2"):
    """Zone a: spot-a1 (empty), spot-a2 (hosts app=db by default).
    Zone b: spot-b1. Zoneless: spot-nz."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zl(ON_DEMAND_LABELS, "b")))
    fc.add_node(make_node("spot-a1", _zl(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-a2", _zl(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zl(SPOT_LABELS, "b")))
    fc.add_node(make_node("spot-nz", SPOT_LABELS))
    if db_on:
        fc.add_pod(make_pod("db-0", 100, db_on, labels={"app": "db"}))
    return fc


def _zone_placement(fc, name):
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    for c, pods in enumerate(meta.cand_pods):
        for k, p in enumerate(pods):
            if p.name == name:
                if not result.feasible[c]:
                    return None
                return meta.spot[int(result.assignment[c, k])].node.name
    raise AssertionError(f"{name} not packed")


def test_zone_affinity_pod_admitted_anywhere_in_matching_zone():
    """The match sits on spot-a2; BOTH zone-a nodes admit the carrier
    (zone topology, unlike hostname) — first-fit probe order picks the
    fuller zone-a node. Zone b and the zoneless node refuse."""
    fc = _zone_cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_zone_match={"app": "db"}))
    assert _zone_placement(fc, "web") in ("spot-a1", "spot-a2")
    _columnar_parity(fc)


def test_zone_affinity_no_match_blocks_drain():
    fc = _zone_cluster(db_on=None)
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_zone_match={"app": "db"}))
    packed, _ = _pack(fc)
    assert not plan_oracle(packed).feasible[:1].any()
    _columnar_parity(fc)


def test_zone_affinity_match_on_own_candidate_excluded():
    """The stranding hazard the context exclusion exists for: the only
    match lives on the DRAINING node (same zone as spot capacity) — it
    leaves in the same drain, so the zone must not count as satisfied."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zl(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-a1", _zl(SPOT_LABELS, "a")))
    fc.add_pod(make_pod("db-0", 100, "od-1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_zone_match={"app": "db"}))
    packed, _ = _pack(fc)
    assert not plan_oracle(packed).feasible[:1].any()
    _columnar_parity(fc)


def test_zone_affinity_match_on_other_candidate_counts():
    """A match on a DIFFERENT on-demand node stays this tick (one drain
    per tick) — its zone satisfies the carrier."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", _zl(ON_DEMAND_LABELS, "b")))
    fc.add_node(make_node("od-2", _zl(ON_DEMAND_LABELS, "a")))
    fc.add_node(make_node("spot-a1", _zl(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zl(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "od-2", labels={"app": "db"}))
    fc.add_pod(make_pod("filler", 600, "od-2"))
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_zone_match={"app": "db"}))
    assert _zone_placement(fc, "web") == "spot-a1"
    _columnar_parity(fc)


def test_zone_affinity_end_to_end_drain():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", _zl(ON_DEMAND_LABELS, "b")))
    fc.add_node(make_node("spot-a1", _zl(SPOT_LABELS, "a")))
    fc.add_node(make_node("spot-b1", _zl(SPOT_LABELS, "b")))
    fc.add_pod(make_pod("db-0", 100, "spot-a1", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_zone_match={"app": "db"}))
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    assert fc.pods["default/web"].node_name == "spot-a1"


# --- columnar parity -------------------------------------------------------

def _columnar_parity(fc):
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
    return store


def test_columnar_parity_with_pod_affinity():
    fc = _cluster()
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    _columnar_parity(fc)


def test_columnar_parity_tracks_match_arrival_and_departure():
    """Presence bits must refresh per tick as matching residents come
    and go — they live outside the label-keyed node-mask cache."""
    fc = _cluster(match_on=None)
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    store = _columnar_parity(fc)  # no match anywhere

    fc.add_pod(make_pod("db-0", 100, "spot-plain", labels={"app": "db"}))
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
    assert bool(plan_oracle(col).feasible[0])

    fc.evict_pod(fc.pods["default/db-0"], 0)
    fc.clock.advance(5.0)
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
    assert not plan_oracle(col).feasible[:1].any()


# --- end to end ------------------------------------------------------------

def test_drain_places_affinity_pod_with_its_match():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a", SPOT_LABELS))
    fc.add_node(make_node("spot-b", SPOT_LABELS))
    fc.add_pod(make_pod("db-0", 100, "spot-b", labels={"app": "db"}))
    fc.add_pod(make_pod("web", 300, "od-1",
                        pod_affinity_match={"app": "db"}))
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    moved = fc.pods["default/web"]
    assert moved.node_name == "spot-b"


def test_fake_scheduler_enforces_positive_affinity():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a", SPOT_LABELS))
    pod = make_pod("web", 300, "od-1", pod_affinity_match={"app": "db"})
    fc.add_pod(pod)
    fc.evict_pod(pod, 0)
    fc.clock.advance(5.0)
    assert "default/web" not in fc.pods  # pending, not placed on spot-a
    assert any(p.name == "web" for p in fc.pending)
