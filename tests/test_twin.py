"""Fleet-twin tests: the lightweight tenant twin (service/twin.py) and
the fleet acceptance core (bench.fleet_twin) that `make fleet-twin-smoke`
runs — heterogeneous twin specs, storm interrupt/restore round-trips,
join/leave churn without resync storms, DRR fairness under realistic
skew, bit-identity spot checks over real HTTP, and the deterministic
shed-edge induction with flight==metric parity per labeled reason.

The service queue/batch mechanics live in tests/test_service.py and the
failure-domain chaos in tests/test_fleet_chaos.py; this file owns the
fleet-scale observability plane.
"""

import pytest

from k8s_spot_rescheduler_tpu.bench.fleet_twin import (
    SHED_REASONS,
    fleet_twin,
    induce_shed_edges,
)
from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS
from k8s_spot_rescheduler_tpu.service.twin import TenantTwin, fleet_specs
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig


# ---------------------------------------------------------------------------
# twin specs: deterministic heterogeneity


def test_fleet_specs_deterministic_and_heterogeneous():
    a = fleet_specs(32, seed=7)
    b = fleet_specs(32, seed=7)
    assert a == b  # same seed -> bit-identical fleet
    assert a != fleet_specs(32, seed=8)
    # the fleet is genuinely mixed: several size tiers, several tick
    # cadences, several churn rates, all four zones
    assert len({(s.n_on_demand, s.n_spot, s.n_pods) for s in a}) >= 3
    assert len({s.cadence_s for s in a}) >= 3
    assert len({s.churn_prob for s in a}) >= 2
    assert {s.zone for s in a} == {0, 1, 2, 3}
    # names and per-twin seeds are unique (twin clusters must differ)
    assert len({s.name for s in a}) == 32
    assert len({s.seed for s in a}) == 32


def test_fleet_specs_deadline_fraction():
    specs = fleet_specs(20, seed=0, deadline_frac=0.5)
    n_deadline = sum(1 for s in specs if s.deadline_s > 0)
    assert 0 < n_deadline < 20


# ---------------------------------------------------------------------------
# storm interrupt/restore round-trip on the columnar store (no HTTP)


def _solo_twin(seed: int = 3) -> TenantTwin:
    spec = fleet_specs(4, seed=seed)[0]
    cfg = ReschedulerConfig(resources=CONFIGS[2].resources, solver="numpy")
    return TenantTwin(spec, cfg, FakeClock(), urls=[])


def test_spot_interrupt_parks_and_restore_rebuilds():
    tw = _solo_twin()
    sig0 = tw.bucket_signature()
    before = len(tw.live_spot_nodes())
    assert before > 0
    ok0 = int(tw.store.pack(tw.pdbs)[0].spot_ok.sum())
    assert tw.spot_interrupt(0.5) >= 1
    assert len(tw.live_spot_nodes()) < before
    # the interruption masks spot targets WITHOUT changing the packed
    # shape: the slot-stable store keeps the compile bucket identical
    # through a storm (no recompile), only spot_ok flips
    assert tw.bucket_signature() == sig0
    assert int(tw.store.pack(tw.pdbs)[0].spot_ok.sum()) < ok0
    tw.spot_restore()
    # kubelet re-registration restores the parked pods with the nodes
    assert len(tw.live_spot_nodes()) == before
    assert int(tw.store.pack(tw.pdbs)[0].spot_ok.sum()) == ok0
    assert tw.bucket_signature() == sig0


def test_spot_interrupt_reports_empty_instead_of_raising():
    tw = _solo_twin()
    assert tw.spot_interrupt(1.0) >= 1  # take everything
    assert tw.live_spot_nodes() == []
    assert tw.spot_interrupt(0.5) == 0  # nothing left: counted, not raised
    tw.spot_restore()
    assert len(tw.live_spot_nodes()) > 0


def test_churn_round_trips_store():
    import dataclasses

    spec = dataclasses.replace(fleet_specs(4, seed=3)[0], churn_prob=1.0)
    cfg = ReschedulerConfig(resources=CONFIGS[2].resources, solver="numpy")
    tw = TenantTwin(spec, cfg, FakeClock(), urls=[])
    n0 = len(tw.store._pod_row)
    assert tw.churn()  # parks one pod
    assert len(tw.store._pod_row) == n0 - 1
    assert tw.churn()  # re-adds it
    assert len(tw.store._pod_row) == n0


# ---------------------------------------------------------------------------
# the fleet acceptance core, at test scale (real HTTP, virtual hours)


@pytest.fixture(scope="module")
def mini_fleet() -> dict:
    return fleet_twin(
        n_twins=16, n_replicas=2, sim_s=480.0, seed=0, phases=2,
        slo_ms=6000.0, cost_base_s=2.0, cost_per_lane_s=0.8,
        max_wall_s=40.0,
    )


def test_fleet_twin_mini_acceptance(mini_fleet):
    art = mini_fleet
    assert art["ok"], art["failures"]
    assert art["crashes"] == 0
    assert art["mismatches"] == []
    assert art["ever_active"] == 16
    assert len(art["capacity_curve"]) == 2
    assert art["wall_s"] < 40.0


def test_fleet_twin_bit_identity_spot_checks(mini_fleet):
    # every spot-checked selection matched the solo in-process plan,
    # and the check actually ran (it is not vacuous)
    assert mini_fleet["verified_selections"] > 0
    assert mini_fleet["mismatches"] == []


def test_seeded_storm_hits_zone_cohort_in_one_window(mini_fleet):
    # phase p storms zone p: with 16 twins over 4 zones the phase-1
    # cohort holds 4 twins, and the seeded storm must hit most of it
    # inside the single storm window
    hits = mini_fleet["storm_hits_per_phase"]
    assert len(hits) == 2
    assert all(h >= 1 for h in hits)
    assert hits[1] >= 3


def test_join_leave_churn_without_resync_storm(mini_fleet):
    # tenants joined/left between phases (the ramp + leave_frac) and
    # twins churned pods throughout — none of it may force a delta-
    # protocol resync storm or crash a twin; both are fleet invariants
    # folded into ok/failures
    assert mini_fleet["ok"]
    assert not any("resync" in f for f in mini_fleet["failures"])
    assert mini_fleet["crashes"] == 0


def test_fairness_under_realistic_skew(mini_fleet):
    # mixed cluster sizes, cadences and churn rates: demand-normalized
    # served shares must stay near-uniform (DRR does its job)
    assert mini_fleet["jain_fleet"] >= 0.9
    for row in mini_fleet["capacity_curve"]:
        assert row["jain"] >= 0.9


def test_failover_ledger_parity(mini_fleet):
    assert mini_fleet["failovers_metric"] == mini_fleet["failovers_flight"]
    assert mini_fleet["failovers_metric"] > 0


def test_capacity_curve_shape(mini_fleet):
    curve = mini_fleet["capacity_curve"]
    occ = [r["occupancy"] for r in curve]
    assert occ == sorted(occ) and len(set(occ)) == len(occ)
    p99 = [r["queue_wait_p99_ms"] for r in curve]
    assert p99[-1] > p99[0]
    assert mini_fleet["capacity_tenants_per_device_at_slo"] >= 1


def test_restart_storm_survives_with_bounded_ingest(mini_fleet):
    # the restart-storm phase (one replica killed + warm-restarted under
    # full load, tenant cache wiped): the resync herd must be absorbed
    # by the bounded ingest admission class, converge in O(affected)
    # full packs, and every ledger must agree exactly
    storm = mini_fleet["resync_storm"]
    assert storm, "restart-storm phase did not run"
    assert storm["affected"] >= 1
    assert storm["ingest_inflight_max"] <= storm["ingest_cap"]
    assert storm["converge_ticks"] >= 1
    assert storm["full_packs"] >= storm["affected"]  # everyone re-seeded
    # anti-entropy parity: server-demanded resyncs == twin-observed,
    # and the resync-shed metric == its flight-event ledger
    assert storm["resyncs_server"] == storm["resyncs_twins"]
    assert storm["resync_sheds"] == storm["resync_sheds_flight"]
    # unaffected tenants held their (load-relative) queue-wait SLO —
    # folded into ok, surfaced here for a readable failure
    assert storm["p99_unaffected_ms"] <= storm["storm_slo_ms"]
    assert (
        mini_fleet["resync_storm_converge_ticks"]
        == storm["converge_ticks"]
    )


# ---------------------------------------------------------------------------
# deterministic shed-edge induction: every labeled reason, ledger parity


def test_induce_shed_edges_all_reasons_with_parity():
    result = induce_shed_edges(seed=0)
    assert result["ok"], result["failures"]
    for reason in SHED_REASONS:
        assert result["metric_delta"].get(reason, 0) >= 1, reason
        assert (
            result["metric_delta"][reason] == result["flight_delta"][reason]
        ), reason


# ---------------------------------------------------------------------------
# twin module constants stay aligned with the agent's breaker


def test_twin_breaker_mirrors_agent_constants():
    from k8s_spot_rescheduler_tpu.service.agent import (
        Endpoint,
        RemotePlanner,
    )

    # the twin reuses the agent's Endpoint state object and backoff
    # constants so fleet failover behavior tracks the production agent
    tw = _solo_twin()
    assert tw.endpoints == [] or isinstance(tw.endpoints[0], Endpoint)
    assert RemotePlanner.FAIL_THRESHOLD >= 1
    assert RemotePlanner.BACKOFF_BASE > 0
