"""Incremental device-resident tick pipeline: parity pins.

Three guarantees the pipeline rests on, each enforced here:

1. **Delta-pack parity** — ``emit_packed_delta`` + scatter application
   reproduce a from-scratch pack bit-identically across randomized churn
   sequences, both through the host reference (``apply_packed_delta``)
   and through the production device path (SolverPlanner's
   donated-buffer scatter, including pow-2 padding and out-of-bounds
   index drops).

2. **Staged-solve selection equivalence** — the chunked early-exit
   planner (solver/select.StagedPlanner) returns the identical
   (index, found, count, assignment-row) tuple as the unstaged fused
   planner, across the property-test cluster generator
   (tests/test_solver._random_packed) and the union-program variants
   production ships; with early exit, the count over the solved prefix
   plus the exactness flag is pinned instead.

3. **Prefilter soundness** — a lane the device prefilter eliminates is
   infeasible under the strongest host oracle union (a single false
   elimination would silently change the drain selection).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
from k8s_spot_rescheduler_tpu.models.columnar import (
    apply_packed_delta,
    emit_packed_delta,
)
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import make_pod
from tests.test_solver import _random_packed

RESOURCES = ("cpu", "memory", "ephemeral-storage", "pods")


def _columnar(fc, resources):
    cfg = ReschedulerConfig(resources=resources)
    return fc.columnar_store(
        resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )


def _churn(fc, rng, step: int) -> None:
    """One randomized churn beat: evict-like removals, reschedules onto
    random nodes (sized so lanes, spot rows and validity bits all move),
    taint flips."""
    action = step % 3
    if action == 0:
        uids = list(fc.pods)
        for uid in rng.choice(
            uids, size=min(8, len(uids)), replace=False
        ):
            fc._remove_pod(str(uid))
    elif action == 1:
        nodes = list(fc.nodes)
        for i in range(6):
            node = str(rng.choice(nodes))
            fc.add_pod(
                make_pod(
                    f"churn-{step}-{i}",
                    int(rng.integers(50, 400)),
                    node,
                    memory=int(rng.integers(1, 64)) << 20,
                )
            )
    else:
        from k8s_spot_rescheduler_tpu.models.cluster import Taint

        node = str(rng.choice(list(fc.nodes)))
        if step % 2:
            fc.add_taint(node, Taint("churn", "t", "NoSchedule"))
        else:
            fc.remove_taint(node, "churn")


def _assert_packed_equal(got, want, context=""):
    for field in want._fields:
        x, y = np.asarray(getattr(got, field)), getattr(want, field)
        np.testing.assert_array_equal(x, y, err_msg=f"{context} {field}")
        assert x.dtype == y.dtype, field


@pytest.mark.parametrize("seed", range(16))
def test_delta_pack_host_parity_random_churn(seed):
    """≥16 randomized churn sequences: delta-applied tensors must be
    bit-identical to a from-scratch pack every step (host reference)."""
    spec = dataclasses.replace(
        CONFIGS[3], n_on_demand=12, n_spot=12, n_pods=140
    )
    fc = generate_cluster(spec, seed=seed)
    store = _columnar(fc, spec.resources)
    rng = np.random.default_rng(seed + 1000)
    # generous fixed pads so shapes survive the churn (the shape-growth
    # fallback has its own test below)
    pads = dict(pad_candidates=16, pad_spot=16, pad_slots=48)
    prev, _ = store.pack(fc.pdbs, **pads)
    for step in range(4):
        _churn(fc, rng, step + seed)
        fresh, _ = store.pack(fc.pdbs, **pads)
        delta = emit_packed_delta(prev, fresh)
        assert delta is not None, "same-shape churn must emit a delta"
        applied = apply_packed_delta(prev, delta)
        _assert_packed_equal(applied, fresh, f"seed {seed} step {step}")
        prev = fresh


def test_delta_emit_none_on_shape_growth():
    """Pads breaching the high-water mark change shapes: the emitter must
    refuse (the planner then counts a full repack)."""
    spec = dataclasses.replace(CONFIGS[1], n_pods=16)
    fc = generate_cluster(spec, seed=0)
    store = _columnar(fc, spec.resources)
    a, _ = store.pack(fc.pdbs, pad_candidates=8)
    b, _ = store.pack(fc.pdbs, pad_candidates=64)
    assert emit_packed_delta(a, b) is None
    # and an unchanged cluster emits an EMPTY delta, not None
    c, _ = store.pack(fc.pdbs, pad_candidates=8)
    d = emit_packed_delta(a, c)
    assert d is not None and d.n_lanes == 0 and len(d.spot_rows) == 0


@pytest.mark.parametrize("seed", [0, 7])
def test_device_cache_matches_host_pack_under_churn(seed):
    """The production path: donated scatter updates of the device-resident
    cache must equal the tick's host pack bit-for-bit, every tick."""
    spec = dataclasses.replace(
        CONFIGS[3], n_on_demand=10, n_spot=10, n_pods=120
    )
    fc = generate_cluster(spec, seed=seed)
    cfg = ReschedulerConfig(
        solver="jax", resources=spec.resources, staged_chunk_lanes=8
    )
    planner = SolverPlanner(cfg)
    store = _columnar(fc, spec.resources)
    rng = np.random.default_rng(seed)
    saw_delta_tick = False
    for step in range(5):
        if step:
            _churn(fc, rng, step)
        report = planner.plan(store, fc.pdbs)
        _assert_packed_equal(
            planner._device_packed, planner.last_packed, f"tick {step}"
        )
        if step:
            assert not report.full_repack or report.upload_bytes > 0
            saw_delta_tick |= not report.full_repack
        else:
            assert report.full_repack  # cold cache
    assert saw_delta_tick, "no tick exercised the delta path"


def test_full_repack_on_shape_growth_through_planner():
    """A pod burst past the slot-pad high-water mark must fall back to a
    counted full re-upload, then resume delta ticks."""
    spec = dataclasses.replace(
        CONFIGS[1], n_on_demand=4, n_spot=4, n_pods=24
    )
    fc = generate_cluster(spec, seed=2)
    cfg = ReschedulerConfig(
        solver="jax",
        resources=spec.resources,
        max_pods_per_node_hint=8,
    )
    planner = SolverPlanner(cfg)
    store = _columnar(fc, spec.resources)
    assert planner.plan(store, fc.pdbs).full_repack  # cold
    assert not planner.plan(store, fc.pdbs).full_repack  # warm delta
    # burst: blow out the K axis on one on-demand node
    node = next(n for n in fc.nodes if "od" in n)
    for i in range(12):
        fc.add_pod(make_pod(f"burst-{i}", 10, node))
    report = planner.plan(store, fc.pdbs)
    assert report.full_repack
    _assert_packed_equal(planner._device_packed, planner.last_packed)
    assert not planner.plan(store, fc.pdbs).full_repack  # warm again


# ----------------------------------------------------------------------
# staged early-exit solve


def _selection_pair(packed, solve_fn, chunk, early_exit):
    from k8s_spot_rescheduler_tpu.solver.select import (
        decode_selection,
        make_fused_planner,
        make_staged_planner,
    )

    fused = make_fused_planner(solve_fn)
    staged = make_staged_planner(
        solve_fn, chunk_lanes=chunk, early_exit=early_exit
    )
    want = decode_selection(fused(packed))
    got, stats = staged.solve(packed)
    return want, got, stats


@pytest.mark.parametrize("seed", range(25))
def test_staged_parity_exhaustive(seed):
    """early_exit off: the full (index, found, count, row) tuple must be
    identical to the unstaged fused planner on the property generator."""
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    packed = _random_packed(np.random.default_rng(seed))
    want, got, stats = _selection_pair(
        packed, plan_ffd, chunk=2, early_exit=False
    )
    assert (got.index, got.found, got.n_feasible) == (
        want.index,
        want.found,
        want.n_feasible,
    )
    np.testing.assert_array_equal(got.row, want.row)
    assert not stats.count_truncated


@pytest.mark.parametrize("seed", range(25, 50))
def test_staged_parity_early_exit(seed):
    """early_exit on (production): selection bit-identical; the count is
    identical unless the exit truncated it, and then it is an exact
    lower bound with the flag raised."""
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    packed = _random_packed(np.random.default_rng(seed))
    want, got, stats = _selection_pair(
        packed, plan_ffd, chunk=2, early_exit=True
    )
    assert (got.index, got.found) == (want.index, want.found)
    np.testing.assert_array_equal(got.row, want.row)
    if stats.count_truncated:
        assert got.found and got.n_feasible <= want.n_feasible
    else:
        assert got.n_feasible == want.n_feasible


@pytest.mark.parametrize("seed", range(50, 58))
def test_staged_parity_union_program(seed):
    """The staged planner wraps the SAME union program production ships
    (first-fit ∪ best-fit ∪ repair): parity must survive the lax.cond
    improvement passes inside each chunk."""
    from k8s_spot_rescheduler_tpu.solver.fallback import with_repair
    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

    union = with_repair(plan_ffd, 2)
    packed = _random_packed(np.random.default_rng(seed))
    want, got, stats = _selection_pair(
        packed, union, chunk=2, early_exit=False
    )
    assert (got.index, got.found, got.n_feasible) == (
        want.index,
        want.found,
        want.n_feasible,
    )
    np.testing.assert_array_equal(got.row, want.row)


@pytest.mark.parametrize("seed", range(40))
def test_prefilter_sound(seed):
    """A prefilter-eliminated lane must be infeasible under the host
    oracle union — the bound may only ever discard provably dead lanes."""
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
    from k8s_spot_rescheduler_tpu.solver.prefilter import lane_maybe_feasible

    packed = _random_packed(np.random.default_rng(seed + 500))
    maybe = np.asarray(lane_maybe_feasible(packed))
    union_feasible = np.asarray(
        plan_oracle(packed).feasible
    ) | np.asarray(plan_oracle(packed, best_fit=True).feasible)
    assert not np.any(union_feasible & ~maybe), (
        "prefilter eliminated a feasible lane"
    )


def test_staged_planner_matches_solver_planner_selection():
    """End to end: the staged+incremental planner and a plain unstaged,
    cache-off planner must pick the same drain on the same cluster."""
    spec = dataclasses.replace(
        CONFIGS[3], n_on_demand=12, n_spot=12, n_pods=150
    )
    fc = generate_cluster(spec, seed=5)
    store = _columnar(fc, spec.resources)
    fast = SolverPlanner(
        ReschedulerConfig(
            solver="jax", resources=spec.resources, staged_chunk_lanes=8
        )
    )
    plain = SolverPlanner(
        ReschedulerConfig(
            solver="jax",
            resources=spec.resources,
            staged_chunk_lanes=0,
            incremental_device_cache=False,
        )
    )
    a = fast.plan(store, fc.pdbs)
    b = plain.plan(store, fc.pdbs)
    assert (a.plan is None) == (b.plan is None)
    if a.plan is not None:
        assert a.plan.node.node.name == b.plan.node.node.name
        assert a.plan.assignments == b.plan.assignments


def test_incremental_metrics_wiring():
    """The control loop mirrors PlanReport telemetry into the registry
    gauges (solver_delta_pack_lanes / solver_full_repack_total /
    solver_chunks_*)."""
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics
    from k8s_spot_rescheduler_tpu.planner.base import PlanReport

    def gauge(g):
        return g.collect()[0].samples[0].value

    before = gauge(metrics.solver_full_repack)
    metrics.update_incremental_tick(
        PlanReport(
            plan=None, n_candidates=4, n_feasible=0, solve_seconds=0.0,
            full_repack=True, upload_bytes=1234, chunks_solved=2,
            chunks_skipped=3,
        )
    )
    assert gauge(metrics.solver_full_repack) == before + 1
    assert gauge(metrics.solver_delta_upload_bytes) == 1234
    assert gauge(metrics.solver_chunks_solved) == 2
    assert gauge(metrics.solver_chunks_skipped) == 3
    metrics.update_incremental_tick(
        PlanReport(
            plan=None, n_candidates=4, n_feasible=1, solve_seconds=0.0,
            delta_pack_lanes=7, upload_bytes=99,
        )
    )
    assert gauge(metrics.solver_delta_pack_lanes) == 7
    assert gauge(metrics.solver_full_repack) == before + 1  # unchanged


def test_pipelined_tick_records_split_phases():
    """One real tick through the controller must time the pipelined
    phases (plan-dispatch / observe-metrics / plan-fetch) AND the
    aggregate plan series, and update the incremental gauges."""
    from prometheus_client import REGISTRY

    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock

    spec = dataclasses.replace(CONFIGS[1], n_pods=12)
    fc = generate_cluster(spec, seed=3)
    cfg = ReschedulerConfig(
        solver="jax", resources=spec.resources, node_drain_delay=0.0,
        # the per-tick pipelined path is what this test times; schedules
        # (the default) serve steps without plan-dispatch/plan-fetch —
        # pin the documented opt-out
        schedule_horizon=0,
    )
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=FakeClock())

    def phase_count(phase):
        return REGISTRY.get_sample_value(
            "spot_rescheduler_tick_phase_duration_seconds_count",
            {"phase": phase},
        ) or 0.0

    before = {
        p: phase_count(p)
        for p in ("plan", "plan-dispatch", "plan-fetch", "observe-metrics")
    }
    r.tick()
    for p in ("plan", "plan-dispatch", "plan-fetch", "observe-metrics"):
        assert phase_count(p) == before[p] + 1, p
