"""Freshness-gated observe path (docs/ROBUSTNESS.md): watch liveness
deadlines, the controller's mirror-staleness gate and its degradation
ladder, the anti-entropy resync audit, the startup watch-sync fallback,
the zero-churn pack memo — and the headline seeded soak (≥300 virtual
ticks with open-but-silent stalls, scripted 410s and one injected mirror
corruption; all invariants asserted via the new metrics)."""

import dataclasses

import pytest

import bench
from k8s_spot_rescheduler_tpu.io.chaos import ChaosClusterClient, FaultPlan
from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.fakewatch import (
    ScriptedWatchSource,
    raw_node,
    raw_pod,
)
from k8s_spot_rescheduler_tpu.io.kube import decode_pod
from k8s_spot_rescheduler_tpu.io.watch import (
    ResourceStore,
    Watcher,
    WatchingKubeClusterClient,
)
from k8s_spot_rescheduler_tpu.loop import health
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.metrics.registry import freshness_snapshot
from k8s_spot_rescheduler_tpu.models.columnar import ColumnarStore
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock, RealClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod


@pytest.fixture(autouse=True)
def _reset_health():
    health.STATE.reset()
    yield
    health.STATE.reset()


def _meta_key(obj):
    return WatchingKubeClusterClient._meta_key(obj)


def _pod_watcher(src, **kw):
    store = ResourceStore()
    return Watcher(
        src, "/api/v1/pods", decode_pod, _meta_key, store, name="pods", **kw
    ), store


# --- watch liveness: stalls, bookmarks, 410 throttling, prompt stop ---


def test_stall_detected_and_reconnects_without_relist():
    """An open-but-silent stream past the progress deadline is killed,
    counted as a stall, and reconnected from the SAME resourceVersion
    with no re-LIST (a wedge loses no events)."""
    clock = FakeClock(1000.0)
    src = ScriptedWatchSource()
    src.objects["pods"]["uid-a"] = raw_pod("a", "od-1")
    chaos = ChaosClusterClient(
        src, FaultPlan(watch_stall_rate=1.0), clock=clock
    )
    w, store = _pod_watcher(
        chaos, clock=clock, progress_deadline=60.0, wait_fn=clock.sleep
    )
    w.step()  # LIST seeds the store; the stream then stalls
    assert len(store.snapshot()) == 1
    assert w.relist_count == 1 and w.stall_count == 1
    assert w.stream_error_count == 0
    # the stall consumed exactly one client read timeout of virtual time
    assert clock.wall() == 1000.0 + 60.0 + 30.0
    # the deadline-killed stream did NOT count as progress
    assert w.staleness() == pytest.approx(90.0)
    # recovery: faults off, a queued event arrives on the reconnect —
    # served from the same rv without another LIST
    chaos.enabled = False
    src.push("pods", "ADDED", raw_pod("b", "od-1"))
    w.step()
    assert len(store.snapshot()) == 2
    assert w.relist_count == 1  # never re-listed
    assert w.staleness() == 0.0


def test_bookmark_advances_rv_without_touching_store():
    clock = FakeClock(0.0)
    src = ScriptedWatchSource()
    src.objects["pods"]["uid-a"] = raw_pod("a", "od-1")
    w, store = _pod_watcher(src, clock=clock, wait_fn=clock.sleep)
    w.step()
    snap_before = store.snapshot_items()
    events_before = w.event_count
    src.bookmark("pods")
    bookmark_rv = src.rv["pods"]
    w.step()  # consumes only the BOOKMARK
    assert store.snapshot_items() == snap_before  # store untouched
    assert w.event_count == events_before  # bookmarks are not events
    # the NEXT stream resumes from the bookmark's version, not the LIST's
    w.step()
    resource, rv = src.watch_params[-1]
    assert resource == "pods" and rv is not None
    assert int(rv) >= bookmark_rv


def test_410_mid_stream_triggers_exactly_one_throttled_relist():
    clock = FakeClock(0.0)
    src = ScriptedWatchSource()
    src.objects["pods"]["uid-a"] = raw_pod("a", "od-1")
    waits = []
    w, store = _pod_watcher(src, clock=clock, wait_fn=waits.append)
    w.step()
    assert w.relist_count == 1
    # an event lands, then the version expires mid-stream
    src.push("pods", "ADDED", raw_pod("b", "od-1"))
    src.queues["pods"].append({
        "type": "ERROR",
        "object": {"kind": "Status", "code": 410, "reason": "Expired"},
    })
    w.step()  # applies ADDED, hits the 410, backs off — NO list yet
    assert len(store.snapshot()) == 2  # the pre-410 event was applied
    assert w.relist_count == 1
    assert waits == [1.0]  # one throttled backoff pause
    w.step()  # exactly one recovery re-LIST
    assert w.relist_count == 2
    assert len(store.snapshot()) == 2
    w.step()  # healthy again: stream resumes, no further lists
    assert w.relist_count == 2


def test_stop_during_reconnect_backoff_returns_promptly():
    class _Down:
        """A source whose LIST always fails: the watcher sits in its
        reconnect backoff forever."""

        use_native_ingest = False

        def _request(self, method, path, body=None, **kw):
            raise ConnectionResetError("apiserver down")

        def _stream(self, path, read_timeout=330.0):
            raise ConnectionResetError("apiserver down")
            yield  # pragma: no cover

    import time

    w, _ = _pod_watcher(_Down())
    w._backoff = 30.0  # as if several failures already backed off
    w.start()
    time.sleep(0.1)  # let it enter the backoff wait
    t0 = time.monotonic()
    w.stop()
    w.join(timeout=5.0)
    assert not w.is_alive()
    assert time.monotonic() - t0 < 2.0  # stop() cut the 30 s wait short


def test_list_timeout_is_a_stream_error_not_a_stall():
    """A timing-out LIST must keep the exponential relist backoff —
    classifying it as a stall would retry the LIST in a tight loop
    against an already-struggling apiserver."""

    class _TimeoutList:
        use_native_ingest = False

        def _request(self, method, path, body=None, **kw):
            raise TimeoutError("LIST timed out")

        def _stream(self, path, read_timeout=330.0):
            raise AssertionError("never reached: the LIST failed first")
            yield  # pragma: no cover

    waits = []
    w, _ = _pod_watcher(
        _TimeoutList(), clock=FakeClock(0.0), progress_deadline=60.0,
        wait_fn=waits.append,
    )
    w.step()
    assert w.stall_count == 0
    assert w.stream_error_count == 1
    assert waits == [1.0]  # backed off, did not spin
    assert w._need_list  # and will re-LIST (with backoff), not re-watch


def test_restart_mid_stream_discards_undelivered_stale_events():
    """When an audit heal lands while the old stream still has queued
    events, the watcher must abandon the stream BEFORE applying them —
    a stale event applied on top of the healed store would never be
    redelivered by the resumed (past-it) stream."""
    src = ScriptedWatchSource()
    src.objects["pods"]["uid-a"] = raw_pod("a", "od-1", cpu_millis=500)
    w, store = _pod_watcher(src, clock=FakeClock(0.0))
    w.step()  # seed

    # two queued events: applying the FIRST triggers the "audit heal"
    # (as the audit thread would, concurrently); the SECOND is the
    # stale one that must now be discarded
    src.push("pods", "ADDED", raw_pod("b", "od-1"))
    src.queues["pods"].append(
        {"type": "MODIFIED", "object": raw_pod("a", "od-1", cpu_millis=1)}
    )
    healed = dict(store.snapshot_items())

    def on_mutation(action, key, obj):
        w.restart_from("999")

    store._listener = on_mutation
    w.step()
    store._listener = None
    pods = {p.name: p for p in store.snapshot()}
    assert "b" in pods  # the pre-heal event was applied...
    assert pods["a"].requests["cpu"] == 500  # ...the stale one was NOT
    w.step()  # resumes from the audit's rv without a re-LIST
    assert w.relist_count == 1
    assert src.watch_params[-1] == ("pods", "999")


# --- the anti-entropy resync audit ---


def _synced_watch_client(clock=None):
    clock = clock or FakeClock(1_000.0)
    src = ScriptedWatchSource()
    for i in range(2):
        src.objects["nodes"][f"uid-od-{i}"] = raw_node(f"od-{i}", "worker")
    src.objects["nodes"]["uid-spot-0"] = raw_node("spot-0", "spot-worker")
    for i in range(3):
        src.objects["pods"][f"uid-p{i}"] = raw_pod(
            f"p{i}", "od-0", cpu_millis=100 + 100 * i
        )
    wc = WatchingKubeClusterClient(
        src, clock=clock, progress_deadline=120.0, wait_fn=clock.sleep
    )
    wc.start(background=False)
    return src, wc, clock


def test_audit_clean_mirror_counts_no_drift():
    src, wc, clock = _synced_watch_client()
    before = freshness_snapshot()
    items_before = wc.pods.snapshot_items()
    drift = wc.resync_audit()
    assert drift == {"nodes": 0, "pods": 0, "pdbs": 0}
    after = freshness_snapshot()
    assert after["watch_drift"] == before["watch_drift"]
    assert after["resync_audits"] == before["resync_audits"] + 1
    # a clean audit does NOT replace the store (same objects, no churn
    # into the columnar feed)
    assert wc.pods.snapshot_items() == items_before
    assert all(
        a is b
        for (_, a), (_, b) in zip(items_before, wc.pods.snapshot_items())
    )


def test_audit_detects_and_heals_corruption_and_missed_events():
    src, wc, clock = _synced_watch_client()
    before = freshness_snapshot()
    # field-level corruption in the mirror
    key, pod = wc.pods.snapshot_items()[0]
    wc.pods.upsert(key, dataclasses.replace(pod, priority=777))
    # plus an event the (dead) stream never delivered: a phantom delete
    src.objects["pods"].pop("uid-p2")
    drift = wc.resync_audit()
    assert drift["pods"] == 2  # one corrupted field, one phantom object
    after = freshness_snapshot()
    # split series: field-level corruption is alarm-grade drift, the
    # phantom (a delete the stream never delivered) is a presence heal
    assert after["watch_drift"] == before["watch_drift"] + 1
    assert (
        after["watch_presence_heals"]
        == before["watch_presence_heals"] + 1
    )
    # healed: the mirror now equals the truth exactly
    mirror = {k: p for k, p in wc.pods.snapshot_items()}
    assert set(mirror) == set(src.objects["pods"])
    assert all(p.priority == 0 for p in mirror.values())


def test_audit_tolerates_churn_landing_during_the_fetch(monkeypatch):
    """An event applied while the audit's LIST is in flight makes the
    mirror legitimately differ from the LIST — that is churn, not
    drift, and must not be counted or healed backwards."""
    src, wc, clock = _synced_watch_client()
    orig_fetch = Watcher._fetch

    def racy_fetch(self, *, native=True):
        items, rv = orig_fetch(self, native=native)
        if self.resource == "pods":
            # a watch event lands between the LIST response and the
            # diff (what the watcher thread does in production)
            key, pod = self.store.snapshot_items()[0]
            self.store.upsert(key, dataclasses.replace(pod, priority=5))
        return items, rv

    monkeypatch.setattr(Watcher, "_fetch", racy_fetch)
    before = freshness_snapshot()
    drift = wc.resync_audit()
    assert drift["pods"] == 0
    assert freshness_snapshot()["watch_drift"] == before["watch_drift"]
    # and the mid-audit event survived (no backwards heal)
    assert any(p.priority == 5 for p in wc.pods.snapshot())


def test_audit_clean_audit_restamps_liveness():
    src, wc, clock = _synced_watch_client()
    clock.advance(500.0)  # streams silent: mirror looks ancient
    assert wc.mirror_staleness() == pytest.approx(500.0)
    wc.resync_audit()
    # mirror == fresh LIST was just proven; staleness resets
    assert wc.mirror_staleness() == 0.0


def test_controller_runs_audit_on_schedule_and_events_drift():
    clock = FakeClock(1_000.0)
    src, wc, _ = _synced_watch_client(clock)
    config = ReschedulerConfig(
        solver="numpy", resync_interval=50.0, node_drain_delay=1e6,
        mirror_staleness_budget=0.0,  # isolate the audit from the gate
    )
    r = Rescheduler(wc, SolverPlanner(config), config, clock=clock,
                    recorder=wc)

    def advance_tick(seconds):
        clock.advance(seconds)
        for w in wc._watchers:
            w.step()
        return r.tick()

    advance_tick(0.0)  # first tick arms the schedule, no audit
    before = freshness_snapshot()
    advance_tick(10.0)  # not due yet
    assert freshness_snapshot()["resync_audits"] == before["resync_audits"]
    # corrupt the mirror (a node: drains never delete those), then
    # advance past the interval
    node = dict(wc.nodes.snapshot_items())["uid-spot-0"]
    wc.nodes.upsert("uid-spot-0", dataclasses.replace(
        node, allocatable={**node.allocatable, "cpu": 1}
    ))
    advance_tick(60.0)
    snap = freshness_snapshot()
    assert snap["resync_audits"] == before["resync_audits"] + 1
    assert snap["watch_drift"] == before["watch_drift"] + 1
    assert any(
        e[2:4] == ("Warning", "WatchDriftHealed") for e in src.events
    ), src.events


# --- the freshness gate ---


class _MirrorFacade:
    """FakeCluster behind a controllable mirror_staleness(); the
    controller sees the watch-client surface without real watchers."""

    def __init__(self, inner, staleness_values, with_direct=True):
        self.inner = inner
        self._staleness = list(staleness_values)
        self.direct_calls = 0
        if not with_direct:
            # hide the bypass path entirely
            self.direct_client = None

    def mirror_staleness(self):
        # last value repeats (the gate may sample more than once)
        if len(self._staleness) > 1:
            return self._staleness.pop(0)
        return self._staleness[0]

    def direct_client(self):
        self.direct_calls += 1
        return self.inner

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _drainable_fake():
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    fc.add_node(make_node("od-small", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    for i, cpu in enumerate([300, 200, 100]):
        fc.add_pod(make_pod(f"small-{i}", cpu, "od-small"))
    return fc, clock


def test_gate_fresh_mirror_plans_normally():
    fc, clock = _drainable_fake()
    facade = _MirrorFacade(fc, [5.0])
    config = ReschedulerConfig(solver="numpy", mirror_staleness_budget=60.0)
    r = Rescheduler(facade, SolverPlanner(config), config, clock=clock,
                    recorder=fc)
    result = r.tick()
    assert result.skipped == ""
    assert facade.direct_calls == 0
    assert health.STATE.snapshot()["degraded"] is False
    assert health.STATE.snapshot()["mirror_staleness_s"] == 5.0


def test_gate_stale_mirror_bypasses_to_direct_list():
    fc, clock = _drainable_fake()
    facade = _MirrorFacade(fc, [500.0])
    config = ReschedulerConfig(solver="numpy", mirror_staleness_budget=60.0)
    r = Rescheduler(facade, SolverPlanner(config), config, clock=clock,
                    recorder=fc)
    before = freshness_snapshot()
    result = r.tick()
    # the tick COMPLETED — on direct LISTs, not the sick mirror
    assert result.skipped == ""
    assert result.drained == ["od-small"]
    assert facade.direct_calls == 1
    snap = freshness_snapshot()
    assert snap["freshness_bypass"] == before["freshness_bypass"] + 1
    assert snap["mirror_stale_planned"] == before["mirror_stale_planned"]
    assert health.STATE.snapshot()["degraded"] is True
    # mirror recovers → gate passes → degradation clears
    facade._staleness = [1.0]
    r.next_drain_time = clock.now()  # disarm the post-drain cooldown
    assert r.tick().skipped == ""
    assert health.STATE.snapshot()["degraded"] is False


def test_gate_stale_mirror_without_direct_path_skips_into_breaker():
    fc, clock = _drainable_fake()
    facade = _MirrorFacade(fc, [500.0], with_direct=False)
    config = ReschedulerConfig(
        solver="numpy", mirror_staleness_budget=60.0, breaker_threshold=2
    )
    r = Rescheduler(facade, SolverPlanner(config), config, clock=clock,
                    recorder=fc)
    for _ in range(3):
        assert r.tick().skipped == "error"
    assert r.breaker_engaged
    assert r.effective_interval() > config.housekeeping_interval


def test_gate_last_line_guard_refuses_plan_from_aged_mirror():
    """If the mirror ages past the budget BETWEEN the gate and the plan
    dispatch, the tick is refused and the (alarm) counter increments —
    no eviction is ever planned from over-budget data."""
    fc, clock = _drainable_fake()
    facade = _MirrorFacade(fc, [5.0, 500.0])  # gate sees 5 s, plan 500 s
    config = ReschedulerConfig(solver="numpy", mirror_staleness_budget=60.0)
    r = Rescheduler(facade, SolverPlanner(config), config, clock=clock,
                    recorder=fc)
    before = freshness_snapshot()
    result = r.tick()
    assert result.skipped == "error"
    assert result.drained == []
    snap = freshness_snapshot()
    assert snap["mirror_stale_planned"] == before["mirror_stale_planned"] + 1


def test_gate_disabled_budget_zero_is_inert():
    fc, clock = _drainable_fake()
    facade = _MirrorFacade(fc, [1e9])
    config = ReschedulerConfig(solver="numpy", mirror_staleness_budget=0.0)
    r = Rescheduler(facade, SolverPlanner(config), config, clock=clock,
                    recorder=fc)
    assert r.tick().skipped == ""
    assert facade.direct_calls == 0


# --- startup graceful degradation (cli/main.py satellite) ---


def test_watch_sync_failure_falls_back_to_polling_client(monkeypatch):
    from k8s_spot_rescheduler_tpu.cli.main import start_watch_client

    def boom(self, *a, **k):
        raise TimeoutError("watch cache for pods failed to sync")

    monkeypatch.setattr(WatchingKubeClusterClient, "start", boom)
    src = ScriptedWatchSource()
    out = start_watch_client(src, ReschedulerConfig(), RealClock())
    assert out is src  # the polling client, not the dead watch wrapper
    assert health.STATE.snapshot()["degraded"] is True
    # sticky: a later successful tick does not clear the startup cause
    health.STATE.note_success()
    assert health.STATE.snapshot()["degraded"] is True


def test_watch_sync_success_returns_watch_client(monkeypatch):
    monkeypatch.setattr(
        WatchingKubeClusterClient, "start", lambda self, *a, **k: None
    )
    from k8s_spot_rescheduler_tpu.cli.main import start_watch_client

    src = ScriptedWatchSource()
    out = start_watch_client(src, ReschedulerConfig(), RealClock())
    assert isinstance(out, WatchingKubeClusterClient)
    assert health.STATE.snapshot()["degraded"] is False


# --- zero-churn pack memo (the O(churn) observe+pack tail) ---


def test_pack_memo_hits_on_quiet_tick_and_invalidates_on_churn():
    store = ColumnarStore(
        ("cpu", "memory"),
        on_demand_label="kubernetes.io/role=worker",
        spot_label="kubernetes.io/role=spot-worker",
    )
    store.pack_memo_enabled = True
    store.add_node(make_node("od-1", ON_DEMAND_LABELS))
    store.add_node(make_node("spot-1", SPOT_LABELS))
    store.add_pod(make_pod("a", 300, "od-1"))
    p1, m1 = store.pack([])
    p2, m2 = store.pack([])
    assert p1 is p2 and m1 is m2  # quiet tick: O(1) observe+pack
    store.add_pod(make_pod("b", 200, "od-1"))
    p3, _ = store.pack([])
    assert p3 is not p1
    assert bool(p3.slot_valid[:, 1].any())  # the new pod is packed
    # parameter changes must also miss
    p4, _ = store.pack([], priority_threshold=5)
    assert p4 is not p3


def test_pack_memo_off_by_default():
    store = ColumnarStore(
        ("cpu", "memory"),
        on_demand_label="kubernetes.io/role=worker",
        spot_label="kubernetes.io/role=spot-worker",
    )
    store.add_node(make_node("od-1", ON_DEMAND_LABELS))
    p1, _ = store.pack([])
    p2, _ = store.pack([])
    assert p1 is not p2


# --- the headline seeded soak (acceptance criteria) ---


def test_watch_soak_300_ticks():
    """≥300 virtual ticks under watch stalls, stream drops, two scripted
    410s, and one injected mirror corruption: zero crashes, zero ticks
    planned from an over-budget mirror, drift healed within one resync
    interval, stalls detected, every full LIST accounted to a relist or
    an audit, and end-state mirror/LIST pack parity — all asserted via
    the new metrics inside bench.watch_soak."""
    stats, violations = bench.watch_soak(300, seed=0)
    assert violations == []
    assert stats["ticks"] == 300
    assert stats["stalls_detected"] >= 1
    assert stats["scripted_410s"] == 2
    assert stats["drift_objects_healed"] >= 1
    assert stats["mirror_stale_planned"] == 0
    assert stats["freshness_bypass_ticks"] >= 1
    assert stats["resync_audits"] >= 1
    assert stats["mirror_parity"] is True
