"""The protocol model itself must keep proving what it claims.

tests/test_analysis.py proves the proto TIER gates (seeded fixtures
turn it red); this file pins the MODEL: the explored state-space sizes
(so a bounds or transition edit that quietly shrinks coverage is
loud), the zero-defect verdict on both declared configurations, and
the structural properties the ISSUE acceptance names — >= 2 agents x
2 replicas with a restart event, a version-mix configuration, and the
mirrored wire constants staying equal to the live ones by import (the
protocol-contract pass re-proves the same equality by AST, so the two
can only drift together, loudly).
"""

from k8s_spot_rescheduler_tpu.service import protocol_model, wire
from tools.analysis.proto.model_check import MAX_STATES, explore

# The exhaustive exploration of both CHECK_BOUNDS configurations, run
# once per test session (explore() is pure; ~2 s total on CPU).
_RESULTS = {
    system.name: explore(system)
    for system in protocol_model.build_systems()
}

# Pinned explored sizes. These numbers ARE the coverage: a transition
# or bounds edit that changes the reachable space must update them
# consciously (and stay under the checker's MAX_STATES headroom).
_PINNED = {
    "storm": dict(n_states=91093, n_edges=243145, n_goal=490),
    "version-mix": dict(n_states=3251, n_edges=8459, n_goal=52),
}


def test_declared_systems_match_pinned_names():
    assert set(_RESULTS) == set(_PINNED)


def test_bounds_meet_acceptance_floor():
    """The proof must cover >= 2 agents x 2 replicas with a replica
    restart, plus a mixed-version fleet."""
    by_name = {b.name: b for b in protocol_model.CHECK_BOUNDS}
    storm = by_name["storm"]
    assert storm.n_agents >= 2 and storm.n_replicas >= 2
    assert storm.restart_budget >= 1
    assert storm.loss_budget >= 1
    mixed = by_name["version-mix"]
    assert len(set(mixed.versions)) >= 2
    assert min(mixed.versions) < protocol_model.WIRE_VERSION


def test_explorations_are_clean():
    """Zero safety violations, zero deadlocks, zero undrainable states
    on every reachable state of both configurations."""
    for name, result in _RESULTS.items():
        assert not result.truncated, name
        assert result.violations == [], (name, result.violations[:3])
        assert result.deadlocks == [], (name, result.deadlocks[:3])
        assert result.undrainable == [], (name, result.undrainable[:3])
        assert result.n_goal > 0, name


def test_explored_sizes_are_pinned():
    for name, pins in _PINNED.items():
        result = _RESULTS[name]
        got = dict(
            n_states=result.n_states,
            n_edges=result.n_edges,
            n_goal=result.n_goal,
        )
        assert got == pins, (
            f"{name} state space drifted: {got} != pinned {pins} — a "
            "model edit changed coverage; re-verify and re-pin "
            "consciously"
        )


def test_pinned_sizes_fit_the_checker_bound():
    """Headroom: the pinned spaces must sit well under the checker's
    MAX_STATES so normal growth doesn't silently approach truncation."""
    total = sum(p["n_states"] for p in _PINNED.values())
    assert total < MAX_STATES // 2


def test_model_mirrors_live_wire_constants():
    """The import-level half of the protocol contract: the model's
    mirrored wire table equals the live module's constants."""
    assert protocol_model.WIRE_VERSION == wire.WIRE_VERSION
    assert tuple(protocol_model.VERSIONS) == tuple(
        wire.SUPPORTED_VERSIONS
    )
    for name, kind in protocol_model.KINDS.items():
        assert getattr(wire, name) == kind.value, name


def test_restart_bumps_epoch_and_wipes_cache():
    """Unit probe of the transition builder: from the initial state, a
    replica restart must bump the epoch and clear the per-agent cache
    and full-pack ledger on that replica only."""
    system = protocol_model.build_systems()[0]
    init = system.initial()
    restarts = [
        (label, nxt) for label, _, nxt in system.successors(init)
        if label.startswith("restart")
    ]
    assert restarts, "no restart event enabled at the initial state"
    for _, nxt in restarts:
        _, replicas, budgets = nxt
        assert budgets[2] == system.bounds.restart_budget - 1
        assert any(epoch == 1 for epoch, *_ in replicas)
        for epoch, cached, bits, _proc, _pressure in replicas:
            if epoch == 1:
                assert all(fp == cached[0] for fp in cached)
                assert all(b == 0 for b in bits)


def test_goal_requires_synced_closed_endpoint():
    """The drained goal is not vacuous: the initial state (nothing
    cached, nothing acked) must NOT be a goal state."""
    system = protocol_model.build_systems()[0]
    assert not system.is_goal(system.initial())
