"""Mesh-sharded solver tests on the virtual 8-device CPU platform
(conftest forces --xla_force_host_platform_device_count=8).

The sharded solver must be bit-identical to the serial oracle — the
collective election of the globally-first fitting spot node must reproduce
exact first-fit probe order across arbitrary shard boundaries.
"""

import jax
import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh, pick_mesh_shape
from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import plan_ffd_sharded
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.test_solver import _pack_drain_case, _random_packed, _test_spot_pool


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_pick_mesh_shape():
    assert pick_mesh_shape(8) == (4, 2)
    assert pick_mesh_shape(4) == (2, 2)
    assert pick_mesh_shape(2) == (2, 1)
    assert pick_mesh_shape(1) == (1, 1)


@pytest.mark.parametrize("shape", [(1, 1), (2, 1), (1, 2), (4, 2), (2, 4), (8, 1)])
def test_sharded_matches_oracle_fixture(shape):
    mesh = make_mesh(shape)
    for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
        packed, _ = _pack_drain_case(_test_spot_pool(), pods)
        want = plan_oracle(packed)
        got = jax.jit(lambda p: plan_ffd_sharded(mesh, p))(packed)
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(12))
def test_sharded_matches_oracle_randomized(seed):
    mesh = make_mesh((2, 2))
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    got = plan_ffd_sharded(mesh, packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


# --- single-chip HBM guard + automatic mesh dispatch ----------------------

def test_hbm_estimate_pins_measured_boundary():
    """The dispatch estimate must reproduce the measured single-chip
    envelope (docs/RESULTS.md): configs through 4x north star fit a
    16 GB v5e; 8x does not. These shapes are the real packed dims of
    config 3 at each scale (C=S=2560x, K=32, R=4, A=2)."""
    from k8s_spot_rescheduler_tpu.solver.memory import (
        BUDGET_FRACTION,
        DEFAULT_HBM_BYTES,
        estimate_union_hbm_bytes,
    )

    budget = int(DEFAULT_HBM_BYTES * BUDGET_FRACTION)
    for mult in (1, 2, 4):
        n = 2560 * mult
        assert estimate_union_hbm_bytes(n, 32, n, 4, 2, 2) <= budget, mult
    assert estimate_union_hbm_bytes(20480, 32, 20480, 4, 2, 2) > budget


def test_hbm_breakdown_components_sum_to_total():
    """The per-component breakdown IS the estimate (the jaxpr-tier
    memory-reconcile pass names drifted components from it), at every
    chunking mode, and the boundary-pin shapes keep the expected
    dominance order: carries > repair working set > everything else."""
    from k8s_spot_rescheduler_tpu.solver.memory import (
        estimate_union_hbm_breakdown,
        estimate_union_hbm_bytes,
    )

    for chunks in (0, 1, 4, 16):
        bd = estimate_union_hbm_breakdown(
            2560, 32, 2560, 4, 2, 2, repair_spot_chunks=chunks
        )
        assert set(bd) == {
            "carries", "temporaries", "repair", "slots", "outputs",
            "spot_static",
        }
        assert sum(bd.values()) == estimate_union_hbm_bytes(
            2560, 32, 2560, 4, 2, 2, repair_spot_chunks=chunks
        )
        assert all(v >= 0 for v in bd.values())
    # the O(C*S)-plane components dominate the O(C*K)/O(S) linear ones
    unchunked = estimate_union_hbm_breakdown(2560, 32, 2560, 4, 2, 2)
    assert unchunked["carries"] > unchunked["slots"]
    assert unchunked["repair"] > unchunked["slots"]
    # chunking shrinks ONLY the repair working set
    chunked = estimate_union_hbm_breakdown(
        2560, 32, 2560, 4, 2, 2, repair_spot_chunks=4
    )
    assert chunked["repair"] < unchunked["repair"]
    for k in ("carries", "temporaries", "slots", "outputs", "spot_static"):
        assert chunked[k] == unchunked[k], k
    norepair = estimate_union_hbm_breakdown(
        2560, 32, 2560, 4, 2, 2, repair_spot_chunks=0
    )
    assert norepair["repair"] == 0


def test_hbm_breakdown_carry_mode_boundary_pins():
    """The carry-streamed estimate (ROADMAP 5): same component names,
    sum == total, the carries term is the narrow layout's
    2·plane_bytes·C·S exactly (the jaxpr memory-reconcile carries band
    0.7-1.4 gates this term against the traced program — measured 1.00
    at introduction), streaming shrinks ONLY the chunk-resident terms,
    and the narrow carries sit strictly under the wide ones."""
    from k8s_spot_rescheduler_tpu.solver.carry import (
        NARROW_LAYOUT,
        plane_bytes,
    )
    from k8s_spot_rescheduler_tpu.solver.memory import (
        estimate_union_hbm_breakdown,
        estimate_union_hbm_bytes,
    )

    npb = plane_bytes(NARROW_LAYOUT, 4, 2)
    wide = estimate_union_hbm_breakdown(2560, 32, 2560, 4, 2, 2)
    for chunks in (1, 4, 16):
        bd = estimate_union_hbm_breakdown(
            2560, 32, 2560, 4, 2, 2,
            repair_spot_chunks=chunks, carry_chunks=chunks,
            carry_plane_bytes=npb,
        )
        assert set(bd) == set(wide)
        assert sum(bd.values()) == estimate_union_hbm_bytes(
            2560, 32, 2560, 4, 2, 2,
            repair_spot_chunks=chunks, carry_chunks=chunks,
            carry_plane_bytes=npb,
        )
        # the sharp term: narrow stacked delta planes, double-buffered
        assert bd["carries"] == 2 * npb * 2560 * 2560
        assert bd["carries"] < wide["carries"]
        # inputs/outputs are layout-independent
        for k in ("slots", "outputs", "spot_static"):
            assert bd[k] == wide[k], k
    one = estimate_union_hbm_breakdown(
        2560, 32, 2560, 4, 2, 2, carry_chunks=1, carry_plane_bytes=npb
    )
    four = estimate_union_hbm_breakdown(
        2560, 32, 2560, 4, 2, 2,
        repair_spot_chunks=4, carry_chunks=4, carry_plane_bytes=npb,
    )
    # streaming shrinks the chunk-resident terms, never the carries
    assert four["temporaries"] < one["temporaries"]
    assert four["repair"] < one["repair"]
    assert four["carries"] == one["carries"]
    # unspecified plane bytes default to the NARROW layout's
    dflt = estimate_union_hbm_breakdown(
        2560, 32, 2560, 4, 2, 2, carry_chunks=1
    )
    assert dflt["carries"] == one["carries"]


def test_should_shard_requires_mesh_and_pressure():
    from k8s_spot_rescheduler_tpu.solver.memory import should_shard

    packed, _ = _pack_drain_case(_test_spot_pool(), [500, 300])
    # tiny problem: never shards, any device count
    assert not should_shard(packed, 8)
    # past budget but single device: keep the single-chip path (honest OOM)
    assert not should_shard(packed, 1, budget_bytes=1)
    # past budget with a mesh: shard
    assert should_shard(packed, 8, budget_bytes=1)


def _drainable_fake():
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from tests.fixtures import (
        ON_DEMAND_LABEL,
        ON_DEMAND_LABELS,
        SPOT_LABEL,
        SPOT_LABELS,
        make_node,
        make_pod,
    )

    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od-2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-1", SPOT_LABELS))
    fc.add_node(make_node("spot-2", SPOT_LABELS))
    fc.add_pod(make_pod("a", 300, "od-1"))
    fc.add_pod(make_pod("b", 200, "od-1"))
    fc.add_pod(make_pod("c", 700, "od-2"))
    nodes = fc.list_ready_nodes()
    return build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )


def test_planner_auto_dispatches_to_mesh_past_budget():
    """End to end: a planner configured for the single-chip solver must
    reroute to the mesh automatically when the problem exceeds the
    (here: artificially tiny) HBM budget — same drain decision, solver
    label records the reroute."""
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    node_map = _drainable_fake()
    want = SolverPlanner(ReschedulerConfig(solver="numpy")).plan(node_map, [])

    cfg = ReschedulerConfig(solver="jax", solver_hbm_budget=1)
    planner = SolverPlanner(cfg)
    report = planner.plan(node_map, [])
    assert report.solver == "jax+sharded"
    assert planner.last_solver == "jax+sharded"
    assert report.n_feasible == want.n_feasible
    assert report.plan is not None and want.plan is not None
    assert report.plan.node.node.name == want.plan.node.node.name
    assert report.plan.assignments == want.plan.assignments
    # the reroute is observable (VERDICT r4 weak #2): the solver_mode
    # gauge names configured vs running, and repair_unavailable flags
    # the dropped repair phase for operators to alarm on
    assert _solver_mode_samples() == {("jax", "jax+sharded"): 1.0}
    assert _repair_unavailable() == 1.0


def _solver_mode_samples():
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics

    return {
        (s.labels["configured"], s.labels["running"]): s.value
        for s in metrics.solver_mode.collect()[0].samples
        if s.value  # zeroed stale pairs drop out
    }


def _repair_unavailable():
    from k8s_spot_rescheduler_tpu.metrics import registry as metrics

    return metrics.repair_unavailable.collect()[0].samples[0].value


def test_planner_auto_dispatch_off_keeps_configured_path():
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    node_map = _drainable_fake()
    cfg = ReschedulerConfig(
        solver="jax", solver_hbm_budget=1, auto_shard=False
    )
    report = SolverPlanner(cfg).plan(node_map, [])
    assert report.solver == "jax"
    assert _solver_mode_samples() == {("jax", "jax"): 1.0}
    assert _repair_unavailable() == 0.0


def test_planner_no_dispatch_under_budget():
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    node_map = _drainable_fake()
    report = SolverPlanner(ReschedulerConfig(solver="jax")).plan(node_map, [])
    assert report.solver == "jax"
    assert _solver_mode_samples() == {("jax", "jax"): 1.0}
    assert _repair_unavailable() == 0.0


# --- cand-only sharding: repair past single-chip (round 5) -----------------

def test_cand_sharded_union_repairs_greedy_failure():
    """The cand-only layout runs the COMPLETE union program per lane
    block — a lane greedy cannot prove must be repaired exactly as on a
    single chip (bit parity with the host union mirror)."""
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
    from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
        plan_union_cand_sharded,
    )
    from k8s_spot_rescheduler_tpu.solver.repair import plan_repair_oracle

    # the self-contained copy: tests/test_repair's import chain needs
    # hypothesis, which not every build image ships
    from tests.test_repair_chunked import _swap_case

    packed = _swap_case()
    assert not plan_oracle(packed).feasible[0]  # greedy fails
    mesh = make_cand_mesh()
    got = plan_union_cand_sharded(mesh, packed, rounds=8)
    want = plan_repair_oracle(packed)
    assert bool(np.asarray(got.feasible)[0])
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), want.assignment
    )


@pytest.mark.parametrize("seed", range(6))
def test_cand_sharded_union_parity_randomized(seed):
    """Randomized bit parity of the cand-sharded union against the host
    union composition (ff ∪ bf ∪ repair with first-fit preference) —
    lanes are independent forks, so sharding them must be invisible."""
    from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
    from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
        plan_union_cand_sharded,
    )
    from k8s_spot_rescheduler_tpu.solver.repair import plan_repair_oracle

    packed = _random_packed(np.random.default_rng(1000 + seed))
    mesh = make_cand_mesh()
    got = plan_union_cand_sharded(mesh, packed, rounds=8)
    ff = plan_oracle(packed)
    bf = plan_oracle(packed, best_fit=True)
    rp = plan_repair_oracle(packed, rounds=8)
    feasible = ff.feasible | bf.feasible | rp.feasible
    assignment = np.where(
        ff.feasible[:, None],
        ff.assignment,
        np.where(bf.feasible[:, None], bf.assignment, rp.assignment),
    )
    np.testing.assert_array_equal(np.asarray(got.feasible), feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), assignment)


def _repair_demanding_fake():
    """FakeCluster analog of test_repair._swap_case: greedy packs b onto
    spot-1 and strands the selector-pinned c; ejecting b unlocks the
    drain. Both greedy passes fail, repair proves it."""
    from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
    from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
    from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
    from tests.fixtures import (
        ON_DEMAND_LABEL,
        ON_DEMAND_LABELS,
        SPOT_LABEL,
        SPOT_LABELS,
        make_node,
        make_pod,
    )

    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node(
        "spot-1", dict(SPOT_LABELS, pin="1"), cpu_millis=1100
    ))
    fc.add_node(make_node("spot-2", SPOT_LABELS, cpu_millis=500))
    fc.add_pod(make_pod("a", 600, "od-1"))
    fc.add_pod(make_pod("b", 500, "od-1"))
    fc.add_pod(make_pod("c", 500, "od-1", node_selector={"pin": "1"}))
    nodes = fc.list_ready_nodes()
    return build_node_map(
        nodes,
        {n.name: fc.list_pods_on_node(n.name) for n in nodes},
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )


def test_planner_prefers_cand_sharded_when_lane_block_fits():
    """Auto-dispatch (round 5): past the HBM budget, the planner must
    prefer the cand-only layout — repair intact — whenever one lane
    block's full spot state fits a device, and only fall back to the
    2-D cand×spot layout (repair off) beyond that. Verified on a drain
    only repair can prove: the rerouted planner must find it, with the
    same placements as the host oracle stack, and repair_unavailable
    must stay 0."""
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.solver import memory
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    node_map = _repair_demanding_fake()
    want = SolverPlanner(ReschedulerConfig(solver="numpy")).plan(node_map, [])
    assert want.plan is not None  # the host stack (with repair) proves it

    # budget between the full estimate and a 1/8 lane block's estimate:
    # the reroute must fire AND choose the cand-only layout
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster

    packed, _ = pack_cluster(node_map, [], resources=("cpu", "memory"))
    C, K, S, R, W, A = memory.packed_shapes(packed)
    full = memory.estimate_union_hbm_bytes(C, K, S, R, W, A)
    lane = memory.estimate_union_hbm_bytes(-(-C // 8), K, S, R, W, A)
    assert lane < full
    budget = (lane + full) // 2

    planner = SolverPlanner(
        ReschedulerConfig(solver="jax", solver_hbm_budget=int(budget))
    )
    report = planner.plan(node_map, [])
    assert report.solver == "jax+cand-sharded"
    assert report.plan is not None
    assert report.plan.node.node.name == want.plan.node.node.name
    assert report.plan.assignments == want.plan.assignments
    assert _solver_mode_samples() == {("jax", "jax+cand-sharded"): 1.0}
    assert _repair_unavailable() == 0.0  # repair survives this layout
