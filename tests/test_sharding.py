"""Mesh-sharded solver tests on the virtual 8-device CPU platform
(conftest forces --xla_force_host_platform_device_count=8).

The sharded solver must be bit-identical to the serial oracle — the
collective election of the globally-first fitting spot node must reproduce
exact first-fit probe order across arbitrary shard boundaries.
"""

import jax
import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh, pick_mesh_shape
from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import plan_ffd_sharded
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.test_solver import _pack_drain_case, _random_packed, _test_spot_pool


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_pick_mesh_shape():
    assert pick_mesh_shape(8) == (4, 2)
    assert pick_mesh_shape(4) == (2, 2)
    assert pick_mesh_shape(2) == (2, 1)
    assert pick_mesh_shape(1) == (1, 1)


@pytest.mark.parametrize("shape", [(1, 1), (2, 1), (1, 2), (4, 2), (2, 4), (8, 1)])
def test_sharded_matches_oracle_fixture(shape):
    mesh = make_mesh(shape)
    for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
        packed, _ = _pack_drain_case(_test_spot_pool(), pods)
        want = plan_oracle(packed)
        got = jax.jit(lambda p: plan_ffd_sharded(mesh, p))(packed)
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(12))
def test_sharded_matches_oracle_randomized(seed):
    mesh = make_mesh((2, 2))
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    got = plan_ffd_sharded(mesh, packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)
