"""Required node-affinity as interned pseudo-taint bits.

The reference delegates node-affinity to the real kube-scheduler's
predicate (reference rescheduler.go:344; predicate list README.md:103-114).
Here each distinct required nodeAffinity expression set canonicalizes to
one ``NodeAffinityBit`` evaluated host-side per node — these tests pin
(a) the k8s NodeSelectorRequirement matcher semantics, (b) the decode
canonicalization, (c) oracle/packer behavior, (d) object-vs-columnar
bit parity, and (e) the end-to-end loop placing affinity pods on
matching spot nodes only.
"""

from __future__ import annotations

import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.io.kube import decode_node_affinity
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.predicates.masks import (
    match_expr,
    match_node_affinity,
)
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    pack_fake,
    ON_DEMAND_LABEL,
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
)


# --- matcher semantics (k8s labels.Requirement.Matches) -------------------

def test_match_expr_in():
    assert match_expr(("z", "In", ("a", "b")), {"z": "a"}, "")
    assert not match_expr(("z", "In", ("a", "b")), {"z": "c"}, "")
    assert not match_expr(("z", "In", ("a", "b")), {}, "")  # missing key


def test_match_expr_not_in_matches_missing_key():
    assert match_expr(("z", "NotIn", ("a",)), {"z": "b"}, "")
    assert not match_expr(("z", "NotIn", ("a",)), {"z": "a"}, "")
    assert match_expr(("z", "NotIn", ("a",)), {}, "")  # k8s: absent key matches


def test_match_expr_exists_and_absent():
    assert match_expr(("z", "Exists", ()), {"z": ""}, "")
    assert not match_expr(("z", "Exists", ()), {}, "")
    assert match_expr(("z", "DoesNotExist", ()), {}, "")
    assert not match_expr(("z", "DoesNotExist", ()), {"z": "x"}, "")


def test_match_expr_gt_lt_integer_base10():
    assert match_expr(("n", "Gt", ("5",)), {"n": "6"}, "")
    assert not match_expr(("n", "Gt", ("5",)), {"n": "5"}, "")
    assert match_expr(("n", "Lt", ("5",)), {"n": "4"}, "")
    assert not match_expr(("n", "Lt", ("5",)), {}, "")  # missing key
    assert not match_expr(("n", "Gt", ("5",)), {"n": "abc"}, "")  # unparseable


def test_match_expr_gt_lt_strict_parse_like_strconv():
    # Exact strconv.ParseInt(s, 10, 64) parity. Python's int() accepts
    # underscores, whitespace, Unicode digits, and arbitrary precision —
    # deeming those satisfying would approve a drain whose pods then
    # fail to place (non-conservative).
    assert not match_expr(("n", "Gt", ("5",)), {"n": "1_0"}, "")
    assert not match_expr(("n", "Gt", ("5",)), {"n": " 10"}, "")
    assert not match_expr(("n", "Gt", ("1_0",)), {"n": "20"}, "")
    assert not match_expr(("n", "Gt", ("5",)), {"n": "١٠"}, "")
    # int64 overflow: ParseInt returns ErrRange -> expr does not match
    assert not match_expr(("n", "Gt", ("5",)), {"n": str(2**63)}, "")
    assert match_expr(("n", "Gt", ("5",)), {"n": str(2**63 - 1)}, "")
    # Go accepts a leading '+' or '-'
    assert match_expr(("n", "Gt", ("5",)), {"n": "+10"}, "")
    assert match_expr(("n", "Gt", ("-5",)), {"n": "-4"}, "")


def test_match_terms_or_of_ands():
    terms = (
        (("a", "In", ("1",)), ("b", "Exists", ())),  # a=1 AND b present
        (("c", "In", ("9",)),),  # OR c=9
    )
    assert match_node_affinity(terms, {"a": "1", "b": "x"}, "")
    assert match_node_affinity(terms, {"c": "9"}, "")
    assert not match_node_affinity(terms, {"a": "1"}, "")  # b missing
    assert match_node_affinity((), {"anything": "1"}, "")  # no constraint


# --- decode canonicalization ---------------------------------------------

def _aff(terms):
    return {"requiredDuringSchedulingIgnoredDuringExecution": {
        "nodeSelectorTerms": terms}}


def test_decode_modeled_shape():
    terms, unmodeled = decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "zone", "operator": "In", "values": ["b", "a", "b"]},
            {"key": "arch", "operator": "Exists"},
        ]},
    ]))
    assert not unmodeled
    # values sorted+deduped, exprs sorted, Exists drops values
    assert terms == ((("arch", "Exists", ()), ("zone", "In", ("a", "b"))),)


def test_decode_equal_requirements_intern_identically():
    a, _ = decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "z", "operator": "In", "values": ["x", "y"]}]}]))
    b, _ = decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "z", "operator": "In", "values": ["y", "x"]}]}]))
    assert a == b


def test_decode_match_fields_modeled():
    """metadata.name matchFields (the one field selector k8s defines)
    canonicalize with the reserved FieldIn/FieldNotIn operators."""
    terms, unmodeled = decode_node_affinity(_aff([
        {"matchFields": [
            {"key": "metadata.name", "operator": "In",
             "values": ["n2", "n1", "n2"]}]}
    ]))
    assert not unmodeled
    assert terms == ((("metadata.name", "FieldIn", ("n1", "n2")),),)
    # mixed matchExpressions + matchFields AND within the term
    terms, unmodeled = decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "zone", "operator": "In", "values": ["a"]}],
         "matchFields": [
            {"key": "metadata.name", "operator": "NotIn", "values": ["n9"]}]}
    ]))
    assert not unmodeled
    assert terms == ((
        ("metadata.name", "FieldNotIn", ("n9",)),
        ("zone", "In", ("a",)),
    ),)


def test_match_fields_evaluation():
    terms = ((("metadata.name", "FieldIn", ("n1", "n2")),),)
    assert match_node_affinity(terms, {}, "n1")
    assert not match_node_affinity(terms, {}, "n3")
    # a label literally named metadata.name cannot shadow the field
    assert not match_node_affinity(terms, {"metadata.name": "n1"}, "n3")
    neg = ((("metadata.name", "FieldNotIn", ("n1",)),),)
    assert not match_node_affinity(neg, {}, "n1")
    assert match_node_affinity(neg, {}, "n2")


def test_decode_unmodeled_shapes():
    # matchFields on any key but metadata.name is not a thing k8s defines
    assert decode_node_affinity(_aff([
        {"matchFields": [
            {"key": "metadata.uid", "operator": "In", "values": ["x"]}]}
    ]))[1]
    # matchFields with a non-membership operator
    assert decode_node_affinity(_aff([
        {"matchFields": [
            {"key": "metadata.name", "operator": "Exists"}]}
    ]))[1]
    # matchFields with no values
    assert decode_node_affinity(_aff([
        {"matchFields": [
            {"key": "metadata.name", "operator": "In", "values": []}]}
    ]))[1]
    # unknown operator
    assert decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "z", "operator": "Glob", "values": ["*"]}]}]))[1]
    # Gt needs exactly one value
    assert decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "z", "operator": "Gt", "values": ["1", "2"]}]}]))[1]
    # In with no values (fails k8s validation)
    assert decode_node_affinity(_aff([
        {"matchExpressions": [
            {"key": "z", "operator": "In", "values": []}]}]))[1]
    # all terms empty -> requirement matches nothing
    assert decode_node_affinity(_aff([{"matchExpressions": []}]))[1]
    # no requirement at all -> modeled, empty
    assert decode_node_affinity({}) == ((), False)


# --- oracle / packer behavior --------------------------------------------

def _cluster():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-plain", SPOT_LABELS))
    fc.add_node(make_node("spot-zone-b", dict(SPOT_LABELS, zone="b")))
    return fc


def _pack(fc, **kw):
    return pack_fake(fc, **kw)


ZONE_B = ((("zone", "In", ("b",)),),)
NOT_ZONE_B = ((("zone", "NotIn", ("b",)),),)


def test_affinity_restricts_placement_to_matching_spot():
    fc = _cluster()
    fc.add_pod(make_pod("aff-pod", 300, "od-1", node_affinity=ZONE_B))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-zone-b"


def test_not_in_affinity_avoids_matching_spot():
    fc = _cluster()
    fc.add_pod(make_pod("anti-b", 300, "od-1", node_affinity=NOT_ZONE_B))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-plain"  # zone label absent: NotIn matches


def test_affinity_with_no_matching_spot_blocks_drain():
    fc = _cluster()
    fc.add_pod(make_pod("picky", 100, "od-1",
                        node_affinity=((("zone", "In", ("mars",)),),)))
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    assert not result.feasible[:1].any()


def test_two_pods_distinct_requirements_share_table():
    fc = _cluster()
    fc.add_pod(make_pod("to-b", 300, "od-1", node_affinity=ZONE_B))
    fc.add_pod(make_pod("not-b", 300, "od-1", node_affinity=NOT_ZONE_B))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    names = [meta.spot[int(result.assignment[0, k])].node.name
             for k in range(2)]
    assert sorted(names) == ["spot-plain", "spot-zone-b"]


def test_columnar_parity_with_node_affinity():
    fc = _cluster()
    fc.add_pod(make_pod("to-b", 300, "od-1", node_affinity=ZONE_B))
    fc.add_pod(make_pod("not-b", 200, "od-1", node_affinity=NOT_ZONE_B))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    fc.add_pod(make_pod("resident", 100, "spot-zone-b"))
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def test_columnar_parity_after_universe_change():
    """The sectioned caches must refresh when the affinity universe
    changes between ticks (new requirement arrives, old one drains)."""
    fc = _cluster()
    fc.add_pod(make_pod("to-b", 300, "od-1", node_affinity=ZONE_B))
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(getattr(obj, field), getattr(col, field))
    # tick 2: a different requirement joins
    fc.add_pod(make_pod("not-b", 200, "od-1", node_affinity=NOT_ZONE_B))
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(getattr(obj, field), getattr(col, field))
    # tick 3: the first requirement leaves
    fc._remove_pod("default/to-b")
    obj, _ = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


# --- end-to-end loop ------------------------------------------------------

def test_loop_drains_affinity_pod_to_matching_node():
    clock = FakeClock()
    fc = FakeCluster(clock, reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-plain", SPOT_LABELS))
    fc.add_node(make_node("spot-zone-b", dict(SPOT_LABELS, zone="b")))
    fc.add_pod(make_pod("aff-pod", 300, "od-1", node_affinity=ZONE_B))
    config = ReschedulerConfig(solver="numpy")
    r = Rescheduler(fc, SolverPlanner(config), config, clock=clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    # the fake scheduler honors the affinity too
    assert [p.name for p in fc.list_pods_on_node("spot-zone-b")] == ["aff-pod"]
    assert fc.list_pods_on_node("spot-plain") == []
    assert fc.pending == []


# --- matchFields (metadata.name) end to end -------------------------------

PIN_PLAIN = ((("metadata.name", "FieldIn", ("spot-plain",)),),)
AVOID_PLAIN = ((("metadata.name", "FieldNotIn", ("spot-plain",)),),)


def test_match_fields_pins_placement_to_named_node():
    fc = _cluster()
    fc.add_pod(make_pod("pinned", 300, "od-1", node_affinity=PIN_PLAIN))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-plain"


def test_match_fields_not_in_avoids_named_node():
    fc = _cluster()
    fc.add_pod(make_pod("averse", 300, "od-1", node_affinity=AVOID_PLAIN))
    packed, meta = _pack(fc)
    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    target = meta.spot[int(result.assignment[0, 0])].node.name
    assert target == "spot-zone-b"


def test_match_fields_no_such_node_blocks_drain():
    fc = _cluster()
    fc.add_pod(make_pod("ghost", 100, "od-1",
                        node_affinity=((("metadata.name", "FieldIn",
                                         ("no-such-node",)),),)))
    packed, _ = _pack(fc)
    result = plan_oracle(packed)
    assert not result.feasible[:1].any()


def test_match_fields_columnar_parity():
    """Two spot nodes share the SAME label profile but different names —
    the columnar node-mask cache must key by name once a Field term is
    in the universe."""
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a", SPOT_LABELS))
    fc.add_node(make_node("spot-b", SPOT_LABELS))  # identical labels
    fc.add_pod(make_pod("pin-b", 300, "od-1",
                        node_affinity=((("metadata.name", "FieldIn",
                                         ("spot-b",)),),)))
    fc.add_pod(make_pod("plain", 100, "od-1"))
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label=ON_DEMAND_LABEL,
        spot_label=SPOT_LABEL,
    )
    obj, meta = _pack(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )
    result = plan_oracle(obj)
    assert bool(result.feasible[0])
    pods = meta.cand_pods[0]
    k = next(i for i, p in enumerate(pods) if p.name == "pin-b")
    assert meta.spot[int(result.assignment[0, k])].node.name == "spot-b"


def test_match_fields_drain_through_loop():
    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.add_node(make_node("od-1", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot-a", SPOT_LABELS))
    fc.add_node(make_node("spot-b", SPOT_LABELS))
    fc.add_pod(make_pod("pin-b", 300, "od-1",
                        node_affinity=((("metadata.name", "FieldIn",
                                         ("spot-b",)),),)))
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    moved = fc.pods["default/pin-b"]
    assert moved.node_name == "spot-b"
