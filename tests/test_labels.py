"""Label matching and validation.

Mirrors reference nodes/nodes_test.go:32-56 (old/new schema matching) and
rescheduler_test.go:84-100 (validateArgs).
"""

import pytest

from k8s_spot_rescheduler_tpu.utils.labels import (
    LabelFormatError,
    matches_label,
    validate_label,
)


class TestMatchesLabel:
    def test_new_schema_value_match(self):
        labels = {"kubernetes.io/role": "spot-worker"}
        assert matches_label(labels, "kubernetes.io/role=spot-worker")
        assert not matches_label(labels, "kubernetes.io/role=worker")

    def test_old_schema_presence_match(self):
        labels = {"node-role.kubernetes.io/spot-worker": ""}
        assert matches_label(labels, "node-role.kubernetes.io/spot-worker")
        assert not matches_label(labels, "node-role.kubernetes.io/worker")

    def test_key_present_wrong_value(self):
        assert not matches_label({"role": "worker"}, "role=spot")

    def test_empty_value_selector(self):
        assert matches_label({"role": ""}, "role=")
        assert not matches_label({"role": "x"}, "role=")

    def test_missing_key(self):
        assert not matches_label({}, "role=worker")
        assert not matches_label({}, "role")


class TestValidateLabel:
    def test_accepts_bare_key(self):
        validate_label("node-role.kubernetes.io/worker")

    def test_accepts_key_value(self):
        validate_label("kubernetes.io/role=worker")

    def test_rejects_double_equals(self):
        # reference rescheduler_test.go:84-100 / rescheduler.go:407-417
        with pytest.raises(LabelFormatError):
            validate_label("kubernetes.io/role=worker=extra")
