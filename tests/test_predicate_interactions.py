"""Kitchen-sink interaction test: every modeled predicate at once.

Each predicate family has its own suite; this one pins that they
compose — one candidate node carrying a nodeSelector pod, a
metadata.name-pinned pod, a zonal-PVC pod, a positive-affinity pod, a
zone-anti-affinity pod, and a hostname-anti pod drains in a single
tick with every pod landing on a node that satisfies ALL of its
constraints, on both packers, with the oracle's plan honored end to
end.
"""

import numpy as np

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import PVCSpec, PVSpec
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.predicates.masks import ZONE_LABEL
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import (
    ON_DEMAND_LABELS,
    SPOT_LABEL,
    SPOT_LABELS,
    make_node,
    make_pod,
    pack_fake,
)


def _kitchen_sink():
    fc = FakeCluster(FakeClock(), reschedule_evicted=True)
    fc.pvs["pv-a"] = PVSpec(
        "pv-a", node_affinity=(((ZONE_LABEL, "In", ("a",)),),)
    )
    fc.pvcs["default/data"] = PVCSpec("data", "default", volume_name="pv-a")

    fc.add_node(make_node("od-1", ON_DEMAND_LABELS, cpu_millis=4000))
    # zone a: pool-labeled + hosts the app=db match the affinity pod needs
    fc.add_node(make_node(
        "spot-a1", dict(SPOT_LABELS, **{ZONE_LABEL: "a", "pool": "gold"})
    ))
    # zone b: hosts an app=cache pod (repels the zone-anti pod from b)
    fc.add_node(make_node("spot-b1", dict(SPOT_LABELS, **{ZONE_LABEL: "b"})))
    # zoneless plain node
    fc.add_node(make_node("spot-nz", SPOT_LABELS))
    fc.add_pod(make_pod("db-0", 100, "spot-a1", labels={"app": "db"}))
    fc.add_pod(make_pod("cache-b", 100, "spot-b1", labels={"app": "cache"}))

    # the candidate's pods, one per constraint family
    fc.add_pod(make_pod("sel", 200, "od-1", node_selector={"pool": "gold"}))
    fc.add_pod(make_pod("pin", 200, "od-1", node_affinity=(
        (("metadata.name", "FieldIn", ("spot-nz",)),),
    )))
    fc.add_pod(make_pod("vol", 200, "od-1", pvc_names=("data",),
                        pvc_resolvable=True, unmodeled_constraints=True))
    fc.add_pod(make_pod("buddy", 200, "od-1",
                        pod_affinity_match={"app": "db"}))
    fc.add_pod(make_pod("spread", 200, "od-1", labels={"app": "web"},
                        anti_affinity_zone_match={"app": "cache"}))
    fc.add_pod(make_pod("hostanti", 200, "od-1",
                        anti_affinity_match={"app": "db"},
                        labels={"tier": "x"}))
    return fc


EXPECTED = {
    "default/sel": {"spot-a1"},  # only pool=gold node
    "default/pin": {"spot-nz"},  # metadata.name pin
    "default/vol": {"spot-a1"},  # zonal volume -> zone a
    "default/buddy": {"spot-a1"},  # must join app=db
    "default/spread": {"spot-a1", "spot-nz"},  # zone b hosts app=cache
    "default/hostanti": {"spot-b1", "spot-nz"},  # not beside app=db
}


def test_all_predicates_compose_in_one_plan():
    fc = _kitchen_sink()
    packed, meta = pack_fake(fc)
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle

    result = plan_oracle(packed)
    assert bool(result.feasible[0])
    pods = meta.cand_pods[0]
    for k, pod in enumerate(pods):
        target = meta.spot[int(result.assignment[0, k])].node.name
        assert target in EXPECTED[pod.uid], (pod.uid, target)


def test_columnar_parity_kitchen_sink():
    fc = _kitchen_sink()
    store = fc.columnar_store(
        ("cpu", "memory"),
        on_demand_label="kubernetes.io/role=worker",
        spot_label=SPOT_LABEL,
    )
    obj, _ = pack_fake(fc)
    col, _ = store.pack(fc.pdbs)
    for field in obj._fields:
        np.testing.assert_array_equal(
            getattr(obj, field), getattr(col, field), err_msg=field
        )


def test_drain_through_loop_honors_every_constraint():
    fc = _kitchen_sink()
    cfg = ReschedulerConfig(solver="numpy", node_drain_delay=0.0)
    r = Rescheduler(fc, SolverPlanner(cfg), cfg, clock=fc.clock, recorder=fc)
    result = r.tick()
    assert result.drained == ["od-1"]
    fc.clock.advance(10.0)
    for uid, allowed in EXPECTED.items():
        assert fc.pods[uid].node_name in allowed, (
            uid, fc.pods[uid].node_name
        )
