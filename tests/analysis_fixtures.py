"""Shared fixture helpers for the two static-analysis gates.

tests/test_lint.py (the fmt/lint half) and tests/test_analysis.py (the
vet half, both tiers) seed violation trees and drive the tools as
subprocesses the same way ``make check`` does; this module is the ONE
copy of that machinery so the two gates stop carrying parallel
implementations.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def run_lint(*roots):
    """tools/lint.py over the given roots (default: the whole repo)."""
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), *map(str, roots)],
        capture_output=True,
        text=True,
        cwd=REPO,
    )


def run_analysis(*args):
    """python -m tools.analysis with the given CLI args, from the repo
    root (the module path and default roots depend on the cwd)."""
    return subprocess.run(
        [sys.executable, "-m", "tools.analysis", *map(str, args)],
        capture_output=True,
        text=True,
        cwd=REPO,
    )


def seed_tree(tmp_path, rel, source):
    """Write a dedented fixture file at ``tmp_path/rel``."""
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def lint_file(tmp_path, source: str, name="seeded.py"):
    """Seed one file (verbatim — format tests need exact bytes) and
    lint it."""
    f = tmp_path / name
    f.write_text(source)
    return run_lint(f)


def analyze_tree(tmp_path, *extra, tier="ast"):
    """Analyze a fixture tree: no baseline, the fixture's parity and
    observability files (created empty when the fixture ships none —
    flight-contract fixtures must document their own kinds, and the
    real repo's docs must never leak into a fixture), ast tier unless
    the test says otherwise (fixture trees exercise one tier at a
    time; the real-tree gate runs all three)."""
    parity = tmp_path / "PARITY.md"
    if not parity.exists():
        parity.write_text("")
    obs = tmp_path / "OBSERVABILITY.md"
    if not obs.exists():
        obs.write_text("")
    return run_analysis(
        tmp_path, "--no-baseline", "--parity", parity,
        "--observability", obs, "--tier", tier,
        *extra,
    )


def seed_jaxpr_manifest(tmp_path, source, *extra, name="manifest.py"):
    """Seed a HOT_PROGRAMS manifest module and run the jaxpr tier over
    it (the fixture tree is also the walked root, so ``# noqa`` on
    manifest lines participates exactly as in-tree)."""
    f = seed_tree(tmp_path, name, source)
    return f, run_analysis(
        tmp_path, "--tier", "jaxpr", "--manifest", f, "--no-baseline",
        *extra,
    )
