"""Pallas kernel parity tests (interpret mode on CPU; the same kernel
compiles for TPU — bench runs it there)."""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.ops.pallas_ffd import plan_ffd_pallas
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.test_solver import _pack_drain_case, _random_packed, _test_spot_pool


def test_pallas_matches_fixture():
    for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
        packed, _ = _pack_drain_case(_test_spot_pool(), pods)
        want = plan_oracle(packed)
        got = plan_ffd_pallas(packed)
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(15))
def test_pallas_matches_oracle_randomized(seed):
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    got = plan_ffd_pallas(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


def test_vmem_guard_thresholds():
    from k8s_spot_rescheduler_tpu.ops.pallas_ffd import needs_scan_fallback

    # north-star shapes stay on the kernel; 2x falls back to the scan
    assert not needs_scan_fallback(2560, 2560, 2, 2)
    assert needs_scan_fallback(5120, 5120, 2, 2)
    # small problems never fall back
    assert not needs_scan_fallback(8, 8, 3, 2)


def test_repeated_solve_deterministic():
    """SURVEY §5.2: determinism in place of race detection — identical
    inputs must give bit-identical plans on every solve and solver."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit

    packed = _random_packed(np.random.default_rng(123))
    a = plan_ffd_jit(packed)
    b = plan_ffd_jit(packed)
    c = plan_ffd_pallas(packed)
    np.testing.assert_array_equal(np.asarray(a.feasible), np.asarray(b.feasible))
    np.testing.assert_array_equal(np.asarray(a.assignment), np.asarray(b.assignment))
    np.testing.assert_array_equal(np.asarray(a.assignment), np.asarray(c.assignment))
