"""Pallas kernel parity tests (interpret mode on CPU; the same kernel
compiles for TPU — bench runs it there)."""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.ops.pallas_ffd import plan_ffd_pallas
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.test_solver import _pack_drain_case, _random_packed, _test_spot_pool


def test_pallas_matches_fixture():
    for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
        packed, _ = _pack_drain_case(_test_spot_pool(), pods)
        want = plan_oracle(packed)
        got = plan_ffd_pallas(packed)
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(15))
def test_pallas_matches_oracle_randomized(seed):
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    got = plan_ffd_pallas(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)
