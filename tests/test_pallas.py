"""Pallas kernel parity tests (interpret mode on CPU; the same kernel
compiles for TPU — bench runs it there)."""

import numpy as np
import pytest

from k8s_spot_rescheduler_tpu.ops.pallas_ffd import plan_ffd_pallas
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from tests.test_solver import _pack_drain_case, _random_packed, _test_spot_pool


def test_pallas_matches_fixture():
    for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
        packed, _ = _pack_drain_case(_test_spot_pool(), pods)
        want = plan_oracle(packed)
        got = plan_ffd_pallas(packed)
        np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
        np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


@pytest.mark.parametrize("seed", range(15))
def test_pallas_matches_oracle_randomized(seed):
    packed = _random_packed(np.random.default_rng(seed))
    want = plan_oracle(packed)
    got = plan_ffd_pallas(packed)
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(np.asarray(got.assignment), want.assignment)


def test_vmem_guard_thresholds():
    from k8s_spot_rescheduler_tpu.ops.pallas_ffd import needs_scan_fallback

    # north-star shapes stay on the kernel; 2x falls back to the scan
    assert not needs_scan_fallback(2560, 2560, 2, 2)
    assert needs_scan_fallback(5120, 5120, 2, 2)
    # small problems never fall back
    assert not needs_scan_fallback(8, 8, 3, 2)


def test_repeated_solve_deterministic():
    """SURVEY §5.2: determinism in place of race detection — identical
    inputs must give bit-identical plans on every solve and solver."""
    import numpy as np

    from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit

    packed = _random_packed(np.random.default_rng(123))
    a = plan_ffd_jit(packed)
    b = plan_ffd_jit(packed)
    c = plan_ffd_pallas(packed)
    np.testing.assert_array_equal(np.asarray(a.feasible), np.asarray(b.feasible))
    np.testing.assert_array_equal(np.asarray(a.assignment), np.asarray(b.assignment))
    np.testing.assert_array_equal(np.asarray(a.assignment), np.asarray(c.assignment))


def test_chunked_first_fit_matches_oracle(monkeypatch):
    """First-fit decomposes exactly over ordered spot chunks
    (ops/pallas_ffd._plan_ffd_chunked): per-spot state is independent
    across chunks and first-fit prefers earlier spots, so chunked
    placement is bit-identical to the global solve. Forced here onto
    multi-chunk splits via a tiny VMEM budget, in interpret mode."""
    import k8s_spot_rescheduler_tpu.ops.pallas_ffd as pf
    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle

    rng = np.random.default_rng(7)
    # tiny budget -> Sc floors at 128 -> S=384 gives 3 chunks
    monkeypatch.setattr(pf, "_VMEM_BUDGET", 1)
    for trial in range(6):
        base = _random_packed(rng)
        C, K, R = base.slot_req.shape
        S = 384
        packed = base._replace(
            spot_free=rng.integers(-100, 2000, (S, R)).astype(np.float32),
            spot_count=rng.integers(0, 5, (S,)).astype(np.int32),
            spot_max_pods=rng.integers(1, 8, (S,)).astype(np.int32),
            spot_taints=rng.integers(0, 4, (S, 1)).astype(np.uint32),
            spot_ok=rng.random((S,)) < 0.6,
            spot_aff=(
                np.uint32(1) << rng.integers(0, 32, (S, 2)).astype(np.uint32)
            ) * (rng.random((S, 2)) < 0.3),
        )
        got = pf._plan_ffd_chunked(packed, interpret=True)
        want = plan_oracle(packed)
        np.testing.assert_array_equal(
            np.asarray(got.feasible), want.feasible, err_msg=f"t{trial}"
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), want.assignment, err_msg=f"t{trial}"
        )


def test_stream_bf_matches_every_chunk_count():
    """The fused elect-then-commit stream kernel is bit-identical to
    the XLA carry-streamed best-fit scan at EVERY chunk count (the
    strict-< lexicographic chunk election IS the global first-min
    argmin), to the unstreamed plan_ffd(best_fit=True), and to the
    host oracle."""
    from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
        plan_stream_bf_pallas,
    )
    from k8s_spot_rescheduler_tpu.solver.ffd import (
        carry_layout,
        plan_ffd,
        plan_ffd_streamed,
    )
    from tests.test_carry_stream import CHUNK_COUNTS

    for seed in range(8):
        packed = _random_packed(np.random.default_rng(seed))
        lay = carry_layout(packed)
        got = plan_stream_bf_pallas(packed, layout=lay, interpret=True)
        for n in CHUNK_COUNTS:
            want = plan_ffd_streamed(
                packed, carry_chunks=n, layout=lay, best_fit=True
            )
            np.testing.assert_array_equal(
                np.asarray(got.feasible), np.asarray(want.feasible),
                err_msg=f"seed {seed} chunks {n}",
            )
            np.testing.assert_array_equal(
                np.asarray(got.assignment), np.asarray(want.assignment),
                err_msg=f"seed {seed} chunks {n}",
            )
        flat = plan_ffd(packed, best_fit=True)
        oracle = plan_oracle(packed, best_fit=True)
        np.testing.assert_array_equal(
            np.asarray(got.feasible), np.asarray(flat.feasible)
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(flat.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.feasible), oracle.feasible
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), oracle.assignment
        )


def test_stream_bf_edge_cases():
    """Handcrafted chunk-boundary packs (tests/test_carry_stream): the
    kernel must reproduce the oracle where leftovers straddle chunk
    splits and where ties must resolve to the earlier probe index."""
    from k8s_spot_rescheduler_tpu.ops.pallas_ffd import (
        plan_stream_bf_pallas,
    )
    from k8s_spot_rescheduler_tpu.solver.ffd import carry_layout
    from tests.test_carry_stream import _edge_pack, _leftover_case

    cases = [
        _leftover_case(),
        _edge_pack(100.0, 3, 100.0),
        _edge_pack(1.0, 1, 3.0),
    ]
    for pods in ([500, 300, 100, 100, 100], [500, 400, 100, 100, 100]):
        packed, _ = _pack_drain_case(_test_spot_pool(), pods)
        cases.append(packed)
    for i, packed in enumerate(cases):
        got = plan_stream_bf_pallas(
            packed, layout=carry_layout(packed), interpret=True
        )
        want = plan_oracle(packed, best_fit=True)
        np.testing.assert_array_equal(
            np.asarray(got.feasible), want.feasible, err_msg=f"case {i}"
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), want.assignment, err_msg=f"case {i}"
        )


def test_stream_bf_vmem_guard_falls_back(monkeypatch):
    """Past the VMEM budget the stream solve must route to the XLA
    carry-streamed scan (bit-identical), not the kernel."""
    import k8s_spot_rescheduler_tpu.ops.pallas_ffd as pf
    from k8s_spot_rescheduler_tpu.solver.ffd import carry_layout

    packed = _random_packed(np.random.default_rng(11))
    lay = carry_layout(packed)
    want = plan_oracle(packed, best_fit=True)

    monkeypatch.setattr(pf, "_VMEM_BUDGET", 1)
    calls = []
    real_invoke = pf._invoke_kernel
    monkeypatch.setattr(
        pf, "_invoke_kernel",
        lambda *a, **kw: calls.append("kernel") or real_invoke(*a, **kw),
    )
    got = pf.plan_stream_bf_pallas(packed, layout=lay, interpret=True)
    assert calls == []  # guard took the scan fallback, never the kernel
    np.testing.assert_array_equal(np.asarray(got.feasible), want.feasible)
    np.testing.assert_array_equal(
        np.asarray(got.assignment), want.assignment
    )


def test_streamed_union_use_pallas_parity():
    """The full streamed union (first-fit ∪ best-fit ∪ repair) with
    ``use_pallas`` must match the XLA composition lane for lane — the
    dispatch swap the ``pallas`` solver takes in
    planner/solver_planner._carry_streamed_fused_planner."""
    from k8s_spot_rescheduler_tpu.solver.fallback import (
        with_repair_streamed,
    )
    from k8s_spot_rescheduler_tpu.solver.ffd import carry_layout

    for seed in (0, 5, 9):
        packed = _random_packed(np.random.default_rng(seed))
        lay = carry_layout(packed)
        xla = with_repair_streamed(2, 3, lay, use_pallas=False)(packed)
        pls = with_repair_streamed(2, 3, lay, use_pallas=True)(packed)
        np.testing.assert_array_equal(
            np.asarray(pls.feasible), np.asarray(xla.feasible),
            err_msg=f"seed {seed}",
        )
        np.testing.assert_array_equal(
            np.asarray(pls.assignment), np.asarray(xla.assignment),
            err_msg=f"seed {seed}",
        )


def test_oversize_first_fit_routes_to_chunked(monkeypatch):
    """On TPU-sized problems past the VMEM budget, first-fit must take
    the chunked kernel path and best-fit the scan fallback."""
    import k8s_spot_rescheduler_tpu.ops.pallas_ffd as pf

    calls = []
    monkeypatch.setattr(pf, "_VMEM_BUDGET", 1)
    monkeypatch.setattr(
        pf, "_plan_ffd_chunked",
        lambda packed, interpret: calls.append("chunked") or None,
    )
    rng = np.random.default_rng(3)
    base = _random_packed(rng)
    C, K, R = base.slot_req.shape
    packed = base._replace(
        spot_free=np.zeros((256, R), np.float32),
        spot_count=np.zeros(256, np.int32),
        spot_max_pods=np.ones(256, np.int32),
        spot_taints=np.zeros((256, 1), np.uint32),
        spot_ok=np.ones(256, bool),
        spot_aff=np.zeros((256, 2), np.uint32),
    )
    pf.plan_ffd_pallas(packed, interpret=False, best_fit=False)
    assert calls == ["chunked"]
    # best-fit: global election does not decompose -> scan fallback
    out = pf.plan_ffd_pallas(packed, interpret=False, best_fit=True)
    assert calls == ["chunked"]  # chunked not called again
    assert out is not None
