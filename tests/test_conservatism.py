"""Conservatism observability (VERDICT round-2 task 4).

The planner's safe-direction over-approximations (unmodeled constraints
pack as placeable-nowhere) can silently pin the controller at zero
drains. These tests pin the why-no-drain metrics: an operator reading
/metrics must see unplaceable-pod counts and per-reason blocked-candidate
counts — the reference only logs the blocking pod per node
(rescheduler.go:232-238).
"""


import pytest
from prometheus_client import REGISTRY

from k8s_spot_rescheduler_tpu.io.fake import FakeCluster
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.models.cluster import PDBSpec
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.clock import FakeClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from tests.fixtures import ON_DEMAND_LABELS, SPOT_LABELS, make_node, make_pod


def _value(name, labels=None):
    return REGISTRY.get_sample_value(f"spot_rescheduler_{name}", labels or {})


def _blocked(reason):
    return _value("blocked_candidates", {"reason": reason})


def _tick(fc, *, use_columnar):
    cfg = ReschedulerConfig(solver="numpy", use_columnar=use_columnar)
    clock = fc.clock
    return Rescheduler(fc, SolverPlanner(cfg), cfg, clock=clock).tick()


@pytest.mark.parametrize("use_columnar", [True, False])
def test_unmodeled_pod_counts_as_unplaceable(use_columnar):
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot", SPOT_LABELS))
    fc.add_pod(make_pod("poison", 100, "od", unmodeled_constraints=True))
    fc.add_pod(make_pod("fine", 100, "od"))
    result = _tick(fc, use_columnar=use_columnar)
    assert not result.drained
    assert _value("unplaceable_pods") == 1
    assert _blocked("unmodeled") == 1
    assert _blocked("no-capacity") == 0
    assert _blocked("pdb") == 0


@pytest.mark.parametrize("use_columnar", [True, False])
def test_pdb_and_nonreplicated_reasons(use_columnar):
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od1", ON_DEMAND_LABELS))
    fc.add_node(make_node("od2", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot", SPOT_LABELS, cpu_millis=4000))
    fc.add_pod(make_pod("pdbpod", 100, "od1", labels={"app": "a"}))
    fc.pdbs.append(
        PDBSpec(name="pdb-a", namespace="default",
                match_labels={"app": "a"}, disruptions_allowed=0)
    )
    fc.add_pod(make_pod("bare", 100, "od2", replicated=False))
    result = _tick(fc, use_columnar=use_columnar)
    assert not result.drained
    assert _blocked("pdb") == 1
    assert _blocked("non-replicated") == 1
    assert _value("unplaceable_pods") == 0


@pytest.mark.parametrize("use_columnar", [True, False])
def test_no_capacity_reason(use_columnar):
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot", SPOT_LABELS, cpu_millis=500))
    fc.add_pod(make_pod("big", 1800, "od"))
    result = _tick(fc, use_columnar=use_columnar)
    assert not result.drained
    assert _blocked("no-capacity") == 1
    assert _blocked("unmodeled") == 0
    assert _value("unplaceable_pods") == 0


def test_gauges_reset_when_cluster_recovers():
    fc = FakeCluster(FakeClock())
    fc.add_node(make_node("od", ON_DEMAND_LABELS))
    fc.add_node(make_node("spot", SPOT_LABELS, cpu_millis=500))
    fc.add_pod(make_pod("big", 1800, "od"))
    _tick(fc, use_columnar=True)
    assert _blocked("no-capacity") == 1
    # capacity arrives: the blocked count must drop back to zero
    fc.add_node(make_node("spot2", SPOT_LABELS, cpu_millis=4000))
    result = _tick(fc, use_columnar=True)
    assert result.drained == ["od"]
    assert _blocked("no-capacity") == 0
    assert _value("unplaceable_pods") == 0
