"""Controller health state, shared process-wide.

The control loop's degradation machinery (planner fallback, observe-error
circuit breaker, taint recovery — loop/controller.py) needs a surface an
operator's probe can read without scraping Prometheus: the sidecar's
``GET /healthz`` (sidecar/server.py) merges ``snapshot()`` into its
response, so a kubelet liveness/readiness probe sees ``degraded`` and
the last-successful-tick age directly.

One module-level ``STATE`` because one controller runs per process
(leader election guarantees one actor per cluster); tests reset it via
``STATE.reset()``. Timestamps come from the controller's injected clock
(``set_clock``) so virtual-clock tests read coherent ages.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class HealthState:
    def __init__(self):
        self._lock = threading.Lock()
        self._now: Optional[Callable[[], float]] = None
        # degraded = OR over independent causes — tracked per cause, so
        # a recovering breaker clears its half without masking a
        # still-fallback planner (and vice versa): planner fallback,
        # breaker engaged, watch mirror past its freshness budget, and
        # the sticky startup watch-sync fallback.
        self._fallback_degraded = False
        self._breaker_degraded = False
        self._freshness_degraded = False
        self._startup_degraded = False
        self.degraded = False
        self.last_success: Optional[float] = None
        self.planner_fallback_total = 0
        self.consecutive_errors = 0
        self.breaker_interval: Optional[float] = None
        self.taints_recovered_total = 0
        self.mirror_staleness_s: Optional[float] = None
        # last dispatched solver program (planner/solver_planner):
        # running label + the carry-streamed tier's chunk count and
        # estimated resident carry bytes — mirrored beside the
        # solver_mode / solver_carry_* gauges from the SAME call site
        self.solver_mode: Optional[str] = None
        self.carry_chunks = 0
        self.solver_carry_bytes: Optional[int] = None

    def reset(self) -> None:
        """Back to process-start state (test isolation)."""
        with self._lock:
            self._now = None
            self._fallback_degraded = False
            self._breaker_degraded = False
            self._freshness_degraded = False
            self._startup_degraded = False
            self.degraded = False
            self.last_success = None
            self.planner_fallback_total = 0
            self.consecutive_errors = 0
            self.breaker_interval = None
            self.taints_recovered_total = 0
            self.mirror_staleness_s = None
            self.solver_mode = None
            self.carry_chunks = 0
            self.solver_carry_bytes = None
        self._mirror_gauge(False)

    def set_clock(self, now_fn: Callable[[], float]) -> None:
        with self._lock:
            self._now = now_fn

    def _clock(self) -> float:
        return (self._now or time.monotonic)()

    @staticmethod
    def _mirror_gauge(degraded: bool) -> None:
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        metrics.update_degraded(degraded)

    def _degraded_locked(self) -> bool:
        """Recompute the OR over causes; caller holds the lock."""
        self.degraded = (
            self._fallback_degraded
            or self._breaker_degraded
            or self._freshness_degraded
            or self._startup_degraded
        )
        return self.degraded

    def note_success(self, *, fallback: bool = False) -> None:
        """A tick completed (observe + plan + actuate all ran).
        ``fallback``: the plan came from the CPU fallback planner — the
        tick counts as degraded until a clean primary tick follows.
        (``planner_fallback_total`` is driven by ``note_planner_fallback``
        per contained exception, not here.)"""
        with self._lock:
            self.last_success = self._clock()
            self.consecutive_errors = 0
            self.breaker_interval = None
            self._breaker_degraded = False
            self._fallback_degraded = bool(fallback)
            degraded = self._degraded_locked()
        self._mirror_gauge(degraded)

    def note_planner_fallback(self) -> None:
        """One contained planner exception — called alongside
        ``metrics.update_planner_fallback()`` from the same event, so
        /healthz and the Prometheus counter of the same name agree."""
        with self._lock:
            self.planner_fallback_total += 1

    def note_observe_ok(self) -> None:
        """Observation succeeded but a healthy gate skipped the tick
        (unschedulable pods pending): the apiserver is provably fine, so
        the observe-error breaker resets — while any fallback-planner
        degradation stands until a tick actually completes."""
        with self._lock:
            self.consecutive_errors = 0
            self.breaker_interval = None
            self._breaker_degraded = False
            degraded = self._degraded_locked()
        self._mirror_gauge(degraded)

    def note_error(
        self, consecutive: int, breaker_interval: Optional[float] = None
    ) -> None:
        """A tick was skipped on an observe/plan error. ``breaker_interval``
        is the widened housekeeping interval when the circuit breaker is
        engaged (None below threshold)."""
        with self._lock:
            self.consecutive_errors = int(consecutive)
            self.breaker_interval = breaker_interval
            self._breaker_degraded = breaker_interval is not None
            degraded = self._degraded_locked()
        self._mirror_gauge(degraded)

    def note_mirror_staleness(self, staleness: float, budget: float) -> None:
        """The freshness gate's per-tick verdict: the watch mirror's age
        versus its budget. Over-budget marks the loop degraded until a
        later gate finds the mirror fresh again — the bypassed ticks
        still complete, so ``note_success`` alone must not clear it."""
        with self._lock:
            self.mirror_staleness_s = (
                None if staleness == float("inf") else round(staleness, 3)
            )
            self._freshness_degraded = budget > 0 and staleness > budget
            degraded = self._degraded_locked()
        self._mirror_gauge(degraded)

    def note_startup_degraded(self) -> None:
        """The watch caches failed to sync at startup and the loop fell
        back to the polling client — sticky for the process lifetime
        (the cache path never re-engages without a restart)."""
        with self._lock:
            self._startup_degraded = True
            degraded = self._degraded_locked()
        self._mirror_gauge(degraded)

    def note_solver_mode(
        self, running: str, carry_chunks: int, carry_bytes: int
    ) -> None:
        """What the last solve actually ran (the dispatch ladder's
        verdict), called beside ``metrics.update_solver_mode`` so
        /healthz and the gauges agree. Negative ``carry_bytes`` =
        estimate unavailable (non-auto-shard paths) — left as-is."""
        with self._lock:
            self.solver_mode = running
            self.carry_chunks = int(carry_chunks)
            if carry_bytes >= 0:
                self.solver_carry_bytes = int(carry_bytes)

    def note_taint_recovered(self) -> None:
        with self._lock:
            self.taints_recovered_total += 1

    def snapshot(self) -> dict:
        """JSON-ready view for /healthz."""
        with self._lock:
            age = (
                None
                if self.last_success is None
                else max(0.0, self._clock() - self.last_success)
            )
            return {
                "degraded": self.degraded,
                "last_successful_tick_age_s": (
                    None if age is None else round(age, 3)
                ),
                "planner_fallback_total": self.planner_fallback_total,
                "consecutive_tick_errors": self.consecutive_errors,
                "breaker_interval_s": self.breaker_interval,
                "taints_recovered_total": self.taints_recovered_total,
                "mirror_staleness_s": self.mirror_staleness_s,
                "solver_mode": self.solver_mode,
                "carry_chunks": self.carry_chunks,
                "solver_carry_bytes": self.solver_carry_bytes,
            }


STATE = HealthState()


def snapshot() -> dict:
    return STATE.snapshot()
