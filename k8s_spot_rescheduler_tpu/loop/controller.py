"""The housekeeping control loop.

Reimplements the reference's ``run`` (reference rescheduler.go:144-293) —
the level-triggered observe → plan → actuate tick — against the
ClusterClient/Planner interfaces:

per tick:
1. gate: drain-delay cooldown still running → skip (rescheduler.go:167-170);
2. gate: any unschedulable pods → skip, don't make things worse
   (rescheduler.go:172-181);
3. observe: list ready nodes, build the classified node map
   (rescheduler.go:186-199), update metrics (202), list PDBs (205);
4. plan: prove per-candidate drain feasibility (the Planner replaces the
   canDrainNode/findSpotNodeForPod nest, rescheduler.go:228-275);
5. actuate: drain the first feasible node, arm the cooldown, stop — at
   most ``max_drains_per_tick`` (=1, faithful) drains per tick
   (rescheduler.go:280-286);
6. any observation error skips the tick (`continue`), never crashes the
   loop — the recovery story is "recompute everything next tick"
   (SURVEY.md §5.3).

Chaos hardening beyond the reference (docs/ROBUSTNESS.md):

- a planner exception degrades the tick to the CPU numpy-oracle fallback
  planner instead of killing ``run_forever`` (``planner_fallback_total``;
  /healthz reports ``degraded: true`` until a clean primary tick);
- consecutive error-skipped ticks past ``breaker_threshold`` engage a
  circuit breaker that doubles the effective housekeeping interval per
  further failure, capped at ``breaker_max_interval``, resetting on the
  next completed tick;
- on startup and once per tick, orphaned ``ToBeDeleted`` taints are
  removed (``ReschedulerRecovered`` event) — a drain interrupted between
  taint and cleanup must not permanently unschedule an on-demand node
  (the reference leaves that residue for the cluster autoscaler to
  collect). Ownership is explicit: the drain stamps the taint value
  with a rescheduler marker + holder identity + wall timestamp, and the
  sweep only ever removes taints carrying that marker — the cluster
  autoscaler applies the SAME taint key during its own scale-downs
  (on-demand nodes included: a drained-empty node is exactly what CA is
  expected to delete), and stripping CA's taint would abort the
  scale-down that is the product's end goal. Another replica's marked
  taint (HA: a demoted leader may still be mid-drain) is only swept once
  older than any drain could run.
"""

from __future__ import annotations

import dataclasses
import socket
import time
from typing import List, Optional

from k8s_spot_rescheduler_tpu.actuator.drain import DrainError, drain_node
from k8s_spot_rescheduler_tpu.io.cluster import ClusterClient, EventSink
from k8s_spot_rescheduler_tpu.loop import flight, health
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeMap,
    TO_BE_DELETED_TAINT,
    build_node_map,
    parse_rescheduler_taint_value,
    rescheduler_taint_identity,
)
from k8s_spot_rescheduler_tpu.models.evictability import get_pods_for_deletion
from k8s_spot_rescheduler_tpu.planner.base import Planner, PlanReport
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


@dataclasses.dataclass
class TickResult:
    """What one housekeeping pass did (the loop's unit-test surface)."""

    skipped: str = ""  # "", "cooldown", "unschedulable", "error"
    drained: List[str] = dataclasses.field(default_factory=list)
    drain_failed: List[str] = dataclasses.field(default_factory=list)
    report: Optional[PlanReport] = None
    # this tick's plan came from the CPU fallback planner (the configured
    # planner raised and was contained)
    planner_fallback: bool = False
    # orphaned ToBeDeleted taints the pre-tick sweep removed
    recovered_taints: List[str] = dataclasses.field(default_factory=list)


class _NullRecorder:
    def event(self, kind, name, event_type, reason, message):
        pass


class Rescheduler:
    def __init__(
        self,
        client: ClusterClient,
        planner: Planner,
        config: ReschedulerConfig,
        *,
        clock: Optional[Clock] = None,
        recorder: Optional[EventSink] = None,
        startup_sweep: bool = True,
        identity: Optional[str] = None,
    ):
        self.client = client
        self.planner = planner
        self.config = config
        self.clock = clock or RealClock()
        self.recorder = recorder or _NullRecorder()
        # stable holder id stamped into drain taints (ownership for the
        # orphan sweep). Must survive a restart of the same replica —
        # the startup sweep heals OUR orphans immediately — and differ
        # between HA replicas, so the hostname (pod name), overridable
        # via --leader-elect-identity.
        self.identity = identity or socket.gethostname()
        # start processing straight away (rescheduler.go:158-159)
        self.next_drain_time = self.clock.now()
        # --- chaos hardening state ---
        # error-skipped ticks in a row (feeds the circuit breaker)
        self._consecutive_errors = 0
        # lazily-built CPU fallback planner (planner crash containment)
        self._fallback_planner = None
        # nodes a drain is actively running on: the orphaned-taint sweep
        # must never untaint a drain in progress (single-threaded today,
        # so empty at every sweep — load-bearing if actuation ever forks)
        self._active_drains: set = set()
        # pending drain schedule (planner/schedule.py): cut by
        # plan_schedule in one device fetch, executed across ticks with
        # per-step live validation; dropped on invalidation/exhaustion
        self._schedule = None
        # churn hysteresis for the default-on schedule path: a schedule
        # churn kills before it served 2 steps wasted a horizon-deep
        # sweep for at most one drain, and under CONSTANT churn (replay-
        # grade event streams) that waste would recur every tick. Each
        # such early invalidation doubles a per-tick-planning backoff
        # window (capped); a schedule that serves >= 2 steps — or runs
        # to exhaustion — resets it. Amortized schedule overhead under
        # constant churn is therefore bounded at ~horizon/cap extra
        # solves per tick instead of horizon per tick.
        self._sched_backoff = 0  # ticks left planning per-tick
        self._sched_backoff_next = 1  # next window on early invalidation
        # --- freshness gate state (docs/ROBUSTNESS.md) ---
        # the client this tick's READS go to: the configured client, or
        # its direct (cache-bypassing) twin while the watch mirror is
        # staler than mirror_staleness_budget; writes always go to
        # self.client
        self._observe_client = client
        # next anti-entropy audit, wall clock; armed on the first tick
        # (the startup LIST is itself fresh)
        self._next_resync_wall: Optional[float] = None
        health.STATE.set_clock(self.clock.now)
        # flight recorder (loop/flight.py): ring size + dump dir come
        # from config; recorded history survives reconstruction (the
        # chaos soak restarts the controller mid-run)
        flight.configure(
            ring_size=config.flight_ring_size,
            dump_dir=config.flight_dump_dir,
        )
        if config.reconcile_orphaned_taints and startup_sweep:
            # startup sweep: a previous process may have died mid-drain,
            # leaving a ToBeDeleted taint nobody owns. ``startup_sweep``
            # is passed False by HA deployments for non-leader replicas
            # (a follower must not write — the per-tick sweep runs once
            # it is leader-gated into ticking); single-replica callers
            # keep the default and heal immediately on restart.
            self.reconcile_orphaned_taints()

    # --- observation ---

    def _columnar_store(self):
        """The vectorized observe path (models/columnar.py): used when the
        client maintains a columnar mirror, the planner can consume it,
        and the config hasn't forced the object path."""
        if not self.config.use_columnar:
            return None
        if self._observe_client is not self.client:
            # freshness bypass in effect: the mirror is the thing being
            # bypassed — this tick observes via direct LISTs only
            return None
        if not getattr(self.planner, "accepts_columnar", False):
            return None
        factory = getattr(self.client, "columnar_store", None)
        if factory is None:
            return None
        try:
            return factory(
                self.config.resources,
                on_demand_label=self.config.on_demand_node_label,
                spot_label=self.config.spot_node_label,
            )
        except Exception as err:  # noqa: BLE001, exception-discipline — fall back to objects: the reference-faithful observe path runs instead; nothing is lost, only vectorization
            log.error("Columnar observe unavailable: %s", err)
            return None

    def observe(self) -> Optional[NodeMap]:
        client = self._observe_client
        try:
            nodes = client.list_ready_nodes()
            # not-ready nodes are presence-only (zone/spread counts —
            # their pods still exist to the real scheduler). All in-tree
            # clients implement the lister; the fallback exists for
            # third-party clients, whose spread/zone verdicts then rest
            # on ready-node visibility alone.
            lister = getattr(client, "list_unready_nodes", None)
            unready = lister() if lister is not None else []
            pods_by_node = {
                n.name: client.list_pods_on_node(n.name)
                for n in list(nodes) + list(unready)
            }
        except Exception as err:  # noqa: BLE001, exception-discipline — skip tick on any API error: the None return flows into the skipped="error" path whose breaker/health accounting (note_error) records it
            log.error("Failed to list cluster state: %s", err)
            return None
        return build_node_map(
            nodes,
            pods_by_node,
            on_demand_label=self.config.on_demand_node_label,
            spot_label=self.config.spot_node_label,
            priority_threshold=self.config.priority_threshold,
            unready_nodes=unready,
        )

    def _update_metrics(self, node_map: NodeMap, pdbs) -> None:
        cfg = self.config
        metrics.update_nodes_map(
            cfg.on_demand_node_label,
            cfg.spot_node_label,
            len(node_map.on_demand),
            len(node_map.spot),
        )
        # pods-the-rescheduler-understands per node, both classes
        # (rescheduler.go:259 for on-demand, 385-399 for spot)
        for info in node_map.on_demand:
            pods, _ = get_pods_for_deletion(
                info.pods, pdbs,
                delete_non_replicated=cfg.delete_non_replicated_pods,
            )
            metrics.update_node_pods_count(
                cfg.on_demand_node_label, info.node.name, len(pods)
            )
        for info in node_map.spot:
            pods, _ = get_pods_for_deletion(
                info.pods, pdbs,
                delete_non_replicated=cfg.delete_non_replicated_pods,
            )
            metrics.update_node_pods_count(
                cfg.spot_node_label, info.node.name, len(pods)
            )

    def _wrap_columnar(self, store, pdbs):
        from k8s_spot_rescheduler_tpu.models.columnar import ColumnarObservation

        cfg = self.config
        return ColumnarObservation(
            store=store,
            verdicts=store.verdicts(
                pdbs,
                priority_threshold=cfg.priority_threshold,
                delete_non_replicated=cfg.delete_non_replicated_pods,
            ),
        )

    def _tick_metrics(self, observation, pdbs) -> None:
        """The per-tick metrics pass (pure host work). In the pipelined
        tick it runs while the device solve is in flight."""
        if isinstance(observation, NodeMap):
            self._update_metrics(observation, pdbs)
            if not observation.on_demand:
                log.vlog(2, "No nodes to process.")
        else:
            self._update_metrics_columnar(observation, pdbs)

    def _update_metrics_columnar(self, obs, pdbs) -> None:
        cfg = self.config
        od, spot = obs.store.node_pod_counts(
            pdbs,
            priority_threshold=cfg.priority_threshold,
            delete_non_replicated=cfg.delete_non_replicated_pods,
            verdicts=obs.verdicts,
        )
        metrics.update_nodes_map(
            cfg.on_demand_node_label, cfg.spot_node_label, len(od), len(spot)
        )
        if not od:
            log.vlog(2, "No nodes to process.")
        for name, count in od:
            metrics.update_node_pods_count(cfg.on_demand_node_label, name, count)
        for name, count in spot:
            metrics.update_node_pods_count(cfg.spot_node_label, name, count)

    # --- planner crash containment ---

    def _dispatch_plan(self, observation, pdbs, run_metrics: bool):
        """Run the (possibly pipelined) plan on the configured planner;
        raises whatever the planner raises — ``_plan_guarded`` owns the
        degradation policy."""
        plan_async = getattr(self.planner, "plan_async", None)
        if plan_async is not None:
            # Pipelined tick: pack + delta-upload + async solve dispatch
            # first, then the host-side metrics pass runs while the
            # device solve is in flight (JAX async dispatch); only the
            # tiny selection fetch blocks. The phase split makes the
            # overlap measurable: observe-metrics wall time is hidden
            # behind the solve, so plan-dispatch + plan-fetch < the old
            # monolithic plan phase whenever the solve outlasts it.
            t0 = time.perf_counter()
            with tracing.phase("plan-dispatch"):
                finish = plan_async(observation, pdbs)
            t1 = time.perf_counter()
            if run_metrics:
                with tracing.phase("observe-metrics"):
                    self._tick_metrics(observation, pdbs)
            t2 = time.perf_counter()
            with tracing.phase("plan-fetch"):
                report = finish()
            # aggregate plan phase (dashboard continuity): the host time
            # actually spent planning, excluding the overlapped window
            metrics.observe_tick_phase(
                "plan", (t1 - t0) + (time.perf_counter() - t2)
            )
        else:
            if run_metrics:
                with tracing.phase("observe-metrics"):
                    self._tick_metrics(observation, pdbs)
            with tracing.phase("plan"):
                report = self.planner.plan(observation, pdbs)
        return report

    def _fallback(self):
        """The CPU numpy-oracle planner a crashing configured planner
        degrades to — same Planner surface, no device dependency, built
        once on first use."""
        if self._fallback_planner is None:
            from k8s_spot_rescheduler_tpu.planner.solver_planner import (
                SolverPlanner,
            )

            self._fallback_planner = SolverPlanner(
                dataclasses.replace(self.config, solver="numpy")
            )
        return self._fallback_planner

    def _plan_guarded(self, observation, pdbs, *, run_metrics: bool = True):
        """(report | None, used_fallback): any planner exception degrades
        the tick to the CPU fallback planner instead of crashing the
        loop. None only when the fallback failed too (the tick then
        skips under the observe-error policy)."""
        try:
            return self._dispatch_plan(observation, pdbs, run_metrics), False
        except Exception as err:  # noqa: BLE001 — contain ANY solver crash
            log.error(
                "Planner %r failed: %s; degrading tick to the numpy-oracle "
                "fallback", self.config.solver, err,
            )
            # one event, three surfaces: the Prometheus counter, the
            # /healthz field and the flight-recorder event fire together,
            # per contained planner exception (re-plans inside a
            # multi-drain tick included), so the three never diverge
            metrics.update_planner_fallback()
            health.STATE.note_planner_fallback()
            flight.note_event(
                "planner-fallback",
                cause=f"{type(err).__name__}: {err}",
                trace_id=tracing.current_trace_id(),
                solver=self.config.solver,
            )
        try:
            if run_metrics:
                # the primary may have died before its metrics pass ran;
                # gauge updates are idempotent, so re-running is safe
                with tracing.phase("observe-metrics"):
                    self._tick_metrics(observation, pdbs)
            with tracing.phase("plan"):
                return self._fallback().plan(observation, pdbs), True
        except Exception as err:  # noqa: BLE001, exception-discipline — both planners dead: the None return becomes skipped="error", counted by the breaker/health path (the primary's crash already fired planner_fallback + the flight event)
            log.error("Fallback planner failed too: %s", err)
            return None, True

    # --- drain-schedule execution (planner/schedule.py) ---

    def _next_plan(self, observation, pdbs, *, run_metrics: bool = True):
        """(report | None, used_fallback): the tick's drain decision —
        from the pending drain schedule when ``plan_schedule_enabled``
        and the planner supports it (one device fetch per
        ``schedule_horizon`` drains), else the per-tick plan path.
        Every schedule-served step was re-packed, precondition-checked
        and from-scratch validated against the live mirror inside
        ``DrainSchedule.next_plan``; any schedule-machinery failure
        degrades to the ordinary guarded per-tick plan."""
        plan_schedule = (
            getattr(self.planner, "plan_schedule", None)
            if self.config.plan_schedule_enabled
            and self.config.schedule_horizon >= 1  # 0 = documented opt-out
            else None
        )
        if plan_schedule is None:
            return self._plan_guarded(
                observation, pdbs, run_metrics=run_metrics
            )
        if self._schedule is None and self._sched_backoff > 0:
            # churn hysteresis window: recent schedules died before
            # paying for themselves — plan per-tick until it expires
            self._sched_backoff -= 1
            return self._plan_guarded(
                observation, pdbs, run_metrics=run_metrics
            )
        try:
            report = self._schedule_step(observation, pdbs, plan_schedule)
        except Exception as err:  # noqa: BLE001, exception-discipline — schedule machinery crash: the tick falls through to _plan_guarded below, whose own containment counts planner failures; nothing is lost but the fetch amortization
            log.error(
                "Drain-schedule path failed (%s); planning per tick", err
            )
            self._schedule = None
            report = None
        if report is None:
            return self._plan_guarded(
                observation, pdbs, run_metrics=run_metrics
            )
        if run_metrics:
            with tracing.phase("observe-metrics"):
                self._tick_metrics(observation, pdbs)
        # dashboard continuity: schedule-served ticks still record a
        # plan phase (the validation + any schedule-cut fetch)
        metrics.observe_tick_phase("plan", report.solve_seconds)
        return report, False

    def _note_schedule_outcome(self, sched) -> None:
        """Feed the churn hysteresis from an invalidated schedule's
        accounting. A schedule that served >= 2 steps amortized its cut
        (one fetch bought several drains): clear any backoff. One that
        churn killed with >= 2 UNSERVED steps wasted a horizon-deep
        sweep: open (and double, capped) the per-tick window. Schedules
        that exhaust never enter here — ``_schedule_step`` resets the
        ladder at their drop site (the device while-loop stops at
        exhaustion, so a short schedule only ever cost its own length
        in solves). Zero-step cuts cost one solve (== a per-tick plan)
        and never back off either."""
        if sched.cursor >= 2:
            self._sched_backoff = 0
            self._sched_backoff_next = 1
        elif len(sched.steps) - sched.cursor >= 2:
            self._sched_backoff = self._sched_backoff_next
            self._sched_backoff_next = min(64, self._sched_backoff_next * 2)

    def _note_schedule_invalidated(self, sched) -> None:
        """One edge, three surfaces: the counter, the flight event and
        the log line fire together so they can never diverge."""
        metrics.update_schedule_invalidated()
        flight.note_event(
            "schedule-invalidated",
            cause=sched.invalid_reason or "live mirror diverged from the "
                  "schedule's predicted state",
            trace_id=tracing.current_trace_id(),
            step=sched.cursor,
            schedule_len=len(sched.steps),
        )
        log.error(
            "Drain schedule invalidated at step %d/%d (%s); re-planning",
            sched.cursor, len(sched.steps), sched.invalid_reason,
        )

    def _schedule_step(self, observation, pdbs, plan_schedule):
        """Serve the next validated schedule step, cutting a fresh
        schedule when none is pending; None degrades to per-tick
        planning."""
        sched = self._schedule
        if sched is not None and sched.exhausted and not sched.invalidated:
            # ran to exhaustion: the cut paid for itself in full —
            # clear the churn-hysteresis ladder before replacing it
            self._sched_backoff = 0
            self._sched_backoff_next = 1
        elif sched is not None and not sched.invalidated:
            report = sched.next_plan(observation, pdbs)
            if report is not None:
                return report
            if sched.invalidated:
                self._note_schedule_invalidated(sched)
                self._note_schedule_outcome(sched)
        self._schedule = None
        if self._sched_backoff > 0:
            # the early invalidation above just opened (or re-opened) a
            # hysteresis window: degrade this tick to per-tick planning
            # instead of paying another doomed horizon-deep cut
            self._sched_backoff -= 1
            return None
        sched = plan_schedule(observation, pdbs)
        if sched is None:
            return None  # planner cannot schedule this problem
        report = sched.next_plan(observation, pdbs)
        if report is None:
            if sched.invalidated:
                # structurally impossible (the schedule was cut from
                # this very observation) but counted, not assumed
                self._note_schedule_invalidated(sched)
                self._note_schedule_outcome(sched)
                return None
            # zero-step schedule: nothing drainable this tick
            return sched.empty_report()
        self._schedule = sched
        return report

    # --- crash-safe drain recovery ---

    def taint_sweep_grace(self) -> float:
        """How long a rescheduler-marked taint written by ANOTHER holder
        can still belong to a live drain. A drain's SCHEDULED lifetime
        is bounded by ``pod_eviction_timeout``, but its final
        eviction/verify rounds start before that deadline and then run
        in real time (sequential apiserver calls, each with its own
        socket timeout, against a possibly slow apiserver) — so the
        horizon doubles the timeout and adds flat slack rather than
        cutting it close; undercutting a live drain uncordons a node
        mid-eviction, while an over-long grace merely delays healing a
        FOREIGN orphan (own-identity orphans heal immediately). Assumes
        HA replicas run the same ``pod_eviction_timeout`` — a rolling
        config change that shrinks it should finish rolling out before
        the old leader's drains are considered sweepable."""
        return 2.0 * self.config.pod_eviction_timeout + 600.0

    def reconcile_orphaned_taints(self) -> List[str]:
        """Remove rescheduler-owned ``ToBeDeleted`` taints no active
        drain owns.

        A drain interrupted between ``add_taint`` and its deferred
        cleanup (process crash, failed un-taint) leaves the node
        permanently unschedulable; the reference relies on the cluster
        autoscaler to collect such nodes, but a spot RESCHEDULER's
        on-demand nodes are exactly the ones CA should keep. Runs on
        startup and once per tick; list/un-taint failures are logged and
        retried next tick (the sweep is idempotent). Returns the
        recovered node names.

        Ownership: only taints whose VALUE carries the rescheduler
        marker (written by ``drain_node``) are candidates. The cluster
        autoscaler applies the same taint key during its own
        scale-downs — on spot nodes AND on the drained-empty on-demand
        nodes this rescheduler produces for it — with a bare-timestamp
        value; those are never touched. A marked taint held by a
        DIFFERENT identity (HA: a demoted leader may still be mid-drain
        after losing the lease) is only swept once older than
        ``taint_sweep_grace()`` — no drain can outlive that horizon, so
        a live drain's taint is never removed from under it. Our own
        identity's taints sweep immediately: within this process
        ``_active_drains`` covers live drains, and across a restart the
        previous same-named incarnation is dead by definition.

        Cost: the in-tree clients serve these listers from their
        per-tick cache (polling) or watch cache, so the pre-gate sweep
        reads the PREVIOUS tick's node view and issues no extra LIST —
        one tick of staleness just means an orphan heals a tick later."""
        try:
            nodes = list(self.client.list_ready_nodes())
            lister = getattr(self.client, "list_unready_nodes", None)
            if lister is not None:
                nodes += list(lister())
        except Exception as err:  # noqa: BLE001, exception-discipline — sweep retries next tick; an orphan heals one tick later and the read failure was already counted by the kube retry layer
            log.error("Orphaned-taint sweep skipped (list failed): %s", err)
            return []
        from k8s_spot_rescheduler_tpu.utils.labels import matches_label

        own = rescheduler_taint_identity(self.identity)
        # wall(), not now(): taint stamps are epoch seconds shared
        # across processes; a clock without wall() must fail loudly
        # rather than compare monotonic seconds against them
        now_wall = self.clock.wall()
        recovered: List[str] = []
        for node in nodes:
            if not matches_label(node.labels, self.config.on_demand_node_label):
                continue  # not ours: only on-demand nodes are ever drained
            if node.name in self._active_drains:
                continue
            taint = next(
                (t for t in node.taints if t.key == TO_BE_DELETED_TAINT), None
            )
            if taint is None:
                continue
            parsed = parse_rescheduler_taint_value(taint.value)
            if parsed is None:
                continue  # CA's (or another component's) taint: not ours
            holder, stamped = parsed
            if (
                holder != own
                and stamped is not None
                and now_wall - stamped < self.taint_sweep_grace()
            ):
                continue  # possibly another replica's LIVE drain
            # an unparsable stamp on a MARKED taint is treated as
            # infinitely old (mangled value, other version's layout):
            # skipping it forever would leave exactly the permanent
            # NoSchedule residue this sweep exists to remove
            try:
                self.client.remove_taint(node.name, TO_BE_DELETED_TAINT)
            except Exception as err:  # noqa: BLE001, exception-discipline — retried next tick by the same sweep; success is what's counted (orphaned_taints_recovered)
                log.error(
                    "Failed to remove orphaned taint on %s: %s "
                    "(will retry next tick)", node.name, err,
                )
                continue
            recovered.append(node.name)
            metrics.update_taint_recovered()
            health.STATE.note_taint_recovered()
            flight.note_event(
                "orphan-taint-recovered",
                cause="removed orphaned ToBeDeleted taint left by an "
                      "interrupted drain",
                trace_id=tracing.current_trace_id(),
                node=node.name,
            )
            log.info("Recovered orphaned %s taint on %s",
                     TO_BE_DELETED_TAINT, node.name)
            self.recorder.event(
                "Node", node.name, "Normal", "ReschedulerRecovered",
                "removed orphaned ToBeDeleted taint left by an "
                "interrupted drain",
            )
        if recovered:
            # a polling client's node cache still shows the taints just
            # removed (the pre-gate sweep deliberately reads the
            # previous tick's view); drop it so cooldown-skipped ticks
            # — which never reach the gate's per-tick refresh — don't
            # re-"recover" the same orphan every sweep (duplicate
            # events, inflated counter, needless PATCHes)
            refresh = getattr(self.client, "refresh", None)
            if refresh is not None:
                try:
                    refresh()
                except Exception as err:  # noqa: BLE001, exception-discipline — advisory cache hygiene: the worst case is one redundant re-recovery next tick, itself counted
                    log.error(
                        "Cache refresh after taint recovery failed: %s", err
                    )
        return recovered

    # --- freshness gate + anti-entropy audit (docs/ROBUSTNESS.md) ---

    def _maybe_resync_audit(self) -> None:
        """Run the client's anti-entropy resync audit when due (every
        ``resync_interval`` of wall time). Pre-gate like the taint
        sweep: the mirror must stay verified even while cooldown or the
        unschedulable gate holds ticks back. Drift is logged, evented,
        and already healed by the client when this returns."""
        audit = getattr(self.client, "resync_audit", None)
        if audit is None or self.config.resync_interval <= 0:
            return
        now = self.clock.wall()
        if self._next_resync_wall is None:
            # first tick: the startup LIST just seeded the mirror
            self._next_resync_wall = now + self.config.resync_interval
            return
        if now < self._next_resync_wall:
            return
        # advance the schedule before running: a failing audit retries
        # at the NEXT interval, not every tick (a down apiserver must
        # not be hammered with the very LISTs the watch path avoids)
        self._next_resync_wall = now + self.config.resync_interval
        try:
            drift = audit()
        except Exception as err:  # noqa: BLE001, exception-discipline — audit is advisory and rescheduled; a LIST failure was counted by the kube retry layer, and mirror staleness has its own gate + gauge
            log.error(
                "Anti-entropy resync audit failed (next attempt in "
                "%.0fs): %s", self.config.resync_interval, err,
            )
            return
        total = sum(drift.values())
        if total:
            detail = ", ".join(
                f"{res}={n}" for res, n in sorted(drift.items()) if n
            )
            log.error(
                "Anti-entropy audit healed %d drifted mirror object(s) "
                "(%s)", total, detail,
            )
            self.recorder.event(
                "Node", "", "Warning", "WatchDriftHealed",
                f"anti-entropy resync found {total} drifted object(s) "
                f"in the watch mirror ({detail}); stores replaced from "
                "a fresh LIST",
            )

    def _freshness_gate(self) -> Optional[TickResult]:
        """Refuse to observe through a watch mirror staler than
        ``mirror_staleness_budget``. Degradation ladder: (1) bypass the
        sick cache with the client's direct-LIST twin for this tick;
        (2) no direct path → skip the tick, which feeds the circuit
        breaker. Returns the skip result, or None to proceed (with
        ``self._observe_client`` pointing at this tick's read path)."""
        self._observe_client = self.client
        budget = self.config.mirror_staleness_budget
        stale_fn = getattr(self.client, "mirror_staleness", None)
        if stale_fn is None or budget <= 0:
            return None
        staleness = float(stale_fn())
        metrics.update_mirror_staleness(staleness)
        health.STATE.note_mirror_staleness(staleness, budget)
        if staleness <= budget:
            return None
        direct = getattr(self.client, "direct_client", None)
        bypass = direct() if direct is not None else None
        if bypass is None:
            log.error(
                "Watch mirror is %.1fs stale (budget %.1fs) and no "
                "direct observe path exists; skipping the tick",
                staleness, budget,
            )
            return TickResult(skipped="error")
        log.error(
            "Watch mirror is %.1fs stale (budget %.1fs); observing via "
            "direct LIST this tick (cache bypassed)", staleness, budget,
        )
        metrics.update_freshness_bypass()
        flight.note_event(
            "freshness-bypass",
            cause="watch mirror %.1fs stale (budget %.1fs); direct-LIST "
                  "observe this tick" % (staleness, budget),
            trace_id=tracing.current_trace_id(),
        )
        self._observe_client = bypass
        return None

    def _planned_from_stale_mirror(self) -> bool:
        """Last-line freshness check at the plan boundary: True if this
        tick's observation came from the mirror and the mirror aged
        past the budget while the tick observed. Structurally never —
        the gate just measured it — but enforced, so no eviction can
        ever be planned from over-budget data."""
        budget = self.config.mirror_staleness_budget
        if budget <= 0 or self._observe_client is not self.client:
            return False
        stale_fn = getattr(self.client, "mirror_staleness", None)
        if stale_fn is None:
            return False
        return float(stale_fn()) > budget

    # --- circuit breaker ---

    @property
    def breaker_engaged(self) -> bool:
        threshold = self.config.breaker_threshold
        return threshold > 0 and self._consecutive_errors >= threshold

    def effective_interval(self) -> float:
        """The housekeeping interval ``run_forever`` actually sleeps:
        the configured one, doubled per consecutive error-skipped tick
        past ``breaker_threshold`` and capped at ``breaker_max_interval``
        — persistent observe errors must not hammer a struggling
        apiserver at full cadence. Resets with the error count on the
        next completed tick."""
        base = self.config.housekeeping_interval
        if not self.breaker_engaged:
            return base
        doublings = min(
            self._consecutive_errors - self.config.breaker_threshold + 1, 16
        )
        cap = max(self.config.breaker_max_interval, base)
        return min(base * (2.0 ** doublings), cap)

    # --- the tick ---

    def tick(self) -> TickResult:
        """One housekeeping pass, scoped under a fresh tick trace
        (``trace_enabled``): every phase, kube read, drain round and —
        in agent mode — the service round trip record into one span
        tree, which lands in the flight ring when the tick completes."""
        trace = (
            tracing.start_trace() if self.config.trace_enabled else None
        )
        try:
            result = self._tick_guarded()
        finally:
            if trace is not None:
                tracing.end_trace(trace)
        if trace is not None:
            trace.set_attr("skipped", result.skipped)
            if result.planner_fallback:
                trace.set_attr("planner_fallback", True)
            if result.report is not None:
                trace.set_attr("solver", result.report.solver)
                trace.set_attr(
                    "solve_ms",
                    round(result.report.solve_seconds * 1e3, 3),
                )
            flight.record_tick(trace.to_dict())
        return result

    def _tick_guarded(self) -> TickResult:
        recovered: List[str] = []
        if self.config.reconcile_orphaned_taints:
            # before the gates: an orphaned taint must not wait out a
            # 10-minute drain cooldown to be healed. Guarded — a
            # recorder/sink that raises must not escape tick()
            try:
                recovered = self.reconcile_orphaned_taints()
            except Exception as err:  # noqa: BLE001, exception-discipline — the sweep re-runs next tick; recovery successes are what's counted
                log.error("Orphaned-taint sweep failed: %s", err)
        try:
            # also pre-gate: the mirror stays audited while cooldown or
            # the unschedulable gate holds ticks back
            self._maybe_resync_audit()
        except Exception as err:  # noqa: BLE001, exception-discipline — the audit retries at its next interval; staleness has its own gate + gauge
            log.error("Anti-entropy resync audit crashed: %s", err)
        try:
            result = self._tick_inner()
        except Exception as err:  # noqa: BLE001, exception-discipline — the loop must not die; skipped="error" below drives the breaker + health accounting that records it
            log.error("Tick aborted by unexpected error: %s", err)
            result = TickResult(skipped="error")
        result.recovered_taints = recovered
        if result.skipped == "error":
            self._consecutive_errors += 1
            if (
                self.config.breaker_threshold > 0
                and self._consecutive_errors == self.config.breaker_threshold
            ):
                # the ENGAGE edge, once per streak (each further failure
                # widens the interval but is the same engagement)
                flight.note_event(
                    "breaker-engage",
                    cause="%d consecutive error-skipped ticks; interval "
                          "widened to %.0fs"
                          % (self._consecutive_errors,
                             self.effective_interval()),
                    trace_id=tracing.current_trace_id(),
                )
            health.STATE.note_error(
                self._consecutive_errors,
                self.effective_interval() if self.breaker_engaged else None,
            )
        elif result.skipped == "":
            self._consecutive_errors = 0
            # agent mode degrades INSIDE the planner (RemotePlanner
            # plans locally when every endpoint is dead, reporting
            # solver "remote-fallback" without raising) — /healthz must
            # read degraded for those ticks exactly as for a contained
            # in-process planner crash
            remote_fell_back = (
                result.report is not None
                and result.report.solver == "remote-fallback"
            )
            health.STATE.note_success(
                fallback=result.planner_fallback or remote_fell_back
            )
        elif result.skipped == "unschedulable":
            # the observation behind this verdict SUCCEEDED — the
            # apiserver is provably healthy, so the observe-error
            # breaker resets even though the gate (correctly) held the
            # tick; fallback-planner degradation stands until a tick
            # completes
            self._consecutive_errors = 0
            health.STATE.note_observe_ok()
        # cooldown skips observe nothing: they neither trip nor reset
        # the breaker
        return result

    def _tick_inner(self) -> TickResult:
        now = self.clock.now()
        if now < self.next_drain_time:
            log.vlog(2, "Waiting %.0fs for drain delay timer.",
                     self.next_drain_time - now)
            return TickResult(skipped="cooldown")

        skip = self._freshness_gate()
        if skip is not None:
            return skip

        try:
            unschedulable = self._observe_client.list_unschedulable_pods()
        except Exception as err:  # noqa: BLE001, exception-discipline — the skipped="error" return feeds the breaker/health accounting (note_error), which records it
            # skip the tick, matching the observe-error policy: treating
            # an unknown state as "zero unschedulable pods" would defeat
            # the don't-make-things-worse gate exactly when the
            # apiserver is flaky
            log.error("Failed to get unschedulable pods: %s", err)
            return TickResult(skipped="error")
        if unschedulable:
            log.vlog(2, "Waiting for unschedulable pods to be scheduled.")
            return TickResult(skipped="unschedulable")

        log.vlog(3, "Starting node processing.")
        with tracing.phase("observe"):
            observation = self._columnar_store()
            if observation is None:
                observation = self.observe()
            if observation is None:
                return TickResult(skipped="error")

            try:
                pdbs = self._observe_client.list_pdbs()
            except Exception as err:  # noqa: BLE001, exception-discipline — skipped="error" feeds the breaker/health accounting, which records it
                log.error("Failed to list PDBs: %s", err)
                return TickResult(skipped="error")

            if not isinstance(observation, NodeMap):
                # one evictability pass per tick, shared between the
                # metrics update and the planner's pack
                observation = self._wrap_columnar(observation, pdbs)

        if self._planned_from_stale_mirror():
            # the mirror aged past the budget while this tick observed
            # — refuse to plan from it (the skip feeds the breaker)
            metrics.update_mirror_stale_planned()
            flight.note_event(
                "stale-mirror-plan-refused",
                cause="mirror aged past the staleness budget between "
                      "the gate and the plan; tick skipped",
                trace_id=tracing.current_trace_id(),
            )
            log.error(
                "Watch mirror aged past the staleness budget between "
                "the gate and the plan; skipping the tick"
            )
            return TickResult(skipped="error")

        report, used_fallback = self._next_plan(observation, pdbs)
        if report is None:
            return TickResult(skipped="error", planner_fallback=True)
        metrics.observe_plan_duration(
            report.solver, report.solve_seconds, report.n_candidates
        )
        metrics.update_incremental_tick(report)

        result = TickResult(report=report, planner_fallback=used_fallback)
        with tracing.phase("actuate"):
            self._actuate(result, report)
        log.vlog(3, "Finished processing nodes.")
        return result

    def _actuate(self, result: TickResult, report: PlanReport) -> None:
        drains = 0
        while drains < self.config.max_drains_per_tick:
            if drains > 0:
                # Multi-drain mode (beyond the reference's one-per-tick):
                # earlier drains changed the spot pool, and every
                # feasibility proof assumed the undisturbed snapshot
                # (independent fork lanes) — so re-observe and re-plan
                # before each additional drain to avoid spot overcommit.
                # Clients with a per-tick cache (polling pod LIST, watch
                # snapshot) must drop it or the re-observe reads the same
                # pre-drain view the first plan used.
                refresh = getattr(self._observe_client, "refresh", None)
                if refresh is not None:
                    refresh()
                observation = self._columnar_store()
                if observation is None:
                    observation = self.observe()
                if observation is None:
                    break
                try:
                    pdbs = self._observe_client.list_pdbs()
                except Exception as err:  # noqa: BLE001, exception-discipline — the multi-drain loop stops at the drains already proven; this tick still completes and reports them
                    log.error("Failed to list PDBs: %s", err)
                    break
                report, used_fallback = self._next_plan(
                    observation, pdbs, run_metrics=False
                )
                if report is None:
                    break
                if used_fallback:
                    result.planner_fallback = True
            plan = report.plan
            if plan is None:
                break
            log.vlog(2, "All pods on %s can be moved. Will drain node.",
                     plan.node.node.name)
            self._active_drains.add(plan.node.node.name)
            try:
                drain_node(
                    self.client,
                    self.recorder,
                    plan.node.node,
                    plan.pods,
                    clock=self.clock,
                    max_graceful_termination=int(
                        self.config.max_graceful_termination
                    ),
                    pod_eviction_timeout=self.config.pod_eviction_timeout,
                    eviction_retry_time=self.config.eviction_retry_time,
                    identity=self.identity,
                    schedule_step=report.schedule_step,
                )
                metrics.update_node_drain_count("Success", plan.node.node.name)
                result.drained.append(plan.node.node.name)
            except DrainError as err:
                log.error("Failed to drain node: %s", err)
                metrics.update_node_drain_count("Failure", plan.node.node.name)
                result.drain_failed.append(plan.node.node.name)
            finally:
                self._active_drains.discard(plan.node.node.name)
            # cooldown arms after a drain attempt, success or not
            # (rescheduler.go:280-286)
            self.next_drain_time = self.clock.now() + self.config.node_drain_delay
            drains += 1

    def run_forever(self) -> None:
        """reference rescheduler.go:161-164: act every housekeeping_interval
        (widened by the circuit breaker while observe errors persist)."""
        while True:
            self.clock.sleep(self.effective_interval())
            try:
                self.tick()
            except Exception as err:  # noqa: BLE001 — belt over tick's guard
                self._consecutive_errors += 1
                log.error("Tick crashed: %s", err)
                # keep /healthz and the breaker state coherent even on
                # this escape path — an operator must see the throttling
                health.STATE.note_error(
                    self._consecutive_errors,
                    self.effective_interval()
                    if self.breaker_engaged
                    else None,
                )
