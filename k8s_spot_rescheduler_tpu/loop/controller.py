"""The housekeeping control loop.

Reimplements the reference's ``run`` (reference rescheduler.go:144-293) —
the level-triggered observe → plan → actuate tick — against the
ClusterClient/Planner interfaces:

per tick:
1. gate: drain-delay cooldown still running → skip (rescheduler.go:167-170);
2. gate: any unschedulable pods → skip, don't make things worse
   (rescheduler.go:172-181);
3. observe: list ready nodes, build the classified node map
   (rescheduler.go:186-199), update metrics (202), list PDBs (205);
4. plan: prove per-candidate drain feasibility (the Planner replaces the
   canDrainNode/findSpotNodeForPod nest, rescheduler.go:228-275);
5. actuate: drain the first feasible node, arm the cooldown, stop — at
   most ``max_drains_per_tick`` (=1, faithful) drains per tick
   (rescheduler.go:280-286);
6. any observation error skips the tick (`continue`), never crashes the
   loop — the recovery story is "recompute everything next tick"
   (SURVEY.md §5.3).
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

from k8s_spot_rescheduler_tpu.actuator.drain import DrainError, drain_node
from k8s_spot_rescheduler_tpu.io.cluster import ClusterClient, EventSink
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import NodeMap, build_node_map
from k8s_spot_rescheduler_tpu.models.evictability import get_pods_for_deletion
from k8s_spot_rescheduler_tpu.planner.base import Planner, PlanReport
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


@dataclasses.dataclass
class TickResult:
    """What one housekeeping pass did (the loop's unit-test surface)."""

    skipped: str = ""  # "", "cooldown", "unschedulable", "error"
    drained: List[str] = dataclasses.field(default_factory=list)
    drain_failed: List[str] = dataclasses.field(default_factory=list)
    report: Optional[PlanReport] = None


class _NullRecorder:
    def event(self, kind, name, event_type, reason, message):
        pass


class Rescheduler:
    def __init__(
        self,
        client: ClusterClient,
        planner: Planner,
        config: ReschedulerConfig,
        *,
        clock: Optional[Clock] = None,
        recorder: Optional[EventSink] = None,
    ):
        self.client = client
        self.planner = planner
        self.config = config
        self.clock = clock or RealClock()
        self.recorder = recorder or _NullRecorder()
        # start processing straight away (rescheduler.go:158-159)
        self.next_drain_time = self.clock.now()

    # --- observation ---

    def _columnar_store(self):
        """The vectorized observe path (models/columnar.py): used when the
        client maintains a columnar mirror, the planner can consume it,
        and the config hasn't forced the object path."""
        if not self.config.use_columnar:
            return None
        if not getattr(self.planner, "accepts_columnar", False):
            return None
        factory = getattr(self.client, "columnar_store", None)
        if factory is None:
            return None
        try:
            return factory(
                self.config.resources,
                on_demand_label=self.config.on_demand_node_label,
                spot_label=self.config.spot_node_label,
            )
        except Exception as err:  # noqa: BLE001 — fall back to objects
            log.error("Columnar observe unavailable: %s", err)
            return None

    def observe(self) -> Optional[NodeMap]:
        try:
            nodes = self.client.list_ready_nodes()
            # not-ready nodes are presence-only (zone/spread counts —
            # their pods still exist to the real scheduler). All in-tree
            # clients implement the lister; the fallback exists for
            # third-party clients, whose spread/zone verdicts then rest
            # on ready-node visibility alone.
            lister = getattr(self.client, "list_unready_nodes", None)
            unready = lister() if lister is not None else []
            pods_by_node = {
                n.name: self.client.list_pods_on_node(n.name)
                for n in list(nodes) + list(unready)
            }
        except Exception as err:  # noqa: BLE001 — skip tick on any API error
            log.error("Failed to list cluster state: %s", err)
            return None
        return build_node_map(
            nodes,
            pods_by_node,
            on_demand_label=self.config.on_demand_node_label,
            spot_label=self.config.spot_node_label,
            priority_threshold=self.config.priority_threshold,
            unready_nodes=unready,
        )

    def _update_metrics(self, node_map: NodeMap, pdbs) -> None:
        cfg = self.config
        metrics.update_nodes_map(
            cfg.on_demand_node_label,
            cfg.spot_node_label,
            len(node_map.on_demand),
            len(node_map.spot),
        )
        # pods-the-rescheduler-understands per node, both classes
        # (rescheduler.go:259 for on-demand, 385-399 for spot)
        for info in node_map.on_demand:
            pods, _ = get_pods_for_deletion(
                info.pods, pdbs,
                delete_non_replicated=cfg.delete_non_replicated_pods,
            )
            metrics.update_node_pods_count(
                cfg.on_demand_node_label, info.node.name, len(pods)
            )
        for info in node_map.spot:
            pods, _ = get_pods_for_deletion(
                info.pods, pdbs,
                delete_non_replicated=cfg.delete_non_replicated_pods,
            )
            metrics.update_node_pods_count(
                cfg.spot_node_label, info.node.name, len(pods)
            )

    def _wrap_columnar(self, store, pdbs):
        from k8s_spot_rescheduler_tpu.models.columnar import ColumnarObservation

        cfg = self.config
        return ColumnarObservation(
            store=store,
            verdicts=store.verdicts(
                pdbs,
                priority_threshold=cfg.priority_threshold,
                delete_non_replicated=cfg.delete_non_replicated_pods,
            ),
        )

    def _tick_metrics(self, observation, pdbs) -> None:
        """The per-tick metrics pass (pure host work). In the pipelined
        tick it runs while the device solve is in flight."""
        if isinstance(observation, NodeMap):
            self._update_metrics(observation, pdbs)
            if not observation.on_demand:
                log.vlog(2, "No nodes to process.")
        else:
            self._update_metrics_columnar(observation, pdbs)

    def _update_metrics_columnar(self, obs, pdbs) -> None:
        cfg = self.config
        od, spot = obs.store.node_pod_counts(
            pdbs,
            priority_threshold=cfg.priority_threshold,
            delete_non_replicated=cfg.delete_non_replicated_pods,
            verdicts=obs.verdicts,
        )
        metrics.update_nodes_map(
            cfg.on_demand_node_label, cfg.spot_node_label, len(od), len(spot)
        )
        if not od:
            log.vlog(2, "No nodes to process.")
        for name, count in od:
            metrics.update_node_pods_count(cfg.on_demand_node_label, name, count)
        for name, count in spot:
            metrics.update_node_pods_count(cfg.spot_node_label, name, count)

    # --- the tick ---

    def tick(self) -> TickResult:
        now = self.clock.now()
        if now < self.next_drain_time:
            log.vlog(2, "Waiting %.0fs for drain delay timer.",
                     self.next_drain_time - now)
            return TickResult(skipped="cooldown")

        try:
            unschedulable = self.client.list_unschedulable_pods()
        except Exception as err:  # noqa: BLE001
            log.error("Failed to get unschedulable pods: %s", err)
            unschedulable = []
        if unschedulable:
            log.vlog(2, "Waiting for unschedulable pods to be scheduled.")
            return TickResult(skipped="unschedulable")

        log.vlog(3, "Starting node processing.")
        with tracing.phase("observe"):
            observation = self._columnar_store()
            if observation is None:
                observation = self.observe()
            if observation is None:
                return TickResult(skipped="error")

            try:
                pdbs = self.client.list_pdbs()
            except Exception as err:  # noqa: BLE001
                log.error("Failed to list PDBs: %s", err)
                return TickResult(skipped="error")

            if not isinstance(observation, NodeMap):
                # one evictability pass per tick, shared between the
                # metrics update and the planner's pack
                observation = self._wrap_columnar(observation, pdbs)

        plan_async = getattr(self.planner, "plan_async", None)
        if plan_async is not None:
            # Pipelined tick: pack + delta-upload + async solve dispatch
            # first, then the host-side metrics pass runs while the
            # device solve is in flight (JAX async dispatch); only the
            # tiny selection fetch blocks. The phase split makes the
            # overlap measurable: observe-metrics wall time is hidden
            # behind the solve, so plan-dispatch + plan-fetch < the old
            # monolithic plan phase whenever the solve outlasts it.
            t0 = time.perf_counter()
            with tracing.phase("plan-dispatch"):
                finish = plan_async(observation, pdbs)
            t1 = time.perf_counter()
            with tracing.phase("observe-metrics"):
                self._tick_metrics(observation, pdbs)
            t2 = time.perf_counter()
            with tracing.phase("plan-fetch"):
                report = finish()
            # aggregate plan phase (dashboard continuity): the host time
            # actually spent planning, excluding the overlapped window
            metrics.observe_tick_phase(
                "plan", (t1 - t0) + (time.perf_counter() - t2)
            )
        else:
            with tracing.phase("observe-metrics"):
                self._tick_metrics(observation, pdbs)
            with tracing.phase("plan"):
                report = self.planner.plan(observation, pdbs)
        metrics.observe_plan_duration(
            report.solver, report.solve_seconds, report.n_candidates
        )
        metrics.update_incremental_tick(report)

        result = TickResult(report=report)
        with tracing.phase("actuate"):
            self._actuate(result, report)
        log.vlog(3, "Finished processing nodes.")
        return result

    def _actuate(self, result: TickResult, report: PlanReport) -> None:
        drains = 0
        while drains < self.config.max_drains_per_tick:
            if drains > 0:
                # Multi-drain mode (beyond the reference's one-per-tick):
                # earlier drains changed the spot pool, and every
                # feasibility proof assumed the undisturbed snapshot
                # (independent fork lanes) — so re-observe and re-plan
                # before each additional drain to avoid spot overcommit.
                # Clients with a per-tick cache (polling pod LIST, watch
                # snapshot) must drop it or the re-observe reads the same
                # pre-drain view the first plan used.
                refresh = getattr(self.client, "refresh", None)
                if refresh is not None:
                    refresh()
                observation = self._columnar_store()
                if observation is None:
                    observation = self.observe()
                if observation is None:
                    break
                try:
                    pdbs = self.client.list_pdbs()
                except Exception as err:  # noqa: BLE001
                    log.error("Failed to list PDBs: %s", err)
                    break
                report = self.planner.plan(observation, pdbs)
            plan = report.plan
            if plan is None:
                break
            log.vlog(2, "All pods on %s can be moved. Will drain node.",
                     plan.node.node.name)
            try:
                drain_node(
                    self.client,
                    self.recorder,
                    plan.node.node,
                    plan.pods,
                    clock=self.clock,
                    max_graceful_termination=int(
                        self.config.max_graceful_termination
                    ),
                    pod_eviction_timeout=self.config.pod_eviction_timeout,
                    eviction_retry_time=self.config.eviction_retry_time,
                )
                metrics.update_node_drain_count("Success", plan.node.node.name)
                result.drained.append(plan.node.node.name)
            except DrainError as err:
                log.error("Failed to drain node: %s", err)
                metrics.update_node_drain_count("Failure", plan.node.node.name)
                result.drain_failed.append(plan.node.node.name)
            # cooldown arms after a drain attempt, success or not
            # (rescheduler.go:280-286)
            self.next_drain_time = self.clock.now() + self.config.node_drain_delay
            drains += 1

    def run_forever(self) -> None:
        """reference rescheduler.go:161-164: act every housekeeping_interval."""
        while True:
            self.clock.sleep(self.config.housekeeping_interval)
            self.tick()
