"""Degradation flight recorder: the last N ticks, reconstructable.

The robustness machinery (planner fallback, circuit breaker, freshness
bypass, watch stalls, service load-shedding — PRs 4/6/8) fires counters,
but counters aggregate away the one thing a postmortem needs: the
*sequence* of decisions that led to a degraded tick. This module keeps
a bounded in-memory ring of the last N completed tick traces
(utils/tracing.py span trees) plus a structured event log of every
degradation decision — each event carrying its kind, cause and the
trace ID of the tick it fired in — and auto-dumps a redacted JSON
snapshot to ``flight_dump_dir`` whenever a *degradation edge* fires, so
every degraded tick is a self-contained postmortem file. Live
inspection: ``/debug/trace`` (last tick tree) and ``/debug/flight``
(ring summary + dump trigger) on the sidecar/service HTTP servers,
gated by ``debug_endpoints`` (off by default).

One module-level ``RECORDER`` because one controller (or one planner
service) runs per process — the same singleton convention as
loop/health.py; tests reset it via ``RECORDER.reset()``.

Redaction policy (docs/OBSERVABILITY.md): dumps and /debug responses
may leave the process, so cluster object identifiers must not travel
verbatim. Numeric/bool attribute values pass through; string attribute
values pass through only for the structural keys in ``SAFE_ATTR_KEYS``
(phase/reason/resource/solver/... vocabulary the code controls) — any
other string (node names, pod names, URL paths, tenant ids) is replaced
by an 8-hex SHA-1 tag, stable within a dump so correlation survives.
Event ``cause`` strings are kept (they are the postmortem) but
truncated to 200 characters.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from k8s_spot_rescheduler_tpu.utils import logging as log

# Degradation edges: firing one of these (with a configured dump dir)
# writes a postmortem file. The non-degradation kinds below ride the
# event log for context but never trigger a dump.
DEGRADATION_KINDS = frozenset({
    "planner-fallback",        # contained planner crash -> numpy oracle
    "remote-planner-fallback",  # EVERY endpoint dead -> local oracle
    "breaker-engage",          # consecutive errors widened the interval
    "freshness-bypass",        # stale mirror -> direct-LIST observe
    "watch-stall",             # open-but-silent stream killed
    "service-shed",            # planner service 503 (inflight/queue/drain)
    "resync-shed",             # full-pack resync ingest refused (storm)
    "device-sick",             # watchdog flipped the service host-side
    "failover",                # served by a non-primary planner endpoint
    "schedule-invalidated",    # churn broke a drain-schedule prediction
    "delta-resync",            # delta base unusable -> full-pack resync
})
CONTEXT_KINDS = frozenset({
    "orphan-taint-recovered",
    "stale-mirror-plan-refused",
    "device-recovered",        # hysteresis probes passed; device resumes
    "twin-crash",              # contained fleet-twin pack/encode crash
})
EVENT_KINDS = DEGRADATION_KINDS | CONTEXT_KINDS

# structural attribute keys whose STRING values survive redaction —
# vocabulary the code itself emits, never cluster-derived identifiers
SAFE_ATTR_KEYS = frozenset({
    "phase", "reason", "resource", "solver", "outcome", "bucket",
    "method", "kind", "skipped", "source",
})
CAUSE_MAX_CHARS = 200

# at most one auto-dump per kind per window: a fault storm must produce
# a postmortem, not a disk-filling firehose (the ring itself still
# records every event)
DUMP_DEBOUNCE_S = 30.0

_EVENT_LOG_SIZE = 1024
# events held for the CURRENT tick entry, bounded: a process that never
# calls record_tick (a --serve service shedding load, a controller with
# trace_enabled off) must not leak one dict per degradation event
# forever — past the cap the oldest open events fall off (the global
# _events log and the per-kind counts still see every one)
_OPEN_EVENTS_MAX = 256


def redact_text(value: str) -> str:
    """The one identifier-redaction primitive (docs/OBSERVABILITY.md):
    an 8-hex SHA-1 tag, stable within a process so correlation across
    spans/events survives redaction."""
    return "sha1:" + hashlib.sha1(value.encode("utf-8")).hexdigest()[:8]


def _redact_attrs(attrs: dict) -> dict:
    out = {}
    for key, value in attrs.items():
        if isinstance(value, str) and key not in SAFE_ATTR_KEYS:
            out[key] = redact_text(value)
        else:
            out[key] = value
    return out


def _redact_span(span: dict) -> dict:
    out = dict(span)
    if "attrs" in out:
        out["attrs"] = _redact_attrs(out["attrs"])
    if "spans" in out:
        out["spans"] = [_redact_span(s) for s in out["spans"]]
    return out


def _redact_trace(trace: dict) -> dict:
    out = dict(trace)
    if "attrs" in out:
        out["attrs"] = _redact_attrs(out["attrs"])
    out["spans"] = [_redact_span(s) for s in trace.get("spans", ())]
    return out


def _redact_event(event: dict) -> dict:
    out = dict(event)
    if "attrs" in out:
        out["attrs"] = _redact_attrs(out["attrs"])
    return out


def _write_dump(payload: dict, count: int, dump_dir: str) -> Optional[str]:
    """Serialize + write one already-snapshotted postmortem. Runs
    OUTSIDE the recorder lock — a slow or throttled disk must not stall
    the tick/watcher/HTTP threads queued on note_event at exactly the
    degraded moment the recorder exists for."""
    try:
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(
            dump_dir,
            "flight_%d_%03d_%s.json"
            % (int(time.time() * 1e3), count, payload.get("reason", "")),
        )
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        return path
    except OSError as err:
        # a full/readonly disk must not take the control loop down with
        # it — the ring keeps recording in memory
        log.error("flight recorder dump failed: %s", err)
        return None


class FlightRecorder:
    def __init__(self, ring_size: int = 64, dump_dir: str = ""):
        self._lock = threading.Lock()
        self._ring_size = max(1, int(ring_size))
        self._dump_dir = str(dump_dir or "")
        self._ticks: deque = deque(maxlen=self._ring_size)
        self._events: deque = deque(maxlen=_EVENT_LOG_SIZE)
        # since the last record_tick (bounded: see _OPEN_EVENTS_MAX)
        self._open_events: deque = deque(maxlen=_OPEN_EVENTS_MAX)
        self._counts: Dict[str, int] = {}
        self._dump_count = 0
        self._last_dump_wall: Dict[str, float] = {}
        self._seq = 0

    # ------------------------------------------------------------------
    # configuration / lifecycle

    def configure(
        self,
        ring_size: Optional[int] = None,
        dump_dir: Optional[str] = None,
    ) -> None:
        """(Re)apply config knobs; recorded history is preserved (the
        controller and the service server both configure on startup)."""
        with self._lock:
            if ring_size is not None and int(ring_size) >= 1 \
                    and int(ring_size) != self._ring_size:
                self._ring_size = int(ring_size)
                self._ticks = deque(self._ticks, maxlen=self._ring_size)
            if dump_dir is not None:
                self._dump_dir = str(dump_dir)

    def reset(self) -> None:
        """Back to process-start state (test isolation); keeps the
        configured sizes/dir."""
        with self._lock:
            self._ticks.clear()
            self._events.clear()
            self._open_events.clear()
            self._counts = {}
            self._dump_count = 0
            self._last_dump_wall = {}
            self._seq = 0

    # ------------------------------------------------------------------
    # recording

    def note_event(
        self, kind: str, cause: str = "", trace_id: str = "", **attrs
    ) -> dict:
        """One structured degradation/decision event. Degradation kinds
        auto-dump a redacted postmortem when a dump dir is configured
        (debounced per kind). Returns the event record."""
        event = {
            "kind": kind,
            "cause": str(cause)[:CAUSE_MAX_CHARS],
            "trace_id": trace_id,
            "wall": round(time.time(), 3),
        }
        if attrs:
            event["attrs"] = attrs
        pending = None
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            self._open_events.append(event)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            if kind in DEGRADATION_KINDS and self._dump_dir:
                now = time.time()
                last = self._last_dump_wall.get(kind)
                if last is None or now - last >= DUMP_DEBOUNCE_S:
                    self._last_dump_wall[kind] = now
                    self._dump_count += 1
                    pending = (
                        self._payload_locked(kind),
                        self._dump_count,
                        self._dump_dir,
                    )
        if pending is not None:
            # serialize + write OUTSIDE the lock: a slow/throttled disk
            # must stall neither the tick thread nor the watcher/HTTP
            # threads queued on note_event at exactly the degraded
            # moment the recorder exists for
            dump_path = _write_dump(*pending)
            if dump_path:
                log.vlog(
                    2, "flight recorder: %s fired; dumped %s",
                    kind, dump_path,
                )
        return event

    def record_tick(self, trace: dict, **attrs) -> None:
        """One completed tick: its trace dict plus the decision events
        that fired during it become one ring entry."""
        with self._lock:
            entry = {"trace": trace, "events": list(self._open_events)}
            if attrs:
                entry["attrs"] = attrs
            self._open_events.clear()
            self._ticks.append(entry)

    # ------------------------------------------------------------------
    # readback

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Unredacted event records (in-process readback for tests and
        the soak harnesses; external surfaces go through snapshot())."""
        with self._lock:
            out = [dict(e) for e in self._events]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        return out

    def attr_counts(self, kind: str, key: str) -> Dict[str, int]:
        """Events of ``kind`` grouped by a SAFE attr (e.g. service-shed
        by ``reason``): the per-label readback the fleet-twin smoke
        diffs against the labeled metric so flight-delta == metric-delta
        holds per reason, not just in total. Bounded by the event log
        (the per-kind totals in counts() see every event; this sees the
        retained window — diff over a window shorter than the log)."""
        out: Dict[str, int] = {}
        with self._lock:
            for event in self._events:
                if event["kind"] != kind:
                    continue
                value = str(event.get("attrs", {}).get(key, ""))
                out[value] = out.get(value, 0) + 1
        return out

    def last_tick(self) -> Optional[dict]:
        """The most recent ring entry, redacted (/debug/trace)."""
        with self._lock:
            if not self._ticks:
                return None
            entry = self._ticks[-1]
        return {
            "trace": _redact_trace(entry["trace"]),
            "events": [_redact_event(e) for e in entry["events"]],
            **({"attrs": entry["attrs"]} if "attrs" in entry else {}),
        }

    def snapshot(self) -> dict:
        """Redacted ring summary (/debug/flight): counts per kind, ring
        occupancy, the most recent events, dump bookkeeping."""
        with self._lock:
            return {
                "ring_ticks": len(self._ticks),
                "ring_size": self._ring_size,
                "event_counts": dict(self._counts),
                "events": [
                    _redact_event(e) for e in list(self._events)[-32:]
                ],
                "dumps_written": self._dump_count,
                "dump_dir_configured": bool(self._dump_dir),
            }

    def dump_count(self) -> int:
        with self._lock:
            return self._dump_count

    # ------------------------------------------------------------------
    # dumping

    def dump(self, reason: str) -> Optional[str]:
        """Write a redacted postmortem of the whole ring; returns the
        file path (None without a configured dump dir). The snapshot is
        taken under the lock; the file write happens outside it."""
        with self._lock:
            if not self._dump_dir:
                return None
            self._dump_count += 1
            pending = (
                self._payload_locked(reason),
                self._dump_count,
                self._dump_dir,
            )
        return _write_dump(*pending)

    def _payload_locked(self, reason: str) -> dict:
        """The redacted dump payload, snapshotted while the caller
        holds the lock (the deques must not mutate mid-iteration)."""
        return {
            "reason": reason,
            "wall": round(time.time(), 3),
            "event_counts": dict(self._counts),
            "events": [_redact_event(e) for e in self._events],
            "ring": [
                {
                    "trace": _redact_trace(entry["trace"]),
                    "events": [_redact_event(e) for e in entry["events"]],
                }
                for entry in self._ticks
            ],
        }


RECORDER = FlightRecorder()


def configure(ring_size: Optional[int] = None,
              dump_dir: Optional[str] = None) -> None:
    RECORDER.configure(ring_size=ring_size, dump_dir=dump_dir)


def note_event(kind: str, cause: str = "", trace_id: str = "", **attrs) -> dict:
    return RECORDER.note_event(kind, cause=cause, trace_id=trace_id, **attrs)


def record_tick(trace: dict, **attrs) -> None:
    RECORDER.record_tick(trace, **attrs)


def counts() -> Dict[str, int]:
    return RECORDER.counts()


def attr_counts(kind: str, key: str) -> Dict[str, int]:
    return RECORDER.attr_counts(kind, key)


def events(kind: Optional[str] = None) -> List[dict]:
    return RECORDER.events(kind)


def snapshot() -> dict:
    return RECORDER.snapshot()


def last_tick() -> Optional[dict]:
    return RECORDER.last_tick()


def dump(reason: str) -> Optional[str]:
    return RECORDER.dump(reason)
