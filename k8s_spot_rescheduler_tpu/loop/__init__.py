"""Housekeeping control loop."""

from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler, TickResult

__all__ = ["Rescheduler", "TickResult"]
