// Native cluster-state ingest: apiserver JSON -> columnar batches.
//
// The framework's one genuinely hot host-side loop outside numpy is
// decoding apiserver LIST responses (50k pods ~= 30 MB of JSON) into the
// cluster model: ~2.3 s in pure Python (json.loads + per-pod decode).
// This engine parses the same bytes into struct-of-arrays batches in one
// pass — the native runtime component backing io/native_ingest.py, used
// by the watch cache's LIST seeding (io/watch.py) and the polling client
// (io/kube.py). Python reads the arrays zero-copy via ctypes and wraps
// rows in lazy views.
//
// Reference parity (citations into /root/reference): the decoded fields
// mirror io/kube.py's decode_pod/decode_node, which in turn mirror what
// client-go hands the reference (nodes/nodes.go:129-165 reads pod CPU
// requests in millicores; rescheduler.go:241-256 reads ownerReferences
// for the DaemonSet filter; scaler/scaler.go:58 needs name/namespace).
// Quantity grammar follows k8s resource.Quantity (utils/quantity.py):
// decimal/binary suffixes, milli/micro/nano, exponents; CPU rounds up to
// millicores like Quantity.MilliValue, sizes floor to base units.
//
// Build: make native (g++ -O2 -shared -fPIC, no dependencies).

#include <cstdint>
#include <cstring>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Minimal JSON DOM over the input buffer. String values are string_views
// into the buffer when escape-free, else decoded into arena storage.

struct Val;
using Member = std::pair<std::string_view, const Val*>;

struct Val {
  enum Kind : uint8_t { Null, Bool, Num, Str, Arr, Obj } kind = Null;
  bool b = false;
  std::string_view text;  // raw number text or string contents
  std::vector<const Val*> arr;
  std::vector<Member> obj;

  const Val* get(std::string_view key) const {
    if (kind != Obj) return nullptr;
    for (const auto& m : obj)
      if (m.first == key) return m.second;
    return nullptr;
  }
};

struct Parser {
  const char* p;
  const char* end;
  std::deque<Val> arena;
  std::deque<std::string> strings;  // storage for escape-decoded strings
  bool ok = true;

  explicit Parser(const char* buf, size_t n) : p(buf), end(buf + n) {}

  Val* make() {
    arena.emplace_back();
    return &arena.back();
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool lit(const char* s, size_t n) {
    if (size_t(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  // append a unicode code point as UTF-8
  static void utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out += char(cp);
    } else if (cp < 0x800) {
      out += char(0xC0 | (cp >> 6));
      out += char(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += char(0xE0 | (cp >> 12));
      out += char(0x80 | ((cp >> 6) & 0x3F));
      out += char(0x80 | (cp & 0x3F));
    } else {
      out += char(0xF0 | (cp >> 18));
      out += char(0x80 | ((cp >> 12) & 0x3F));
      out += char(0x80 | ((cp >> 6) & 0x3F));
      out += char(0x80 | (cp & 0x3F));
    }
  }

  bool hex4(uint32_t* out) {
    if (end - p < 4) return false;
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else return false;
    }
    p += 4;
    *out = v;
    return true;
  }

  bool parse_string(std::string_view* out) {
    if (p >= end || *p != '"') return false;
    ++p;
    const char* start = p;
    // fast path: no escapes
    while (p < end && *p != '"' && *p != '\\') ++p;
    if (p < end && *p == '"') {
      *out = std::string_view(start, p - start);
      ++p;
      return true;
    }
    // slow path: decode escapes into arena storage
    strings.emplace_back(start, p - start);
    std::string& s = strings.back();
    while (p < end && *p != '"') {
      char c = *p;
      if (c == '\\') {
        ++p;
        if (p >= end) return false;
        switch (*p) {
          case '"': s += '"'; ++p; break;
          case '\\': s += '\\'; ++p; break;
          case '/': s += '/'; ++p; break;
          case 'b': s += '\b'; ++p; break;
          case 'f': s += '\f'; ++p; break;
          case 'n': s += '\n'; ++p; break;
          case 'r': s += '\r'; ++p; break;
          case 't': s += '\t'; ++p; break;
          case 'u': {
            ++p;
            uint32_t cp;
            if (!hex4(&cp)) return false;
            if (cp >= 0xD800 && cp < 0xDC00 && end - p >= 6 && p[0] == '\\' &&
                p[1] == 'u') {
              p += 2;
              uint32_t lo;
              if (!hex4(&lo)) return false;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            utf8(s, cp);
            break;
          }
          default:
            return false;
        }
      } else {
        s += c;
        ++p;
      }
    }
    if (p >= end) return false;
    ++p;  // closing quote
    *out = std::string_view(s);
    return true;
  }

  const Val* parse_value(int depth = 0) {
    if (depth > 64) { ok = false; return nullptr; }
    skip_ws();
    if (p >= end) { ok = false; return nullptr; }
    char c = *p;
    Val* v = make();
    if (c == '{') {
      ++p;
      v->kind = Val::Obj;
      skip_ws();
      if (p < end && *p == '}') { ++p; return v; }
      while (true) {
        skip_ws();
        std::string_view key;
        if (!parse_string(&key)) { ok = false; return nullptr; }
        skip_ws();
        if (p >= end || *p != ':') { ok = false; return nullptr; }
        ++p;
        const Val* child = parse_value(depth + 1);
        if (!ok) return nullptr;
        v->obj.emplace_back(key, child);
        skip_ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == '}') { ++p; return v; }
        ok = false;
        return nullptr;
      }
    }
    if (c == '[') {
      ++p;
      v->kind = Val::Arr;
      skip_ws();
      if (p < end && *p == ']') { ++p; return v; }
      while (true) {
        const Val* child = parse_value(depth + 1);
        if (!ok) return nullptr;
        v->arr.push_back(child);
        skip_ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == ']') { ++p; return v; }
        ok = false;
        return nullptr;
      }
    }
    if (c == '"') {
      v->kind = Val::Str;
      if (!parse_string(&v->text)) { ok = false; return nullptr; }
      return v;
    }
    if (c == 't') {
      if (!lit("true", 4)) { ok = false; return nullptr; }
      v->kind = Val::Bool;
      v->b = true;
      return v;
    }
    if (c == 'f') {
      if (!lit("false", 5)) { ok = false; return nullptr; }
      v->kind = Val::Bool;
      return v;
    }
    if (c == 'n') {
      if (!lit("null", 4)) { ok = false; return nullptr; }
      return v;  // Null
    }
    // number: capture raw text (quantities parse it exactly, no doubles)
    const char* start = p;
    if (p < end && (*p == '-' || *p == '+')) ++p;
    while (p < end &&
           ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' || *p == 'E' ||
            *p == '-' || *p == '+'))
      ++p;
    if (p == start) { ok = false; return nullptr; }
    v->kind = Val::Num;
    v->text = std::string_view(start, p - start);
    return v;
  }
};

// ---------------------------------------------------------------------------
// k8s resource.Quantity: exact integer results with k8s rounding.
// value = digits * 10^e10 * mult; cpu -> ceil(value*1000), else floor.

struct Quantity {
  __int128 num = 0;   // numerator
  __int128 den = 1;   // denominator (positive powers of 10 only)
  bool valid = false;
};

const __int128 SATURATE = (__int128)1 << 100;

bool mul_pow(__int128* v, __int128 base, int exp) {
  while (exp-- > 0) {
    *v *= base;
    if (*v > SATURATE || *v < -SATURATE) return false;
  }
  return true;
}

Quantity parse_quantity(std::string_view s) {
  Quantity q;
  // strip whitespace
  while (!s.empty() && (s.front() == ' ' || s.front() == '\t'))
    s.remove_prefix(1);
  while (!s.empty() && (s.back() == ' ' || s.back() == '\t'))
    s.remove_suffix(1);
  if (s.empty()) return q;

  // suffix
  int pow10 = 0, pow2 = 0, div10 = 0;
  auto ends = [&](const char* suf) {
    size_t n = strlen(suf);
    if (s.size() >= n && s.substr(s.size() - n) == suf) {
      s.remove_suffix(n);
      return true;
    }
    return false;
  };
  if (ends("Ki")) pow2 = 10;
  else if (ends("Mi")) pow2 = 20;
  else if (ends("Gi")) pow2 = 30;
  else if (ends("Ti")) pow2 = 40;
  else if (ends("Pi")) pow2 = 50;
  else if (ends("Ei")) pow2 = 60;
  else if (!s.empty()) {
    switch (s.back()) {
      case 'n': div10 = 9; s.remove_suffix(1); break;
      case 'u': div10 = 6; s.remove_suffix(1); break;
      case 'm': div10 = 3; s.remove_suffix(1); break;
      case 'k': pow10 = 3; s.remove_suffix(1); break;
      case 'M': pow10 = 6; s.remove_suffix(1); break;
      case 'G': pow10 = 9; s.remove_suffix(1); break;
      case 'T': pow10 = 12; s.remove_suffix(1); break;
      case 'P': pow10 = 15; s.remove_suffix(1); break;
      case 'E': pow10 = 18; s.remove_suffix(1); break;
      default: break;
    }
  }
  if (s.empty()) return q;

  bool neg = false;
  size_t i = 0;
  if (s[i] == '+' || s[i] == '-') {
    neg = s[i] == '-';
    ++i;
  }
  __int128 digits = 0;
  int frac = 0;
  bool any = false, in_frac = false;
  for (; i < s.size(); ++i) {
    char c = s[i];
    if (c >= '0' && c <= '9') {
      digits = digits * 10 + (c - '0');
      if (digits > SATURATE) return q;
      if (in_frac) ++frac;
      any = true;
    } else if (c == '.' && !in_frac) {
      in_frac = true;
    } else if ((c == 'e' || c == 'E') && any) {
      int esign = 1;
      ++i;
      if (i < s.size() && (s[i] == '+' || s[i] == '-')) {
        if (s[i] == '-') esign = -1;
        ++i;
      }
      int ev = 0;
      bool edig = false;
      for (; i < s.size(); ++i) {
        if (s[i] < '0' || s[i] > '9') return q;
        ev = ev * 10 + (s[i] - '0');
        if (ev > 40) return q;  // beyond saturation anyway
        edig = true;
      }
      if (!edig) return q;
      if (esign > 0) pow10 += ev;
      else div10 += ev;
      break;
    } else {
      return q;
    }
  }
  if (!any) return q;

  q.num = digits;
  q.den = 1;
  div10 += frac;
  // cancel common powers of 10 before saturating multiplies
  int common = pow10 < div10 ? pow10 : div10;
  pow10 -= common;
  div10 -= common;
  if (!mul_pow(&q.num, 10, pow10)) return q;
  if (!mul_pow(&q.num, 2, pow2)) return q;
  if (!mul_pow(&q.den, 10, div10)) return q;
  if (neg) q.num = -q.num;
  q.valid = true;
  return q;
}

int64_t clamp_i64(__int128 v) {
  if (v > INT64_MAX) return INT64_MAX;
  if (v < INT64_MIN) return INT64_MIN;
  return (int64_t)v;
}

// CPU -> millicores, ceil (k8s MilliValue; utils/quantity.parse_cpu_millis)
int64_t cpu_millis(const Val* v) {
  if (!v || (v->kind != Val::Str && v->kind != Val::Num)) return 0;
  Quantity q = parse_quantity(v->text);
  if (!q.valid) return 0;
  __int128 n = q.num * 1000;
  __int128 r = n >= 0 ? (n + q.den - 1) / q.den : n / q.den;
  return clamp_i64(r);
}

// sizes -> base units, floor (utils/quantity: int(num // den))
int64_t base_units(const Val* v) {
  if (!v || (v->kind != Val::Str && v->kind != Val::Num)) return 0;
  Quantity q = parse_quantity(v->text);
  if (!q.valid) return 0;
  __int128 r = q.num >= 0 ? q.num / q.den
                          : -((-q.num + q.den - 1) / q.den);  // python floor
  return clamp_i64(r);
}

int64_t as_int(const Val* v) {
  if (!v) return 0;
  if (v->kind == Val::Bool) return v->b;
  if (v->kind != Val::Num && v->kind != Val::Str) return 0;
  // integer prefix is enough (priority, disruptionsAllowed)
  return base_units(v);
}

// ---------------------------------------------------------------------------
// Output batches. String columns share one heap; each cell is (off, len).

constexpr char UNIT_SEP = '\x1f';
constexpr char REC_SEP = '\x1e';
constexpr char TERM_SEP = '\x1d';
constexpr char VAL_SEP = '\x1c';

// Interned-string tables: repeated values (node names, namespaces,
// toleration sets, label sets, nodeSelector sets, anti-affinity
// selectors) are stored once; rows carry int32 ids. At 50k pods this
// collapses ~200k string decodes into a few thousand.
enum {
  TBL_NODE = 0,
  TBL_NS,
  TBL_TOLS,
  TBL_LABELS,
  TBL_NODESEL,
  TBL_AAFF,
  TBL_NAFF,  // required node-affinity blobs (see extract_node_affinity)
  TBL_PAFF,  // required POSITIVE pod-affinity matchLabels blobs
  TBL_ZAFF,  // zone-topology anti-affinity matchLabels blobs
  TBL_PVC,   // PVC claim-name lists (REC_SEP-joined)
  TBL_SPREAD,  // canonical hard topologySpreadConstraints blobs
  TBL_PZAFF,   // required POSITIVE zone-topology pod-affinity blobs
  TBL_COUNT,
};

struct Batch {
  long count = 0;
  std::vector<int64_t> i64;      // count * NI64 column-major blocks
  std::vector<int32_t> i32;      // count * NI32
  std::vector<uint8_t> u8;       // count * NU8
  std::string heap;              // shared string storage
  std::vector<int64_t> str;      // count * nstrcols * 2 (off, len)
  std::string rv;                // list metadata.resourceVersion
  int ncols_i64 = 0, ncols_i32 = 0, ncols_u8 = 0, ncols_str = 0;

  std::vector<int64_t> tbl[TBL_COUNT];  // interned blobs: (off, len) pairs
  std::unordered_map<std::string, int32_t> intern[TBL_COUNT];

  void put_str(int col, std::string_view s) {
    str[(size_t)count * ncols_str * 2 + col * 2] = (int64_t)heap.size();
    str[(size_t)count * ncols_str * 2 + col * 2 + 1] = (int64_t)s.size();
    heap.append(s.data(), s.size());
  }

  int32_t intern_str(int family, const std::string& s) {
    auto it = intern[family].find(s);
    if (it != intern[family].end()) return it->second;
    int32_t id = (int32_t)(tbl[family].size() / 2);
    intern[family].emplace(s, id);
    tbl[family].push_back((int64_t)heap.size());
    tbl[family].push_back((int64_t)s.size());
    heap.append(s);
    return id;
  }
};

// pod columns
enum { P_CPU = 0, P_MEM, P_EPH, P_NI64 };
enum {
  P_PRIO = 0,
  P_NODEID,
  P_NSID,
  P_TOLID,
  P_LABELSID,
  P_SELID,
  P_AAFFID,
  P_NAFFID,
  P_PAFFID,
  P_ZAFFID,
  P_PVCID,
  P_SPREADID,
  P_PZAFFID,
  P_NI32,
};
enum { P_FLAGS = 0, P_NU8 };
enum { PS_NAME = 0, PS_UID, PS_NSTR };
enum {
  F_MIRROR = 1,
  F_DAEMONSET = 2,
  F_REPLICATED = 4,
  F_TERMINAL = 8,
  F_PENDING = 16,
  F_PVC = 32,      // any volume backed by a persistentVolumeClaim
  F_REQAFF = 64,   // required affinity beyond the modeled spread shape
};

// Python truthiness of a JSON value — the decode contract is "exact
// lockstep with io/kube.py", whose guards are plain `if value:` checks.
bool py_truthy(const Val* v) {
  if (!v) return false;
  switch (v->kind) {
    case Val::Null: return false;
    case Val::Bool: return v->b;
    case Val::Num: {
      std::string txt(v->text);
      return strtod(txt.c_str(), nullptr) != 0.0;
    }
    case Val::Str: return !v->text.empty();
    case Val::Arr: return !v->arr.empty();
    case Val::Obj: return !v->obj.empty();
  }
  return false;
}

// --- widened pod-affinity term selectors (round 5) -----------------------
//
// Exact lockstep with io/kube.py _decode_term: explicit (cross-
// namespace) `namespaces` lists are modeled; `namespaceSelector: {}`
// is the all-namespaces "*" wildcard scope and null means "no
// selector", while label-matching namespaceSelectors stay unmodeled;
// matchLabels pairs and matchExpressions with
// In / NotIn / Exists / DoesNotExist (multi-value In/NotIn) all emit as
// requirement records. The blob carries source order and own-namespace
// scopes unresolved; canonicalization (sorting, dedup, own-ns
// resolution, matches-nothing drops) happens on the Python side
// (io/native_ingest.py _parse_affinity_terms / _resolve_terms), so no
// cross-language sort contract is needed.

enum SelVerdict { SEL_OK = 0, SEL_UNMODELED = 2 };

bool has_sep_bytes(std::string_view s);  // defined with the naff blobs

// Emit one labelSelector's requirements into *out: requirements joined
// by req_sep, fields key/op/values joined by field_sep, values joined
// by val_sep. matchLabels entries become single-value In requirements
// (duplicate keys keep the LAST value — Python dict semantics);
// matchExpressions validate exactly like io/kube.py (In/NotIn need a
// non-empty string list; Exists/DoesNotExist must carry no values).
int selector_reqs_blob(const Val* sel, char req_sep, char field_sep,
                       char val_sep, std::string* out) {
  if (!sel || sel->kind != Val::Obj) return SEL_UNMODELED;
  std::string reqs;
  bool any = false;
  std::vector<std::pair<std::string_view, std::string_view>> pairs;
  const Val* ml = sel->get("matchLabels");
  if (ml) {
    if (ml->kind != Val::Obj) return SEL_UNMODELED;
    for (const auto& m : ml->obj) {
      if (!m.second || m.second->kind != Val::Str) return SEL_UNMODELED;
      if (has_sep_bytes(m.first) || has_sep_bytes(m.second->text))
        return SEL_UNMODELED;
      bool dup = false;
      for (auto& p : pairs) {
        if (p.first == m.first) {
          p.second = m.second->text;  // JSON duplicate key: last wins
          dup = true;
        }
      }
      if (!dup) pairs.emplace_back(m.first, m.second->text);
    }
  }
  for (const auto& p : pairs) {
    if (any) reqs += req_sep;
    any = true;
    reqs.append(p.first.data(), p.first.size());
    reqs += field_sep;
    reqs += "In";
    reqs += field_sep;
    reqs.append(p.second.data(), p.second.size());
  }
  const Val* me = sel->get("matchExpressions");
  if (py_truthy(me)) {
    if (me->kind != Val::Arr) return SEL_UNMODELED;
    for (const Val* e : me->arr) {
      if (!e || e->kind != Val::Obj) return SEL_UNMODELED;
      const Val* key = e->get("key");
      const Val* op = e->get("operator");
      if (!key || key->kind != Val::Str || has_sep_bytes(key->text) ||
          !op || op->kind != Val::Str)
        return SEL_UNMODELED;
      bool exists_like =
          op->text == "Exists" || op->text == "DoesNotExist";
      bool in_like = op->text == "In" || op->text == "NotIn";
      if (!exists_like && !in_like) return SEL_UNMODELED;
      const Val* values = e->get("values");
      if (exists_like) {
        // k8s validation: Exists/DoesNotExist carry no values
        if (py_truthy(values)) return SEL_UNMODELED;
      } else {
        if (!values || values->kind != Val::Arr || values->arr.empty())
          return SEL_UNMODELED;
        for (const Val* v : values->arr) {
          if (!v || v->kind != Val::Str || has_sep_bytes(v->text))
            return SEL_UNMODELED;
        }
      }
      if (any) reqs += req_sep;
      any = true;
      reqs.append(key->text.data(), key->text.size());
      reqs += field_sep;
      reqs.append(op->text.data(), op->text.size());
      reqs += field_sep;
      if (!exists_like) {
        for (size_t vi = 0; vi < values->arr.size(); ++vi) {
          if (vi) reqs += val_sep;
          const auto& t = values->arr[vi]->text;
          reqs.append(t.data(), t.size());
        }
      }
    }
  }
  if (!any) return SEL_UNMODELED;  // empty selector: not modeled
  *out += reqs;
  return SEL_OK;
}

// One affinity term -> `ns_record REC_SEP requirement records`, the
// round-5 term encoding (io/native_ingest.py _parse_affinity_terms).
// The ns record is the explicit namespaces list joined by VAL_SEP, or
// empty for own-namespace scope.
int term_selector_blob(const Val* term, std::string* blob) {
  blob->clear();
  std::string ns_rec;
  const Val* ns_list = term->get("namespaces");
  if (py_truthy(ns_list)) {
    if (ns_list->kind != Val::Arr) return SEL_UNMODELED;
    bool first = true;
    for (const Val* x : ns_list->arr) {
      // "*" is reserved as the all-namespaces sentinel: a literal
      // entry is malformed and must not silently widen the scope
      if (!x || x->kind != Val::Str || x->text.empty() || x->text == "*" ||
          has_sep_bytes(x->text))
        return SEL_UNMODELED;
      if (!first) ns_rec += VAL_SEP;
      first = false;
      ns_rec.append(x->text.data(), x->text.size());
    }
  }
  if (const Val* ns_sel = term->get("namespaceSelector")) {
    if (ns_sel->kind == Val::Obj && ns_sel->obj.empty()) {
      // {} selects EVERY namespace (round 5): the "*" wildcard scope —
      // namespace names are DNS labels, so "*" cannot collide. It
      // subsumes any `namespaces` list.
      ns_rec = "*";
    } else if (ns_sel->kind != Val::Null) {
      // non-empty selectors match namespace LABELS (unobserved):
      // conservatively unmodeled; null is the API's "no selector"
      return SEL_UNMODELED;
    }
  }
  std::string reqs;
  int verdict = selector_reqs_blob(term->get("labelSelector"), REC_SEP,
                                   UNIT_SEP, VAL_SEP, &reqs);
  if (verdict != SEL_OK) return verdict;
  *blob = ns_rec;
  *blob += REC_SEP;
  *blob += reqs;
  return SEL_OK;
}

// podAntiAffinity: ANY number of required terms, hostname or zone
// topology, widened selectors. Never-matching terms are dropped on the
// Python parse side (io/native_ingest.py), in lockstep with io/kube.py
// decode_anti_affinity.
void extract_anti_affinity(const Val* block, std::string* host_blob,
                           std::string* zone_blob, bool* unmodeled) {
  host_blob->clear();
  zone_blob->clear();
  if (!block || block->kind != Val::Obj) return;
  const Val* req = block->get("requiredDuringSchedulingIgnoredDuringExecution");
  if (!req || !py_truthy(req)) return;
  if (req->kind != Val::Arr) {
    *unmodeled = true;
    return;
  }
  for (const Val* term : req->arr) {
    if (!term || term->kind != Val::Obj) {
      *unmodeled = true;
      host_blob->clear();  // an earlier valid term must not leak: its
      zone_blob->clear();  // symmetric presence would over-constrain
      return;              // OTHER pods on this ingest path only
    }
    const Val* topo = term->get("topologyKey");
    bool zone;
    if (topo && topo->kind == Val::Str &&
        topo->text == "kubernetes.io/hostname") {
      zone = false;
    } else if (topo && topo->kind == Val::Str &&
               topo->text == "topology.kubernetes.io/zone") {
      zone = true;
    } else {
      *unmodeled = true;
      host_blob->clear();
      zone_blob->clear();
      return;
    }
    std::string blob;
    if (term_selector_blob(term, &blob) != SEL_OK) {
      *unmodeled = true;
      host_blob->clear();
      zone_blob->clear();
      return;
    }
    std::string* slot = zone ? zone_blob : host_blob;
    if (!slot->empty()) *slot += TERM_SEP;
    *slot += blob;
  }
}

// required POSITIVE podAffinity: ANY number of required terms, hostname
// or zone topology, widened selectors; every term must hold.
// Never-matching selectors are KEPT (the carrier is exactly
// unplaceable). Lockstep: io/kube.py decode_pod_affinity.
void extract_pod_affinity(const Val* block, std::string* host_blob,
                          std::string* zone_blob, bool* unmodeled) {
  host_blob->clear();
  zone_blob->clear();
  if (!block || block->kind != Val::Obj) return;
  const Val* req = block->get("requiredDuringSchedulingIgnoredDuringExecution");
  if (!req || !py_truthy(req)) return;
  if (req->kind != Val::Arr) {
    *unmodeled = true;
    return;
  }
  for (const Val* term : req->arr) {
    if (!term || term->kind != Val::Obj) {
      *unmodeled = true;
      host_blob->clear();
      zone_blob->clear();
      return;
    }
    const Val* topo = term->get("topologyKey");
    bool zone;
    if (topo && topo->kind == Val::Str &&
        topo->text == "kubernetes.io/hostname") {
      zone = false;
    } else if (topo && topo->kind == Val::Str &&
               topo->text == "topology.kubernetes.io/zone") {
      zone = true;
    } else {
      *unmodeled = true;
      host_blob->clear();
      zone_blob->clear();
      return;
    }
    std::string blob;
    if (term_selector_blob(term, &blob) != SEL_OK) {
      *unmodeled = true;
      host_blob->clear();
      zone_blob->clear();
      return;
    }
    std::string* slot = zone ? zone_blob : host_blob;
    if (!slot->empty()) *slot += TERM_SEP;
    *slot += blob;
  }
}

// Required node-affinity, in lockstep with io/kube.py
// decode_node_affinity's MODELED/UNMODELED decisions. The blob carries
// the terms in source order — canonicalization (sorting, dedup) happens
// once on the Python side when the blob is parsed, so no cross-language
// sort-order contract is needed. Encoding (k8s label keys/values are
// control-char-free): terms '\x1d' (TERM_SEP), exprs within a term
// '\x1e' (REC_SEP), expr fields key/op/values '\x1f' (UNIT_SEP),
// values '\x1c' (VAL_SEP). Empty blob = no modeled requirement.

static const char* const kNaffOps[] = {"In",     "NotIn", "Exists",
                                       "DoesNotExist", "Gt", "Lt"};

// Unlike labels/nodeSelector (apiserver-validated label syntax),
// NodeSelectorRequirement.values are NOT validated as label values — a
// value may contain the blob separator bytes. Such requirements are
// conservatively unmodeled (in lockstep with io/kube.py
// decode_node_affinity) so the blob framing can never be corrupted.
bool has_sep_bytes(std::string_view s) {
  for (char c : s)
    if (c >= '\x1c' && c <= '\x1f') return true;
  return false;
}

// Hard topologySpreadConstraints, in exact lockstep with io/kube.py
// decode_topology_spread: each hard entry (whenUnsatisfiable absent or
// anything but the literal "ScheduleAnyway") must have a non-empty
// sep-free topologyKey (ANY label key — round 5), an integer
// maxSkew >= 1, a non-empty widened selector
// (matchLabels and/or matchExpressions with the four label operators —
// round 5), and none of the counting-modifier fields — else the whole
// pod is unmodeled. Soft entries are dropped. Blob: entries joined by
// REC_SEP; entry = topo UNIT_SEP skew UNIT_SEP reqs, reqs joined by
// TERM_SEP, req = key VAL_SEP op VAL_SEP values (VAL_SEP-joined).
// Source order; the Python side canonicalizes (sort + dedup) on parse.
// Round 5: explicit DEFAULT values of the counting-modifier fields are
// semantically identical to absence and accepted (lockstep with
// io/kube.py _spread_modifiers_default): minDomains null/1 (nil
// behaves as 1 per KEP-3022), matchLabelKeys null/[], nodeAffinityPolicy
// null/"Honor", nodeTaintsPolicy null/"Ignore". Anything else keeps the
// pod conservatively unmodeled.
bool spread_modifier_is_default(const Val* c) {
  if (const Val* v = c->get("minDomains")) {
    if (v->kind != Val::Null && !(v->kind == Val::Num && v->text == "1"))
      return false;
  }
  if (const Val* v = c->get("matchLabelKeys")) {
    if (v->kind != Val::Null && !(v->kind == Val::Arr && v->arr.empty()))
      return false;
  }
  if (const Val* v = c->get("nodeAffinityPolicy")) {
    if (v->kind != Val::Null && !(v->kind == Val::Str && v->text == "Honor"))
      return false;
  }
  if (const Val* v = c->get("nodeTaintsPolicy")) {
    if (v->kind != Val::Null && !(v->kind == Val::Str && v->text == "Ignore"))
      return false;
  }
  return true;
}

bool json_int_ge1(const Val* v) {
  // Python's json gives int only for digit literals (no '.', no
  // exponent); bool is excluded there by the isinstance(bool) guard.
  if (!v || v->kind != Val::Num) return false;
  std::string_view t = v->text;
  size_t i = (t.size() && (t[0] == '-' || t[0] == '+')) ? 1 : 0;
  if (i >= t.size()) return false;
  for (size_t j = i; j < t.size(); ++j)
    if (t[j] < '0' || t[j] > '9') return false;
  return t[0] != '-' && !(t == "0") && !(i == 1 && t == "+0");
}

void extract_topology_spread(const Val* spread, bool* unmodeled,
                             std::string* blob) {
  blob->clear();
  if (!spread || !py_truthy(spread)) return;
  if (spread->kind != Val::Arr) {
    *unmodeled = true;
    return;
  }
  std::string out;
  for (const Val* c : spread->arr) {
    if (!c || c->kind != Val::Obj) {
      *unmodeled = true;
      return;
    }
    const Val* wu = c->get("whenUnsatisfiable");
    if (wu && wu->kind == Val::Str && wu->text == "ScheduleAnyway")
      continue;  // soft: advisory only
    if (!spread_modifier_is_default(c)) {
      *unmodeled = true;
      return;
    }
    // spread topology is generic (round 5): any non-empty sep-free
    // label key — the SpreadBit verdict machinery keys counts/domains
    // by the constraint's own topology key
    const Val* topo = c->get("topologyKey");
    if (!topo || topo->kind != Val::Str || topo->text.empty() ||
        has_sep_bytes(topo->text)) {
      *unmodeled = true;
      return;
    }
    const Val* skew = c->get("maxSkew");
    if (!json_int_ge1(skew)) {
      *unmodeled = true;
      return;
    }
    // round-5 widened selector: requirements joined by TERM_SEP, each
    // `key VAL_SEP op VAL_SEP v1 VAL_SEP v2 ...` (spread is always
    // own-namespace; no ns record needed)
    std::string reqs;
    if (selector_reqs_blob(c->get("labelSelector"), TERM_SEP, VAL_SEP,
                           VAL_SEP, &reqs) != SEL_OK) {
      *unmodeled = true;
      return;
    }
    if (!out.empty()) out += REC_SEP;
    out.append(topo->text.data(), topo->text.size());
    out += UNIT_SEP;
    out.append(skew->text.data(), skew->text.size());
    out += UNIT_SEP;
    out += reqs;
  }
  *blob = out;
}

void extract_node_affinity(const Val* naff, bool* unmodeled,
                           std::string* blob) {
  blob->clear();
  if (!naff || naff->kind != Val::Obj) return;
  const Val* req = naff->get("requiredDuringSchedulingIgnoredDuringExecution");
  if (!py_truthy(req)) return;  // falsy: no requirement
  if (req->kind != Val::Obj) {
    *unmodeled = true;
    return;
  }
  const Val* term_list = req->get("nodeSelectorTerms");
  if (!term_list || term_list->kind != Val::Arr || term_list->arr.empty()) {
    *unmodeled = true;
    return;
  }
  std::string out;
  bool any_term = false;
  for (const Val* term : term_list->arr) {
    if (!term || term->kind != Val::Obj) {
      *unmodeled = true;
      return;
    }
    const Val* exprs = term->get("matchExpressions");
    const Val* fields = term->get("matchFields");
    bool have_exprs = py_truthy(exprs);
    bool have_fields = py_truthy(fields);
    if (!have_exprs && !have_fields) continue;  // empty term: drop
    if ((have_exprs && exprs->kind != Val::Arr) ||
        (have_fields && fields->kind != Val::Arr)) {
      *unmodeled = true;
      return;
    }
    std::string term_out;
    bool first_expr = true;
    if (have_fields) {
      // matchFields: metadata.name In/NotIn only (the one field selector
      // k8s defines). Emitted with the reserved FieldIn/FieldNotIn ops —
      // exact lockstep with io/kube.py decode_node_affinity.
      for (const Val* e : fields->arr) {
        if (!e || e->kind != Val::Obj) {
          *unmodeled = true;
          return;
        }
        const Val* key = e->get("key");
        const Val* op = e->get("operator");
        if (!key || key->kind != Val::Str || key->text != "metadata.name" ||
            !op || op->kind != Val::Str ||
            (op->text != "In" && op->text != "NotIn")) {
          *unmodeled = true;
          return;
        }
        const Val* values = e->get("values");
        if (!values || values->kind != Val::Arr || values->arr.empty()) {
          *unmodeled = true;
          return;
        }
        for (const Val* v : values->arr) {
          if (!v || v->kind != Val::Str || has_sep_bytes(v->text)) {
            *unmodeled = true;
            return;
          }
        }
        if (!first_expr) term_out += REC_SEP;
        first_expr = false;
        term_out += "metadata.name";
        term_out += UNIT_SEP;
        term_out += (op->text == "In") ? "FieldIn" : "FieldNotIn";
        term_out += UNIT_SEP;
        for (size_t vi = 0; vi < values->arr.size(); ++vi) {
          if (vi) term_out += VAL_SEP;
          const auto& t = values->arr[vi]->text;
          term_out.append(t.data(), t.size());
        }
      }
    }
    if (!have_exprs) {
      // term_out is necessarily non-empty here: have_fields held (else
      // the term was dropped above) and every field either appended a
      // record or returned unmodeled
      if (any_term) out += TERM_SEP;
      any_term = true;
      out += term_out;
      continue;
    }
    for (const Val* e : exprs->arr) {
      if (!e || e->kind != Val::Obj) {
        *unmodeled = true;
        return;
      }
      const Val* key = e->get("key");
      const Val* op = e->get("operator");
      if (!key || key->kind != Val::Str || !op || op->kind != Val::Str) {
        *unmodeled = true;
        return;
      }
      if (has_sep_bytes(key->text)) {
        *unmodeled = true;
        return;
      }
      bool known = false;
      for (const char* k : kNaffOps) known |= (op->text == k);
      if (!known) {
        *unmodeled = true;
        return;
      }
      const Val* values = e->get("values");
      size_t n_values = 0;
      if (values && py_truthy(values)) {
        if (values->kind != Val::Arr) {
          *unmodeled = true;
          return;
        }
        for (const Val* v : values->arr) {
          if (!v || v->kind != Val::Str || has_sep_bytes(v->text)) {
            *unmodeled = true;
            return;
          }
        }
        n_values = values->arr.size();
      }
      bool exists_op =
          op->text == "Exists" || op->text == "DoesNotExist";
      if (op->text == "Gt" || op->text == "Lt") {
        if (n_values != 1) {
          *unmodeled = true;
          return;
        }
      } else if (!exists_op && n_values == 0) {  // In/NotIn need values
        *unmodeled = true;
        return;
      }
      if (!first_expr) term_out += REC_SEP;
      first_expr = false;
      term_out.append(key->text.data(), key->text.size());
      term_out += UNIT_SEP;
      term_out.append(op->text.data(), op->text.size());
      term_out += UNIT_SEP;
      if (!exists_op) {
        for (size_t vi = 0; vi < n_values; ++vi) {
          if (vi) term_out += VAL_SEP;
          const auto& t = values->arr[vi]->text;
          term_out.append(t.data(), t.size());
        }
      }
    }
    if (term_out.empty()) continue;  // all-empty term: drop
    if (any_term) out += TERM_SEP;
    any_term = true;
    out += term_out;
  }
  if (!any_term) {
    *unmodeled = true;  // every term matches nothing: unplaceable
    return;
  }
  *blob = std::move(out);
}

// node columns
enum { N_CPU = 0, N_MEM, N_EPH, N_PODS, N_NI64 };
enum { N_READY = 0, N_UNSCHED, N_HASPODS, N_NU8 };
enum { NS_NAME = 0, NS_UID, NS_LABELS, NS_TAINTS, NS_NSTR };

// labels as k\x1fv\x1e... (k8s forbids control chars in keys/values)
void blob_kv_into(std::string* out, const Val* obj) {
  if (obj && obj->kind == Val::Obj) {
    for (const auto& m : obj->obj) {
      if (!m.second || m.second->kind != Val::Str) continue;
      out->append(m.first.data(), m.first.size());
      *out += UNIT_SEP;
      out->append(m.second->text.data(), m.second->text.size());
      *out += REC_SEP;
    }
  }
}

void blob_kv(Batch* b, int col, const Val* obj) {
  size_t start = b->heap.size();
  std::string tmp;
  blob_kv_into(&tmp, obj);
  b->heap += tmp;
  b->str[(size_t)b->count * b->ncols_str * 2 + col * 2] = (int64_t)start;
  b->str[(size_t)b->count * b->ncols_str * 2 + col * 2 + 1] =
      (int64_t)(b->heap.size() - start);
}

void field(std::string* out, const Val* obj, std::string_view key) {
  const Val* v = obj ? obj->get(key) : nullptr;
  if (v && v->kind == Val::Str) out->append(v->text.data(), v->text.size());
}

Batch* ingest_pods_impl(const char* buf, long n) {
  Parser parser(buf, (size_t)n);
  const Val* root = parser.parse_value();
  if (!parser.ok || !root || root->kind != Val::Obj) return nullptr;
  const Val* items = root->get("items");
  if (!items || items->kind != Val::Arr) return nullptr;

  auto* b = new Batch();
  b->ncols_i64 = P_NI64;
  b->ncols_i32 = P_NI32;
  b->ncols_u8 = P_NU8;
  b->ncols_str = PS_NSTR;
  size_t cnt = items->arr.size();
  b->i64.resize(cnt * P_NI64);
  b->i32.resize(cnt * P_NI32);
  b->u8.resize(cnt * P_NU8);
  b->str.resize(cnt * PS_NSTR * 2);
  b->heap.reserve((size_t)n / 8);
  if (const Val* meta = root->get("metadata"))
    if (const Val* rv = meta->get("resourceVersion"))
      if (rv->kind == Val::Str) b->rv.assign(rv->text);

  for (const Val* item : items->arr) {
    if (!item || item->kind != Val::Obj) continue;
    const Val* meta = item->get("metadata");
    const Val* spec = item->get("spec");
    const Val* status = item->get("status");
    long i = b->count;

    int64_t cpu = 0, mem = 0, eph = 0;
    if (spec) {
      if (const Val* containers = spec->get("containers")) {
        if (containers->kind == Val::Arr) {
          for (const Val* c : containers->arr) {
            const Val* res = c ? c->get("resources") : nullptr;
            const Val* req = res ? res->get("requests") : nullptr;
            if (!req || req->kind != Val::Obj) continue;
            for (const auto& m : req->obj) {
              if (m.first == "cpu") cpu += cpu_millis(m.second);
              else if (m.first == "memory") mem += base_units(m.second);
              else if (m.first == "ephemeral-storage")
                eph += base_units(m.second);
            }
          }
        }
      }
    }
    b->i64[(size_t)i * P_NI64 + P_CPU] = cpu;
    b->i64[(size_t)i * P_NI64 + P_MEM] = mem;
    b->i64[(size_t)i * P_NI64 + P_EPH] = eph;
    auto i32row = [&](int col) -> int32_t& {
      return b->i32[(size_t)i * P_NI32 + col];
    };
    i32row(P_PRIO) = (int32_t)(spec ? as_int(spec->get("priority")) : 0);

    uint8_t flags = 0;
    if (meta) {
      if (const Val* ann = meta->get("annotations"))
        if (ann->get("kubernetes.io/config.mirror")) flags |= F_MIRROR;
      if (const Val* owners = meta->get("ownerReferences")) {
        if (owners->kind == Val::Arr) {
          for (const Val* ref : owners->arr) {
            const Val* ctl = ref ? ref->get("controller") : nullptr;
            if (ctl && ctl->kind == Val::Bool && ctl->b) {
              flags |= F_REPLICATED;
              const Val* kind = ref->get("kind");
              if (kind && kind->kind == Val::Str && kind->text == "DaemonSet")
                flags |= F_DAEMONSET;
              break;  // first controller ref, like controller_ref()
            }
          }
        }
      }
    }
    std::string_view phase = "Running";
    if (status) {
      const Val* ph = status->get("phase");
      if (ph && ph->kind == Val::Str) phase = ph->text;
    }
    if (phase == "Succeeded" || phase == "Failed") flags |= F_TERMINAL;
    if (phase == "Pending") flags |= F_PENDING;
    std::string pod_ns;
    field(&pod_ns, meta, "namespace");
    if (pod_ns.empty()) pod_ns = "default";
    std::string anti_host_blob;
    std::string anti_zone_blob;
    std::string paff_blob;
    std::string pzaff_blob;
    std::string naff_blob;
    std::string pvc_blob;
    std::string spread_blob;
    if (spec) {
      bool unmodeled = false;
      const Val* affinity = spec->get("affinity");
      const Val* aff_obj =
          (affinity && affinity->kind == Val::Obj) ? affinity : nullptr;
      extract_anti_affinity(
          aff_obj ? aff_obj->get("podAntiAffinity") : nullptr,
          &anti_host_blob, &anti_zone_blob, &unmodeled);
      extract_pod_affinity(
          aff_obj ? aff_obj->get("podAffinity") : nullptr,
          &paff_blob, &pzaff_blob, &unmodeled);
      extract_node_affinity(
          aff_obj ? aff_obj->get("nodeAffinity") : nullptr,
          &unmodeled, &naff_blob);
      if (unmodeled) flags |= F_REQAFF;
      if (const Val* vols = spec->get("volumes")) {
        if (vols->kind == Val::Arr) {
          bool names_ok = true;
          for (const Val* vol : vols->arr) {
            const Val* claim = vol ? vol->get("persistentVolumeClaim") : nullptr;
            if (!claim) continue;
            flags |= F_PVC;
            // claim names feed the volume-affinity resolver; any
            // malformed (or blob-unsafe) name voids the whole list so
            // the pod can never be resolved - decode_pod lockstep
            const Val* cn =
                claim->kind == Val::Obj ? claim->get("claimName") : nullptr;
            if (!names_ok || !cn || cn->kind != Val::Str || cn->text.empty() ||
                has_sep_bytes(cn->text)) {
              names_ok = false;
              pvc_blob.clear();
              continue;
            }
            if (!pvc_blob.empty()) pvc_blob += REC_SEP;
            pvc_blob.append(cn->text.data(), cn->text.size());
          }
        }
      }
      // Hard topology-spread constraints: canonical shapes are modeled
      // (blob -> SpreadBit verdicts in the packers); anything beyond
      // stays unmodeled — exact lockstep with io/kube.py
      // decode_topology_spread.
      {
        bool spread_unmodeled = false;
        extract_topology_spread(spec->get("topologySpreadConstraints"),
                                &spread_unmodeled, &spread_blob);
        if (spread_unmodeled) {
          flags |= F_REQAFF;
          spread_blob.clear();
        }
      }
    }
    b->u8[(size_t)i * P_NU8 + P_FLAGS] = flags;

    std::string tmp;
    field(&tmp, meta, "name");
    b->put_str(PS_NAME, tmp);
    tmp.clear();
    field(&tmp, meta, "uid");
    b->put_str(PS_UID, tmp);

    i32row(P_NSID) = b->intern_str(TBL_NS, pod_ns);
    std::string tmp2;
    field(&tmp2, spec, "nodeName");
    i32row(P_NODEID) = b->intern_str(TBL_NODE, tmp2);
    tmp2.clear();
    blob_kv_into(&tmp2, meta ? meta->get("labels") : nullptr);
    i32row(P_LABELSID) = b->intern_str(TBL_LABELS, tmp2);
    tmp2.clear();
    blob_kv_into(&tmp2, spec ? spec->get("nodeSelector") : nullptr);
    i32row(P_SELID) = b->intern_str(TBL_NODESEL, tmp2);
    i32row(P_AAFFID) = b->intern_str(TBL_AAFF, anti_host_blob);
    i32row(P_NAFFID) = b->intern_str(TBL_NAFF, naff_blob);
    i32row(P_PAFFID) = b->intern_str(TBL_PAFF, paff_blob);
    i32row(P_ZAFFID) = b->intern_str(TBL_ZAFF, anti_zone_blob);
    i32row(P_PVCID) = b->intern_str(TBL_PVC, pvc_blob);
    i32row(P_SPREADID) = b->intern_str(TBL_SPREAD, spread_blob);
    i32row(P_PZAFFID) = b->intern_str(TBL_PZAFF, pzaff_blob);

    // tolerations: key\x1fvalue\x1foperator\x1feffect\x1e...
    tmp.clear();
    if (spec) {
      if (const Val* tols = spec->get("tolerations")) {
        if (tols->kind == Val::Arr) {
          for (const Val* t : tols->arr) {
            if (!t || t->kind != Val::Obj) continue;
            field(&tmp, t, "key");
            tmp += UNIT_SEP;
            field(&tmp, t, "value");
            tmp += UNIT_SEP;
            {
              std::string op;
              field(&op, t, "operator");
              tmp += op.empty() ? "Equal" : op;
            }
            tmp += UNIT_SEP;
            field(&tmp, t, "effect");
            tmp += REC_SEP;
          }
        }
      }
    }
    i32row(P_TOLID) = b->intern_str(TBL_TOLS, tmp);

    b->count++;
  }
  return b;
}

Batch* ingest_nodes_impl(const char* buf, long n) {
  Parser parser(buf, (size_t)n);
  const Val* root = parser.parse_value();
  if (!parser.ok || !root || root->kind != Val::Obj) return nullptr;
  const Val* items = root->get("items");
  if (!items || items->kind != Val::Arr) return nullptr;

  auto* b = new Batch();
  b->ncols_i64 = N_NI64;
  b->ncols_i32 = 0;
  b->ncols_u8 = N_NU8;
  b->ncols_str = NS_NSTR;
  size_t cnt = items->arr.size();
  b->i64.resize(cnt * N_NI64);
  b->u8.resize(cnt * N_NU8);
  b->str.resize(cnt * NS_NSTR * 2);
  if (const Val* meta = root->get("metadata"))
    if (const Val* rv = meta->get("resourceVersion"))
      if (rv->kind == Val::Str) b->rv.assign(rv->text);

  for (const Val* item : items->arr) {
    if (!item || item->kind != Val::Obj) continue;
    const Val* meta = item->get("metadata");
    const Val* spec = item->get("spec");
    const Val* status = item->get("status");
    long i = b->count;

    int64_t cpu = 0, mem = 0, eph = 0, pods = 0;
    bool has_pods = false;
    if (status) {
      if (const Val* alloc = status->get("allocatable")) {
        if (alloc->kind == Val::Obj) {
          for (const auto& m : alloc->obj) {
            if (m.first == "cpu") cpu = cpu_millis(m.second);
            else if (m.first == "memory") mem = base_units(m.second);
            else if (m.first == "ephemeral-storage") eph = base_units(m.second);
            else if (m.first == "pods") {
              pods = base_units(m.second);
              has_pods = true;
            }
          }
        }
      }
    }
    b->i64[(size_t)i * N_NI64 + N_CPU] = cpu;
    b->i64[(size_t)i * N_NI64 + N_MEM] = mem;
    b->i64[(size_t)i * N_NI64 + N_EPH] = eph;
    b->i64[(size_t)i * N_NI64 + N_PODS] = pods;

    bool ready = false;
    if (status) {
      if (const Val* conds = status->get("conditions")) {
        if (conds->kind == Val::Arr) {
          for (const Val* c : conds->arr) {
            const Val* t = c ? c->get("type") : nullptr;
            const Val* s = c ? c->get("status") : nullptr;
            if (t && t->kind == Val::Str && t->text == "Ready" && s &&
                s->kind == Val::Str && s->text == "True")
              ready = true;
          }
        }
      }
    }
    const Val* unsched = spec ? spec->get("unschedulable") : nullptr;
    b->u8[(size_t)i * N_NU8 + N_READY] = ready;
    b->u8[(size_t)i * N_NU8 + N_UNSCHED] =
        unsched && unsched->kind == Val::Bool && unsched->b;
    b->u8[(size_t)i * N_NU8 + N_HASPODS] = has_pods;

    std::string tmp;
    field(&tmp, meta, "name");
    b->put_str(NS_NAME, tmp);
    tmp.clear();
    field(&tmp, meta, "uid");
    b->put_str(NS_UID, tmp);
    blob_kv(b, NS_LABELS, meta ? meta->get("labels") : nullptr);

    // taints: key\x1fvalue\x1feffect\x1e...
    size_t start = b->heap.size();
    if (spec) {
      if (const Val* taints = spec->get("taints")) {
        if (taints->kind == Val::Arr) {
          for (const Val* t : taints->arr) {
            if (!t || t->kind != Val::Obj) continue;
            std::string row;
            field(&row, t, "key");
            row += UNIT_SEP;
            field(&row, t, "value");
            row += UNIT_SEP;
            {
              std::string eff;
              field(&eff, t, "effect");
              row += eff.empty() ? "NoSchedule" : eff;
            }
            row += REC_SEP;
            b->heap += row;
          }
        }
      }
    }
    b->str[(size_t)i * NS_NSTR * 2 + NS_TAINTS * 2] = (int64_t)start;
    b->str[(size_t)i * NS_NSTR * 2 + NS_TAINTS * 2 + 1] =
        (int64_t)(b->heap.size() - start);

    b->count++;
  }
  return b;
}

}  // namespace

extern "C" {

void* ingest_pods(const char* buf, long n) { return ingest_pods_impl(buf, n); }
void* ingest_nodes(const char* buf, long n) {
  return ingest_nodes_impl(buf, n);
}
void ingest_free(void* h) { delete (Batch*)h; }

long batch_count(void* h) { return ((Batch*)h)->count; }
const int64_t* batch_i64(void* h) { return ((Batch*)h)->i64.data(); }
const int32_t* batch_i32(void* h) { return ((Batch*)h)->i32.data(); }
const uint8_t* batch_u8(void* h) { return ((Batch*)h)->u8.data(); }
const int64_t* batch_str(void* h) { return ((Batch*)h)->str.data(); }
const char* batch_heap(void* h, long* len) {
  Batch* b = (Batch*)h;
  *len = (long)b->heap.size();
  return b->heap.data();
}
const char* batch_rv(void* h) { return ((Batch*)h)->rv.c_str(); }
const int64_t* batch_table(void* h, int family, long* count) {
  Batch* b = (Batch*)h;
  if (family < 0 || family >= TBL_COUNT) {
    *count = 0;
    return nullptr;
  }
  *count = (long)(b->tbl[family].size() / 2);
  return b->tbl[family].data();
}

// self-description so the Python side never hardcodes layouts twice
int pod_ncols_i64() { return P_NI64; }
int pod_ncols_i32() { return P_NI32; }
int pod_ncols_u8() { return P_NU8; }
int pod_ncols_str() { return PS_NSTR; }
int node_ncols_i64() { return N_NI64; }
int node_ncols_u8() { return N_NU8; }
int node_ncols_str() { return NS_NSTR; }
int table_count() { return TBL_COUNT; }
// Interned-blob ACCEPTANCE version: bumped whenever either the blob
// encoding OR the modeled/unmodeled decision surface changes, so a
// stale .so can never silently disagree with the Python reference
// decoder (io/native_ingest.py refuses it and falls back).
// 2 = round-5 widened affinity/spread term format;
// 3 = + namespaceSelector {} wildcard, explicit-default spread
//     modifiers, arbitrary spread topology keys.
int blob_format_version() { return 3; }

}  // extern "C"
