from k8s_spot_rescheduler_tpu.cli.main import main
import sys

sys.exit(main())
