"""Planner sidecar service."""

from k8s_spot_rescheduler_tpu.sidecar.server import PlannerSidecar

__all__ = ["PlannerSidecar"]
