"""Planner sidecar: the solver behind a JSON/HTTP service boundary.

BASELINE.json's north star splits control loop and solver across a
process boundary ("the Go side calls a gRPC/JAX sidecar") so an existing
controller — including the Go reference itself — can delegate only the
per-tick drain *plan* to the TPU while keeping its own eviction path.
This is that boundary: POST a cluster snapshot in Kubernetes API shapes
(the same objects the controller already holds), get back the drain
decision.

    POST /v1/plan
      {"nodes": [<k8s Node>...], "pods": [<k8s Pod>...],
       "pdbs": [<k8s PDB>...]}
    → {"found": true, "node": "od-17", "pods": [...],
       "assignments": {"ns/pod": "spot-3", ...},
       "nCandidates": 2500, "nFeasible": 856, "solveMs": 66.2}

    GET /healthz → {"ok": true, "solver": "pallas"}

One SolverPlanner lives for the process lifetime, so jit caches and the
high-water-mark padding survive across requests — a steady stream of
plans never recompiles.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s_spot_rescheduler_tpu.io.kube import decode_node, decode_pdb, decode_pod
from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log


class PlannerSidecar:
    def __init__(self, config: ReschedulerConfig, address: str = "127.0.0.1:8642"):
        self.config = config
        self.planner = SolverPlanner(config)
        self._lock = threading.Lock()  # one solve at a time; jit is cached
        host, _, port = address.rpartition(":")
        sidecar = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    return self._send({"ok": True, "solver": sidecar.config.solver})
                return self._send({"error": "not found"}, 404)

            def do_POST(self):
                if self.path != "/v1/plan":
                    return self._send({"error": "not found"}, 404)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length))
                    result = sidecar.plan(body)
                except (ValueError, KeyError) as err:
                    return self._send({"error": str(err)}, 400)
                except Exception as err:  # noqa: BLE001 — solver failure
                    log.error("sidecar plan failed: %s", err)
                    return self._send({"error": str(err)}, 500)
                return self._send(result)

        self.server = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)

    @property
    def address(self) -> str:
        host, port = self.server.server_address
        return f"{host}:{port}"

    def plan(self, body: dict) -> dict:
        nodes = [decode_node(o) for o in body.get("nodes", [])]
        pods = [decode_pod(o) for o in body.get("pods", [])]
        pdbs = [decode_pdb(o) for o in body.get("pdbs", [])]
        pods_by_node: dict = {}
        for pod in pods:
            pods_by_node.setdefault(pod.node_name, []).append(pod)
        node_map = build_node_map(
            [n for n in nodes if n.ready],
            pods_by_node,
            on_demand_label=self.config.on_demand_node_label,
            spot_label=self.config.spot_node_label,
            priority_threshold=self.config.priority_threshold,
        )
        with self._lock:
            report = self.planner.plan(node_map, pdbs)
        out = {
            "found": report.plan is not None,
            "nCandidates": report.n_candidates,
            "nFeasible": report.n_feasible,
            "solveMs": round(report.solve_seconds * 1e3, 3),
        }
        if report.plan is not None:
            out["node"] = report.plan.node.node.name
            out["pods"] = [p.uid for p in report.plan.pods]
            out["assignments"] = report.plan.assignments
        return out

    def serve_forever(self) -> None:
        log.info("planner sidecar listening on %s", self.address)
        self.server.serve_forever()

    def start_background(self) -> None:
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self) -> None:
        self.server.shutdown()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="spot-rescheduler-sidecar")
    ap.add_argument("--listen", default="127.0.0.1:8642")
    ap.add_argument("--solver", default="jax",
                    choices=["jax", "numpy", "pallas", "sharded"])
    ap.add_argument("-v", "--verbosity", type=int, default=0)
    args = ap.parse_args(argv)
    log.setup(args.verbosity)
    sidecar = PlannerSidecar(
        ReschedulerConfig(solver=args.solver), args.listen
    )
    sidecar.serve_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
