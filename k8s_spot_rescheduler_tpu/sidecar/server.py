"""Planner sidecar: the solver behind a JSON/HTTP service boundary.

BASELINE.json's north star splits control loop and solver across a
process boundary ("the Go side calls a gRPC/JAX sidecar") so an existing
controller — including the Go reference itself — can delegate only the
per-tick drain *plan* to the TPU while keeping its own eviction path.
This is that boundary: POST a cluster snapshot in Kubernetes API shapes
(the same objects the controller already holds), get back the drain
decision.

    POST /v1/plan
      {"nodes": [<k8s Node>...], "pods": [<k8s Pod>...],
       "pdbs": [<k8s PDB>...],
       "pvcs": [<k8s PVC>...], "pvs": [<k8s PV>...]}   # optional
    → {"found": true, "node": "od-17", "pods": [...],
       "assignments": {"ns/pod": "spot-3", ...},
       "nCandidates": 2500, "nFeasible": 856, "solveMs": 66.2}

    PVC/PV sections are optional: with them, PVC-backed pods resolve
    their volume topology (models/volumes.py) exactly as the in-process
    loop does; without them such pods stay conservatively unplaceable.

    GET /healthz → {"ok": true, "solver": "pallas"}

One SolverPlanner lives for the process lifetime, so jit caches and the
high-water-mark padding survive across requests — a steady stream of
plans never recompiles.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s_spot_rescheduler_tpu.io.kube import decode_node, decode_pdb, decode_pod
from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log


class PlannerSidecar:
    """Deployable solver service (deploy/sidecar.yaml ships it), so its
    edges are bounded:

    - ``max_body_bytes`` caps the snapshot size (413 beyond it; a 50k-pod
      cluster LIST is ~30 MB, so the default leaves ample headroom while
      keeping a misdirected upload from exhausting memory);
    - one solve runs at a time (jit caches are per-process; concurrent
      tracing would thrash them); a request whose turn has not come
      within ``busy_timeout_s`` gets 503 + Retry-After. The solve itself
      is not interruptible (an XLA dispatch cannot be safely cancelled
      mid-flight), so the busy timeout is the deadline knob;
    - ``max_inflight`` caps queue DEPTH: past it, /v1/plan returns 503
      immediately — before the body is even read — so a burst cannot
      hold more than max_inflight x max_body_bytes of request memory
      (ThreadingHTTPServer is thread-per-request; the busy timeout
      alone only capped queue *time*).
    """

    def __init__(
        self,
        config: ReschedulerConfig,
        address: str = "127.0.0.1:8642",
        *,
        max_body_bytes: int = 128 << 20,
        busy_timeout_s: float = 30.0,
        max_inflight: int = 4,
    ):
        self.config = config
        self.planner = SolverPlanner(config)
        self.max_body_bytes = int(max_body_bytes)
        self.busy_timeout_s = float(busy_timeout_s)
        self.max_inflight = int(max_inflight)
        self._lock = threading.Lock()  # one solve at a time; jit is cached
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        host, _, port = address.rpartition(":")
        sidecar = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, obj, code=200, headers=()):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    # merge the control loop's degradation state
                    # (loop/health.py): when a controller shares this
                    # process, a liveness probe here sees planner
                    # fallback / breaker status and the age of the last
                    # completed tick without scraping Prometheus
                    from k8s_spot_rescheduler_tpu.loop import health

                    out = {"ok": True, "solver": sidecar.config.solver}
                    out.update(health.snapshot())
                    return self._send(out)
                return self._send({"error": "not found"}, 404)

            def _reject_unread(self, obj, code, headers=()):
                """A response sent BEFORE the body was read must close
                the connection: under keep-alive the unconsumed body
                bytes would desync the next request on this socket
                (advisor r4; harmless today with HTTP/1.0
                close-per-request, load-bearing the day
                protocol_version is raised). Applies to every pre-read
                reject — 400/404/413/503 alike."""
                self.close_connection = True
                return self._send(
                    obj, code,
                    headers=tuple(headers) + (("Connection", "close"),),
                )

            def do_POST(self):
                if self.path != "/v1/plan":
                    return self._reject_unread({"error": "not found"}, 404)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    return self._reject_unread(
                        {"error": "bad Content-Length"}, 400
                    )
                if length < 0:
                    # a negative length must not reach rfile.read(-1),
                    # which would buffer the stream until EOF — the exact
                    # exhaustion the size cap exists to prevent
                    return self._reject_unread(
                        {"error": "bad Content-Length"}, 400
                    )
                if length > sidecar.max_body_bytes:
                    return self._reject_unread(
                        {
                            "error": "snapshot exceeds %d-byte limit"
                            % sidecar.max_body_bytes
                        },
                        413,
                    )
                # depth guard BEFORE the body read: a rejected request
                # never buffers its payload, so a burst holds at most
                # max_inflight parsed bodies regardless of its size
                if not sidecar._admit():
                    return self._reject_unread(
                        {
                            "error": "planner overloaded (%d requests in "
                            "flight)" % sidecar.max_inflight
                        },
                        503,
                        headers=[("Retry-After", "1")],
                    )
                try:
                    try:
                        body = json.loads(self.rfile.read(length))
                    except ValueError as err:
                        return self._send({"error": str(err)}, 400)
                    if not sidecar._lock.acquire(
                        timeout=sidecar.busy_timeout_s
                    ):
                        return self._send(
                            {"error": "planner busy (solve in progress)"},
                            503,
                            headers=[("Retry-After", "1")],
                        )
                    try:
                        result = sidecar.plan_locked(body)
                    except (ValueError, KeyError) as err:
                        return self._send({"error": str(err)}, 400)
                    except Exception as err:  # noqa: BLE001 — solver failure
                        log.error("sidecar plan failed: %s", err)
                        return self._send({"error": str(err)}, 500)
                    finally:
                        sidecar._lock.release()
                    return self._send(result)
                finally:
                    sidecar._release()

        self.server = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)

    def _admit(self) -> bool:
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            return True

    def _release(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    @property
    def address(self) -> str:
        host, port = self.server.server_address
        return f"{host}:{port}"

    def plan(self, body: dict) -> dict:
        """Decode + solve, serialized on the sidecar lock (public entry
        for in-process callers; the HTTP handler holds the lock already
        and calls plan_locked)."""
        if not self._lock.acquire(timeout=self.busy_timeout_s):
            raise TimeoutError("planner busy (solve in progress)")
        try:
            return self.plan_locked(body)
        finally:
            self._lock.release()

    def plan_locked(self, body: dict) -> dict:
        nodes = [decode_node(o) for o in body.get("nodes", [])]
        pods = [decode_pod(o) for o in body.get("pods", [])]
        pdbs = [decode_pdb(o) for o in body.get("pdbs", [])]
        pvc_objs = body.get("pvcs") or []
        pv_objs = body.get("pvs") or []
        if pvc_objs or pv_objs:
            from k8s_spot_rescheduler_tpu.io.kube import (
                decode_volume_snapshots,
            )
            from k8s_spot_rescheduler_tpu.models.volumes import (
                resolve_volume_affinity,
            )

            pvcs, pvs = decode_volume_snapshots(pvc_objs, pv_objs)
            pods = [
                resolve_volume_affinity(p, pvcs, pvs)
                if p.pvc_resolvable
                else p
                for p in pods
            ]
        pods_by_node: dict = {}
        for pod in pods:
            pods_by_node.setdefault(pod.node_name, []).append(pod)
        node_map = build_node_map(
            [n for n in nodes if n.ready],
            pods_by_node,
            on_demand_label=self.config.on_demand_node_label,
            spot_label=self.config.spot_node_label,
            priority_threshold=self.config.priority_threshold,
            # not-ready nodes are presence-only (zone/spread counts) —
            # dropping them would overstate the spread domain-min, the
            # permissive direction (same rule as the control loop)
            unready_nodes=[n for n in nodes if not n.ready],
        )
        report = self.planner.plan(node_map, pdbs)
        out = {
            "found": report.plan is not None,
            "nCandidates": report.n_candidates,
            "nFeasible": report.n_feasible,
            "solveMs": round(report.solve_seconds * 1e3, 3),
        }
        if report.plan is not None:
            out["node"] = report.plan.node.node.name
            out["pods"] = [p.uid for p in report.plan.pods]
            out["assignments"] = report.plan.assignments
        return out

    def serve_forever(self) -> None:
        log.info("planner sidecar listening on %s", self.address)
        self.server.serve_forever()

    def start_background(self) -> None:
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self) -> None:
        self.server.shutdown()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="spot-rescheduler-sidecar")
    ap.add_argument("--listen", default="127.0.0.1:8642")
    ap.add_argument("--solver", default="jax",
                    choices=["jax", "numpy", "pallas", "sharded"])
    ap.add_argument("--max-body-mb", type=int, default=128,
                    help="reject /v1/plan snapshots larger than this (413)")
    ap.add_argument("--busy-timeout", type=float, default=30.0,
                    help="seconds a request may wait for the in-flight "
                         "solve before 503 (backpressure, not queueing)")
    ap.add_argument("--max-inflight", type=int, default=4,
                    help="reject /v1/plan immediately (503) past this many "
                         "concurrent requests — bounds worst-case request "
                         "memory at max-inflight x max-body-mb")
    ap.add_argument("-v", "--verbosity", type=int, default=0)
    args = ap.parse_args(argv)
    log.setup(args.verbosity)
    sidecar = PlannerSidecar(
        ReschedulerConfig(solver=args.solver), args.listen,
        max_body_bytes=args.max_body_mb << 20,
        busy_timeout_s=args.busy_timeout,
        max_inflight=args.max_inflight,
    )
    sidecar.serve_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
