"""Planner sidecar: the JSON/HTTP face of the multi-tenant service.

BASELINE.json's north star splits control loop and solver across a
process boundary ("the Go side calls a gRPC/JAX sidecar") so an existing
controller — including the Go reference itself — can delegate only the
per-tick drain *plan* to the TPU while keeping its own eviction path.
This module keeps that JSON boundary:

    POST /v1/plan
      {"nodes": [<k8s Node>...], "pods": [<k8s Pod>...],
       "pdbs": [<k8s PDB>...],
       "pvcs": [<k8s PVC>...], "pvs": [<k8s PV>...]}   # optional
    → {"found": true, "node": "od-17", "pods": [...],
       "assignments": {"ns/pod": "spot-3", ...},
       "nCandidates": 2500, "nFeasible": 856, "solveMs": 1.2,
       "batchLanes": 24, "batchTenants": 3}

    PVC/PV sections are optional: with them, PVC-backed pods resolve
    their volume topology (models/volumes.py) exactly as the in-process
    loop does; without them such pods stay conservatively unplaceable.

    GET /healthz → {"ok": true, "solver": "pallas",
                    "queue_depth": 0, "bucket_occupancy": {...},
                    "tenant_last_plan_age_s": {...},
                    "batch_cadence_s": 0.004, ...}

Since the multi-tenant promotion (service/server.py), the sidecar IS the
planner service: ``PlannerSidecar`` is the service's HTTP server with
the historical constructor surface (``busy_timeout_s`` maps onto the
queue's bounded wait). The one-solve-at-a-time lock is gone — /v1/plan
requests decode, pack and ride the SAME batching queue as the binary
``/v2/plan`` tenants, so there is exactly one solve path and JSON
callers co-batch with wire-protocol agents. Consequences visible at
this boundary:

- a request that cannot be batched within ``busy_timeout_s`` gets 503
  with ``Retry-After`` derived from the MEASURED batch cadence (how
  long until a batch slot actually frees), not the static timeout;
- ``max_inflight``/``max_body_bytes`` keep their pre-body-read
  rejection semantics (a burst holds at most max_inflight bodies);
- jit caches and shape-bucket compiles live for the process lifetime —
  a steady stream of plans never recompiles.
"""

from __future__ import annotations

from typing import Optional

from k8s_spot_rescheduler_tpu.service.server import ServiceServer
from k8s_spot_rescheduler_tpu.utils.clock import Clock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log


class PlannerSidecar(ServiceServer):
    """Deployable solver service (deploy/sidecar.yaml ships it). The
    historical single-tenant surface over the multi-tenant core."""

    def __init__(
        self,
        config: ReschedulerConfig,
        address: str = "127.0.0.1:8642",
        *,
        max_body_bytes: int = 128 << 20,
        busy_timeout_s: float = 30.0,
        max_inflight: int = 4,
        batch_window_s: Optional[float] = None,
        clock: Optional[Clock] = None,
    ):
        super().__init__(
            config,
            address,
            max_body_bytes=max_body_bytes,
            queue_timeout_s=busy_timeout_s,
            max_inflight=max_inflight,
            batch_window_s=batch_window_s,
            clock=clock,
        )

    def plan(self, body: dict) -> dict:
        """Decode + pack + solve through the batching queue (public
        entry for in-process callers; HTTP callers use /v1/plan)."""
        return self.plan_json(body)

    def serve_forever(self) -> None:
        log.info("planner sidecar listening on %s", self.address)
        super().serve_forever()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="spot-rescheduler-sidecar")
    ap.add_argument("--listen", default="127.0.0.1:8642")
    ap.add_argument("--solver", default="jax",
                    choices=["jax", "numpy", "pallas", "sharded"])
    ap.add_argument("--max-body-mb", type=int, default=128,
                    help="reject /v1/plan snapshots larger than this (413)")
    ap.add_argument("--busy-timeout", type=float, default=30.0,
                    help="seconds a request may wait in the batching "
                         "queue before 503 (backpressure; Retry-After "
                         "reports the measured batch cadence)")
    ap.add_argument("--max-inflight", type=int, default=4,
                    help="reject /v1/plan immediately (503) past this many "
                         "concurrent requests — bounds worst-case request "
                         "memory at max-inflight x max-body-mb")
    ap.add_argument("-v", "--verbosity", type=int, default=0)
    args = ap.parse_args(argv)
    log.setup(args.verbosity)
    sidecar = PlannerSidecar(
        ReschedulerConfig(solver=args.solver), args.listen,
        max_body_bytes=args.max_body_mb << 20,
        busy_timeout_s=args.busy_timeout,
        max_inflight=args.max_inflight,
    )
    sidecar.serve_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
