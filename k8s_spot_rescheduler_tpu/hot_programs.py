"""HOT_PROGRAMS manifest infrastructure: the traced-program registry.

The jaxpr-tier auditor (tools/analysis/jaxpr, ``make audit-jaxpr``)
proves dtype, index-width, transfer, and memory properties on the
programs XLA actually traces — not on the source the AST tier vets. For
that it needs a declared list of hot programs and a way to trace each
one shape-only on CPU (``jax.make_jaxpr`` over ``ShapeDtypeStruct``
args: no device buffers, no execution, cost independent of the probe
shape). This module owns the shared pieces:

- :class:`ProbeShapes` — the parameterized packed dims ``(C, K, S, R,
  W, A)`` a program is traced at, with the declared scale points
  (:data:`MAX_SHAPES` = the 20x ROADMAP-5 target, 1M pods / 100k
  nodes; :data:`RECONCILE_SHAPES` = the measured single-chip boundary
  pins of tests/test_sharding.py);
- :func:`packed_struct` / :func:`delta_struct` — ShapeDtypeStruct
  pytrees mirroring models/tensors.PackedCluster and
  models/columnar.PackedDelta at a ProbeShapes point;
- :class:`HotProgram` — one manifest entry: a lazy ``build`` closure
  returning ``(fn, args)`` to trace, the ``covers`` list of jit-root
  qualnames the trace exercises (checked by the AST-tier
  ``manifest-contract`` pass against the roots the PR-5 call graph
  discovers), the declared ``donate_argnums`` (audited for true
  aliasing), and the optional ``reconcile`` spec tying the trace to
  solver/memory's HBM estimate;
- :func:`collect` — import the manifest-bearing solver modules and
  merge their ``HOT_PROGRAMS`` dicts (lazy: importing this module pulls
  in no solver code).

Every ``jax.jit`` / ``pjit`` / ``shard_map`` root under solver/, ops/,
parallel/, planner/ must be covered by some entry here or listed in
:data:`EXEMPT_JIT_ROOTS` with a justification — ``manifest-contract``
(tools/analysis/passes/contracts.py) turns ``make check`` red
otherwise, so the jaxpr tier's coverage can never silently shrink.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple


class ProbeShapes(NamedTuple):
    """Packed problem dims a hot program is traced at. C candidate
    lanes, K pod slots per lane, S spot nodes, R resources, W taint
    words, A affinity words (models/tensors.PackedCluster)."""

    C: int
    K: int
    S: int
    R: int = 4
    W: int = 2
    A: int = 2


# The declared maximum scale for the index-width pass: ROADMAP item 5's
# 20x target, 1M pods / 100k nodes. C+S = 102_400 nodes and C*K = 1.64M
# pod slots cover the target with headroom; these are 20x the measured
# config-3 packed dims (C=S=2560, K=32) that tests/test_sharding.py pins
# the HBM boundary at. Every index the traced programs compute must fit
# its carrying dtype AT THESE SHAPES — the precondition for the
# narrow-int carry packing ROADMAP 5 plans.
MAX_SHAPES = ProbeShapes(C=51_200, K=32, S=51_200, R=4, W=2, A=2)

# memory-reconcile probe points: the 1x and 4x config-3 shapes whose
# estimate tests/test_sharding.py pins against the measured single-chip
# envelope (4x fits a 16 GB v5e, 8x does not). Two scales so the pass
# can also prove the estimator's ASYMPTOTICS match the traced program.
RECONCILE_SHAPES = (
    ProbeShapes(C=2_560, K=32, S=2_560, R=4, W=2, A=2),
    ProbeShapes(C=10_240, K=32, S=10_240, R=4, W=2, A=2),
)

# How many scatter rows the delta-scatter probe carries (any power of
# two works; the real pad ladder is solver_planner._pad_pow2).
DELTA_PROBE_ROWS = 256


class HotProgram(NamedTuple):
    """One manifest entry (see module docstring).

    ``build(shapes)`` returns ``(fn, args)`` or ``(fn, args,
    static_argnums)`` — args are ShapeDtypeStruct pytrees, so building
    is allocation-free. ``covers`` strings are matched as
    dot/colon-bounded suffixes of discovered jit-root qualnames
    (``<module>:<qualname>``). ``reconcile`` is either
    ``{"repair_spot_chunks": n}`` (diff the trace against
    solver/memory.estimate_union_hbm_breakdown at that chunking) or
    ``{"estimator": fn}`` (fixture/test hook: ``fn(shapes) -> {component
    -> bytes}``). ``index_width=False`` skips the max-shape probe for
    programs whose trace is only meaningful at bounded shapes."""

    build: Callable
    covers: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    reconcile: Optional[dict] = None
    index_width: bool = True


# jit roots deliberately NOT in any HOT_PROGRAMS manifest, pattern ->
# justification. Matched like ``covers``. Currently empty: every root
# in the tree is traced. The mechanism exists so a future hardware-only
# program can opt out loudly instead of silently shrinking coverage.
EXEMPT_JIT_ROOTS: dict = {}

# Modules owning a HOT_PROGRAMS dict, registered beside their jit
# roots. manifest-contract proves this list and the discovered roots
# stay in lockstep.
MANIFEST_MODULES = (
    "k8s_spot_rescheduler_tpu.solver.ffd",
    "k8s_spot_rescheduler_tpu.solver.repair",
    "k8s_spot_rescheduler_tpu.solver.select",
    "k8s_spot_rescheduler_tpu.solver.prefilter",
    "k8s_spot_rescheduler_tpu.solver.fallback",
    "k8s_spot_rescheduler_tpu.solver.schedule",
    "k8s_spot_rescheduler_tpu.ops.pallas_ffd",
    "k8s_spot_rescheduler_tpu.parallel.sharded_ffd",
    "k8s_spot_rescheduler_tpu.parallel.tenant_batch",
    "k8s_spot_rescheduler_tpu.planner.solver_planner",
)


def _sds(shape, dtype):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def packed_struct(s: ProbeShapes):
    """A models/tensors.PackedCluster of ShapeDtypeStructs at ``s`` —
    the canonical shape-only probe argument (dtypes are the pack
    contract pinned in the PackedCluster docstring)."""
    from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

    return PackedCluster(
        slot_req=_sds((s.C, s.K, s.R), "float32"),
        slot_valid=_sds((s.C, s.K), "bool"),
        slot_tol=_sds((s.C, s.K, s.W), "uint32"),
        slot_aff=_sds((s.C, s.K, s.A), "uint32"),
        cand_valid=_sds((s.C,), "bool"),
        spot_free=_sds((s.S, s.R), "float32"),
        spot_count=_sds((s.S,), "int32"),
        spot_max_pods=_sds((s.S,), "int32"),
        spot_taints=_sds((s.S, s.W), "uint32"),
        spot_ok=_sds((s.S,), "bool"),
        spot_aff=_sds((s.S, s.A), "uint32"),
    )


def delta_struct(s: ProbeShapes, rows: int = DELTA_PROBE_ROWS):
    """A models/columnar.PackedDelta of ShapeDtypeStructs: ``rows``
    changed lanes / cand rows / spot rows (the padded sections the
    donated scatter consumes)."""
    from k8s_spot_rescheduler_tpu.models.columnar import PackedDelta

    return PackedDelta(
        lanes=_sds((rows,), "int32"),
        lane_slot_req=_sds((rows, s.K, s.R), "float32"),
        lane_slot_valid=_sds((rows, s.K), "bool"),
        lane_slot_tol=_sds((rows, s.K, s.W), "uint32"),
        lane_slot_aff=_sds((rows, s.K, s.A), "uint32"),
        cand_rows=_sds((rows,), "int32"),
        cand_valid=_sds((rows,), "bool"),
        spot_rows=_sds((rows,), "int32"),
        spot_free=_sds((rows, s.R), "float32"),
        spot_count=_sds((rows,), "int32"),
        spot_max_pods=_sds((rows,), "int32"),
        spot_taints=_sds((rows, s.W), "uint32"),
        spot_ok=_sds((rows,), "bool"),
        spot_aff=_sds((rows, s.A), "uint32"),
    )


def collect():
    """Import every manifest module and merge the entries. Returns
    ``{name: (HotProgram, module_file_path)}``; duplicate names raise
    (two modules claiming one program name is a manifest bug)."""
    import importlib

    out = {}
    for mod_name in MANIFEST_MODULES:
        mod = importlib.import_module(mod_name)
        programs = getattr(mod, "HOT_PROGRAMS", {})
        for name, hp in programs.items():
            if name in out:
                raise ValueError(
                    f"duplicate HOT_PROGRAMS entry {name!r} "
                    f"(in {mod.__file__} and {out[name][1]})"
                )
            out[name] = (hp, mod.__file__)
    return out
