"""Planner backed by the batched solvers.

Pack → solve → select. Selection reproduces the reference's loop policy
(reference rescheduler.go:228-287): candidates are in least-requested-CPU
order, the first feasible one is drained.

Device discipline (the lesson of the bandwidth-constrained host↔device
boundary): the accelerator solvers run *solve + selection* fused on device
(solver/select.py) and the host fetches only (index, found, count,
assignment-row) — a few hundred bytes — never the full [C, K] assignment
matrix. The numpy oracle backend returns everything on the host anyway.

Shape discipline: pad floors persist across calls (high-water marks) so
the jitted solver does not recompile every tick as the cluster breathes.

Incremental device residency (the per-tick upload was ~60 ms of the
1.2 s CPU-fallback tick, BENCH_r05): the previous tick's problem tensors
stay resident in device memory; each tick the host pack is DIFFED against
the previous one (models/columnar.emit_packed_delta) and only the changed
candidate lanes / validity bits / spot rows ship across the boundary,
applied by a donated-buffer scatter program so the update is in-place in
HBM. Shape growth past the high-water pads falls back to a full
re-upload, counted in ``solver_full_repack_total``. The solve itself is
staged (solver/select.StagedPlanner): chunks of lanes in selection
order, prefilter-eliminated chunks skipped, stop at the first feasible
chunk — the selection the loop acts on is bit-identical either way.
"""

from __future__ import annotations

import functools
import time
from typing import Sequence

import numpy as np

from k8s_spot_rescheduler_tpu.models.cluster import PDBSpec
from k8s_spot_rescheduler_tpu.planner.base import PlanReport, pack_observation
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


def _enable_jax_compilation_cache(cache_dir: str) -> None:
    """Point XLA's persistent compilation cache at ``cache_dir`` (created
    if absent) so the multi-second cold compiles of the solver programs
    are paid once per image, not per process restart. Threshold knobs
    are forced to cache-everything where the jax build has them — the
    programs here are few and large, never a cache-pollution risk."""
    import os

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # noqa: BLE001 — older jax: defaults still
            pass  # cache the big solver compiles


class SolverPlanner:
    """The production Planner: TPU ("jax"/"pallas"/"sharded") or host
    ("numpy") solver behind one interface."""

    def __init__(self, config: ReschedulerConfig):
        self.config = config
        if config.jax_cache_dir and config.solver != "numpy":
            _enable_jax_compilation_cache(config.jax_cache_dir)
        self._pad_c = 0
        self._pad_s = 0
        self._pad_k = config.max_pods_per_node_hint
        self._fused = None  # device path
        self._union_fn = None  # the raw union program behind _fused
        self._staged = None  # lazy chunked early-exit planner
        self._fused_sharded = None  # lazy 2-D auto-shard reroute
        self._fused_cand_sharded = None  # lazy cand-only reroute (repair on)
        self._fused_carry = None  # lazy carry-streamed narrow reroute
        # incremental device cache: last tick's problem, resident in HBM,
        # plus the host copy the next tick's delta is diffed against
        self._device_packed = None
        self._host_prev = None
        self._apply_delta_jit = None
        self.last_solver = config.solver  # what the last plan actually ran
        # drain-schedule machinery (solver/schedule.py): one jitted
        # while-loop program per horizon, plus the fetch accounting the
        # consolidation benches assert O(1) on
        self._sched_planners = {}
        self.fetches_total = 0  # blocking planner fetches (plan + schedule)
        self.schedule_lens = []  # steps per cut schedule, this planner's life
        if config.solver == "numpy":
            self._solve_host = plan_oracle
        else:
            self._fused = self._make_fused(config.solver)

    def _make_fused(self, name: str):
        from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

        base = self._base_solver(name)
        if self.config.fallback_best_fit and self.config.repair_rounds > 0:
            from k8s_spot_rescheduler_tpu.solver.fallback import with_repair

            union = with_repair(base, self.config.repair_rounds)
        elif self.config.fallback_best_fit:
            from k8s_spot_rescheduler_tpu.solver.fallback import with_best_fit_fallback

            union = with_best_fit_fallback(base)
        else:
            union = base
        self._union_fn = union
        return make_fused_planner(union)

    def _staged_planner(self):
        """The chunked early-exit wrapper around the SAME union program
        ``_fused`` runs (selection-equivalent by tests/test_incremental)."""
        if self._staged is None:
            from k8s_spot_rescheduler_tpu.solver.select import (
                make_staged_planner,
            )

            self._staged = make_staged_planner(
                self._union_fn,
                chunk_lanes=self.config.staged_chunk_lanes,
                early_exit=self.config.staged_early_exit,
            )
        return self._staged

    # ------------------------------------------------------------------
    # incremental device cache (delta-pack + donated scatter update)

    @staticmethod
    def _pad_pow2(n: int) -> int:
        """Pad delta sections to power-of-two lengths so the donated
        scatter program compiles O(log(max churn)) times, not per tick
        (models/columnar.pad_pow2 — one ladder, shared with the planner
        service's batched tenant scatter)."""
        from k8s_spot_rescheduler_tpu.models.columnar import pad_pow2

        return pad_pow2(n)

    def _delta_apply_fn(self):
        if self._apply_delta_jit is None:
            import jax

            from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

            # donate the 11 resident tensors: the scatter updates alias
            # them in place in device memory — per-tick traffic is the
            # (padded) delta alone, not the cluster
            @functools.partial(jax.jit, donate_argnums=tuple(range(11)))
            def apply(
                slot_req, slot_valid, slot_tol, slot_aff, cand_valid,
                spot_free, spot_count, spot_max_pods, spot_taints,
                spot_ok, spot_aff, d,
            ):
                # pad entries carry an out-of-bounds index -> dropped
                return PackedCluster(
                    slot_req=slot_req.at[d.lanes].set(
                        d.lane_slot_req, mode="drop"
                    ),
                    slot_valid=slot_valid.at[d.lanes].set(
                        d.lane_slot_valid, mode="drop"
                    ),
                    slot_tol=slot_tol.at[d.lanes].set(
                        d.lane_slot_tol, mode="drop"
                    ),
                    slot_aff=slot_aff.at[d.lanes].set(
                        d.lane_slot_aff, mode="drop"
                    ),
                    cand_valid=cand_valid.at[d.cand_rows].set(
                        d.cand_valid, mode="drop"
                    ),
                    spot_free=spot_free.at[d.spot_rows].set(
                        d.spot_free, mode="drop"
                    ),
                    spot_count=spot_count.at[d.spot_rows].set(
                        d.spot_count, mode="drop"
                    ),
                    spot_max_pods=spot_max_pods.at[d.spot_rows].set(
                        d.spot_max_pods, mode="drop"
                    ),
                    spot_taints=spot_taints.at[d.spot_rows].set(
                        d.spot_taints, mode="drop"
                    ),
                    spot_ok=spot_ok.at[d.spot_rows].set(
                        d.spot_ok, mode="drop"
                    ),
                    spot_aff=spot_aff.at[d.spot_rows].set(
                        d.spot_aff, mode="drop"
                    ),
                )

            self._apply_delta_jit = apply
        return self._apply_delta_jit

    def _pad_delta(self, delta, C: int, S: int):
        """Pad each delta section to a power-of-two length; index pads
        point one past the axis end (dropped by the scatter). The
        shared models/columnar.pad_packed_delta — the planner service's
        wire-delta path pads with the same helper."""
        from k8s_spot_rescheduler_tpu.models.columnar import (
            pad_packed_delta,
        )

        return pad_packed_delta(delta, C, S)

    def _upload_incremental(self, packed):
        """Move this tick's problem to the device through the resident
        cache. Returns (device_packed, delta_lanes, full_repack,
        upload_bytes); ``delta_lanes`` is -1 on a full re-upload."""
        import jax

        from k8s_spot_rescheduler_tpu.models.columnar import emit_packed_delta

        delta = None
        if self._device_packed is not None and self._host_prev is not None:
            delta = emit_packed_delta(self._host_prev, packed)
        if delta is not None:
            try:
                padded = self._pad_delta(
                    delta, packed.slot_req.shape[0], packed.spot_free.shape[0]
                )
                device_packed = self._delta_apply_fn()(
                    *self._device_packed, padded
                )
                self._host_prev = packed
                self._device_packed = device_packed
                upload = sum(np.asarray(f).nbytes for f in padded)
                return device_packed, delta.n_lanes, False, upload
            except Exception as err:  # noqa: BLE001 — donation may have
                # consumed the cache mid-failure: rebuild from scratch
                log.error("delta apply failed (%s); full re-upload", err)
                self._device_packed = None
        device_packed = jax.device_put(packed)
        self._host_prev = packed
        self._device_packed = device_packed
        upload = sum(getattr(packed, f).nbytes for f in packed._fields)
        return device_packed, -1, True, upload

    def _base_solver(self, name: str):
        """A solve(packed, best_fit=False) callable for the backend."""
        if name == "jax":
            from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd

            return plan_ffd
        try:
            if name == "pallas":
                from k8s_spot_rescheduler_tpu.ops.pallas_ffd import plan_ffd_pallas

                return lambda p, best_fit=False: plan_ffd_pallas(
                    p, best_fit=best_fit
                )
            if name == "sharded":
                import functools

                from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh
                from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
                    plan_ffd_sharded,
                )

                mesh = make_mesh(
                    self.config.mesh_shape
                    if self.config.mesh_shape != (1, 1)
                    else None
                )
                return functools.partial(plan_ffd_sharded, mesh)
        except ImportError as err:
            raise ValueError(
                f"solver {name!r} is not available in this build: {err}"
            ) from err
        raise ValueError(f"unknown solver {name!r}")

    def _sharded_fused_planner(self):
        """The 2-D (cand×spot) auto-shard reroute: first-fit ∪ best-fit
        over the device mesh (parallel/sharded_ffd.py), built once on
        first use. The repair phase is absent on THIS layout — its
        eject-reinsert search state needs a lane's full spot axis on one
        device, which is exactly what the spot sharding splits.
        Conservative: may prove fewer drains than the union program
        would have, never an invalid one. ``_maybe_shard`` only lands
        here when even the cand-only layout's per-device block — with
        its repair rounds fully spot-CHUNKED — exceeds the budget."""
        if self._fused_sharded is None:
            import functools

            from k8s_spot_rescheduler_tpu.parallel.mesh import make_mesh
            from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
                plan_ffd_sharded,
            )
            from k8s_spot_rescheduler_tpu.solver.fallback import (
                with_best_fit_fallback,
            )
            from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

            mesh = make_mesh(
                self.config.mesh_shape
                if self.config.mesh_shape != (1, 1)
                else None
            )
            base = functools.partial(plan_ffd_sharded, mesh)
            self._mesh_shape = tuple(mesh.devices.shape)
            self._fused_sharded = make_fused_planner(
                with_best_fit_fallback(base)
                if self.config.fallback_best_fit
                else base
            )
        return self._fused_sharded

    def _cand_sharded_fused_planner(self, repair_chunks: int = 1):
        """The cand-only reroute (round 5, VERDICT r4 #2): candidate
        lanes shard over ALL devices, the spot axis replicates, and each
        device runs the COMPLETE union program — repair included — on
        its lane block (parallel/sharded_ffd.plan_union_cand_sharded).
        Preferred over the 2-D layout whenever one lane block's full
        spot state fits a device: same quality as single-chip, just
        more lanes in flight. ``repair_chunks`` > 1 runs the
        elect-then-commit spot-chunked repair inside each device
        (bit-identical; round 6) — the tier's reach past the unchunked
        ceiling. One fused planner is built per chunk count (the count
        is a compile-time shape decision, stable across ticks at the
        high-water pads)."""
        if self._fused_cand_sharded is None:
            self._fused_cand_sharded = {}
        if repair_chunks not in self._fused_cand_sharded:
            import functools

            from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
            from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
                plan_union_cand_sharded,
            )
            from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

            cfg = self.config
            mesh = make_cand_mesh()
            self._fused_cand_sharded[repair_chunks] = make_fused_planner(
                functools.partial(
                    plan_union_cand_sharded,
                    mesh,
                    rounds=(
                        cfg.repair_rounds if cfg.fallback_best_fit else 0
                    ),
                    best_fit_fallback=cfg.fallback_best_fit,
                    repair_spot_chunks=repair_chunks,
                )
            )
        return self._fused_cand_sharded[repair_chunks]

    def _carry_streamed_fused_planner(self, carry_chunks: int, layout):
        """The carry-streamed cand tier (ROADMAP 5): lanes shard over
        all devices and each device runs the NARROW delta-carry
        streamed union (solver/fallback.with_repair_streamed) on its
        block — first-fit spot-streamed with leftovers flowing forward,
        best-fit and the repair rounds on the stacked narrow state —
        bit-identical to the single-chip union, resident carries ~2x
        smaller and per-round temporaries O(S / carry_chunks). With the
        ``pallas`` solver the best-fit pass runs the fused
        elect-then-commit stream kernel instead of the XLA scan
        (ops/pallas_ffd.plan_stream_bf_pallas, bit-identical — the
        narrow carry stays resident in VMEM). One fused planner per
        (chunk count, layout) — both are compile-time decisions, stable
        across ticks at the high-water pads."""
        if self._fused_carry is None:
            self._fused_carry = {}
        key = (carry_chunks, layout)
        if key not in self._fused_carry:
            import functools

            from k8s_spot_rescheduler_tpu.parallel.mesh import make_cand_mesh
            from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
                plan_union_cand_sharded,
            )
            from k8s_spot_rescheduler_tpu.solver.select import make_fused_planner

            cfg = self.config
            mesh = make_cand_mesh()
            self._fused_carry[key] = make_fused_planner(
                functools.partial(
                    plan_union_cand_sharded,
                    mesh,
                    rounds=(
                        cfg.repair_rounds if cfg.fallback_best_fit else 0
                    ),
                    best_fit_fallback=cfg.fallback_best_fit,
                    carry_chunks=carry_chunks,
                    carry_layout=layout,
                    use_pallas=(cfg.solver == "pallas"),
                )
            )
        return self._fused_carry[key]

    def _maybe_shard(self, packed):
        """Pick the device program for this problem's shapes: the
        configured solver; past the single-chip HBM estimate, the
        cand-only sharded union (repair INTACT — each device runs the
        full single-chip program on a lane block) when a block fits one
        device; past THAT, the same tier with elect-then-commit
        spot-CHUNKED repair (solver/repair.plan_repair_chunked,
        bit-identical) at the chunk count solver/memory.
        pick_repair_chunks sizes to the budget; past the wide chunked
        ceiling, the CARRY-STREAMED tier (ROADMAP 5): narrow delta
        carries sized by the pack's exact layout guard
        (solver/carry.carry_layout) with the spot axis streamed at
        ``solver/memory.pick_carry_chunks``'s count — repair still
        LIVE, results still bit-identical; only when even the narrow
        streamed block exceeds the budget does the 2-D cand×spot
        layout (repair off) engage — the one regime
        ``repair_unavailable`` fires in. The ladder decision itself is
        ``solver/memory.pick_tier`` (shared with bench.py and
        ``make scale-smoke``, so the surfaces can't drift). Returns
        (fused, label, repair_dropped, repair_chunks, carry_chunks,
        carry_bytes)."""
        cfg = self.config
        wants_repair = cfg.fallback_best_fit and cfg.repair_rounds > 0
        own_chunks = 1 if wants_repair else 0
        if (
            not cfg.auto_shard
            or self._fused is None
            or cfg.solver == "sharded"  # already the mesh path
        ):
            return self._fused, cfg.solver, False, own_chunks, 0, -1
        from k8s_spot_rescheduler_tpu.solver import carry as carry_mod
        from k8s_spot_rescheduler_tpu.solver import memory

        try:
            import jax

            n_devices = len(jax.devices())
        except Exception:  # noqa: BLE001 — no backend: keep configured path
            return self._fused, cfg.solver, False, own_chunks, 0, -1
        budget = cfg.solver_hbm_budget or None
        C, K, S, R, W, A = memory.packed_shapes(packed)
        # deferred + memoized: the exact layout guard is an O(C·K·R)
        # host pass only the carry rung pays, and it pays it ONCE (the
        # dispatch branch below reuses the same verdict)
        layout_memo = []

        def _layout():
            if not layout_memo:
                layout_memo.append(carry_mod.carry_layout(packed))
            return layout_memo[0]

        tier = memory.pick_tier(
            C, K, S, R, W, A,
            n_devices=n_devices,
            budget_bytes=budget,
            wants_repair=wants_repair,
            carry_plane_bytes=lambda: carry_mod.plane_bytes(
                _layout(), R, A
            ),
            forced_carry_chunks=cfg.carry_chunks,
        )
        if tier.kind == "single":
            return self._fused, cfg.solver, False, own_chunks, 0, tier.carry_bytes
        if tier.kind == "cand":
            fused = self._cand_sharded_fused_planner()
            log.info(
                "Problem exceeds single-chip HBM; dispatching to "
                "cand-sharded union over %d devices (%d-lane blocks, "
                "est %.1f GB/device; repair intact)",
                n_devices,
                tier.lane_block,
                tier.est_bytes / 1e9,
            )
            return (
                fused, f"{cfg.solver}+cand-sharded", False, own_chunks, 0,
                tier.carry_bytes,
            )
        if tier.kind == "cand-chunked":
            fused = self._cand_sharded_fused_planner(tier.repair_chunks)
            log.info(
                "Problem exceeds single-chip HBM; dispatching to "
                "cand-sharded union with repair chunked over %d spot "
                "chunks (est %.1f GB/device; repair intact)",
                tier.repair_chunks,
                tier.est_bytes / 1e9,
            )
            return (
                fused,
                f"{cfg.solver}+cand-sharded",
                False,
                tier.repair_chunks,
                0,
                tier.carry_bytes,
            )
        if tier.kind == "cand-carry":
            layout = _layout()  # memoized: computed once per dispatch
            fused = self._carry_streamed_fused_planner(
                tier.carry_chunks, layout
            )
            log.info(
                "Problem exceeds the wide chunked ceiling; dispatching "
                "to cand-sharded CARRY-STREAMED union over %d devices "
                "(%d-lane blocks, %d carry chunks, layout %s/%s/%s, "
                "est %.1f GB/device of which carries %.1f GB; repair "
                "intact)",
                n_devices,
                tier.lane_block,
                tier.carry_chunks,
                layout.used,
                layout.count,
                layout.aff,
                tier.est_bytes / 1e9,
                tier.carry_bytes / 1e9,
            )
            return (
                fused,
                f"{cfg.solver}+cand-carry",
                False,
                tier.repair_chunks,
                tier.carry_chunks,
                tier.carry_bytes,
            )
        fused = self._sharded_fused_planner()
        log.info(
            "Problem exceeds single-chip HBM (even the narrow "
            "carry-streamed 1/%d lane block exceeds it); dispatching to "
            "2-D mesh-sharded solver (%s mesh); repair phase "
            "unavailable at this scale",
            n_devices,
            "x".join(map(str, getattr(self, "_mesh_shape", ()))),
        )
        return fused, f"{cfg.solver}+sharded", wants_repair, 0, 0, tier.carry_bytes

    # SolverPlanner can plan straight from a ColumnarStore snapshot (the
    # vectorized observe path); the control loop checks this before
    # handing it one instead of a NodeMap.
    accepts_columnar = True

    def _pack_observation(self, observation, pdbs):
        """The shared pack path (planner/base.pack_observation): used
        by plan_async, plan_schedule, and the drain-schedule execution
        handle, whose per-step live re-pack must be exactly what a
        fresh plan would solve."""
        return pack_observation(self, observation, pdbs)

    def plan(self, observation, pdbs: Sequence[PDBSpec]) -> PlanReport:
        """``observation`` is either a classified ``NodeMap`` (object
        path, reference-faithful) or a ``models/columnar.ColumnarStore``
        (vectorized fast path); both pack to the same tensors."""
        return self.plan_async(observation, pdbs)()

    def plan_async(self, observation, pdbs: Sequence[PDBSpec]):
        """The pipelined half-tick: pack on host, ship the delta (or the
        full problem) to the device, and async-dispatch the solve — JAX
        returns control before the device finishes. The returned zero-arg
        ``finish`` callable blocks on the tiny selection fetch and builds
        the PlanReport; the control loop runs its host-side metrics pass
        between the two so it overlaps the in-flight solve."""
        t0 = time.perf_counter()
        cfg = self.config
        # spans land on the controller's ambient tick trace (no-ops
        # when tracing is off or no trace is active)
        with tracing.span("plan.pack") as pack_sp:
            packed, meta = self._pack_observation(observation, pdbs)
            if pack_sp is not None:
                pack_sp.attrs["lanes"] = int(packed.slot_req.shape[0])

        for blocked in meta.blocking_pods():
            log.info("BlockingPod: %s (%s)", blocked.pod.uid, blocked.reason)

        solver_label = cfg.solver
        repair_dropped = False
        repair_chunks = (
            1 if cfg.fallback_best_fit and cfg.repair_rounds > 0 else 0
        )
        carry_chunks = 0
        carry_bytes = -1
        fetch = None
        delta_lanes, full_repack, upload_bytes = -1, False, -1
        if self._fused is not None:
            from k8s_spot_rescheduler_tpu.solver.select import decode_selection

            (
                fused,
                solver_label,
                repair_dropped,
                repair_chunks,
                carry_chunks,
                carry_bytes,
            ) = self._maybe_shard(packed)
            # the incremental cache and the staged solve apply only to the
            # plain single-chip program: the mesh reroutes manage their own
            # placement (shard_map shardings), and slicing a sharded axis
            # would fight the mesh layout
            single_chip = fused is self._fused and cfg.solver in (
                "jax",
                "pallas",
            )
            if not single_chip and self._device_packed is not None:
                # a mesh reroute engaged (the problem outgrew one chip):
                # holding the stale single-chip cache would pin a near-
                # budget tensor set in device memory exactly when the
                # sharded program needs the headroom
                self._device_packed = None
                self._host_prev = None
            device_packed = packed
            if cfg.incremental_device_cache and single_chip:
                with tracing.span("plan.delta-upload") as up_sp:
                    (
                        device_packed,
                        delta_lanes,
                        full_repack,
                        upload_bytes,
                    ) = self._upload_incremental(packed)
                    if up_sp is not None:
                        up_sp.attrs["delta_bytes"] = int(upload_bytes)
                        up_sp.attrs["lanes"] = int(delta_lanes)
                        if full_repack:
                            up_sp.attrs["full_repack"] = True
            elif cfg.staged_chunk_lanes > 0 and single_chip:
                # cache off but staging on: ship the problem ONCE — the
                # per-chunk jit calls would otherwise each re-upload the
                # host arrays
                import jax

                device_packed = jax.device_put(packed)
            if cfg.staged_chunk_lanes > 0 and single_chip:
                staged = self._staged_planner()
                # blocks on the tiny prefilter fetch, then the first
                # chunk is already solving while the caller's host work
                # (the controller's metrics pass) runs
                run = staged.start(device_packed)

                def fetch(r=run):
                    return staged.finish_run(r)

            else:
                pending_vec = fused(device_packed)  # async dispatch

                def fetch(pv=pending_vec):
                    return decode_selection(pv), None

        def finish() -> PlanReport:
            staged_stats = None
            # one blocking planner fetch per completed plan (device
            # selection fetch or host solve) — the denominator of the
            # consolidation benches' O(1)-fetch assertion
            self.fetches_total += 1
            with tracing.span("plan.solve"):
                if fetch is not None:
                    sel, staged_stats = fetch()
                    plan = (
                        meta.build_plan(sel.index, sel.row)
                        if sel.found
                        else None
                    )
                    n_feasible = sel.n_feasible
                else:
                    # the shared host union (first-fit ∪ best-fit ∪
                    # repair, cond-gated like the device path) — one
                    # implementation for this branch and the planner
                    # service's host path
                    from k8s_spot_rescheduler_tpu.solver.numpy_oracle import (
                        plan_union_oracle,
                    )

                    result = plan_union_oracle(
                        packed,
                        best_fit_fallback=cfg.fallback_best_fit,
                        repair_rounds=cfg.repair_rounds,
                    )
                    feasible = np.asarray(result.feasible)
                    n_feasible = int(feasible.sum())
                    plan = None
                    if n_feasible:
                        c = int(np.argmax(feasible))
                        plan = meta.build_plan(
                            c, np.asarray(result.assignment[c])
                        )

            self._report_conservatism(packed, meta, n_feasible)

            # solver-mode observability: what actually ran, and whether the
            # repair phase the config asked for was available on that path
            # (the sharded program drops it past single-chip scale)
            from k8s_spot_rescheduler_tpu.metrics import registry as metrics

            # repair_dropped comes from the dispatch decision itself: only
            # the 2-D cand×spot reroute loses the repair phase (cand-only
            # keeps it — spot-chunked past the unchunked ceiling, counted
            # in solver_repair_chunks; a solver CONFIGURED as 'sharded'
            # keeps its wrapper)
            metrics.update_solver_mode(
                cfg.solver, solver_label, repair_dropped,
                repair_chunks=repair_chunks,
                carry_chunks=carry_chunks,
                carry_bytes=carry_bytes,
            )
            # /healthz mirrors the same verdict beside solver_mode
            # (loop/health.py) — one site, surfaces agree
            from k8s_spot_rescheduler_tpu.loop import health

            health.STATE.note_solver_mode(
                solver_label, carry_chunks, carry_bytes
            )

            self.last_solver = solver_label
            report = PlanReport(
                plan=plan,
                n_candidates=meta.n_candidates,
                n_feasible=n_feasible,
                solve_seconds=time.perf_counter() - t0,
                solver=solver_label,
                feasible_candidates=[plan] if plan else [],
                delta_pack_lanes=delta_lanes,
                full_repack=full_repack,
                upload_bytes=upload_bytes,
                chunks_solved=(
                    staged_stats.chunks_solved if staged_stats else -1
                ),
                chunks_skipped=(
                    staged_stats.chunks_skipped if staged_stats else 0
                ),
                count_truncated=(
                    staged_stats.count_truncated if staged_stats else False
                ),
                repair_chunks=repair_chunks,
                carry_chunks=carry_chunks,
            )
            return report

        return finish

    # ------------------------------------------------------------------
    # drain-to-exhaustion schedules (solver/schedule.py)

    def _schedule_planner_for(self, horizon: int):
        """The jitted while-loop schedule program over the SAME union
        program ``_fused`` wraps, one compile per horizon value."""
        if horizon not in self._sched_planners:
            from k8s_spot_rescheduler_tpu.solver.schedule import (
                make_schedule_planner,
            )

            self._sched_planners[horizon] = make_schedule_planner(
                self._union_fn, horizon
            )
        return self._sched_planners[horizon]

    def plan_schedule(self, observation, pdbs: Sequence[PDBSpec]):
        """Cut a whole drain schedule in ONE fetch: pack, run the
        device drain→commit→re-solve loop (solver/schedule.py), and
        return a ``planner/schedule.DrainSchedule`` the control loop
        executes across ticks with per-step live validation. Returns
        None when this problem's shapes dispatch to a mesh reroute
        (the schedule program is single-chip; the caller then plans
        per-tick, losing only the fetch amortization)."""
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics
        from k8s_spot_rescheduler_tpu.planner.schedule import DrainSchedule
        from k8s_spot_rescheduler_tpu.solver import schedule as sched_mod

        cfg = self.config
        horizon = max(1, cfg.schedule_horizon)
        with tracing.span("plan.schedule") as sp:
            packed, meta = self._pack_observation(observation, pdbs)
            for blocked in meta.blocking_pods():
                log.info(
                    "BlockingPod: %s (%s)", blocked.pod.uid, blocked.reason
                )
            if self._fused is None:
                mat = sched_mod.plan_schedule_oracle(
                    packed,
                    horizon,
                    best_fit_fallback=cfg.fallback_best_fit,
                    repair_rounds=cfg.repair_rounds,
                )
                label = cfg.solver
            elif cfg.solver not in ("jax", "pallas"):
                # the configured mesh solver composes its own sharded
                # placement; the schedule while-loop is single-chip
                log.vlog(
                    2,
                    "solver %r has no drain-schedule program; planning "
                    "per tick", cfg.solver,
                )
                return None
            else:
                fused, label, _, _, _, _ = self._maybe_shard(packed)
                if fused is not self._fused:
                    # the problem outgrew one chip: the mesh tiers
                    # manage their own placement and the schedule
                    # program is single-chip — per-tick planning takes
                    # over (correctness unchanged, fetches O(drains))
                    log.vlog(
                        2,
                        "mesh reroute engaged; drain schedules "
                        "unavailable at this scale — planning per tick",
                    )
                    return None
                device_packed = packed
                if cfg.incremental_device_cache and cfg.solver in (
                    "jax",
                    "pallas",
                ):
                    # ship through the resident delta cache: the
                    # schedule program reads the cached tensors without
                    # donating them, so the next tick's diff still holds
                    device_packed = self._upload_incremental(packed)[0]
                else:
                    import jax

                    device_packed = jax.device_put(packed)
                mat = np.asarray(
                    self._schedule_planner_for(horizon)(device_packed)
                )  # the ONE fetch for up to `horizon` drains
            steps = sched_mod.decode_schedule(mat)
            self.fetches_total += 1
            self.schedule_lens.append(len(steps))
            metrics.update_plan_schedule_len(len(steps))
            # why-no-drain observability per CUT (schedules are the
            # default path now): step 0's feasible count IS the fresh
            # solve's — a zero-step cut classifies every blocked
            # candidate exactly like a per-tick no-drain plan would
            self._report_conservatism(
                packed, meta, steps[0].n_feasible if steps else 0
            )
            if sp is not None:
                sp.attrs["steps"] = len(steps)
                sp.attrs["horizon"] = horizon
        self.last_solver = label
        return DrainSchedule(
            steps,
            packed,
            meta,
            pack_fn=self._pack_observation,
            solver_label=f"{label}+schedule",
            horizon=horizon,
            base_observation=observation,
        )

    def _report_conservatism(self, packed, meta, n_feasible: int) -> None:
        """Why-no-drain observability (metrics/registry.py conservatism
        gauges): classify every non-drainable candidate. The reference
        only logs the blocking pod per node (rescheduler.go:232-238);
        here the safe-direction over-approximations (unmodeled
        constraints pack as placeable-nowhere) additionally surface as
        metrics, because one such pod per on-demand node silently pins
        the controller at zero drains forever."""
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        by_reason = {"pdb": 0, "non-replicated": 0}
        for blocked in meta.blocking_pods():
            if blocked.reason.startswith("pod is not replicated"):
                by_reason["non-replicated"] += 1
            else:
                by_reason["pdb"] += 1
        unmodeled_mask = meta.unmodeled_candidate_mask()
        by_reason["unmodeled"] = int(unmodeled_mask.sum())
        cand_valid = np.asarray(packed.cand_valid)[: meta.n_candidates]
        by_reason["no-capacity"] = max(
            0,
            int(cand_valid.sum()) - n_feasible - by_reason["unmodeled"],
        )
        n_unplaceable = meta.unplaceable_pod_count()
        metrics.update_conservatism(n_unplaceable, by_reason)
        if n_feasible == 0 and any(by_reason.values()):
            log.vlog(
                2,
                "No drainable candidate: %d blocked (%s); %d unplaceable "
                "pod(s) on candidate nodes.",
                sum(by_reason.values()),
                ", ".join(f"{k}={v}" for k, v in sorted(by_reason.items()) if v),
                n_unplaceable,
            )


# Jaxpr-tier audit manifest (k8s_spot_rescheduler_tpu/hot_programs.py,
# tools/analysis/jaxpr): the donated-buffer scatter. The transfer-audit
# pass proves every donate_argnums position actually aliases an output
# (shape/dtype match) — a donated-but-copied resident tensor would
# silently double the steady-state footprint.
from k8s_spot_rescheduler_tpu.hot_programs import (  # noqa: E402
    HotProgram,
    delta_struct,
    packed_struct,
)


def _delta_scatter_build(s):
    planner = SolverPlanner.__new__(SolverPlanner)  # no config/compile
    planner._apply_delta_jit = None
    return (
        planner._delta_apply_fn(),
        (*packed_struct(s), delta_struct(s)),
    )


HOT_PROGRAMS = {
    "planner.delta_scatter": HotProgram(
        build=_delta_scatter_build,
        covers=("planner.solver_planner:SolverPlanner._delta_apply_fn.apply",),
        donate_argnums=tuple(range(11)),
    ),
}
