"""Planner backed by the batched solvers.

Pack → solve → select. Selection reproduces the reference's loop policy
(reference rescheduler.go:228-287): candidates are in least-requested-CPU
order, the first feasible one is drained. Because the batched solver judges
*every* candidate in one pass, all feasible candidates come back in the
report — the faithful loop drains only the first; benchmarks and the
multi-drain mode read the rest.

Shape discipline: pad floors persist across calls (high-water marks) so the
jitted solver does not recompile every tick as the cluster breathes.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from k8s_spot_rescheduler_tpu.models.cluster import NodeMap, PDBSpec
from k8s_spot_rescheduler_tpu.models.tensors import PackMeta, pack_cluster
from k8s_spot_rescheduler_tpu.planner.base import DrainPlan, PlanReport
from k8s_spot_rescheduler_tpu.solver.numpy_oracle import plan_oracle
from k8s_spot_rescheduler_tpu.solver.result import SolveResult
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log


class SolverPlanner:
    """The production Planner: TPU ("jax"/"pallas"/"sharded") or host
    ("numpy") solver behind one interface."""

    def __init__(self, config: ReschedulerConfig):
        self.config = config
        self._pad_c = 0
        self._pad_s = 0
        self._pad_k = config.max_pods_per_node_hint
        self._solve = self._make_solver(config.solver)

    def _make_solver(self, name: str):
        if name == "numpy":
            return plan_oracle
        if name in ("pallas", "sharded"):
            try:
                return self._make_accel_solver(name)
            except ImportError as err:
                raise ValueError(
                    f"solver {name!r} is not available in this build: {err}"
                ) from err
        if name == "jax":
            from k8s_spot_rescheduler_tpu.solver.ffd import plan_ffd_jit

            return plan_ffd_jit
        raise ValueError(f"unknown solver {name!r}")

    def _make_accel_solver(self, name: str):
        if name == "pallas":
            from k8s_spot_rescheduler_tpu.ops.pallas_ffd import plan_ffd_pallas_jit

            return plan_ffd_pallas_jit
        from k8s_spot_rescheduler_tpu.parallel.sharded_ffd import (
            make_sharded_planner,
        )

        return make_sharded_planner(self.config.mesh_shape)

    def plan(self, node_map: NodeMap, pdbs: Sequence[PDBSpec]) -> PlanReport:
        t0 = time.perf_counter()
        packed, meta = pack_cluster(
            node_map,
            pdbs,
            resources=self.config.resources,
            delete_non_replicated=self.config.delete_non_replicated_pods,
            pad_candidates=self._pad_c,
            pad_spot=self._pad_s,
            pad_slots=self._pad_k,
        )
        # high-water-mark padding: shapes only ever grow → no recompile churn
        self._pad_c = max(self._pad_c, packed.slot_req.shape[0])
        self._pad_k = max(self._pad_k, packed.slot_req.shape[1])
        self._pad_s = max(self._pad_s, packed.spot_free.shape[0])

        for blocked in meta.blocking:
            if blocked is not None:
                log.info("BlockingPod: %s (%s)", blocked.pod.uid, blocked.reason)

        result = self._solve(packed)
        feasible = np.asarray(result.feasible)
        assignment = np.asarray(result.assignment)
        report = self._select(meta, feasible, assignment)
        report.solve_seconds = time.perf_counter() - t0
        report.solver = self.config.solver
        return report

    def _select(
        self, meta: PackMeta, feasible: np.ndarray, assignment: np.ndarray
    ) -> PlanReport:
        plans = []
        for c in range(len(meta.candidates)):
            if not feasible[c]:
                continue
            pods = meta.cand_pods[c]
            assignments = {
                pod.uid: meta.spot[int(assignment[c, k])].node.name
                for k, pod in enumerate(pods)
            }
            plans.append(
                DrainPlan(
                    node=meta.candidates[c],
                    pods=list(pods),
                    assignments=assignments,
                    candidate_index=c,
                )
            )
        return PlanReport(
            plan=plans[0] if plans else None,
            n_candidates=len(meta.candidates),
            n_feasible=len(plans),
            solve_seconds=0.0,
            feasible_candidates=plans,
        )
