"""Planner layer: plan(state) -> DrainPlan."""

from k8s_spot_rescheduler_tpu.planner.base import DrainPlan, Planner
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner

__all__ = ["DrainPlan", "Planner", "SolverPlanner"]
