"""Drain-schedule execution: the host-side half of the device loop.

``solver/schedule.py`` cuts a whole drain schedule in one device fetch
under the quiescent-cluster assumption; this module is the layer that
makes executing it SAFE. A :class:`DrainSchedule` wraps one cut
schedule plus the packed snapshot it was cut against, and the control
loop draws drains from it across ticks through ``next_plan`` — which,
per *executed* step:

1. **re-packs the live mirror** (the same observe path a fresh plan
   uses — the schedule never acts on stale tensors);
2. **checks the step's precondition**: the live pack must still match
   the schedule's *predicted* state — the base snapshot evolved by the
   host twin of the device commit (``commit_step_host``) — compared BY
   NODE NAME so the packer's re-sorting between ticks (spot probe
   order follows requested CPU, which the controller's own drains
   change) is not mistaken for churn. Compared surfaces: the candidate
   set and each remaining lane's slot requests/validity, and every
   spot node's free/count/max-pods/admission state. The interned
   taint/affinity WORDS are deliberately not compared across packs
   (their bit layouts are pack-relative); the admission surface is
   instead re-proven from scratch per step, below;
3. **re-proves the placement from scratch** (solver/validate.py)
   against the LIVE pack — the same proven-placement invariant every
   other path honors: a search (or prediction) bug can lose a drain,
   never strand a pod.

Any failed check *invalidates the schedule tail*: ``next_plan`` returns
None with ``invalidated`` set, the controller counts it
(``schedule_invalidated_total`` + a ``schedule-invalidated`` flight
event) and re-plans fresh. Churn costs a fetch, never a wrong eviction.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import numpy as np

from k8s_spot_rescheduler_tpu.planner.base import PlanReport
from k8s_spot_rescheduler_tpu.solver.schedule import (
    ScheduleStep,
    commit_step_host,
    slice_lane,
)
from k8s_spot_rescheduler_tpu.solver.validate import validate_assignment


def _meta_names(meta):
    """(candidate node names, spot node names) for either meta flavor
    (models/tensors.PackMeta or models/columnar.ColumnarMeta)."""
    store = getattr(meta, "store", None)
    if store is not None:
        cand = [store.node_objs[int(r)].name for r in meta.cand_rows]
        spot = [store.node_objs[int(r)].name for r in meta.spot_rows]
    else:
        cand = [info.node.name for info in meta.candidates]
        spot = [info.node.name for info in meta.spot]
    return cand, spot


class DrainSchedule:
    """One cut drain schedule plus the machinery to execute it safely.

    ``pack_fn(observation, pdbs) -> (packed, meta)`` is the owning
    planner's observe->tensors path (high-water pads included), so the
    live pack a step validates against is exactly what a fresh plan
    would solve. ``on_step`` (optional) receives each served
    PlanReport — the quality benches' hint-recording hook."""

    def __init__(
        self,
        steps: List[ScheduleStep],
        packed,
        meta,
        *,
        pack_fn: Callable,
        solver_label: str,
        horizon: int,
        base_observation=None,
    ):
        self.steps = steps
        self.cursor = 0
        self.invalidated = False
        self.invalid_reason = ""
        self.horizon = int(horizon)
        self.solver_label = solver_label
        self.on_step: Optional[Callable] = None
        self._pack_fn = pack_fn
        self._base_packed = packed
        self._base_meta = meta
        self._base_observation = base_observation
        self._expected = packed  # evolves via commit_step_host
        cand, spot = _meta_names(meta)
        self._cand_names = cand
        self._spot_names = spot
        self._cand_index: Dict[str, int] = {n: i for i, n in enumerate(cand)}
        self._drained: set = set()

    # ------------------------------------------------------------------

    @property
    def exhausted(self) -> bool:
        return self.cursor >= len(self.steps)

    def empty_report(self) -> PlanReport:
        """A no-drain report for a zero-step schedule (no candidate was
        drainable when it was cut) — the tick's metrics stay coherent."""
        return PlanReport(
            plan=None,
            n_candidates=self._base_meta.n_candidates,
            n_feasible=0,
            solve_seconds=0.0,
            solver=self.solver_label,
            schedule_len=0,
            schedule_step=-1,
        )

    def _invalidate(self, why: str) -> None:
        self.invalidated = True
        self.invalid_reason = why

    # ------------------------------------------------------------------

    def _precondition(self, live_packed, live_cand, live_spot) -> str:
        """'' when the live pack still matches the predicted state;
        otherwise the churn that broke it (the invalidation cause).
        Name-keyed: the packer's own re-sorting is not churn."""
        exp = self._expected
        base = self._base_packed
        live_cand_index = {n: i for i, n in enumerate(live_cand)}
        # candidate set: a new on-demand node (or a vanished live one)
        # changes what a fresh solve would choose from
        fresh = set(live_cand) - set(self._cand_names)
        if fresh:
            return f"candidate set changed: new node(s) {sorted(fresh)[:3]}"
        for name, i_base in self._cand_index.items():
            i_live = live_cand_index.get(name)
            if name in self._drained:
                # an executed drain's node either left the cluster (CA
                # collected it) or packs as an empty, invalid lane
                if i_live is not None and bool(
                    np.asarray(live_packed.slot_valid[i_live]).any()
                ):
                    return f"drained node {name} has pods again"
                continue
            if i_live is None:
                return f"candidate node {name} vanished"
            if bool(live_packed.cand_valid[i_live]) != bool(
                base.cand_valid[i_base]
            ):
                return f"candidate {name} drainability flipped"
            nb = int(np.asarray(base.slot_valid[i_base]).sum())
            nl = int(np.asarray(live_packed.slot_valid[i_live]).sum())
            if nb != nl:
                return f"candidate {name} pod count changed ({nb}->{nl})"
            if nb and not np.array_equal(
                np.asarray(live_packed.slot_req[i_live][:nb]),
                np.asarray(base.slot_req[i_base][:nb]),
            ):
                return f"candidate {name} pod requests changed"
        # spot pool: names + capacity surface vs the committed prediction
        live_spot_index = {n: i for i, n in enumerate(live_spot)}
        if set(live_spot) != set(self._spot_names):
            return "spot pool membership changed"
        for name, i_base in (
            (n, i) for i, n in enumerate(self._spot_names)
        ):
            i_live = live_spot_index[name]
            if (
                not np.array_equal(
                    np.asarray(live_packed.spot_free[i_live]),
                    np.asarray(exp.spot_free[i_base]),
                )
                or int(live_packed.spot_count[i_live])
                != int(exp.spot_count[i_base])
                or int(live_packed.spot_max_pods[i_live])
                != int(exp.spot_max_pods[i_base])
                or bool(live_packed.spot_ok[i_live])
                != bool(base.spot_ok[i_base])
            ):
                return f"spot node {name} state drifted from prediction"
        return ""

    def next_plan(self, observation, pdbs) -> Optional[PlanReport]:
        """Validate and serve the next schedule step against the LIVE
        observation. None means no step was served: ``invalidated``
        distinguishes churn (re-plan now) from plain exhaustion."""
        if self.invalidated or self.exhausted:
            return None
        t0 = time.perf_counter()
        step = self.steps[self.cursor]
        if self.cursor == 0 and observation is self._base_observation:
            # step 0, same tick, same observation object the schedule
            # was just cut from: the live pack IS the base pack (the
            # tick thread is the only mutator) — skip the re-pack, keep
            # the from-scratch proof below
            live_packed, live_meta = self._base_packed, self._base_meta
            live_cand, live_spot = self._cand_names, self._spot_names
        else:
            live_packed, live_meta = self._pack_fn(observation, pdbs)
            live_cand, live_spot = _meta_names(live_meta)
        why = self._precondition(live_packed, live_cand, live_spot)
        if why:
            self._invalidate(why)
            return None
        if not 0 <= step.index < len(self._cand_names):
            # a wire-decoded schedule's indices are frame-validated for
            # dtype/shape only; a corrupt VALUE must invalidate (counted,
            # re-planned), never negative-index into the candidate list
            self._invalidate(
                f"schedule step index {step.index} outside the "
                f"{len(self._cand_names)}-candidate base pack"
            )
            return None
        name = self._cand_names[step.index]
        c_live = live_cand.index(name) if name in live_cand else -1
        if c_live < 0:
            self._invalidate(f"scheduled candidate {name} vanished")
            return None
        # remap the placement row into the live pack's spot index space
        K_live = live_packed.slot_req.shape[1]
        live_spot_index = {n: i for i, n in enumerate(live_spot)}
        row_live = np.full(K_live, -1, np.int32)
        for k in range(min(len(step.row), K_live)):
            s = int(step.row[k])
            if s < 0:
                continue
            if s >= len(self._spot_names):
                self._invalidate("scheduled placement indexes a pad lane")
                return None
            s_live = live_spot_index.get(self._spot_names[s])
            if s_live is None:
                self._invalidate(
                    f"placement target {self._spot_names[s]} vanished"
                )
                return None
            row_live[k] = s_live
        # the invariant: EVERY executed step is re-proven from scratch
        # against the live pack (live taint/affinity words included)
        ok = validate_assignment(
            np, slice_lane(live_packed, c_live), row_live[None]
        )
        if not bool(np.asarray(ok)[0]):
            self._invalidate(
                f"step {self.cursor} failed from-scratch validation "
                f"against the live pack"
            )
            return None
        plan = live_meta.build_plan(c_live, row_live)
        self._expected = commit_step_host(
            self._expected, step.index, step.row
        )
        self._drained.add(name)
        self.cursor += 1
        report = PlanReport(
            plan=plan,
            n_candidates=live_meta.n_candidates,
            n_feasible=step.n_feasible,
            solve_seconds=time.perf_counter() - t0,
            solver=self.solver_label,
            feasible_candidates=[plan],
            schedule_len=len(self.steps),
            schedule_step=self.cursor - 1,
        )
        if self.on_step is not None:
            self.on_step(report)
        return report
