"""The Planner interface.

BASELINE.json's north star puts the solver "behind a Planner interface so
the eviction/drain path stays unchanged": the control loop hands the
classified node map + PDBs to ``plan`` and gets back either a drain
decision or None — it never sees tensors, meshes or devices.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Protocol, Sequence

from k8s_spot_rescheduler_tpu.models.cluster import NodeInfo, NodeMap, PDBSpec, PodSpec


@dataclasses.dataclass
class DrainPlan:
    """A proven-feasible drain of one on-demand node.

    ``assignments`` maps pod uid -> spot node name: the placement the
    feasibility proof found. The reference discards this (the live
    kube-scheduler re-places evicted pods, README.md:116-123); we surface it
    for observability and the quality benchmarks.
    """

    node: NodeInfo
    pods: List[PodSpec]
    assignments: Dict[str, str]
    candidate_index: int


@dataclasses.dataclass
class PlanReport:
    """Telemetry of one solve, for metrics and the loop's logging."""

    plan: Optional[DrainPlan]
    n_candidates: int
    n_feasible: int
    solve_seconds: float
    solver: str = ""
    # all feasible candidates in drain-priority order (multi-drain planning
    # and the quality benchmarks read this; the faithful loop uses plan only)
    feasible_candidates: List[DrainPlan] = dataclasses.field(default_factory=list)
    # --- incremental device-resident tick telemetry (solver planner;
    # loop/controller.py mirrors these into metrics/registry.py) ---
    # changed lanes the delta-pack applied; -1 = device cache not in play
    delta_pack_lanes: int = -1
    # this tick re-uploaded the whole problem (cold cache / shape growth)
    full_repack: bool = False
    # host→device bytes this tick actually shipped; -1 = unknown (the
    # non-incremental device path uploads inside jit, untracked)
    upload_bytes: int = -1
    # staged-solve coverage; -1 chunks_solved = unstaged full solve
    chunks_solved: int = -1
    chunks_skipped: int = 0
    # early exit truncated n_feasible to the solved prefix (a drain WAS
    # found; the why-no-drain gauges read this tick as an upper bound)
    count_truncated: bool = False
    # spot chunks the repair phase ran with: 1 = unchunked, >1 = the
    # elect-then-commit spot-chunked search engaged (per-lane repair
    # state exceeded one device), 0 = repair off/unavailable this solve
    repair_chunks: int = 1
    # carry chunks of the carry-streamed narrow tier (solver/carry.py +
    # solver/fallback.with_repair_streamed): 0 = a wide-carry tier ran
    carry_chunks: int = 0
    # --- drain-schedule telemetry (planner/schedule.py) ---
    # steps in the schedule this plan was served from; 0 = per-tick plan
    schedule_len: int = 0
    # which schedule step this report executed; -1 = not a schedule step
    schedule_step: int = -1


class Planner(Protocol):
    def plan(self, node_map: NodeMap, pdbs: Sequence[PDBSpec]) -> PlanReport: ...


def pack_observation(planner, observation, pdbs: Sequence[PDBSpec]):
    """Observation -> (packed, meta) through the production pack path
    with ``planner``'s high-water pads — THE one implementation behind
    ``SolverPlanner._pack_observation`` and
    ``RemotePlanner._pack_observation`` (and therefore behind every
    drain-schedule step's live re-pack), so the local and wire pack
    paths cannot drift. ``planner`` carries ``config``, the
    ``_pad_c/_pad_k/_pad_s`` high-water marks (grown in place: shapes
    only ever grow, so neither jit compiles nor service-side buckets
    churn), and ``last_packed`` (the offline analyzers' tap)."""
    from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster

    cfg = planner.config
    if hasattr(observation, "pack"):  # ColumnarStore / ColumnarObservation
        packed, meta = observation.pack(
            pdbs,
            priority_threshold=cfg.priority_threshold,
            delete_non_replicated=cfg.delete_non_replicated_pods,
            pad_candidates=planner._pad_c,
            pad_spot=planner._pad_s,
            pad_slots=planner._pad_k,
        )
    else:
        packed, meta = pack_cluster(
            observation,
            pdbs,
            resources=cfg.resources,
            delete_non_replicated=cfg.delete_non_replicated_pods,
            pad_candidates=planner._pad_c,
            pad_spot=planner._pad_s,
            pad_slots=planner._pad_k,
        )
    planner._pad_c = max(planner._pad_c, packed.slot_req.shape[0])
    planner._pad_k = max(planner._pad_k, packed.slot_req.shape[1])
    planner._pad_s = max(planner._pad_s, packed.spot_free.shape[0])
    planner.last_packed = packed
    return packed, meta
