"""Evictability filter: which pods may be moved off a node, and which pods
block the whole drain.

Framework equivalent of the cluster-autoscaler ``GetPodsForDeletionOnNodeDrain``
call (reference rescheduler.go:231 with ``deleteNonReplicated`` flag,
``skipNodesWithSystemPods=false``, ``skipNodesWithLocalStorage=false``) plus
the reference's second DaemonSet ownerRef pass (rescheduler.go:241-256).

Semantics (the reference's observable behavior, per README.md:103-114 and
the call sites):

- mirror (static) pods are skipped silently — they vanish with the node;
- DaemonSet-controlled pods are skipped silently (rescheduler.go:243-252);
- pods in a Succeeded/Failed phase are skipped — nothing to move;
- a pod with no controller owner reference **blocks the drain** unless
  ``delete_non_replicated`` is set (reference flag rescheduler.go:84; a
  blocking pod aborts the whole node, rescheduler.go:232-238 logs it and
  ``continue``s to the next node);
- a pod covered by a PodDisruptionBudget with no disruptions left **blocks
  the drain**;
- everything else is returned as "must be replanned onto spot nodes".
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from k8s_spot_rescheduler_tpu.models.cluster import PDBSpec, PodSpec


@dataclasses.dataclass
class BlockingPod:
    pod: PodSpec
    reason: str


def get_pods_for_deletion(
    pods: Sequence[PodSpec],
    pdbs: Sequence[PDBSpec],
    *,
    delete_non_replicated: bool = False,
) -> Tuple[List[PodSpec], Optional[BlockingPod]]:
    """Return (pods that must be re-placed to drain the node, blocking pod).

    If a blocking pod is returned the node must not be drained this tick —
    the caller skips it, like reference rescheduler.go:232-239.
    """
    result: List[PodSpec] = []
    for pod in pods:
        if pod.is_mirror():
            continue
        if pod.phase in ("Succeeded", "Failed"):
            continue
        if pod.is_daemonset():
            continue
        if pod.controller_ref() is None and not delete_non_replicated:
            return [], BlockingPod(pod, "pod is not replicated")
        for pdb in pdbs:
            if pdb.selects(pod) and pdb.disruptions_allowed < 1:
                return [], BlockingPod(
                    pod, f"not enough pod disruption budget ({pdb.name})"
                )
        result.append(pod)
    return result, None
