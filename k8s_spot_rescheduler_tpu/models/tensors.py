"""Dense-tensor packing of cluster state.

This is where the host object model (``NodeMap`` of ``NodeInfo``/``PodSpec``)
becomes the static-shape tensor problem the TPU solver consumes — the
framework's replacement for the reference's ``ClusterSnapshot`` build
(reference nodes/nodes.go:226-232) and its per-candidate ``Fork``/``Revert``
(rescheduler.go:269-275): every candidate on-demand node becomes an
independent *batch lane* over the same initial spot-pool tensors, so lanes
cannot see each other's hypothetical placements — exactly the fork-per-
candidate semantics, but data-parallel.

Layout:

- candidate axis ``C`` — on-demand nodes in drain-priority order
  (least-requested-CPU first, nodes/nodes.go:99-101);
- slot axis ``K`` — each candidate's evictable pods in placement order
  (biggest-CPU-request first, nodes/nodes.go:76-80), padded with invalid
  slots;
- spot axis ``S`` — spot nodes in first-fit probe order (most-requested-CPU
  first, nodes/nodes.go:95-97), padded with never-fitting nodes;
- resource axis ``R`` — from ``ReschedulerConfig.resources``.

Numerics: requests are ceil-scaled and allocatable floor-scaled into units
that stay below 2**24 (exact in float32) — memory in MiB, CPU in millicores.
Rounding is asymmetric on purpose: a plan must never be approved because of
a rounding error (safe-direction conservatism, SURVEY.md §7 (e)).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeInfo,
    NodeMap,
    PDBSpec,
    PodSpec,
)
from k8s_spot_rescheduler_tpu.models.evictability import (
    BlockingPod,
    get_pods_for_deletion,
)
from k8s_spot_rescheduler_tpu.predicates.masks import (
    AFFINITY_WORDS,
    TaintTable,
    collect_match_universe,
    compute_spread_bit,
    constraint_mask,
    intern_constraints,
    match_affinity_mask,
    node_affinity_universe,
    node_constraint_mask,
    pod_affinity_mask,
    pod_affinity_universe,
    selector_universe,
    spread_lane_guard,
    spread_self_match,
    ZONE_LABEL,
    collect_zone_universe,
    zone_lane_guard,
    zone_match_affinity_mask,
)
from k8s_spot_rescheduler_tpu.predicates.selectors import (
    selector_matches,
    term_matches,
)

# Scale divisor per resource so packed values stay < 2**24 (float32-exact).
RESOURCE_SCALE: Dict[str, int] = {
    "cpu": 1,  # millicores
    "memory": 1 << 20,  # bytes -> MiB
    "ephemeral-storage": 1 << 20,
    "pods": 1,
}

DEFAULT_MAX_PODS = 110  # k8s kubelet default when a node publishes no cap


def _ceil_div(v: int, d: int) -> int:
    return -(-int(v) // d)


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _pad_dim(n: int) -> int:
    """Pad to a TPU-friendly size: multiples of 8 below 128, multiples of
    128 above (the lane width; pallas_guide tiling constraints)."""
    if n <= 0:
        return 8
    if n < 128:
        return _round_up(n, 8)
    return _round_up(n, 128)


class PackedCluster(NamedTuple):
    """The static-shape device problem. All arrays are host numpy; the
    solver moves them to the device. Shapes: C candidates, K pod slots,
    S spot nodes, R resources, W taint words, A affinity words."""

    # candidate pod slots
    slot_req: np.ndarray  # f32 [C, K, R]
    slot_valid: np.ndarray  # bool [C, K]
    slot_tol: np.ndarray  # uint32 [C, K, W]
    slot_aff: np.ndarray  # uint32 [C, K, A]
    cand_valid: np.ndarray  # bool [C]
    # spot pool
    spot_free: np.ndarray  # f32 [S, R]
    spot_count: np.ndarray  # i32 [S]
    spot_max_pods: np.ndarray  # i32 [S]
    spot_taints: np.ndarray  # uint32 [S, W]
    spot_ok: np.ndarray  # bool [S]
    spot_aff: np.ndarray  # uint32 [S, A]


@dataclasses.dataclass
class PackMeta:
    """Host-side mapping from tensor indices back to cluster objects.

    Shares a planner-facing surface (``n_candidates`` / ``blocking_pods``
    / ``build_plan``) with ``models/columnar.ColumnarMeta``.
    """

    candidates: List[NodeInfo]  # index = candidate lane (unpadded prefix)
    cand_pods: List[List[PodSpec]]  # per lane, slot order
    blocking: List[Optional[BlockingPod]]
    spot: List[NodeInfo]  # index = spot lane (unpadded prefix)
    taint_table: TaintTable
    resources: Sequence[str]

    @property
    def n_candidates(self) -> int:
        return len(self.candidates)

    def blocking_pods(self) -> List[BlockingPod]:
        return [b for b in self.blocking if b is not None]

    def unmodeled_candidate_mask(self) -> np.ndarray:
        """bool [n_candidates]: lane carries >=1 unmodeled-constraint pod
        (packed as placeable-nowhere -> the lane can never prove)."""
        return np.array(
            [any(p.unmodeled_constraints for p in pods) for pods in self.cand_pods],
            bool,
        )

    def unplaceable_pod_count(self) -> int:
        return sum(
            1
            for pods in self.cand_pods
            for p in pods
            if p.unmodeled_constraints
        )

    def build_plan(self, c: int, row: np.ndarray):
        from k8s_spot_rescheduler_tpu.planner.base import DrainPlan

        pods = self.cand_pods[c]
        assignments = {
            pod.uid: self.spot[int(row[k])].node.name
            for k, pod in enumerate(pods)
        }
        return DrainPlan(
            node=self.candidates[c],
            pods=list(pods),
            assignments=assignments,
            candidate_index=c,
        )


def scale_allocatable(alloc: Dict[str, int], resources: Sequence[str]) -> np.ndarray:
    # A node that publishes no pods cap gets the kubelet default, matching
    # the spot_max_pods predicate — not 0, which would make nothing fit.
    return np.array(
        [
            int(alloc.get(r, DEFAULT_MAX_PODS if r == "pods" else 0))
            // RESOURCE_SCALE.get(r, 1)
            for r in resources
        ],
        dtype=np.float32,
    )


def _build_spread_bits(node_map, candidates, cand_pods) -> Dict:
    """(lane, slot) -> frozenset of SpreadBit for hard-spread carriers.

    The static verdict machinery of predicates/masks.py: per carrier
    context, the refused-domain set from this tick's per-domain match
    counts. Counts and domains span every model-visible node — both
    classes, unclassified ready nodes (NodeMap.other), AND not-ready
    nodes of any class (NodeMap.unready: kube-scheduler's default
    nodeTaintsPolicy=Ignore counts their domains and pods, and an
    unseen low-count domain would overstate the min — the permissive
    direction); spot residents below the priority threshold are
    invisible exactly as they are to the reference's own snapshot
    (nodes/nodes.go:137-141). Replaces the reference's delegation to
    the PodTopologySpread plugin inside CheckPredicates
    (rescheduler.go:344; README.md:103-114)."""
    if not any(p.spread_constraints for pods in cand_pods for p in pods):
        return {}
    infos = (
        list(node_map.on_demand) + list(node_map.spot)
        + list(node_map.other) + list(node_map.unready)
    )
    domain_cache: Dict = {}
    count_cache: Dict = {}
    bit_cache: Dict = {}

    def all_domains(topo):
        doms = domain_cache.get(topo)
        if doms is None:
            doms = domain_cache[topo] = sorted(
                {
                    info.node.labels[topo]
                    for info in infos
                    if topo in info.node.labels
                }
            )
        return doms

    def counts_for(ns, topo, items):
        key = (ns, topo, items)
        c = count_cache.get(key)
        if c is None:
            c = count_cache[key] = {}
            for info in infos:
                d = info.node.labels.get(topo)
                if d is None:
                    continue
                for p in info.pods:
                    if p.namespace == ns and selector_matches(
                        items, p.labels
                    ):
                        c[d] = c.get(d, 0) + 1
        return c

    out: Dict = {}
    for c, (info, pods) in enumerate(zip(candidates, cand_pods)):
        for k, p in enumerate(pods):
            if not p.spread_constraints:
                continue
            bits = []
            for topo, skew, items in p.spread_constraints:
                self_m = spread_self_match(p, items)
                own = info.node.labels.get(topo)
                bkey = (p.namespace, topo, skew, items, own, self_m)
                bit = bit_cache.get(bkey)
                if bit is None:
                    bit = bit_cache[bkey] = compute_spread_bit(
                        topo,
                        skew,
                        own,
                        counts_for(p.namespace, topo, items),
                        all_domains(topo),
                        self_m,
                    )
                bits.append(bit)
            out[(c, k)] = frozenset(bits)
    return out


def _build_zone_paff_bits(candidates, spot, cand_pods) -> Dict:
    """(lane, slot) -> frozenset of ZonePodAffinityBit for
    zone-positive-affinity carriers (one bit per carried TERM — every
    term must hold). Allowed zones = zones of COUNTED residents (both
    classes, post priority filter) in the term's scope matching its
    selector, EXCLUDING residents of the lane's own candidate node —
    those leave in the same drain, and a zone satisfied only by them
    would strand the carrier at reschedule time. In-plan placements
    could only add matches (ignoring them loses a drain, never
    strands)."""
    if not any(
        p.pod_affinity_zone_match for pods in cand_pods for p in pods
    ):
        return {}
    from k8s_spot_rescheduler_tpu.predicates.masks import ZonePodAffinityBit

    infos = list(candidates) + list(spot)
    hits_cache: Dict = {}

    def zone_hits(term):
        cached = hits_cache.get(term)
        if cached is not None:
            return cached
        per_zone: Dict[str, int] = {}
        per_info: Dict[int, int] = {}
        for idx, info in enumerate(infos):
            zone = info.node.labels.get(ZONE_LABEL)
            n = sum(
                1
                for q in info.pods
                if term_matches(term, q.namespace, q.labels)
            )
            per_info[idx] = n
            if zone is not None and n:
                per_zone[zone] = per_zone.get(zone, 0) + n
        cached = hits_cache[term] = (per_zone, per_info)
        return cached

    out: Dict = {}
    for c, (info, pods) in enumerate(zip(candidates, cand_pods)):
        for k, p in enumerate(pods):
            if not p.pod_affinity_zone_match:
                continue
            bits = []
            for term in p.pod_affinity_zone_match:
                per_zone, per_info = zone_hits(term)
                own_zone = info.node.labels.get(ZONE_LABEL)
                own_hits = per_info.get(c, 0)
                allowed = tuple(sorted(
                    z for z, n in per_zone.items()
                    if n - (own_hits if z == own_zone else 0) > 0
                ))
                bits.append(ZonePodAffinityBit(
                    namespaces=term[0], items=term[1], allowed_zones=allowed
                ))
            out[(c, k)] = frozenset(bits)
    return out


def pack_cluster(
    node_map: NodeMap,
    pdbs: Sequence[PDBSpec] = (),
    *,
    resources: Sequence[str] = ("cpu", "memory"),
    delete_non_replicated: bool = False,
    pad_candidates: int = 0,
    pad_spot: int = 0,
    pad_slots: int = 0,
) -> tuple[PackedCluster, PackMeta]:
    """Pack a classified node map into the solver problem.

    The evictability filter runs here, per candidate, exactly as the control
    loop does per node (reference rescheduler.go:231-256): a blocking pod or
    an empty evictable set invalidates the candidate lane (it is skipped,
    not drained). Explicit ``pad_*`` floors let callers keep shapes constant
    across ticks to avoid recompilation (streaming replay).
    """
    candidates = node_map.on_demand
    spot = node_map.spot

    cand_pods: List[List[PodSpec]] = []
    blocking: List[Optional[BlockingPod]] = []
    for info in candidates:
        pods, blocked = get_pods_for_deletion(
            info.pods, pdbs, delete_non_replicated=delete_non_replicated
        )
        cand_pods.append(pods if not blocked else [])
        blocking.append(blocked)

    # constraint table: the spot pool's hard taints + pseudo-taints for
    # the slot pods' nodeSelector pairs, required node-affinity
    # expressions, spread verdicts, and unmodeled constraints
    slot_pods_flat = [p for pods in cand_pods for p in pods]
    spread_bits_by = _build_spread_bits(
        node_map, candidates, cand_pods
    )  # (lane, slot) -> frozenset(SpreadBit)
    spread_universe = sorted(
        {b for bits in spread_bits_by.values() for b in bits},
        key=lambda b: (b.topology_key, b.refused),
    )
    zone_paff_by = _build_zone_paff_bits(
        candidates, spot, cand_pods
    )  # (lane, slot) -> frozenset(ZonePodAffinityBit)
    zone_paff_universe = sorted(
        {b for bits in zone_paff_by.values() for b in bits},
        key=lambda b: (b.namespaces, b.items, b.allowed_zones),
    )
    table = intern_constraints(
        [n.node for n in spot],
        selector_universe(slot_pods_flat),
        node_affinity_universe(slot_pods_flat),
        pod_affinity_universe(slot_pods_flat),
        spread_universe,
        zone_paff_universe,
    )
    # anti-affinity selector universes span every counted pod (resident
    # pods repel incoming matches and vice versa; zone identities reach
    # across node classes because zones do). The ZONE family additionally
    # spans pods on unclassified ready nodes (NodeMap.other) AND on
    # not-ready nodes of any class (NodeMap.unready): a requirer or
    # match resident there still repels zone-wide in the real scheduler,
    # and missing it would approve a drain whose pod then strands.
    # Hostname-family presence stays scoped to candidates+spot — we
    # never place onto those nodes, so their residents cannot create
    # per-node conflicts.
    presence_extra = list(node_map.other) + list(node_map.unready)
    counted_pods = [p for info in candidates for p in info.pods] + [
        p for info in spot for p in info.pods
    ]
    zone_pods = counted_pods + [
        p for info in presence_extra for p in info.pods
    ]
    match_universe = collect_match_universe(counted_pods)
    zone_universe = collect_zone_universe(zone_pods)
    W, A, R = table.words, AFFINITY_WORDS, len(resources)

    C = max(_pad_dim(len(candidates)), _pad_dim(pad_candidates))
    S = max(_pad_dim(len(spot)), _pad_dim(pad_spot))
    K = max(
        _pad_dim(max((len(p) for p in cand_pods), default=1)),
        _pad_dim(pad_slots),
    )

    packed = PackedCluster(
        slot_req=np.zeros((C, K, R), np.float32),
        slot_valid=np.zeros((C, K), bool),
        slot_tol=np.zeros((C, K, W), np.uint32),
        slot_aff=np.zeros((C, K, A), np.uint32),
        cand_valid=np.zeros((C,), bool),
        spot_free=np.zeros((S, R), np.float32),
        spot_count=np.zeros((S,), np.int32),
        spot_max_pods=np.zeros((S,), np.int32),
        spot_taints=np.zeros((S, W), np.uint32),
        spot_ok=np.zeros((S,), bool),
        spot_aff=np.zeros((S, A), np.uint32),
    )

    # Memoized per-pod mask helpers: pods overwhelmingly share toleration
    # sets and affinity groups — compute each distinct value once. Request
    # rows are batched per node (req_matrix): per-pod Python helpers were
    # the packing hot spot at 50k pods (~45% of pack time).
    scales = [RESOURCE_SCALE.get(r, 1) for r in resources]
    tol_cache: dict = {}
    aff_cache: dict = {}

    def req_matrix(pods: List[PodSpec]) -> np.ndarray:
        # "pods" is synthesized: every pod counts exactly 1 toward a node's
        # pod capacity regardless of its requests dict (kubelet semantics),
        # so no pod source needs to emit it. As a packed dimension it
        # intentionally duplicates the spot_count/spot_max_pods predicate —
        # BASELINE config 3/4 promise 4 resource dimensions; the VMEM guard
        # (ops/pallas_ffd.needs_scan_fallback) covers the extra plane.
        n = len(pods)
        out = np.empty((n, R), np.float32)
        for j, (r, d) in enumerate(zip(resources, scales)):
            if r == "pods":
                out[:, j] = 1.0
            else:
                col = np.fromiter(
                    (p.requests.get(r, 0) for p in pods),
                    dtype=np.int64, count=n,
                )
                # vectorized ceil-div: requests round up (safe direction)
                out[:, j] = -(-col // d) if d != 1 else col
        return out

    def tol_row(
        pod: PodSpec,
        sbits: frozenset = frozenset(),
        zpbits: frozenset = frozenset(),
    ):
        # sbits/zpbits join the key: a carrier's verdict depends on its
        # LANE's node, so identical pods on different candidates may
        # carry different context bits
        key = (
            tuple(pod.tolerations),
            tuple(sorted(pod.node_selector.items())),
            pod.node_affinity,
            pod.pod_affinity_match,
            sbits,
            zpbits,
            pod.unmodeled_constraints,
        )
        row = tol_cache.get(key)
        if row is None:
            row = tol_cache[key] = constraint_mask(
                pod.tolerations, pod.node_selector,
                pod.unmodeled_constraints, table,
                node_affinity=pod.node_affinity,
                pod_affinity=pod.pod_affinity_match,
                spread_bits=sbits,
                zone_paff_bits=zpbits,
            )
        return row

    zone_cache: dict = {}

    def zone_row(pod: PodSpec):
        """Zone-family bits only (aggregated zone-wide on the node side)."""
        key = (
            pod.namespace,
            pod.anti_affinity_zone_match,
            tuple(sorted(pod.labels.items())),
        )
        row = zone_cache.get(key)
        if row is None:
            row = zone_cache[key] = zone_match_affinity_mask(
                pod.anti_affinity_zone_match, pod.namespace, pod.labels,
                zone_universe,
            )
        return row

    host_cache: dict = {}

    def host_row(pod: PodSpec):
        """Hostname-family bits only — what a resident contributes to
        its OWN node's mask. Zone bits must never ride along here: they
        flow exclusively through the zone-wide accumulation below, so a
        zoneless node never acquires zone conflicts."""
        key = (
            pod.anti_affinity_group,
            pod.namespace,
            pod.anti_affinity_match,
            tuple(sorted(pod.labels.items())),
        )
        row = host_cache.get(key)
        if row is None:
            row = host_cache[key] = pod_affinity_mask(pod) | match_affinity_mask(
                pod.anti_affinity_match, pod.namespace, pod.labels,
                match_universe,
            )
        return row

    def aff_row(pod: PodSpec):
        """Pod-side mask (slots): hostname family | zone family."""
        key = (
            pod.anti_affinity_group,
            pod.namespace,
            pod.anti_affinity_match,
            pod.anti_affinity_zone_match,
            tuple(sorted(pod.labels.items())),
        )
        row = aff_cache.get(key)
        if row is None:
            row = aff_cache[key] = host_row(pod) | zone_row(pod)
        return row

    # zone-wide presence: OR of the zone-family masks of every counted
    # pod — plus every pod on an unclassified-ready or not-ready node —
    # keyed by its node's zone label (nodes without the label are
    # zoneless and neither contribute nor receive)
    zone_accum: dict = {}
    if zone_universe:
        for info in list(candidates) + list(spot) + presence_extra:
            zone = info.node.labels.get(ZONE_LABEL)
            if zone is None:
                continue
            for pod in info.pods:
                acc = zone_accum.get(zone)
                row = zone_row(pod)
                zone_accum[zone] = row.copy() if acc is None else acc | row

    # the unplaceable bit is always the table's last entry
    unplace_idx = len(table.taints) - 1
    unplace_word, unplace_bit = unplace_idx // 32, np.uint32(
        1 << (unplace_idx % 32)
    )

    for c, (info, pods, blocked) in enumerate(zip(candidates, cand_pods, blocking)):
        # a candidate with no evictable pods is skipped, not drained
        # (reference rescheduler.go:260-265); likewise a blocked one.
        packed.cand_valid[c] = blocked is None and len(pods) > 0
        if pods:
            n = len(pods)
            packed.slot_req[c, :n] = req_matrix(pods)
            packed.slot_valid[c, :n] = True
            packed.slot_tol[c, :n] = [
                tol_row(
                    p,
                    spread_bits_by.get((c, k), frozenset()),
                    zone_paff_by.get((c, k), frozenset()),
                )
                for k, p in enumerate(pods)
            ]
            packed.slot_aff[c, :n] = [aff_row(p) for p in pods]
            if zone_universe:
                # two zone-involved pods in one lane: static zone bits
                # cannot prove their in-plan interaction safe — mark
                # them unplaceable (clears the lane, conservatively)
                for k in zone_lane_guard(pods):
                    packed.slot_tol[c, k, unplace_word] &= ~unplace_bit
            if spread_universe:
                # likewise for spread: two in-plan movers involved with
                # one spread identity shift each other's domain counts
                for k in spread_lane_guard(pods):
                    packed.slot_tol[c, k, unplace_word] &= ~unplace_bit

    for s, info in enumerate(spot):
        alloc = scale_allocatable(info.node.allocatable, resources)
        if info.pods:
            used = req_matrix(info.pods).sum(0)
        else:
            used = np.zeros(R, np.float32)
        packed.spot_free[s] = alloc - used
        packed.spot_count[s] = len(info.pods)
        packed.spot_max_pods[s] = int(
            info.node.allocatable.get("pods", DEFAULT_MAX_PODS)
        )
        packed.spot_taints[s] = node_constraint_mask(
            info.node, table, residents=info.pods
        )
        packed.spot_ok[s] = info.node.ready and not info.node.unschedulable
        aff = np.zeros(AFFINITY_WORDS, np.uint32)
        for pod in info.pods:
            if pod.anti_affinity_group or pod.anti_affinity_match or match_universe:
                aff |= host_row(pod)
        if zone_universe:
            zone = info.node.labels.get(ZONE_LABEL)
            if zone is not None and zone in zone_accum:
                aff |= zone_accum[zone]
        packed.spot_aff[s] = aff

    meta = PackMeta(
        candidates=list(candidates),
        cand_pods=cand_pods,
        blocking=blocking,
        spot=list(spot),
        taint_table=table,
        resources=tuple(resources),
    )
    return packed, meta
