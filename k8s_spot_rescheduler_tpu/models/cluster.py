"""Host-side cluster model: pods, nodes, and the classified node map.

This is the framework's equivalent of the reference's ``nodes`` package
(reference nodes/nodes.go): plain-data pod/node specs (instead of client-go
API objects), a ``NodeInfo`` carrying per-node accounting, and
``build_node_map`` reproducing the reference's classification and sort
policy — spot nodes most-requested-CPU-first, on-demand nodes
least-requested-first, pods biggest-CPU-request-first
(nodes/nodes.go:63-101; policy rationale README.md:136-149).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from k8s_spot_rescheduler_tpu.utils.labels import matches_label

# Resource names use k8s conventions. Base units: "cpu" is in millicores
# (the reference's MilliValue, nodes/nodes.go:149-165), "memory" and
# "ephemeral-storage" in bytes, "pods" in count.
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL = "ephemeral-storage"
PODS = "pods"

MIRROR_POD_ANNOTATION = "kubernetes.io/config.mirror"

# Taint key the actuator sets while draining; equivalent of the cluster-
# autoscaler ToBeDeleted taint applied via deletetaint.MarkToBeDeleted
# (reference scaler/scaler.go:77).
TO_BE_DELETED_TAINT = "ToBeDeletedByClusterAutoscaler"

# Value the actuator writes into its ToBeDeleted taint: an explicit
# ownership marker. The REAL cluster autoscaler applies the same taint
# key during its own scale-downs (with a bare unix timestamp as the
# value) — including on the drained-empty on-demand nodes this
# rescheduler produces, whose deletion is the product's end goal. The
# orphaned-taint sweep must therefore be able to tell "mine, left by a
# crashed drain" apart from "CA's, mid scale-down"; only values carrying
# this marker are ever swept. Format:
# ``spot-rescheduler_<unix-wall-ts>_<holder-identity>``, capped at the
# 63 characters a taint value allows.
RESCHEDULER_TAINT_MARKER = "spot-rescheduler"
_TAINT_VALUE_MAX = 63
# marker + two "_" separators + an up-to-11-digit timestamp
_TAINT_IDENTITY_MAX = _TAINT_VALUE_MAX - len(RESCHEDULER_TAINT_MARKER) - 2 - 11


def rescheduler_taint_identity(identity: str) -> str:
    """Holder identity exactly as embedded in (and parsed back out of) a
    rescheduler taint value: sanitized to legal taint-value characters,
    shortened so the full value fits in 63 chars, and guaranteed to end
    alphanumeric (k8s validates taint values as label values — a
    trailing '_'/'-'/'.' would make every add_taint 422). Over-long
    identities keep a prefix PLUS a hash of the whole string — pod
    names carry their distinguishing hash at the END, and two replicas
    must never truncate to the same embedded identity (a shared "own"
    identity would let one sweep the other's live drain with no grace
    wait). Sweepers must compare against THIS, not the raw identity."""
    cleaned = re.sub(r"[^A-Za-z0-9._-]", "-", identity or "")
    if len(cleaned) > _TAINT_IDENTITY_MAX:
        import hashlib

        digest = hashlib.sha1(cleaned.encode()).hexdigest()[:8]
        cleaned = cleaned[: _TAINT_IDENTITY_MAX - 9] + "-" + digest
    cleaned = cleaned.rstrip("_.-")
    return cleaned or "unknown"


def rescheduler_taint_value(identity: str, wall_ts: float) -> str:
    return (
        f"{RESCHEDULER_TAINT_MARKER}_{int(wall_ts)}_"
        f"{rescheduler_taint_identity(identity)}"
    )


def parse_rescheduler_taint_value(
    value: str,
) -> Optional[Tuple[str, Optional[float]]]:
    """``(holder-identity, wall-ts | None)`` when ``value`` carries the
    rescheduler marker, else None — not our taint, leave it alone."""
    prefix = RESCHEDULER_TAINT_MARKER + "_"
    if not value or not value.startswith(prefix):
        return None
    ts_str, _, identity = value[len(prefix):].partition("_")
    try:
        ts: Optional[float] = float(ts_str)
    except ValueError:
        ts = None
    return identity, ts


@dataclasses.dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclasses.dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    value: str = ""
    operator: str = "Equal"  # Equal | Exists
    effect: str = ""  # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        """k8s toleration matching semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclasses.dataclass(frozen=True)
class OwnerRef:
    kind: str
    name: str
    controller: bool = True


@dataclasses.dataclass
class PodSpec:
    """A pod, reduced to what scheduling/eviction decisions need."""

    name: str
    namespace: str = "default"
    node_name: str = ""
    requests: Dict[str, int] = dataclasses.field(default_factory=dict)
    priority: int = 0
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    owner_refs: List[OwnerRef] = dataclasses.field(default_factory=list)
    tolerations: List[Toleration] = dataclasses.field(default_factory=list)
    # Simplified pod-anti-affinity: pods sharing a non-empty group refuse to
    # co-locate on one node (topologyKey=hostname requiredDuringScheduling).
    anti_affinity_group: str = ""
    # Required podAntiAffinity terms with topologyKey=hostname, in the
    # round-5 canonical form (predicates/selectors.py): a tuple of
    # ``(namespaces, selector)`` terms, any number of them, each
    # selector the full LabelSelector operator surface (In / NotIn /
    # Exists / DoesNotExist, multi-value In) and each namespaces tuple
    # either the pod's own namespace (the implicit default) or an
    # explicit cross-namespace list. The pod refuses nodes hosting any
    # pod in a term's scope matched by its selector, and — symmetrically,
    # like the real scheduler — matched pods refuse nodes hosting this
    # pod. Construction accepts the matchLabels-dict shorthand (one
    # own-namespace term); ``__post_init__`` canonicalizes. Shapes
    # beyond this (namespaceSelector, other topology keys) fall back to
    # ``unmodeled_constraints``.
    anti_affinity_match: Tuple = ()
    # Required anti-affinity terms with
    # topologyKey=topology.kubernetes.io/zone (same canonical term
    # shape): the pod refuses nodes in any ZONE hosting a matched pod,
    # and — symmetrically — matched pods refuse zones hosting this pod.
    # Zones come from the standard node label. Modeled statically per
    # tick via zone-salted affinity-group bits
    # (predicates/masks.zone_match_affinity_mask); when two
    # zone-involved pods share one candidate lane the packers
    # conservatively mark them unplaceable (static bits cannot prove the
    # in-plan interaction safe). Legacy zone label keys and other
    # topology keys fall back to ``unmodeled_constraints``.
    anti_affinity_zone_match: Tuple = ()
    # Required POSITIVE pod-affinity terms, topologyKey=hostname (same
    # canonical term shape, any number of terms — every term must be
    # satisfied): the pod may only schedule onto a node already hosting
    # a pod matched by each selector in its scope. The planner is
    # conservative about the dynamics: only pods RESIDENT on a spot node
    # before the plan count as matches (placements made by the plan
    # itself could only create additional matches, so ignoring them can
    # only lose a drain, never strand a pod). A term whose selector can
    # match no pod keeps the pod exactly unplaceable (no node can ever
    # qualify — the scheduler's own verdict).
    pod_affinity_match: Tuple = ()
    # Required POSITIVE pod-affinity terms with ZONE topology: the pod
    # may only schedule into a zone already hosting a match per term.
    # Same canonical term rules; per-carrier allowed-zone verdicts
    # (masks.ZonePodAffinityBit) computed from pre-plan counted
    # residents, excluding matches on the carrier's own candidate node
    # (they leave in the same drain). Hostname and zone positive terms
    # may coexist in any number.
    pod_affinity_zone_match: Tuple = ()
    phase: str = "Running"
    # spec.nodeSelector: the pod only schedules onto nodes carrying every
    # one of these labels (the kube-scheduler's NodeSelector predicate,
    # part of the reference's CheckPredicates surface, README.md:103-114).
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Required node-affinity (spec.affinity.nodeAffinity.requiredDuring
    # SchedulingIgnoredDuringExecution), canonicalized: a tuple of terms
    # (OR), each a tuple of (key, operator, values) expressions (AND)
    # with operators In/NotIn/Exists/DoesNotExist/Gt/Lt — the full
    # NodeSelectorTerm matchExpressions surface. Evaluated host-side per
    # node (predicates/masks.match_node_affinity) and interned as one
    # pseudo-taint bit per distinct requirement. matchFields and
    # malformed shapes fall back to ``unmodeled_constraints``.
    node_affinity: Tuple = ()
    # PersistentVolumeClaim names this pod's volumes reference (the
    # pod's own namespace). Decode marks such pods unmodeled; the
    # volume-affinity resolver (models/volumes.py) lifts that when every
    # claim is Bound to a PV whose nodeAffinity is absent or modelable,
    # folding the PVs' terms into ``node_affinity``.
    pvc_names: Tuple = ()
    # True iff the ONLY reason this pod is unmodeled is its PVCs — the
    # resolver may clear ``unmodeled_constraints`` exactly then. Keeping
    # the flag separate keeps every unresolved path fail-safe: a pod
    # that never meets the resolver stays placeable-nowhere.
    pvc_resolvable: bool = False
    # Hard topologySpreadConstraints (whenUnsatisfiable=DoNotSchedule,
    # the k8s default), modeled in the canonical shape: topologyKey is
    # hostname or the standard zone label, a non-empty selector in the
    # round-5 widened operator form (matchLabels and/or matchExpressions
    # with In/NotIn/Exists/DoesNotExist — always own-namespace, per the
    # k8s API), integer maxSkew >= 1, and none of the counting-semantics
    # modifiers (minDomains, matchLabelKeys, nodeAffinityPolicy,
    # nodeTaintsPolicy). Each entry is a canonical tuple
    # (topology_key, max_skew, selector requirements); any number of
    # entries (the hostname+zone pair is the common Deployment shape).
    # The packers turn each into a per-carrier SpreadBit pseudo-taint
    # (predicates/masks.py) whose refused-domain set is computed from
    # this tick's per-domain match counts; ScheduleAnyway entries are
    # soft and ignored; shapes beyond the canonical form fall back to
    # ``unmodeled_constraints``. Construction accepts legacy
    # ((key, value), ...) selector items; ``__post_init__``
    # canonicalizes.
    spread_constraints: Tuple = ()
    # Scheduling constraints this framework does not model (unresolved
    # volume topology, cross-namespace affinity, non-canonical spread
    # constraints, ...). Conservative in the safe direction: such a pod
    # is treated as placeable nowhere, so its node can never be proven
    # drainable — we may miss a drain the real scheduler would allow,
    # but never approve one that strands the pod.
    unmodeled_constraints: bool = False

    def __post_init__(self) -> None:
        # canonicalize the affinity/spread selector fields (the dict /
        # legacy-items shorthands used by tests and synthetic generators
        # become full canonical terms; decode output passes through)
        from k8s_spot_rescheduler_tpu.predicates.selectors import (
            canon_match_terms,
            canon_spread_entries,
        )

        self.anti_affinity_match = canon_match_terms(
            self.anti_affinity_match, self.namespace
        )
        self.anti_affinity_zone_match = canon_match_terms(
            self.anti_affinity_zone_match, self.namespace
        )
        self.pod_affinity_match = canon_match_terms(
            self.pod_affinity_match, self.namespace
        )
        self.pod_affinity_zone_match = canon_match_terms(
            self.pod_affinity_zone_match, self.namespace
        )
        self.spread_constraints = canon_spread_entries(self.spread_constraints)

    @property
    def uid(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_mirror(self) -> bool:
        return MIRROR_POD_ANNOTATION in self.annotations

    def controller_ref(self) -> Optional[OwnerRef]:
        for ref in self.owner_refs:
            if ref.controller:
                return ref
        return None

    def is_daemonset(self) -> bool:
        """DaemonSet-controlled, per the reference's ownerRef check
        (rescheduler.go:243-249)."""
        ref = self.controller_ref()
        return ref is not None and ref.kind == "DaemonSet"


@dataclasses.dataclass
class NodeSpec:
    name: str
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    allocatable: Dict[str, int] = dataclasses.field(default_factory=dict)
    taints: List[Taint] = dataclasses.field(default_factory=list)
    ready: bool = True
    unschedulable: bool = False

    def allocatable_cpu(self) -> int:
        return int(self.allocatable.get(CPU, 0))


@dataclasses.dataclass
class PVCSpec:
    """PersistentVolumeClaim, reduced to the binding the volume-affinity
    resolver needs."""

    name: str
    namespace: str = "default"
    volume_name: str = ""  # bound PV name; "" while unbound
    phase: str = "Bound"

    @property
    def uid(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class PVSpec:
    """PersistentVolume, reduced to its node-affinity constraint
    (spec.nodeAffinity.required — zonal/local volumes pin their pods to
    matching nodes; the same canonical terms form as pod nodeAffinity)."""

    name: str
    node_affinity: Tuple = ()  # canonical terms; () = no constraint
    unmodeled: bool = False  # affinity shape beyond the canonical form


@dataclasses.dataclass
class PDBSpec:
    """PodDisruptionBudget, reduced to the evictability decision: which pods
    it selects and how many more disruptions it currently allows.

    ``match_labels`` holds the canonical requirement selector
    (predicates/selectors.py; round 5 widened to the full
    matchLabels/matchExpressions operator surface — the reference gets
    this free through cluster-autoscaler's drain filter,
    rescheduler.go:231). Construction accepts the matchLabels-dict
    shorthand. An EMPTY selector selects every pod in the namespace
    (k8s PDB semantics — also the conservative decode fallback for
    selector shapes beyond the modeled surface, so an unparseable PDB
    blocks rather than under-protects)."""

    name: str
    namespace: str = "default"
    match_labels: Tuple = ()
    disruptions_allowed: int = 0

    def __post_init__(self) -> None:
        if isinstance(self.match_labels, dict):
            from k8s_spot_rescheduler_tpu.predicates.selectors import (
                canon_labels,
            )

            self.match_labels = canon_labels(self.match_labels)
        else:
            self.match_labels = tuple(sorted(set(self.match_labels)))

    def selects(self, pod: PodSpec) -> bool:
        if pod.namespace != self.namespace:
            return False
        from k8s_spot_rescheduler_tpu.predicates.selectors import (
            selector_matches,
        )

        return selector_matches(self.match_labels, pod.labels)


def pod_cpu_requests(pod: PodSpec) -> int:
    """Total requested CPU millicores (reference nodes/nodes.go:158-165
    ``getPodCPURequests``; containers are pre-summed into ``requests``)."""
    return int(pod.requests.get(CPU, 0))


def pods_requested(pods: Iterable[PodSpec], resource: str = CPU) -> int:
    """Reference nodes/nodes.go:149-155 ``calculateRequestedCPU``,
    generalized over the resource axis."""
    return sum(int(p.requests.get(resource, 0)) for p in pods)


@dataclasses.dataclass
class NodeInfo:
    """Reference nodes/nodes.go:46-51 ``NodeInfo``."""

    node: NodeSpec
    pods: List[PodSpec]
    requested_cpu: int
    free_cpu: int

    @classmethod
    def build(cls, node: NodeSpec, pods: Sequence[PodSpec]) -> "NodeInfo":
        requested = pods_requested(pods)
        return cls(
            node=node,
            pods=list(pods),
            requested_cpu=requested,
            free_cpu=node.allocatable_cpu() - requested,
        )

    def add_pod(self, pod: PodSpec) -> None:
        """Reference nodes/nodes.go:121-126 ``AddPod``: append and
        recompute requested/free."""
        self.pods.append(pod)
        self.requested_cpu = pods_requested(self.pods)
        self.free_cpu = self.node.allocatable_cpu() - self.requested_cpu

    def copy(self) -> "NodeInfo":
        """Shallow copy with its own pods list, like the reference's
        ``CopyNodeInfos`` element copy (nodes/nodes.go:211-224)."""
        return NodeInfo(
            node=self.node,
            pods=list(self.pods),
            requested_cpu=self.requested_cpu,
            free_cpu=self.free_cpu,
        )


@dataclasses.dataclass
class NodeMap:
    """Reference nodes/nodes.go:37-39, 54-60 ``Map``: node infos keyed by
    class, in planning order.

    ``other`` holds ready nodes matching neither class label; ``unready``
    holds not-ready nodes of ANY class (the reference's lister drops
    both, rescheduler.go:154 / nodes/nodes.go:90-91, and so does our
    planning surface) — but their RESIDENT PODS still exist to the real
    scheduler: zone anti-affinity presence reaches them, and
    PodTopologySpread counts their domains and pods (NotReady manifests
    as taints, which the default nodeTaintsPolicy=Ignore ignores).
    Missing either could approve a drain the scheduler then refuses.
    The packers fold both buckets into the zone/spread presence only;
    they never become candidates or placement targets."""

    on_demand: List[NodeInfo]
    spot: List[NodeInfo]
    other: List[NodeInfo] = dataclasses.field(default_factory=list)
    unready: List[NodeInfo] = dataclasses.field(default_factory=list)


def is_spot_node(node: NodeSpec, spot_label: str) -> bool:
    return matches_label(node.labels, spot_label)


def is_on_demand_node(node: NodeSpec, on_demand_label: str) -> bool:
    return matches_label(node.labels, on_demand_label)


def build_node_map(
    nodes: Sequence[NodeSpec],
    pods_by_node: Mapping[str, Sequence[PodSpec]],
    *,
    on_demand_label: str,
    spot_label: str,
    priority_threshold: int = 0,
    unready_nodes: Sequence[NodeSpec] = (),
) -> NodeMap:
    """Classify and sort nodes; reference nodes/nodes.go:63-119 ``NewNodeMap``
    + ``newNodeInfo`` + ``getPodsOnNode``.

    Policy reproduced exactly:
    - pods with priority below ``priority_threshold`` are ignored **on spot
      nodes only** (they are presumed preemptible; nodes/nodes.go:137-141),
    - each node's pods sort biggest-CPU-request-first (nodes/nodes.go:76-80),
    - spot-before-on-demand classification precedence (the ``switch`` at
      nodes/nodes.go:82-92: a node carrying both labels lands in spot),
    - spot nodes sort most-requested-CPU-first, on-demand nodes
      least-requested-first (nodes/nodes.go:95-101) — empty the emptiest
      on-demand node onto the fullest spot nodes (README.md:136-149).
    """
    on_demand: List[NodeInfo] = []
    spot: List[NodeInfo] = []
    other: List[NodeInfo] = []

    for node in nodes:
        spot_node = is_spot_node(node, spot_label)
        pods = [
            p
            for p in pods_by_node.get(node.name, [])
            if not (spot_node and p.priority < priority_threshold)
        ]
        pods.sort(key=pod_cpu_requests, reverse=True)
        info = NodeInfo.build(node, pods)
        if spot_node:
            spot.append(info)
        elif is_on_demand_node(node, on_demand_label):
            on_demand.append(info)
        else:
            # Unclassified nodes are not planning surface (the reference
            # ignores them, nodes/nodes.go:90-91) but their pods are kept
            # visible for zone-wide anti-affinity presence (NodeMap.other).
            other.append(info)

    # Python's sort is stable, like Go's sort.Slice is not — but ties keep
    # input order here, which is deterministic for our packers.
    spot.sort(key=lambda n: n.requested_cpu, reverse=True)
    on_demand.sort(key=lambda n: n.requested_cpu)
    # not-ready nodes (any class): presence-only visibility, no planning
    unready = [
        NodeInfo.build(n, pods_by_node.get(n.name, []))
        for n in unready_nodes
    ]
    return NodeMap(on_demand=on_demand, spot=spot, other=other,
                   unready=unready)
