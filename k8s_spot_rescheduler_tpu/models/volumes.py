"""Volume-topology resolution: PVC -> PV -> node-affinity.

The reference inherits volume predicates from the real scheduler
(``CheckPredicates``; predicate list reference README.md:103-114): a pod
whose PersistentVolumeClaim is bound to a zonal or local PV can only run
on nodes matching the PV's ``spec.nodeAffinity``. Decode marks every
PVC-bearing pod conservatively unplaceable (io/kube.decode_pod) — this
module is the step that LIFTS that conservatism when it can prove more:

- every claim the pod references must exist, be Bound, and name a known
  PV whose nodeAffinity is absent or in the canonical modeled form;
- the PVs' terms are ANDed into the pod's own requirement by term
  distribution (masks.merge_affinity_terms), so the result rides the
  existing NodeAffinityBit pseudo-taint machinery with zero solver or
  packer changes;
- anything else (unbound claim, missing PV, unmodeled PV affinity, term
  blow-up) leaves the pod exactly as decode made it: placeable nowhere.

Resolution happens where pods enter the model — the polling kube client
decorates its LIST results using same-tick PVC/PV LISTs, the fake
cluster decorates at add_pod, and the watch-mode client resolves at
event decode plus a per-tick retry for late bindings
(io/watch.WatchingKubeClusterClient._refresh_volumes). Bindings are
immutable for running pods — the only pods the planner ever moves.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from k8s_spot_rescheduler_tpu.models.cluster import PodSpec, PVCSpec, PVSpec
from k8s_spot_rescheduler_tpu.predicates.masks import merge_affinity_terms


def resolve_volume_affinity(
    pod: PodSpec,
    pvcs: Dict[str, PVCSpec],  # keyed by "namespace/name"
    pvs: Dict[str, PVSpec],  # keyed by PV name
) -> PodSpec:
    """Return the pod with its PVCs' volume topology folded into
    ``node_affinity``, or the pod unchanged when that cannot be proven
    (fail-safe: unchanged means placeable nowhere)."""
    if not pod.pvc_resolvable or not pod.pvc_names:
        return pod
    term_sets = [pod.node_affinity]
    for claim in pod.pvc_names:
        pvc = pvcs.get(f"{pod.namespace}/{claim}")
        if pvc is None or pvc.phase != "Bound" or not pvc.volume_name:
            return pod
        pv = pvs.get(pvc.volume_name)
        if pv is None or pv.unmodeled:
            return pod
        if pv.node_affinity:
            term_sets.append(pv.node_affinity)
    merged = merge_affinity_terms(*term_sets)
    if merged is None:  # term blow-up: stay conservative
        return pod
    return dataclasses.replace(
        pod,
        node_affinity=merged,
        unmodeled_constraints=False,
        pvc_resolvable=False,
    )


def maybe_resolve_view(pod, pvc_map, pv_map) -> Optional[PodSpec]:
    """Native-path helper: a lazy PodView only needs materializing when
    it actually carries resolvable claims; returns the resolved PodSpec
    then, else None (keep the view)."""
    if not getattr(pod, "pvc_resolvable", False):
        return None
    spec = pod.to_pod_spec()
    resolved = resolve_volume_affinity(spec, pvc_map, pv_map)
    return resolved if resolved is not spec else None


def terminally_unresolvable(pod: PodSpec, pvcs, pvs) -> bool:
    """True when resolution failed for a reason that can never clear:
    every claim is Bound to a PRESENT PV, yet resolution still declined
    (an unmodeled PV affinity shape, or term blow-up). PV affinity is
    immutable, so retrying such a pod re-LISTs the cluster's volumes
    every tick for zero possible progress — the watch client flips its
    ``pvc_resolvable`` off instead (staying unmodeled: conservative)."""
    for claim in pod.pvc_names:
        pvc = pvcs.get(f"{pod.namespace}/{claim}")
        if pvc is None or pvc.phase != "Bound" or not pvc.volume_name:
            return False  # binding may still happen: keep retrying
        if pvs.get(pvc.volume_name) is None:
            return False  # PV may still appear
    return True
