"""Host-side cluster model and dense-tensor packing."""

from k8s_spot_rescheduler_tpu.models.cluster import (
    NodeInfo,
    NodeMap,
    NodeSpec,
    OwnerRef,
    PDBSpec,
    PodSpec,
    Taint,
    Toleration,
    build_node_map,
    pod_cpu_requests,
)
from k8s_spot_rescheduler_tpu.models.evictability import (
    BlockingPod,
    get_pods_for_deletion,
)

__all__ = [
    "NodeInfo",
    "NodeMap",
    "NodeSpec",
    "OwnerRef",
    "PDBSpec",
    "PodSpec",
    "Taint",
    "Toleration",
    "build_node_map",
    "pod_cpu_requests",
    "BlockingPod",
    "get_pods_for_deletion",
]
