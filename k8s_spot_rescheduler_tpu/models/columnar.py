"""Columnar cluster state: the zero-copy observe→pack fast path.

SURVEY.md §5.8 names the TPU-native replacement for the reference's
watch-cache listers (reference rescheduler.go:154-156): *"host-side async
cluster-state ingestion (watch → arrow/numpy buffers)"*. This module is
those buffers. ``ColumnarStore`` maintains the whole cluster as a struct
of numpy arrays — one row per pod / node, updated incrementally as state
changes — so a housekeeping tick never walks Python objects:

- the reference rebuilds its ``NodeInfo`` map from scratch each tick with
  one pod LIST per node (reference nodes/nodes.go:63-145, an O(pods)
  object walk); the object-model path here (``models/cluster.py`` +
  ``models/tensors.pack_cluster``) reproduces that and costs ~275 ms at
  the 50k-pod north star;
- this path amortizes all per-pod work (request scaling, evictability
  flags, toleration interning, affinity hashing) into ``add_pod`` — each
  pod pays once when it *changes*, not every tick — and the per-tick
  ``pack()`` is pure vectorized numpy (sorts, bincounts, scatters) that
  emits the exact same ``PackedCluster`` tensors as ``pack_cluster``.

Parity contract: given the same cluster, ``pack()`` is **bit-identical**
to ``pack_cluster`` over a ``build_node_map`` of the same state — same
sort policies (spot most-requested-CPU-first, on-demand least-first,
pods biggest-request-first, insertion-order ties; nodes/nodes.go:76-101),
same evictability semantics (mirror/DaemonSet/terminal skipped, non-
replicated or exhausted-PDB pods block the node; rescheduler.go:231-256),
same taint interning order and scaled numerics. ``tests/test_columnar.py``
pins this across randomized churn.

Known model simplifications (safe direction): a pod's phase, requests,
labels and tolerations are read once at ``add_pod`` — k8s pods are
immutable in those fields for scheduling purposes (a phase change to
Succeeded/Failed is followed by deletion, which removes the row). Node
taints / readiness / schedulability ARE re-read every ``pack()`` because
the actuator and the cloud mutate them mid-drain.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import numpy as np

from k8s_spot_rescheduler_tpu.models.cluster import (
    CPU,
    NodeInfo,
    NodeSpec,
    PDBSpec,
    PodSpec,
)
from k8s_spot_rescheduler_tpu.models.evictability import BlockingPod
from k8s_spot_rescheduler_tpu.models.tensors import (
    DEFAULT_MAX_PODS,
    RESOURCE_SCALE,
    PackedCluster,
    _pad_dim,
)
from k8s_spot_rescheduler_tpu.predicates.masks import (
    AFFINITY_WORDS,
    HARD_EFFECTS,
    NodeAffinityBit,
    PodAffinityBit,
    SelectorBit,
    SpreadBit,
    ZonePodAffinityBit,
    Taint,
    TaintTable,
    affinity_bits,
    intern_constraints,
    match_affinity_mask,
    match_node_affinity,
    spread_lane_guard,
    ZONE_LABEL,
    zone_lane_guard,
    zone_match_affinity_mask,
)
from k8s_spot_rescheduler_tpu.predicates.selectors import (
    ALL_NAMESPACES,
    selector_matches,
    term_matches,
)
from k8s_spot_rescheduler_tpu.utils.labels import matches_label

# pod flag bits
_MIRROR = 1
_DAEMONSET = 2
_TERMINAL = 4
_REPLICATED = 8

_ON_DEMAND, _SPOT, _OTHER = 0, 1, 2


def _scale_requests(requests: Dict[str, int], resources: Sequence[str]) -> np.ndarray:
    """Per-pod scaled request row — same asymmetric ceil rounding as
    ``models/tensors.req_matrix`` (requests round *up*: a plan must never
    pass on a rounding error)."""
    out = np.empty(len(resources), np.float32)
    for j, r in enumerate(resources):
        if r == "pods":
            out[j] = 1.0
        else:
            d = RESOURCE_SCALE.get(r, 1)
            v = int(requests.get(r, 0))
            out[j] = v if d == 1 else -(-v // d)
    return out


@dataclasses.dataclass
class _Verdicts:
    """One evictability pass over the columns (see ``_verdicts``)."""

    nhi: int
    hi: int
    od_rows: np.ndarray
    spot_rows: np.ndarray
    safe_node: np.ndarray  # p_node with -1 clamped to 0 (for fancy indexing)
    counted: np.ndarray  # bool [hi] — visible to the node model
    blocks: np.ndarray  # bool [hi] — would abort its node's drain
    evict: np.ndarray  # bool [hi] — must be re-placed to drain
    nonrep: np.ndarray  # bool [hi] — blocking because non-replicated
    pdb_names: Dict[int, str]  # row -> exhausted PDB name


@dataclasses.dataclass
class ColumnarMeta:
    """Maps solver tensor indices back to cluster objects — the columnar
    counterpart of ``models/tensors.PackMeta`` (same planner-facing
    surface: ``n_candidates`` / ``blocking_pods`` / ``build_plan``)."""

    store: "ColumnarStore"
    cand_rows: np.ndarray  # i32 [C_actual] node rows, candidate order
    spot_rows: np.ndarray  # i32 [S_actual] node rows, probe order
    slot_rows: np.ndarray  # i32 pod rows, (candidate, slot) order
    slot_starts: np.ndarray  # i32 [C_actual] offsets into slot_rows
    slot_counts: np.ndarray  # i32 [C_actual]
    blocking: List[Tuple[int, str]]  # (pod row, reason) per blocked candidate
    resources: Tuple[str, ...]

    @property
    def n_candidates(self) -> int:
        return len(self.cand_rows)

    def blocking_pods(self) -> List[BlockingPod]:
        return [
            BlockingPod(self.store.pod_objs[row], reason)
            for row, reason in self.blocking
        ]

    def _unmodeled_slot_mask(self) -> np.ndarray:
        """bool per slot row: the pod's constraint profile is unmodeled.
        Computed once per pack (the conservatism report reads it twice
        per plan call)."""
        cached = getattr(self, "_unmod_slots", None)
        if cached is not None:
            return cached
        store = self.store
        if not len(self.slot_rows):
            mask = np.zeros(0, bool)
        else:
            unmod_by_tid = np.fromiter(
                (prof[-1] for prof in store._tol_lists),
                bool,
                count=len(store._tol_lists),
            )
            mask = unmod_by_tid[store.p_tol_id[self.slot_rows]]
        self._unmod_slots = mask
        return mask

    def unmodeled_candidate_mask(self) -> np.ndarray:
        """bool [n_candidates]: lane carries >=1 unmodeled-constraint pod
        (packed as placeable-nowhere -> the lane can never prove).
        Vectorized: one gather over the interned constraint profiles."""
        C = self.n_candidates
        if not C:
            return np.zeros(0, bool)
        slot_unmod = self._unmodeled_slot_mask()
        out = np.zeros(C, bool)
        if len(slot_unmod):
            cand_of_slot = np.repeat(np.arange(C), self.slot_counts)
            np.logical_or.at(out, cand_of_slot, slot_unmod)
        return out

    def unplaceable_pod_count(self) -> int:
        return int(self._unmodeled_slot_mask().sum())

    def candidate_pods(self, c: int) -> List[PodSpec]:
        rows = self.slot_rows[
            self.slot_starts[c] : self.slot_starts[c] + self.slot_counts[c]
        ]
        return [self.store.pod_objs[int(r)] for r in rows]

    def build_plan(self, c: int, row: np.ndarray):
        from k8s_spot_rescheduler_tpu.planner.base import DrainPlan

        store = self.store
        pods = self.candidate_pods(c)
        assignments = {
            pod.uid: store.node_objs[int(self.spot_rows[int(row[k])])].name
            for k, pod in enumerate(pods)
        }
        node_row = int(self.cand_rows[c])
        node = store.node_objs[node_row]
        on_node = store.pods_on_node_sorted(node_row)
        return DrainPlan(
            node=NodeInfo.build(node, on_node),
            pods=pods,
            assignments=assignments,
            candidate_index=c,
        )


@dataclasses.dataclass
class ColumnarObservation:
    """A tick-scoped view of a ``ColumnarStore`` carrying one precomputed
    verdict pass, so metrics and planning share it instead of each paying
    the evictability scan. Valid only while the cluster does not mutate —
    i.e. within a single housekeeping tick."""

    store: "ColumnarStore"
    verdicts: Optional[_Verdicts] = None

    def pack(self, pdbs: Sequence[PDBSpec] = (), **kwargs):
        return self.store.pack(pdbs, verdicts=self.verdicts, **kwargs)


class ColumnarStore:
    """Struct-of-arrays cluster mirror with incremental updates.

    Attach it to a state source (``FakeCluster.columnar_store`` or the
    watch cache) which calls ``add_pod``/``remove_pod``/``add_node``/
    ``remove_node`` as the cluster changes; call ``pack()`` once per tick.
    """

    def __init__(
        self,
        resources: Sequence[str],
        *,
        on_demand_label: str,
        spot_label: str,
    ):
        self.resources = tuple(resources)
        self.on_demand_label = on_demand_label
        self.spot_label = spot_label
        R = len(self.resources)

        # --- pod columns ---
        cap = 1024
        self.p_req = np.zeros((cap, R), np.float32)
        self.p_cpu = np.zeros(cap, np.int64)  # raw millicores (sort key)
        self.p_node = np.full(cap, -1, np.int32)
        self.p_prio = np.zeros(cap, np.int32)
        self.p_flags = np.zeros(cap, np.uint8)
        self.p_tol_id = np.zeros(cap, np.int32)
        self.p_aff_id = np.zeros(cap, np.int32)
        self.p_seq = np.zeros(cap, np.int64)
        self.p_live = np.zeros(cap, bool)
        self.pod_objs: List[Optional[PodSpec]] = [None] * cap
        self._pod_row: Dict[str, int] = {}  # uid -> row
        self._pod_free: List[int] = list(range(cap - 1, -1, -1))
        self._pod_hi = 0  # rows < hi may be live
        self._seq = 0

        # --- node columns ---
        ncap = 256
        self.n_alloc = np.zeros((ncap, R), np.float32)
        self.n_max_pods = np.zeros(ncap, np.int32)
        self.n_class = np.full(ncap, _OTHER, np.int8)
        self.n_ready = np.zeros(ncap, bool)
        self.n_unsched = np.zeros(ncap, bool)
        self.n_seq = np.zeros(ncap, np.int64)
        self.n_live = np.zeros(ncap, bool)
        self.node_objs: List[Optional[NodeSpec]] = [None] * ncap
        self._node_row: Dict[str, int] = {}
        self._node_free: List[int] = list(range(ncap - 1, -1, -1))
        self._node_hi = 0

        # toleration interning: distinct toleration tuples -> small id;
        # masks are recomputed only when the taint table changes.
        self._tol_keys: Dict[tuple, int] = {}
        self._tol_lists: List[tuple] = []
        self._table_key: Optional[tuple] = None
        self._tol_matrix = np.zeros((0, 1), np.uint32)  # [n_tol_ids, W]
        self._node_mask_cache: Dict[tuple, np.ndarray] = {}
        # Sectioned constraint-table caches. The table is [real taints |
        # selector pairs | node-affinity requirements | unplaceable]; the
        # real prefix is stable across ticks while the pseudo-taint tail
        # follows the current slot set — caching *bit positions* per
        # section means a universe change only recomputes the cheap
        # tail, not every toleration mask.
        self._real_section: tuple = ()
        self._sel_section: tuple = (0, ())
        self._sel_keys: List[str] = []  # selector keys in the current table
        self._naff_section: tuple = (0, ())
        self._naff_keys: List[str] = []  # label keys affinity exprs read
        self._naff_uses_name = False  # any FieldIn/FieldNotIn term active
        self._paff_section: tuple = (0, ())  # positive pod-affinity bits
        self._spread_section: tuple = (0, ())  # per-tick spread verdicts
        self._zpaff_section: tuple = (0, ())  # per-tick zone-paff verdicts
        self._unplace_pos: int = 0
        self._real_tol_pos: Dict[tuple, tuple] = {}
        self._sel_tol_pos: Dict[tuple, tuple] = {}
        self._naff_tol_pos: Dict[tuple, tuple] = {}
        self._paff_tol_pos: Dict[tuple, tuple] = {}
        # per-tick positive-affinity match matrix cache (see
        # _pod_affinity_node_bits)
        self._paff_match_key: Optional[tuple] = None
        self._paff_match_matrix = np.zeros((0, 0), bool)
        self._real_node_pos: Dict[tuple, tuple] = {}
        self._sel_node_pos: Dict[tuple, tuple] = {}
        self._naff_node_pos: Dict[tuple, tuple] = {}
        # per-ROW static mask cache (round 5, the pack hotspot): the
        # content-keyed _node_mask_cache dedups masks, but BUILDING its
        # key (taints/labels tuples) per spot row per tick was ~half of
        # pack time at config 3. Rows re-validate by object identity —
        # safe because every mutation path replaces objects (watch/kube
        # deliver fresh NodeSpecs; update_node swaps node_objs;
        # FakeCluster.add_taint replaces the taint list).
        self._nmask_matrix = np.zeros((0, 0), np.uint32)
        self._nmask_node: List[object] = []
        self._nmask_taints: List[object] = []

        # affinity-profile interning: (group, ns, match sel, labels) -> id;
        # the per-profile mask matrix depends on the tick's selector
        # universe and is rebuilt only when either changes
        self._aff_keys: Dict[tuple, int] = {}
        self._aff_lists: List[tuple] = []
        self._aff_universe_key: Optional[tuple] = None
        self._aff_matrix = np.zeros((0, AFFINITY_WORDS), np.uint32)
        self._host_matrix = np.zeros((0, AFFINITY_WORDS), np.uint32)
        self._zone_matrix = np.zeros((0, AFFINITY_WORDS), np.uint32)
        self._zone_universe: tuple = ()

        # label index for PDB selection: (ns, key, value) -> live pod rows
        self._label_index: Dict[Tuple[str, str, str], Set[int]] = {}
        # (ns, key) -> rows carrying the key at all (Exists requirements)
        self._key_index: Dict[Tuple[str, str], Set[int]] = {}
        self._ns_index: Dict[str, Set[int]] = {}

        # Mutation stamp + single-entry result memos: a tick whose watch
        # feed drained ZERO deltas (and whose PDB list and parameters
        # match) re-reads the previous verdict pass and pack verbatim —
        # the observe+pack cost of a quiet tick is O(1), not O(cluster),
        # which is what makes the steady-state watch tick truly
        # churn-proportional end to end. Every mutator bumps _version;
        # an upsert that changes nothing still bumps (correct, merely
        # conservative).
        self._version = 0
        self._verdict_memo: Optional[tuple] = None  # (key, _Verdicts)
        self._pack_memo: Optional[tuple] = None  # (key, (packed, meta))
        # Memoization is only sound when EVERY mutation flows through
        # the store's mutators (so _version can't miss one). The watch
        # ColumnarFeed guarantees that (fresh decoded objects per
        # event) and opts in; FakeCluster mutates shared NodeSpec
        # objects in place (taints/readiness) and must stay opted out.
        self.pack_memo_enabled = False

        # pods whose node hasn't been observed yet (a watch can deliver a
        # pod ADDED before its node ADDED); flushed when the node appears
        self._orphans: Dict[str, Dict[str, PodSpec]] = {}
        # slot sequence of a parked pod: the object path's dict keeps a
        # parked pod's insertion position, so when it un-parks it must get
        # its old seq back, not a fresh one (CPU-tie slot-order parity)
        self._parked_seq: Dict[str, int] = {}

    # ------------------------------------------------------------------
    # growth helpers

    def _grow_pods(self) -> None:
        old = len(self.p_live)
        new = old * 2
        R = len(self.resources)
        for name, shape, fill in (
            ("p_req", (new, R), 0),
            ("p_cpu", (new,), 0),
            ("p_node", (new,), -1),
            ("p_prio", (new,), 0),
            ("p_flags", (new,), 0),
            ("p_tol_id", (new,), 0),
            ("p_aff_id", (new,), 0),
            ("p_seq", (new,), 0),
            ("p_live", (new,), False),
        ):
            cur = getattr(self, name)
            arr = np.full(shape, fill, dtype=cur.dtype)
            arr[:old] = cur
            setattr(self, name, arr)
        self.pod_objs.extend([None] * (new - old))
        self._pod_free.extend(range(new - 1, old - 1, -1))

    def _grow_nodes(self) -> None:
        old = len(self.n_live)
        new = old * 2
        R = len(self.resources)
        for name, shape, fill in (
            ("n_alloc", (new, R), 0),
            ("n_max_pods", (new,), 0),
            ("n_class", (new,), _OTHER),
            ("n_ready", (new,), False),
            ("n_unsched", (new,), False),
            ("n_seq", (new,), 0),
            ("n_live", (new,), False),
        ):
            cur = getattr(self, name)
            arr = np.full(shape, fill, dtype=cur.dtype)
            arr[:old] = cur
            setattr(self, name, arr)
        self.node_objs.extend([None] * (new - old))
        self._node_free.extend(range(new - 1, old - 1, -1))

    # ------------------------------------------------------------------
    # incremental updates (the ingestion surface)

    def add_node(self, node: NodeSpec) -> None:
        self._version += 1
        if node.name in self._node_row:
            self.update_node(node)
            return
        if not self._node_free:
            self._grow_nodes()
        r = self._node_free.pop()
        self._node_row[node.name] = r
        self._node_hi = max(self._node_hi, r + 1)
        self.node_objs[r] = node
        R = len(self.resources)
        alloc = np.empty(R, np.float32)
        for j, res in enumerate(self.resources):
            default = DEFAULT_MAX_PODS if res == "pods" else 0
            alloc[j] = int(node.allocatable.get(res, default)) // RESOURCE_SCALE.get(res, 1)
        self.n_alloc[r] = alloc
        self.n_max_pods[r] = int(node.allocatable.get("pods", DEFAULT_MAX_PODS))
        # spot-before-on-demand classification precedence (nodes/nodes.go:82-92)
        if matches_label(node.labels, self.spot_label):
            self.n_class[r] = _SPOT
        elif matches_label(node.labels, self.on_demand_label):
            self.n_class[r] = _ON_DEMAND
        else:
            self.n_class[r] = _OTHER
        self.n_ready[r] = node.ready
        self.n_unsched[r] = node.unschedulable
        self._seq += 1
        self.n_seq[r] = self._seq
        self.n_live[r] = True
        for orphan in self._orphans.pop(node.name, {}).values():
            self.add_pod(orphan)

    def update_node(self, node: NodeSpec) -> None:
        """Re-read a node's mutable fields (labels/allocatable changes are
        rare but legal; readiness/taints are also re-read per pack())."""
        r = self._node_row.get(node.name)
        if r is None:
            self.add_node(node)
            return
        seq = self.n_seq[r]
        self.node_objs[r] = node
        self.n_live[r] = False
        self._node_row.pop(node.name)
        self._node_free.append(r)
        self.add_node(node)
        self.n_seq[self._node_row[node.name]] = seq  # keep original order

    def remove_node(self, name: str) -> None:
        self._version += 1
        r = self._node_row.pop(name, None)
        if r is None:
            return
        # Pods still referencing this row leave the columns with it (a
        # watch can deliver the node delete before its pods' deletes) —
        # otherwise row reuse by a future add_node would silently reattach
        # them to the new node. They park as orphans keyed by this node's
        # name: a node recreated under the same name (kubelet
        # re-registration) gets its still-bound pods back, and a pod
        # DELETED event or re-list purges them.
        hi = self._pod_hi
        stale = np.nonzero(self.p_live[:hi] & (self.p_node[:hi] == r))[0]
        for row in stale:
            pod = self.pod_objs[int(row)]
            if pod is not None:
                seq = int(self.p_seq[int(row)])
                self.remove_pod(pod.uid)
                self._orphans.setdefault(name, {})[pod.uid] = pod
                self._parked_seq[pod.uid] = seq
        self.n_live[r] = False
        self.node_objs[r] = None
        self._node_free.append(r)

    def add_pod(self, pod: PodSpec) -> None:
        self._version += 1
        if self._orphans:  # a parked copy under any node name is stale now
            for orphans in self._orphans.values():
                if orphans.pop(pod.uid, None) is not None:
                    break
        keep_seq = None
        old_row = self._pod_row.get(pod.uid)
        if old_row is not None:
            old_pod = self.pod_objs[old_row]
            if old_pod is not None:
                # upsert (a watch MODIFIED event): the object path's dict
                # update keeps the pod's position regardless of which
                # field changed, so keep its sequence too — slot ties must
                # not reorder (parity). Real k8s never changes
                # spec.nodeName for a uid, but synthetic/fake feeds can,
                # and the bit-parity contract must hold there as well.
                keep_seq = int(self.p_seq[old_row])
            self.remove_pod(pod.uid)
        node_row = self._node_row.get(pod.node_name)
        if node_row is None:
            # invisible until its node is observed (unscheduled pods have
            # node_name "" and stay invisible, like the object path)
            if pod.node_name:
                self._orphans.setdefault(pod.node_name, {})[pod.uid] = pod
                if keep_seq is not None:
                    # a live pod moving to an unseen node keeps its dict
                    # position on the object path — remember its seq for
                    # the un-park
                    self._parked_seq[pod.uid] = keep_seq
            return
        if not self._pod_free:
            self._grow_pods()
        r = self._pod_free.pop()
        self._pod_row[pod.uid] = r
        self._pod_hi = max(self._pod_hi, r + 1)
        self.pod_objs[r] = pod
        self.p_req[r] = _scale_requests(pod.requests, self.resources)
        self.p_cpu[r] = int(pod.requests.get(CPU, 0))
        self.p_node[r] = node_row
        self.p_prio[r] = pod.priority
        flags = 0
        if pod.is_mirror():
            flags |= _MIRROR
        if pod.phase in ("Succeeded", "Failed"):
            flags |= _TERMINAL
        ref = pod.controller_ref()
        if ref is not None:
            flags |= _REPLICATED
            if ref.kind == "DaemonSet":
                flags |= _DAEMONSET
        self.p_flags[r] = flags
        # one interned id per distinct scheduling-constraint profile:
        # (tolerations, nodeSelector, node-affinity, pod-affinity terms,
        # spread constraints, zone-pod-affinity terms, unmodeled flag).
        # The affinity fields are round-5 canonical terms that carry
        # their namespace scope internally; spread stays ns-paired (the
        # k8s API scopes spread to the pod's own namespace).
        key = (
            tuple(pod.tolerations),
            tuple(sorted(pod.node_selector.items())),
            pod.node_affinity,
            pod.pod_affinity_match,
            (
                (pod.namespace, tuple(pod.spread_constraints))
                if getattr(pod, "spread_constraints", ())
                else ()
            ),
            pod.pod_affinity_zone_match,
            bool(pod.unmodeled_constraints),
        )
        tid = self._tol_keys.get(key)
        if tid is None:
            tid = self._tol_keys[key] = len(self._tol_lists)
            self._tol_lists.append(key)
            self._table_key = None  # force toleration matrix rebuild
        self.p_tol_id[r] = tid
        # affinity profile: (group, ns, hostname terms, zone terms,
        # labels) determines the pod's affinity mask for any universe
        akey = (
            pod.anti_affinity_group,
            pod.namespace,
            pod.anti_affinity_match,
            pod.anti_affinity_zone_match,
            tuple(sorted(pod.labels.items())),
        )
        aid = self._aff_keys.get(akey)
        if aid is None:
            aid = self._aff_keys[akey] = len(self._aff_lists)
            self._aff_lists.append(akey)
            self._aff_universe_key = None  # force matrix rebuild
        self.p_aff_id[r] = aid
        if keep_seq is None:
            keep_seq = self._parked_seq.pop(pod.uid, None)  # un-park
        else:
            self._parked_seq.pop(pod.uid, None)
        if keep_seq is not None:
            self.p_seq[r] = keep_seq
        else:
            self._seq += 1
            self.p_seq[r] = self._seq
        self.p_live[r] = True
        # PDB / selector label index
        self._ns_index.setdefault(pod.namespace, set()).add(r)
        for k, v in pod.labels.items():
            self._label_index.setdefault((pod.namespace, k, v), set()).add(r)
            self._key_index.setdefault((pod.namespace, k), set()).add(r)

    def remove_pod(self, uid: str) -> None:
        self._version += 1
        r = self._pod_row.pop(uid, None)
        if r is None:
            for orphans in self._orphans.values():
                if orphans.pop(uid, None) is not None:
                    break
            self._parked_seq.pop(uid, None)
            return
        pod = self.pod_objs[r]
        self.p_live[r] = False
        self.pod_objs[r] = None
        self._pod_free.append(r)
        if pod is not None:
            ns = self._ns_index.get(pod.namespace)
            if ns is not None:
                ns.discard(r)
            for k, v in pod.labels.items():
                rows = self._label_index.get((pod.namespace, k, v))
                if rows is not None:
                    rows.discard(r)
                krows = self._key_index.get((pod.namespace, k))
                if krows is not None:
                    krows.discard(r)

    def bulk_add_pods(self, batch) -> bool:
        """Vectorized ingestion of a native ``PodBatch``
        (io/native_ingest.py) into empty pod columns — the LIST-seeding
        fast path: numpy column assignments instead of 50k ``add_pod``
        calls. Returns False (caller falls back to per-pod) when the
        store already holds pods, since bulk assignment has no upsert
        semantics."""
        if self._pod_row:
            return False
        self._version += 1
        from k8s_spot_rescheduler_tpu.io import native_ingest as ni

        n = batch.count
        if n == 0:
            return True
        while len(self.p_live) < n:
            self._grow_pods()
        R = len(self.resources)

        # resolve batch node ids -> store node rows (-1 = unknown)
        node_rows = np.array(
            [self._node_row.get(name, -1) for name in batch.node_names],
            np.int32,
        )
        p_node = node_rows[batch.i32[:, ni.P_NODEID]]
        named = np.array([bool(s) for s in batch.node_names], bool)[
            batch.i32[:, ni.P_NODEID]
        ]
        keep = np.nonzero(p_node >= 0)[0]
        k = len(keep)
        # a bulk load is an authoritative full LIST: previously parked
        # orphans either reappear in this batch (and re-park below if
        # their node is still unknown) or no longer exist
        self._orphans.clear()
        self._parked_seq.clear()

        # numeric columns, scaled exactly like _scale_requests
        req = np.empty((k, R), np.float32)
        src = {"cpu": ni.P_CPU, "memory": ni.P_MEM, "ephemeral-storage": ni.P_EPH}
        for j, r in enumerate(self.resources):
            if r == "pods":
                req[:, j] = 1.0
            elif r in src:
                col = batch.i64[keep, src[r]]
                d = RESOURCE_SCALE.get(r, 1)
                req[:, j] = col if d == 1 else -(-col // d)
            else:  # resource the native schema doesn't carry
                req[:, j] = 0.0
        self.p_req[:k] = req
        self.p_cpu[:k] = batch.i64[keep, ni.P_CPU]
        self.p_node[:k] = p_node[keep]
        self.p_prio[:k] = batch.i32[keep, ni.P_PRIO]
        # flag-bit remap: native (M=1,DS=2,R=4,T=8) -> store (M=1,DS=2,T=4,R=8)
        f = batch.u8[keep, 0]
        self.p_flags[:k] = (
            (f & (ni.F_MIRROR | ni.F_DAEMONSET))
            | ((f & ni.F_TERMINAL) >> 1)
            | ((f & ni.F_REPLICATED) << 1)
        )
        # constraint-profile interning: one lookup per distinct
        # (toleration set, nodeSelector set, node-affinity, pod-affinity,
        # unmodeled). The pod-affinity identity is namespace-scoped, so
        # the namespace joins the combo only when the selector is
        # non-empty (keeping plain pods to one profile per shape).
        unmod = (f & (ni.F_PVC | ni.F_REQAFF)) != 0
        paff_ids = batch.i32[keep, ni.P_PAFFID]
        paff_nonempty = np.fromiter(
            (len(s) > 0 for s in batch.paff_protos),
            bool,
            count=len(batch.paff_protos),
        )[paff_ids]
        spread_ids = batch.i32[keep, ni.P_SPREADID]
        spread_nonempty = np.fromiter(
            (len(s) > 0 for s in batch.spread_sets),
            bool,
            count=len(batch.spread_sets),
        )[spread_ids]
        pzaff_ids = batch.i32[keep, ni.P_PZAFFID]
        pzaff_nonempty = np.fromiter(
            (len(s) > 0 for s in batch.pzaff_protos),
            bool,
            count=len(batch.pzaff_protos),
        )[pzaff_ids]
        # paff/pzaff and spread identities are namespace-scoped: the
        # namespace joins the combo only when any is non-empty (keeping
        # plain pods to one profile per shape)
        ns_eff = np.where(
            paff_nonempty | spread_nonempty | pzaff_nonempty,
            batch.i32[keep, ni.P_NSID],
            np.int32(-1),
        )
        combos = np.stack(
            [
                batch.i32[keep, ni.P_TOLID],
                batch.i32[keep, ni.P_SELID],
                batch.i32[keep, ni.P_NAFFID],
                paff_ids,
                spread_ids,
                pzaff_ids,
                ns_eff,
                unmod.astype(np.int32),
            ],
            axis=1,
        )
        uniq, inverse = np.unique(combos, axis=0, return_inverse=True)
        ids = np.empty(len(uniq), np.int32)
        for i, (
            tol_id, sel_id, naff_id, paff_id, spread_id, pzaff_id, ns_id, um
        ) in enumerate(uniq):
            # ns_id is -1 exactly when paff/spread/pzaff are all empty —
            # then term resolution never reads the namespace
            ns = batch.namespaces[int(ns_id)] if ns_id >= 0 else ""
            spread_set = batch.spread_sets[int(spread_id)]
            key = (
                tuple(batch.tol_sets[tol_id]),
                tuple(sorted(batch.selector_set(int(sel_id)).items())),
                batch.naff_sets[int(naff_id)],
                batch.paff_terms(int(paff_id), ns),
                ((ns, tuple(spread_set)) if spread_set else ()),
                batch.pzaff_terms(int(pzaff_id), ns),
                bool(um),
            )
            tid = self._tol_keys.get(key)
            if tid is None:
                tid = self._tol_keys[key] = len(self._tol_lists)
                self._tol_lists.append(key)
                self._table_key = None
            ids[i] = tid
        self.p_tol_id[:k] = ids[inverse]
        # affinity-profile interning per distinct (ns, hostname terms,
        # zone terms, labels)
        acombos = np.stack(
            [
                batch.i32[keep, ni.P_NSID],
                batch.i32[keep, ni.P_AAFFID],
                batch.i32[keep, ni.P_ZAFFID],
                batch.i32[keep, ni.P_LABELSID],
            ],
            axis=1,
        )
        auniq, ainv = np.unique(acombos, axis=0, return_inverse=True)
        aids = np.empty(len(auniq), np.int32)
        for i, (ns_id, aaff_id, zaff_id, l_id) in enumerate(auniq):
            ns = batch.namespaces[ns_id]
            akey = (
                "",  # kube pods carry no synthetic group
                ns,
                batch.match_terms(int(aaff_id), ns),
                batch.zaff_terms(int(zaff_id), ns),
                tuple(sorted(batch.label_set(int(l_id)).items())),
            )
            aid = self._aff_keys.get(akey)
            if aid is None:
                aid = self._aff_keys[akey] = len(self._aff_lists)
                self._aff_lists.append(akey)
                self._aff_universe_key = None
            aids[i] = aid
        self.p_aff_id[:k] = aids[ainv]
        seq0 = self._seq + 1
        self._seq += k
        self.p_seq[:k] = np.arange(seq0, seq0 + k, dtype=np.int64)
        self.p_live[:k] = True
        self._pod_hi = max(self._pod_hi, k)
        self._pod_free = [
            r for r in range(len(self.p_live) - 1, -1, -1) if r >= k
        ]

        # identity + PDB label index (the only per-pod Python left)
        heap, stroff = batch.heap, batch.stroff
        ns_ids = batch.i32[keep, ni.P_NSID].tolist()
        label_ids = batch.i32[keep, ni.P_LABELSID].tolist()
        namespaces = batch.namespaces
        for r, (i, ns_id, l_id) in enumerate(
            zip(keep.tolist(), ns_ids, label_ids)
        ):
            view = batch.view(i)
            self.pod_objs[r] = view
            off, ln = stroff[i, 0]  # PS_NAME
            ns = namespaces[ns_id]
            uid = ns + "/" + heap[off : off + ln].decode()
            self._pod_row[uid] = r
            self._ns_index.setdefault(ns, set()).add(r)
            for key, v in batch.label_set(l_id).items():
                self._label_index.setdefault((ns, key, v), set()).add(r)
                self._key_index.setdefault((ns, key), set()).add(r)

        # pods on nodes the store hasn't seen yet park as orphans
        for i in np.nonzero((p_node < 0) & named)[0]:
            view = batch.view(int(i))
            self._orphans.setdefault(view.node_name, {})[view.uid] = view
        return True

    def reconcile_pods(self, pods: Sequence[PodSpec]) -> None:
        """Make the pod columns match exactly the given set (a watcher
        re-list after 410 Gone): vanished pods are removed — including
        orphans — and everything present is upserted (same-node upserts
        keep their slot order)."""
        new_uids = {p.uid for p in pods}
        for uid in [u for u in self._pod_row if u not in new_uids]:
            self.remove_pod(uid)
        for orphans in self._orphans.values():
            for uid in [u for u in orphans if u not in new_uids]:
                del orphans[uid]
                self._parked_seq.pop(uid, None)
        for pod in pods:
            self.add_pod(pod)

    def reconcile_nodes(self, nodes: Sequence[NodeSpec]) -> None:
        """Same as ``reconcile_pods`` for the node columns."""
        new_names = {n.name for n in nodes}
        for name in [n for n in self._node_row if n not in new_names]:
            self.remove_node(name)
        # orphans parked on nodes absent from the re-list stay parked; a
        # pod re-list purges them if their pod vanished too
        for node in nodes:
            self.add_node(node)

    # ------------------------------------------------------------------
    # snapshot-time helpers

    def _refresh_nodes(self) -> None:
        """Re-read the per-node mutable scalars (ready/unschedulable) the
        actuator and cloud flip mid-operation. O(nodes) attribute reads."""
        hi = self._node_hi
        for r in range(hi):
            obj = self.node_objs[r]
            if obj is not None:
                self.n_ready[r] = obj.ready
                self.n_unsched[r] = obj.unschedulable

    def _selector_rows(self, ns: str, selector) -> Set[int]:
        """Pod rows in namespace ``ns`` matched by a canonical
        requirement selector (predicates/selectors.py; liveness
        filtering is the caller's). Positive requirements (In / Exists)
        narrow via the label/key indexes; any negative ones
        (NotIn / DoesNotExist) filter the narrowed set per row — an
        all-negative selector falls back to the namespace index."""
        positive: List[Set[int]] = []
        for key, op, values in selector:
            if op == "In":
                rows: Set[int] = set()
                for v in values:
                    rows |= self._label_index.get((ns, key, v), set())
                positive.append(rows)
            elif op == "Exists":
                positive.append(self._key_index.get((ns, key), set()))
        if positive:
            cand = set.intersection(*sorted(positive, key=len))
        else:
            cand = set(self._ns_index.get(ns, set()))
        if len(positive) == len(selector):
            return cand
        out: Set[int] = set()
        for r in cand:
            pod = self.pod_objs[r]
            if pod is not None and selector_matches(selector, pod.labels):
                out.add(r)
        return out

    def _term_rows(self, term) -> Set[int]:
        """Rows matched by a full term — union of ``_selector_rows``
        over the term's namespace scope (every live namespace for the
        all-namespaces wildcard)."""
        namespaces, selector = term
        if namespaces == ALL_NAMESPACES:
            namespaces = list(self._ns_index)
        rows: Set[int] = set()
        for ns in namespaces:
            rows |= self._selector_rows(ns, selector)
        return rows

    def _build_taint_table(
        self,
        spot_order: np.ndarray,
        slot_rows: np.ndarray,
        spread_bits: Sequence = (),
        zone_paff_bits: Sequence = (),
    ) -> TaintTable:
        """Intern the constraint table over ready spot nodes in probe
        order, with the slot pods' nodeSelector universe as the
        pseudo-taint tail — identical bit layout to the object packer
        (``masks.intern_constraints`` over the sorted ``node_map.spot``
        and the concatenated ``cand_pods``). ``spread_bits`` is the
        tick's sorted SpreadBit universe (computed in pack() — it needs
        match counts, which live there)."""
        pairs = set()
        naffs = set()
        paffs = set()
        if len(slot_rows):
            for cid in np.unique(self.p_tol_id[slot_rows]):
                profile = self._tol_lists[int(cid)]
                pairs.update(profile[1])
                if profile[2]:
                    naffs.add(profile[2])
                paffs.update(profile[3])  # positive-affinity TERMS
        return intern_constraints(
            [self.node_objs[int(r)] for r in spot_order],
            sorted(pairs),
            sorted(naffs),
            sorted(paffs),
            spread_bits,
            zone_paff_bits,
        )

    def _spread_contexts(
        self,
        slot_rows: np.ndarray,
        p_node: np.ndarray,
        visible: np.ndarray,
        presence_extra: np.ndarray,
        od_rows: np.ndarray,
        spot_rows: np.ndarray,
    ) -> Tuple[Dict[int, frozenset], list]:
        """Per-carrier-slot SpreadBit sets + the sorted universe — the
        columnar mirror of tensors._build_spread_bits, bit-identical by
        construction (same compute_spread_bit, same visibility rule:
        counted pods of both classes + pods on unclassified-ready and
        not-ready nodes; domains over every visible node). Carriers are
        found via a per-profile flag array indexed by p_tol_id (plain
        clusters pay O(#profiles), not O(#slots)); matches come from
        the PDB label index."""
        if not len(slot_rows):
            return {}, []
        prof_has_spread = np.fromiter(
            (bool(prof[4]) for prof in self._tol_lists),
            bool,
            count=len(self._tol_lists),
        )
        has_spread = prof_has_spread[self.p_tol_id[slot_rows]]
        if not has_spread.any():
            return {}, []
        from k8s_spot_rescheduler_tpu.predicates.masks import (
            compute_spread_bit,
            spread_self_match,
        )

        hi = len(visible)
        visible_nodes = sorted(
            set(int(r) for r in od_rows)
            | set(int(r) for r in spot_rows)
            | set(np.nonzero(presence_extra)[0].tolist())
        )
        domain_cache: Dict = {}
        count_cache: Dict = {}
        bit_cache: Dict = {}

        def all_domains(topo):
            doms = domain_cache.get(topo)
            if doms is None:
                vals = set()
                for nr in visible_nodes:
                    obj = self.node_objs[nr]
                    if obj is not None:
                        d = obj.labels.get(topo)
                        if d is not None:
                            vals.add(d)
                doms = domain_cache[topo] = sorted(vals)
            return doms

        def counts_for(ns, topo, items):
            key = (ns, topo, items)
            c = count_cache.get(key)
            if c is not None:
                return c
            c = count_cache[key] = {}
            for r in self._selector_rows(ns, items):
                if r >= hi or not visible[r]:
                    continue
                nr = int(p_node[r])
                if nr < 0:
                    continue
                obj = self.node_objs[nr]
                if obj is None:
                    continue
                d = obj.labels.get(topo)
                if d is not None:
                    c[d] = c.get(d, 0) + 1
            return c

        out: Dict[int, frozenset] = {}
        universe: set = set()
        for j in np.nonzero(has_spread)[0]:
            r = int(slot_rows[j])
            pod = self.pod_objs[r]
            own_node = self.node_objs[int(p_node[r])]
            bits = []
            for topo, skew, items in pod.spread_constraints:
                self_m = spread_self_match(pod, items)
                own = own_node.labels.get(topo) if own_node else None
                bkey = (pod.namespace, topo, skew, items, own, self_m)
                bit = bit_cache.get(bkey)
                if bit is None:
                    bit = bit_cache[bkey] = compute_spread_bit(
                        topo,
                        skew,
                        own,
                        counts_for(pod.namespace, topo, items),
                        all_domains(topo),
                        self_m,
                    )
                bits.append(bit)
            out[int(j)] = frozenset(bits)
            universe.update(bits)
        return out, sorted(universe, key=lambda b: (b.topology_key, b.refused))

    def _zone_paff_contexts(
        self,
        slot_rows: np.ndarray,
        p_node: np.ndarray,
        counted: np.ndarray,
    ) -> Tuple[Dict[int, frozenset], list]:
        """Per-carrier-slot frozenset of ZonePodAffinityBit (one bit per
        carried TERM) + the sorted universe — the columnar mirror of
        tensors._build_zone_paff_bits (bit-identical: counted residents
        only, lane's own candidate excluded)."""
        if not len(slot_rows):
            return {}, []
        prof_has = np.fromiter(
            (bool(prof[5]) for prof in self._tol_lists),
            bool,
            count=len(self._tol_lists),
        )
        hasz = prof_has[self.p_tol_id[slot_rows]]
        if not hasz.any():
            return {}, []
        hi = len(counted)
        hits_cache: Dict = {}

        def zone_hits(term):
            cached = hits_cache.get(term)
            if cached is not None:
                return cached
            per_zone: Dict[str, int] = {}
            per_node: Dict[int, int] = {}
            for r in self._term_rows(term):
                if r >= hi or not counted[r]:
                    continue
                nr = int(p_node[r])
                if nr < 0:
                    continue
                per_node[nr] = per_node.get(nr, 0) + 1
                obj = self.node_objs[nr]
                z = obj.labels.get(ZONE_LABEL) if obj else None
                if z is not None:
                    per_zone[z] = per_zone.get(z, 0) + 1
            cached = hits_cache[term] = (per_zone, per_node)
            return cached

        out: Dict[int, frozenset] = {}
        universe: set = set()
        for j in np.nonzero(hasz)[0]:
            r = int(slot_rows[j])
            pod = self.pod_objs[r]
            cand_row = int(p_node[r])
            obj = self.node_objs[cand_row]
            own_zone = obj.labels.get(ZONE_LABEL) if obj else None
            bits = []
            for term in pod.pod_affinity_zone_match:
                per_zone, per_node = zone_hits(term)
                own_hits = per_node.get(cand_row, 0)
                allowed = tuple(sorted(
                    z for z, n in per_zone.items()
                    if n - (own_hits if z == own_zone else 0) > 0
                ))
                bits.append(ZonePodAffinityBit(
                    namespaces=term[0], items=term[1], allowed_zones=allowed
                ))
            out[int(j)] = frozenset(bits)
            universe.update(bits)
        return out, sorted(
            universe, key=lambda b: (b.namespaces, b.items, b.allowed_zones)
        )

    def _refresh_sections(self, table: TaintTable) -> None:
        real = tuple(e for e in table.taints if isinstance(e, Taint))
        pairs = tuple(
            (e.key, e.value) for e in table.taints if isinstance(e, SelectorBit)
        )
        naffs = tuple(
            e.terms for e in table.taints if isinstance(e, NodeAffinityBit)
        )
        offset = len(real)
        if self._real_section != real:
            self._real_section = real
            self._real_tol_pos.clear()
            self._real_node_pos.clear()
        if self._sel_section != (offset, pairs):
            self._sel_section = (offset, pairs)
            self._sel_tol_pos.clear()
            self._sel_node_pos.clear()
            self._sel_keys = sorted({k for k, _ in pairs})
        naff_off = offset + len(pairs)
        if self._naff_section != (naff_off, naffs):
            self._naff_section = (naff_off, naffs)
            self._naff_tol_pos.clear()
            self._naff_node_pos.clear()
            # label keys the affinity exprs read (Field* exprs read the
            # node NAME, not labels — exclude them here and key the node
            # mask cache by name instead, below)
            self._naff_keys = sorted(
                {
                    e[0]
                    for terms in naffs
                    for term in terms
                    for e in term
                    if e[1] not in ("FieldIn", "FieldNotIn")
                }
            )
            self._naff_uses_name = any(
                e[1] in ("FieldIn", "FieldNotIn")
                for terms in naffs
                for term in terms
                for e in term
            )
        paffs = tuple(
            (e.namespaces, e.items)
            for e in table.taints
            if isinstance(e, PodAffinityBit)
        )
        paff_off = naff_off + len(naffs)
        if self._paff_section != (paff_off, paffs):
            self._paff_section = (paff_off, paffs)
            self._paff_tol_pos.clear()
            self._paff_match_key = None
        # spread section: per-carrier-context verdict bits, recomputed
        # per tick from match counts (pack() passes them to the table
        # build); every profile tolerates them — carriers get their own
        # bits cleared per slot in pack(), since the verdict depends on
        # the carrier's LANE, which a per-profile row cannot know
        spreads = tuple(
            e for e in table.taints if isinstance(e, SpreadBit)
        )
        spread_off = paff_off + len(paffs)
        self._spread_section = (spread_off, spreads)
        # zone-positive-affinity section: per-carrier-context verdicts,
        # same per-tick lifecycle as the spread section
        zpaffs = tuple(
            e for e in table.taints if isinstance(e, ZonePodAffinityBit)
        )
        zpaff_off = spread_off + len(spreads)
        self._zpaff_section = (zpaff_off, zpaffs)
        self._unplace_pos = zpaff_off + len(zpaffs)

    @staticmethod
    def _mk_mask(positions, words: int) -> np.ndarray:
        m = np.zeros(words, np.uint32)
        for p in positions:
            m[p // 32] |= np.uint32(1 << (p % 32))
        return m

    def _toleration_matrix(self, table: TaintTable) -> np.ndarray:
        key = tuple(table.taints)
        if self._table_key != key or self._tol_matrix.shape[0] != len(self._tol_lists):
            self._refresh_sections(table)
            self._table_key = key
            self._node_mask_cache.clear()  # rebuilt from position caches
            self._nmask_matrix = np.zeros((0, 0), np.uint32)  # row cache too
            W = table.words
            rows = np.zeros((len(self._tol_lists), W), np.uint32)
            off, pairs = self._sel_section
            naff_off, naffs = self._naff_section
            paff_off, paffs = self._paff_section
            spread_off, spread_entries = self._spread_section
            zpaff_off, zpaff_entries = self._zpaff_section
            # every profile tolerates all per-tick context bits (spread
            # + zone-paff); carriers get their own cleared per slot in
            # pack(), since the verdicts depend on the carrier's LANE
            ctx_pos = tuple(
                range(spread_off, spread_off + len(spread_entries))
            ) + tuple(range(zpaff_off, zpaff_off + len(zpaff_entries)))
            for i, (
                tols, sel, naff, paff, _spread, _zpaff, unmodeled
            ) in enumerate(self._tol_lists):
                pos = self._real_tol_pos.get(tols)
                if pos is None:
                    pos = self._real_tol_pos[tols] = tuple(
                        j for j, t in enumerate(self._real_section)
                        if any(tol.tolerates(t) for tol in tols)
                    )
                spos = self._sel_tol_pos.get(sel)
                if spos is None:
                    required = dict(sel)
                    spos = self._sel_tol_pos[sel] = tuple(
                        off + j for j, (k, v) in enumerate(pairs)
                        if required.get(k) != v
                    )
                npos = self._naff_tol_pos.get(naff)
                if npos is None:
                    # tolerate every requirement bit except the pod's own
                    npos = self._naff_tol_pos[naff] = tuple(
                        naff_off + j for j, t in enumerate(naffs)
                        if t != naff
                    )
                ppos = self._paff_tol_pos.get(paff)
                if ppos is None:
                    # tolerate every positive-affinity bit except the
                    # pod's OWN terms (all of which must hold)
                    ppos = self._paff_tol_pos[paff] = tuple(
                        paff_off + j for j, t in enumerate(paffs)
                        if t not in paff
                    )
                unplace = () if unmodeled else (self._unplace_pos,)
                rows[i] = self._mk_mask(
                    pos + spos + npos + ppos + ctx_pos + unplace, W
                )
            self._tol_matrix = rows
        return self._tol_matrix


    def _pod_affinity_node_bits(
        self, sp_rows: np.ndarray, sp: np.ndarray, S_actual: int, W: int
    ) -> Optional[np.ndarray]:
        """Per-spot-node PodAffinityBit words for this tick: bit j set on
        nodes hosting NO counted resident matched by universe selector j
        (masks.hosts_affinity_match, vectorized). The node side depends
        on resident pods, so it lives outside the label-keyed node-mask
        cache; the per-aff-profile match matrix is cached until either
        the selector universe or the profile list changes."""
        paff_off, paffs = self._paff_section
        if not paffs:
            return None
        key = (self._paff_section, len(self._aff_lists))
        if self._paff_match_key != key:
            self._paff_match_key = key
            m = np.zeros((len(self._aff_lists), len(paffs)), bool)
            for i, (_, ns, _, _, labels) in enumerate(self._aff_lists):
                have = dict(labels)
                for j, term in enumerate(paffs):
                    m[i, j] = term_matches(term, ns, have)
            self._paff_match_matrix = m
        hosted = np.zeros((S_actual, len(paffs)), bool)
        if len(sp_rows):
            np.logical_or.at(
                hosted, sp, self._paff_match_matrix[self.p_aff_id[sp_rows]]
            )
        bits = np.zeros((S_actual, W), np.uint32)
        for j in range(len(paffs)):
            pos = paff_off + j
            bits[:, pos // 32] |= np.where(
                hosted[:, j], np.uint32(0), np.uint32(1 << (pos % 32))
            )
        return bits

    def _spot_taint_rows(
        self, spot_order: np.ndarray, table: TaintTable
    ) -> np.ndarray:
        """[S_actual, W] static node-side words for the probe-ordered
        spot pool — ``_node_taint_mask`` behind a per-ROW identity
        cache. A row recomputes only when its node object or its taint
        list is a different OBJECT than last tick (all mutation paths
        replace objects; see __init__ comment); the toleration-matrix
        rebuild wipes the cache wholesale on any table change."""
        n = len(self.node_objs)
        if self._nmask_matrix.shape != (n, table.words):
            self._nmask_matrix = np.zeros((n, table.words), np.uint32)
            self._nmask_node = [None] * n
            self._nmask_taints = [None] * n
        objs = self.node_objs
        nodes_c = self._nmask_node
        taints_c = self._nmask_taints
        matrix = self._nmask_matrix
        for r in spot_order:
            r = int(r)
            node = objs[r]
            taints = node.taints
            if nodes_c[r] is not node or taints_c[r] is not taints:
                matrix[r] = self._node_taint_mask(r, table)
                nodes_c[r] = node
                taints_c[r] = taints
        return matrix[spot_order]

    def _node_taint_mask(self, row: int, table: TaintTable) -> np.ndarray:
        node = self.node_objs[row]
        taints = tuple(t for t in node.taints if t.effect in HARD_EFFECTS)
        labelvals = tuple(node.labels.get(k) for k in self._sel_keys)
        nlabelvals = tuple(node.labels.get(k) for k in self._naff_keys)
        if self._naff_uses_name:
            # matchFields terms read metadata.name: the label profile no
            # longer determines the mask — key per node name too
            nlabelvals = (node.name, *nlabelvals)
        cache_key = (taints, labelvals, nlabelvals)
        cached = self._node_mask_cache.get(cache_key)
        if cached is None:
            pos = self._real_node_pos.get(taints)
            if pos is None:
                index = {t: j for j, t in enumerate(self._real_section)}
                pos = self._real_node_pos[taints] = tuple(
                    index[t] for t in taints if t in index
                )
            spos = self._sel_node_pos.get(labelvals)
            if spos is None:
                off, pairs = self._sel_section
                labels = node.labels
                spos = self._sel_node_pos[labelvals] = tuple(
                    off + j for j, (k, v) in enumerate(pairs)
                    if labels.get(k) != v
                )
            npos = self._naff_node_pos.get(nlabelvals)
            if npos is None:
                naff_off, naffs = self._naff_section
                # affinity label exprs read only _naff_keys and Field*
                # exprs read the name (nlabelvals[0] when present), so
                # this pair is a complete stand-in for the node here
                if self._naff_uses_name:
                    name, labelvals_only = nlabelvals[0], nlabelvals[1:]
                else:
                    name, labelvals_only = "", nlabelvals
                labels = dict(zip(self._naff_keys, labelvals_only))
                npos = self._naff_node_pos[nlabelvals] = tuple(
                    naff_off + j for j, terms in enumerate(naffs)
                    if not match_node_affinity(
                        terms,
                        {k: v for k, v in labels.items() if v is not None},
                        name,
                    )
                )
            cached = self._node_mask_cache[cache_key] = self._mk_mask(
                pos + spos + npos + (self._unplace_pos,), table.words
            )
        return cached

    def _affinity_matrix(
        self, counted_rows: np.ndarray, zone_rows: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """Per-profile affinity masks for the current tick's selector
        universe (distinct ``anti_affinity_match`` selectors among the
        counted pods). The ZONE universe spans ``zone_rows`` — counted
        pods plus pods on unclassified ready nodes (zone presence reaches
        any node class; see pack()). Rebuilt only when a universe or the
        profile list changes; plain clusters keep a zero universe and
        never rebuild."""
        ids = np.unique(self.p_aff_id[counted_rows]) if len(counted_rows) else []
        if zone_rows is None:
            zone_rows = counted_rows
        zids = np.unique(self.p_aff_id[zone_rows]) if len(zone_rows) else []
        universe = sorted(
            {
                t
                for i in ids
                for t in self._aff_lists[int(i)][2]
            }
        )
        zone_universe = sorted(
            {
                t
                for i in zids
                for t in self._aff_lists[int(i)][3]
            }
        )
        key = (tuple(universe), tuple(zone_universe), len(self._aff_lists))
        if self._aff_universe_key != key:
            self._aff_universe_key = key
            rows = np.zeros((len(self._aff_lists), AFFINITY_WORDS), np.uint32)
            hrows = np.zeros((len(self._aff_lists), AFFINITY_WORDS), np.uint32)
            zrows = np.zeros((len(self._aff_lists), AFFINITY_WORDS), np.uint32)
            for i, (group, ns, match_terms, zone_terms, labels) in enumerate(
                self._aff_lists
            ):
                lbl = dict(labels)
                m = match_affinity_mask(match_terms, ns, lbl, universe)
                if group:
                    w, b = affinity_bits(group)
                    m[w] |= np.uint32(1 << b)
                z = zone_match_affinity_mask(zone_terms, ns, lbl, zone_universe)
                hrows[i] = m
                zrows[i] = z
                rows[i] = m | z  # pod side (slot_aff)
            self._aff_matrix = rows
            # node side: a resident contributes hostname bits to its OWN
            # node only; zone bits flow exclusively through the zone-wide
            # accumulation (a zoneless node must never acquire them)
            self._host_matrix = hrows
            self._zone_matrix = zrows
            self._zone_universe = tuple(zone_universe)
        return self._aff_matrix

    def pods_on_node_sorted(self, node_row: int) -> List[PodSpec]:
        """All live pods on a node, biggest-CPU-request-first (insertion-
        order ties) — materialized only for the one node being drained."""
        hi = self._pod_hi
        rows = np.nonzero(self.p_live[:hi] & (self.p_node[:hi] == node_row))[0]
        order = np.lexsort((self.p_seq[rows], -self.p_cpu[rows]))
        return [self.pod_objs[int(r)] for r in rows[order]]

    def _pdb_blocked(
        self, pdbs: Sequence[PDBSpec]
    ) -> Tuple[np.ndarray, Dict[int, str]]:
        """Rows blocked by an exhausted PDB + the blocking PDB's name.
        First matching PDB in list order wins, like the object path."""
        hi = self._pod_hi
        blocked = np.zeros(hi, bool)
        names: Dict[int, str] = {}
        for pdb in pdbs:
            if pdb.disruptions_allowed >= 1:
                continue
            if pdb.match_labels:
                # canonical requirement selector (round 5 widened):
                # the shared index-backed matcher handles every operator
                rows = self._selector_rows(pdb.namespace, pdb.match_labels)
            else:
                # empty PDB selector: every pod in the namespace
                rows = self._ns_index.get(pdb.namespace, set())
            for r in rows:
                if r < hi and not blocked[r]:
                    blocked[r] = True
                    names[r] = pdb.name
        return blocked, names

    # ------------------------------------------------------------------
    # the shared pod-verdict pipeline (pack + metrics)

    def _verdicts(
        self,
        pdbs: Sequence[PDBSpec],
        *,
        priority_threshold: int,
        delete_non_replicated: bool,
    ) -> "_Verdicts":
        """One vectorized evictability pass over the live columns — the
        single source of truth for both ``pack()`` and
        ``node_pod_counts()`` (models/evictability.py semantics)."""
        if self.pack_memo_enabled:
            key = (
                self._version, tuple(pdbs), priority_threshold,
                delete_non_replicated,
            )
            if self._verdict_memo is not None and self._verdict_memo[0] == key:
                return self._verdict_memo[1]
        self._refresh_nodes()
        nhi, hi = self._node_hi, self._pod_hi

        # node classification; the controller only ever sees ready nodes
        # (NewReadyNodeLister, reference rescheduler.go:154,186)
        n_live = self.n_live[:nhi] & self.n_ready[:nhi]
        od_rows = np.nonzero(n_live & (self.n_class[:nhi] == _ON_DEMAND))[0]
        spot_rows = np.nonzero(n_live & (self.n_class[:nhi] == _SPOT))[0]

        # counted pods: live, on a live listed node; low-priority pods are
        # ignored on spot nodes only (nodes/nodes.go:137-141)
        p_node = self.p_node[:hi]
        node_listed = np.zeros(nhi, bool)
        node_listed[od_rows] = True
        node_listed[spot_rows] = True
        safe_node = np.where(p_node >= 0, p_node, 0)
        p_ok = self.p_live[:hi] & (p_node >= 0) & node_listed[safe_node]
        node_is_spot = np.zeros(nhi, bool)
        node_is_spot[spot_rows] = True
        counted = p_ok & ~(
            node_is_spot[safe_node] & (self.p_prio[:hi] < priority_threshold)
        )

        flags = self.p_flags[:hi]
        skip = (flags & (_MIRROR | _TERMINAL | _DAEMONSET)) != 0
        pdb_blocked, pdb_names = self._pdb_blocked(pdbs)
        nonrep = (flags & _REPLICATED) == 0
        if delete_non_replicated:
            nonrep = np.zeros(hi, bool)
        blocks = counted & ~skip & (nonrep | pdb_blocked)
        evict = counted & ~skip & ~blocks
        out = _Verdicts(
            nhi=nhi, hi=hi, od_rows=od_rows, spot_rows=spot_rows,
            safe_node=safe_node, counted=counted, blocks=blocks,
            evict=evict, nonrep=nonrep, pdb_names=pdb_names,
        )
        if self.pack_memo_enabled:
            self._verdict_memo = (key, out)
        return out

    def verdicts(
        self,
        pdbs: Sequence[PDBSpec] = (),
        *,
        priority_threshold: int = 0,
        delete_non_replicated: bool = False,
    ) -> "_Verdicts":
        """Public handle on the verdict pass for tick-scoped sharing
        (see ``ColumnarObservation``)."""
        return self._verdicts(
            pdbs,
            priority_threshold=priority_threshold,
            delete_non_replicated=delete_non_replicated,
        )

    # ------------------------------------------------------------------
    # the per-tick pack

    def pack(
        self,
        pdbs: Sequence[PDBSpec] = (),
        *,
        priority_threshold: int = 0,
        delete_non_replicated: bool = False,
        pad_candidates: int = 0,
        pad_spot: int = 0,
        pad_slots: int = 0,
        verdicts: Optional[_Verdicts] = None,
    ) -> Tuple[PackedCluster, ColumnarMeta]:
        """Vectorized observe+pack: emits the same ``PackedCluster`` the
        object path does (build_node_map → pack_cluster), in one pass of
        numpy ops over the live columns.

        ``verdicts`` may carry a pass precomputed *from the same state and
        parameters* (the controller computes one per tick and shares it
        between metrics and planning); it is trusted, not re-validated.
        """
        memo_key = None
        if self.pack_memo_enabled:
            memo_key = (
                self._version, tuple(pdbs), priority_threshold,
                delete_non_replicated, pad_candidates, pad_spot, pad_slots,
            )
            if self._pack_memo is not None and self._pack_memo[0] == memo_key:
                # zero-churn tick with identical PDBs/params: the
                # previous pack is bit-identical by construction — the
                # planner's delta emitter then sees prev IS new and
                # ships zero bytes
                return self._pack_memo[1]
        v = verdicts if verdicts is not None else self._verdicts(
            pdbs,
            priority_threshold=priority_threshold,
            delete_non_replicated=delete_non_replicated,
        )
        nhi, hi = v.nhi, v.hi
        od_rows, spot_rows = v.od_rows, v.spot_rows
        p_node = self.p_node[:hi]
        safe_node, counted = v.safe_node, v.counted
        R = len(self.resources)

        # per-node requested CPU -> sort orders (nodes/nodes.go:95-101)
        req_cpu = np.bincount(
            p_node[counted], weights=self.p_cpu[:hi][counted].astype(np.float64),
            minlength=nhi,
        )
        od_order = od_rows[
            np.lexsort((self.n_seq[od_rows], req_cpu[od_rows]))
        ]  # least-requested first
        spot_order = spot_rows[
            np.lexsort((self.n_seq[spot_rows], -req_cpu[spot_rows]))
        ]  # most-requested first

        blocks, evict, nonrep = v.blocks, v.evict, v.nonrep
        pdb_names = v.pdb_names

        # per-candidate verdicts
        cand_rank = np.full(nhi, -1, np.int32)
        cand_rank[od_order] = np.arange(len(od_order), dtype=np.int32)
        C_actual = len(od_order)
        n_evict = np.bincount(
            cand_rank[p_node[evict & (cand_rank[safe_node] >= 0)]],
            minlength=C_actual,
        ) if C_actual else np.zeros(0, np.int64)
        block_rows = np.nonzero(blocks & (cand_rank[safe_node] >= 0))[0]
        has_block = np.zeros(C_actual, bool)
        has_block[cand_rank[p_node[block_rows]]] = True

        # blocking-pod report: per blocked candidate, the first blocker in
        # slot order (cpu desc, seq ties) — rescheduler.go:232-238
        blocking: List[Tuple[int, str]] = []
        if len(block_rows):
            order = np.lexsort(
                (self.p_seq[block_rows], -self.p_cpu[block_rows],
                 cand_rank[p_node[block_rows]])
            )
            seen_cand: Set[int] = set()
            for r in block_rows[order]:
                c = int(cand_rank[p_node[r]])
                if c not in seen_cand:
                    seen_cand.add(c)
                    reason = (
                        "pod is not replicated" if nonrep[r]
                        else f"not enough pod disruption budget ({pdb_names[int(r)]})"
                    )
                    blocking.append((int(r), reason))

        # slot packing: evictable pods of non-blocked candidates, ordered
        # (candidate, cpu desc, insertion) — nodes/nodes.go:76-80
        cand_ok = ~has_block
        pod_cand = cand_rank[safe_node]
        packable = evict & (pod_cand >= 0)
        if C_actual:
            packable &= cand_ok[np.where(pod_cand >= 0, pod_cand, 0)]
        slot_rows_u = np.nonzero(packable)[0]
        order = np.lexsort(
            (self.p_seq[slot_rows_u], -self.p_cpu[slot_rows_u],
             pod_cand[slot_rows_u])
        )
        slot_rows = slot_rows_u[order].astype(np.int32)
        slot_cand = pod_cand[slot_rows]

        # presence visibility: counted pods plus pods on unclassified
        # ready nodes AND not-ready nodes of any class (a requirer/match
        # there still exists to the real scheduler, and spread's
        # domain-min must see their domains; the object packer folds
        # NodeMap.other/.unready identically) — shared by zone presence
        # and spread counts
        presence_extra = self.n_live[:nhi] & (
            ~self.n_ready[:nhi] | (self.n_class[:nhi] == _OTHER)
        )
        zone_counted = counted | (
            self.p_live[:hi] & (p_node >= 0) & presence_extra[safe_node]
        )
        # hard topology-spread carrier contexts (masks.SpreadBit): per
        # carrier slot, the refused-domain verdict from this tick's
        # match counts — must exist before the table is interned
        slot_spread_bits, spread_universe = self._spread_contexts(
            slot_rows, p_node, zone_counted, presence_extra,
            od_rows, spot_rows,
        )
        slot_zpaff_bits, zpaff_universe = self._zone_paff_contexts(
            slot_rows, p_node, counted
        )

        # constraint table: built AFTER the slot set is known — its
        # pseudo-taint tail is the slot pods' nodeSelector universe
        # (identical to the object packer's, masks.intern_constraints)
        table = self._build_taint_table(
            spot_order, slot_rows, spread_universe, zpaff_universe
        )
        tol_matrix = self._toleration_matrix(table)
        W = table.words
        aff_matrix = self._affinity_matrix(
            np.nonzero(counted)[0], np.nonzero(zone_counted)[0]
        )
        slot_counts = np.bincount(slot_cand, minlength=C_actual).astype(np.int32)
        slot_starts = np.concatenate(
            ([0], np.cumsum(slot_counts[:-1]))
        ).astype(np.int32) if C_actual else np.zeros(0, np.int32)
        slot_idx = (
            np.arange(len(slot_rows), dtype=np.int32) - slot_starts[slot_cand]
        ) if len(slot_rows) else np.zeros(0, np.int32)

        # static shapes (same padding policy as pack_cluster)
        C = max(_pad_dim(C_actual), _pad_dim(pad_candidates))
        S = max(_pad_dim(len(spot_order)), _pad_dim(pad_spot))
        K = max(
            _pad_dim(int(slot_counts.max()) if len(slot_counts) else 1),
            _pad_dim(pad_slots),
        )

        packed = PackedCluster(
            slot_req=np.zeros((C, K, R), np.float32),
            slot_valid=np.zeros((C, K), bool),
            slot_tol=np.zeros((C, K, W), np.uint32),
            slot_aff=np.zeros((C, K, AFFINITY_WORDS), np.uint32),
            cand_valid=np.zeros((C,), bool),
            spot_free=np.zeros((S, R), np.float32),
            spot_count=np.zeros((S,), np.int32),
            spot_max_pods=np.zeros((S,), np.int32),
            spot_taints=np.zeros((S, W), np.uint32),
            spot_ok=np.zeros((S,), bool),
            spot_aff=np.zeros((S, AFFINITY_WORDS), np.uint32),
        )

        if len(slot_rows):
            packed.slot_req[slot_cand, slot_idx] = self.p_req[slot_rows]
            packed.slot_valid[slot_cand, slot_idx] = True
            packed.slot_tol[slot_cand, slot_idx] = tol_matrix[
                self.p_tol_id[slot_rows]
            ]
            packed.slot_aff[slot_cand, slot_idx] = aff_matrix[
                self.p_aff_id[slot_rows]
            ]
            if self._zone_universe:
                # zone lane guard (masks.zone_lane_guard, shared with the
                # object packer): lanes holding a zone-anti CARRIER get
                # the per-lane safety analysis; flagged pods lose their
                # unplaceable-bit tolerance
                carrier = np.fromiter(
                    (bool(prof[3]) for prof in self._aff_lists),
                    bool,
                    count=len(self._aff_lists),
                )[self.p_aff_id[slot_rows]]
                if carrier.any():
                    up = self._unplace_pos
                    uw, ub = up // 32, np.uint32(1 << (up % 32))
                    for c in np.unique(slot_cand[carrier]):
                        rows = slot_rows[slot_cand == c]
                        pods = [self.pod_objs[int(r)] for r in rows]
                        for k in zone_lane_guard(pods):
                            packed.slot_tol[int(c), int(k), uw] &= ~ub
            if slot_spread_bits:
                # spread carriers lose tolerance of their own verdict
                # bits (per slot — the verdict depends on the lane's
                # node, which the per-profile toleration row cannot know)
                spread_pos = {
                    e: i
                    for i, e in enumerate(table.taints)
                    if isinstance(e, SpreadBit)
                }
                for j, bits in slot_spread_bits.items():
                    c, k = int(slot_cand[j]), int(slot_idx[j])
                    for b in bits:
                        pos = spread_pos[b]
                        packed.slot_tol[c, k, pos // 32] &= ~np.uint32(
                            1 << (pos % 32)
                        )
                # spread lane guard (masks.spread_lane_guard, shared
                # with the object packer): >=2 in-plan movers involved
                # with one identity shift each other's counts
                up = self._unplace_pos
                uw, ub = up // 32, np.uint32(1 << (up % 32))
                for c in np.unique(slot_cand[sorted(slot_spread_bits)]):
                    rows = slot_rows[slot_cand == c]
                    pods = [self.pod_objs[int(r)] for r in rows]
                    for k in spread_lane_guard(pods):
                        packed.slot_tol[int(c), int(k), uw] &= ~ub
            if slot_zpaff_bits:
                # zone-positive-affinity carriers lose tolerance of
                # their own context bits (per slot, lane-dependent; one
                # bit per carried term — every term must hold)
                zpaff_pos = {
                    e: i
                    for i, e in enumerate(table.taints)
                    if isinstance(e, ZonePodAffinityBit)
                }
                for j, bits in slot_zpaff_bits.items():
                    c, k = int(slot_cand[j]), int(slot_idx[j])
                    for bit in bits:
                        pos = zpaff_pos[bit]
                        packed.slot_tol[c, k, pos // 32] &= ~np.uint32(
                            1 << (pos % 32)
                        )
        if C_actual:
            packed.cand_valid[:C_actual] = cand_ok & (n_evict > 0)

        S_actual = len(spot_order)
        if S_actual:
            # spot pool accounting over counted pods (used = sum of scaled
            # request rows; exact in f32 — values bounded by allocatable)
            spot_rank = np.full(nhi, -1, np.int32)
            spot_rank[spot_order] = np.arange(S_actual, dtype=np.int32)
            sp_rows = np.nonzero(counted & (spot_rank[safe_node] >= 0))[0]
            sp = spot_rank[p_node[sp_rows]]
            used = np.zeros((S_actual, R), np.float64)
            for j in range(R):
                used[:, j] = np.bincount(
                    sp, weights=self.p_req[sp_rows, j].astype(np.float64),
                    minlength=S_actual,
                )
            packed.spot_free[:S_actual] = (
                self.n_alloc[spot_order] - used.astype(np.float32)
            )
            packed.spot_count[:S_actual] = np.bincount(
                sp, minlength=S_actual
            ).astype(np.int32)
            packed.spot_max_pods[:S_actual] = self.n_max_pods[spot_order]
            packed.spot_ok[:S_actual] = ~self.n_unsched[spot_order]
            packed.spot_taints[:S_actual] = self._spot_taint_rows(
                spot_order, table
            )
            paff_bits = self._pod_affinity_node_bits(sp_rows, sp, S_actual, W)
            if paff_bits is not None:
                packed.spot_taints[:S_actual] |= paff_bits
            if spread_universe or zpaff_universe:
                # per-tick context node sides: a spot node repels a
                # spread carrier when it lacks the topology key or sits
                # in a refused domain, and a zone-paff carrier when its
                # zone hosts no qualifying match. Vectorized per entry
                # over the spot axis (advisor r4: the S×E Python loop
                # was hot at scale): one per-topology-key domain column,
                # then numpy membership tests per entry.
                entries = [
                    (i, e)
                    for i, e in enumerate(table.taints)
                    if isinstance(e, (SpreadBit, ZonePodAffinityBit))
                ]
                MISSING = "\x00"  # impossible as a k8s label value
                topo_cols: Dict[str, np.ndarray] = {}

                def col(topo):
                    vals = topo_cols.get(topo)
                    if vals is None:
                        vals = topo_cols[topo] = np.array(
                            [
                                self.node_objs[int(r)].labels.get(
                                    topo, MISSING
                                )
                                for r in spot_order
                            ]
                        )
                    return vals

                for pos, e in entries:
                    if isinstance(e, SpreadBit):
                        vals = col(e.topology_key)
                        bad = (vals == MISSING) | np.isin(
                            vals, list(e.refused)
                        )
                    else:
                        vals = col(ZONE_LABEL)
                        bad = (vals == MISSING) | ~np.isin(
                            vals, list(e.allowed_zones)
                        )
                    packed.spot_taints[:S_actual][bad, pos // 32] |= (
                        np.uint32(1 << (pos % 32))
                    )
            aff = np.zeros((S_actual, AFFINITY_WORDS), np.uint32)
            np.bitwise_or.at(aff, sp, self._host_matrix[self.p_aff_id[sp_rows]])
            if self._zone_universe:
                # zone-wide presence: OR the zone-family masks of EVERY
                # counted pod plus every pod on an unclassified ready
                # node (any node class) into its node's zone, then into
                # each spot node in that zone
                zone_ids: Dict[str, int] = {}
                zid_node = np.full(nhi, -1, np.int32)
                for nr in range(nhi):
                    obj = self.node_objs[nr]
                    if obj is None:
                        continue
                    z = obj.labels.get(ZONE_LABEL)
                    if z is not None:
                        zid_node[nr] = zone_ids.setdefault(z, len(zone_ids))
                if zone_ids:
                    crows = np.nonzero(zone_counted)[0]
                    pz = zid_node[p_node[crows]]
                    live = pz >= 0
                    accum = np.zeros((len(zone_ids), AFFINITY_WORDS), np.uint32)
                    np.bitwise_or.at(
                        accum, pz[live],
                        self._zone_matrix[self.p_aff_id[crows[live]]],
                    )
                    spot_z = zid_node[spot_order]
                    has_z = spot_z >= 0
                    aff[has_z] |= accum[spot_z[has_z]]
            packed.spot_aff[:S_actual] = aff

        meta = ColumnarMeta(
            store=self,
            cand_rows=od_order.astype(np.int32),
            spot_rows=spot_order.astype(np.int32),
            slot_rows=slot_rows,
            slot_starts=slot_starts,
            slot_counts=slot_counts,
            blocking=blocking,
            resources=self.resources,
        )
        if memo_key is not None:
            self._pack_memo = (memo_key, (packed, meta))
        return packed, meta

    # ------------------------------------------------------------------
    # metrics support (vectorized _update_metrics inputs)

    def node_pod_counts(
        self,
        pdbs: Sequence[PDBSpec] = (),
        *,
        priority_threshold: int = 0,
        delete_non_replicated: bool = False,
        verdicts: Optional[_Verdicts] = None,
    ) -> Tuple[List[Tuple[str, int]], List[Tuple[str, int]]]:
        """(on_demand, spot) lists of (node name, pods-the-rescheduler-
        understands) — what the reference recomputes per node via the drain
        filter (rescheduler.go:259, 385-399). A blocked node reports 0."""
        v = verdicts if verdicts is not None else self._verdicts(
            pdbs,
            priority_threshold=priority_threshold,
            delete_non_replicated=delete_non_replicated,
        )
        p_node = self.p_node[: v.hi]
        n_evict = np.bincount(p_node[v.evict], minlength=v.nhi)
        blocked_nodes = np.zeros(v.nhi, bool)
        blocked_nodes[p_node[v.blocks]] = True
        out_od = [
            (
                self.node_objs[int(r)].name,
                0 if blocked_nodes[r] else int(n_evict[r]),
            )
            for r in v.od_rows
        ]
        out_spot = [
            (
                self.node_objs[int(r)].name,
                0 if blocked_nodes[r] else int(n_evict[r]),
            )
            for r in v.spot_rows
        ]
        return out_od, out_spot

    # convenience for tests / debugging
    @property
    def n_pods(self) -> int:
        return len(self._pod_row)

    @property
    def n_nodes(self) -> int:
        return len(self._node_row)


# ----------------------------------------------------------------------
# incremental device-resident tick pipeline: the delta emitter
#
# Ticks are overwhelmingly incremental (the watch/ColumnarFeed path feeds
# this store a handful of events between packs), yet the planner used to
# re-ship the whole (C×K×·) tensor set across the host↔device boundary
# every tick. ``emit_packed_delta`` turns two consecutive packs into a
# compact update at three granularities matching the tensor layout:
#
# - **changed candidate lanes** — a lane's [K, ·] slot slabs (req /
#   valid / tol / aff) travel whole: any slot edit reorders the whole
#   lane (slots are sorted biggest-request-first within the lane);
# - **changed cand_valid entries** — 1 byte per flipped lane, kept
#   separate so a feasibility flip without slot churn ships no slab;
# - **changed spot rows** — a spot node's free/count/taints/aff row.
#
# The diff is exact (bitwise compare of the two host packs), so the
# scatter-apply on the device cache reproduces the full re-pack
# bit-identically BY CONSTRUCTION — ``tests/test_incremental.py`` pins
# the whole machinery (padding, dtype, out-of-bounds drop) across
# randomized churn. Shape growth past the high-water pads returns None:
# the caller must fall back to a full re-upload (and count it).


class PackedDelta(NamedTuple):
    """Churn-proportional update between two same-shape PackedClusters."""

    # changed candidate lanes (full [K, ·] slabs, lane-major)
    lanes: np.ndarray  # i32 [L]
    lane_slot_req: np.ndarray  # f32 [L, K, R]
    lane_slot_valid: np.ndarray  # bool [L, K]
    lane_slot_tol: np.ndarray  # u32 [L, K, W]
    lane_slot_aff: np.ndarray  # u32 [L, K, A]
    # changed per-lane validity bits
    cand_rows: np.ndarray  # i32 [Lc]
    cand_valid: np.ndarray  # bool [Lc]
    # changed spot rows
    spot_rows: np.ndarray  # i32 [M]
    spot_free: np.ndarray  # f32 [M, R]
    spot_count: np.ndarray  # i32 [M]
    spot_max_pods: np.ndarray  # i32 [M]
    spot_taints: np.ndarray  # u32 [M, W]
    spot_ok: np.ndarray  # bool [M]
    spot_aff: np.ndarray  # u32 [M, A]

    @property
    def nbytes(self) -> int:
        """Bytes this delta ships host→device (unpadded)."""
        return sum(np.asarray(f).nbytes for f in self)

    @property
    def n_lanes(self) -> int:
        return len(self.lanes)


def emit_packed_delta(prev: PackedCluster, new: PackedCluster):
    """Diff two consecutive packs into a :class:`PackedDelta`.

    Returns None when any tensor shape differs (the cluster outgrew the
    high-water pad floors) — the caller must re-upload in full. An
    identical pack yields an all-empty delta (zero upload).
    """
    for f in PackedCluster._fields:
        if getattr(prev, f).shape != getattr(new, f).shape:
            return None
    lane_changed = (
        np.any(prev.slot_req != new.slot_req, axis=(1, 2))
        | np.any(prev.slot_valid != new.slot_valid, axis=1)
        | np.any(prev.slot_tol != new.slot_tol, axis=(1, 2))
        | np.any(prev.slot_aff != new.slot_aff, axis=(1, 2))
    )
    lanes = np.nonzero(lane_changed)[0].astype(np.int32)
    cand_rows = np.nonzero(prev.cand_valid != new.cand_valid)[0].astype(
        np.int32
    )
    spot_changed = (
        np.any(prev.spot_free != new.spot_free, axis=1)
        | (prev.spot_count != new.spot_count)
        | (prev.spot_max_pods != new.spot_max_pods)
        | np.any(prev.spot_taints != new.spot_taints, axis=1)
        | (prev.spot_ok != new.spot_ok)
        | np.any(prev.spot_aff != new.spot_aff, axis=1)
    )
    spot_rows = np.nonzero(spot_changed)[0].astype(np.int32)
    return PackedDelta(
        lanes=lanes,
        lane_slot_req=np.ascontiguousarray(new.slot_req[lanes]),
        lane_slot_valid=np.ascontiguousarray(new.slot_valid[lanes]),
        lane_slot_tol=np.ascontiguousarray(new.slot_tol[lanes]),
        lane_slot_aff=np.ascontiguousarray(new.slot_aff[lanes]),
        cand_rows=cand_rows,
        cand_valid=np.ascontiguousarray(new.cand_valid[cand_rows]),
        spot_rows=spot_rows,
        spot_free=np.ascontiguousarray(new.spot_free[spot_rows]),
        spot_count=np.ascontiguousarray(new.spot_count[spot_rows]),
        spot_max_pods=np.ascontiguousarray(new.spot_max_pods[spot_rows]),
        spot_taints=np.ascontiguousarray(new.spot_taints[spot_rows]),
        spot_ok=np.ascontiguousarray(new.spot_ok[spot_rows]),
        spot_aff=np.ascontiguousarray(new.spot_aff[spot_rows]),
    )


def apply_packed_delta(packed: PackedCluster, delta: PackedDelta) -> PackedCluster:
    """Host-side reference application of a delta (the device path in
    ``planner/solver_planner.py`` mirrors this with a donated-buffer
    scatter program; both must agree bit-for-bit with the full pack).
    The planner service's tenant cache applies deltas with its own
    in-place variant (``PlannerService._apply_delta_host`` — the cached
    state is bucket-padded, so the lane slabs scatter at the delta's
    own K into the wider arrays)."""

    def upd(arr, idx, vals):
        out = arr.copy()
        out[idx] = vals
        return out

    return PackedCluster(
        slot_req=upd(packed.slot_req, delta.lanes, delta.lane_slot_req),
        slot_valid=upd(packed.slot_valid, delta.lanes, delta.lane_slot_valid),
        slot_tol=upd(packed.slot_tol, delta.lanes, delta.lane_slot_tol),
        slot_aff=upd(packed.slot_aff, delta.lanes, delta.lane_slot_aff),
        cand_valid=upd(packed.cand_valid, delta.cand_rows, delta.cand_valid),
        spot_free=upd(packed.spot_free, delta.spot_rows, delta.spot_free),
        spot_count=upd(packed.spot_count, delta.spot_rows, delta.spot_count),
        spot_max_pods=upd(
            packed.spot_max_pods, delta.spot_rows, delta.spot_max_pods
        ),
        spot_taints=upd(packed.spot_taints, delta.spot_rows, delta.spot_taints),
        spot_ok=upd(packed.spot_ok, delta.spot_rows, delta.spot_ok),
        spot_aff=upd(packed.spot_aff, delta.spot_rows, delta.spot_aff),
    )


def update_tensor_digest(h, name: str, arr) -> None:
    """Feed one named tensor into a running sha256: field name, shape,
    and little-endian contiguous bytes. THE canonical tensor-hash step
    of the delta wire's anti-entropy protocol — shared by
    :func:`pack_fingerprint` and the wire integrity digest
    (service/wire.delta_digest). Both sides of the protocol must hash
    bit-identically forever; change this in one place only."""
    arr = np.asarray(arr)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    h.update(name.encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())


def pack_fingerprint(packed) -> str:
    """Content fingerprint of a packed tensor set: sha256 over every
    field's shape, dtype and little-endian bytes. The anti-entropy key
    of the delta wire (service/wire.py v4): an agent's delta names the
    fingerprint of the pack it diffs FROM, the service applies it only
    when its cached tenant state carries that exact fingerprint, and
    any disagreement — restart, eviction, a missed tick — degrades to
    one full-pack resync, never a wrong plan. Content-addressed, so
    the check is correct regardless of how either side got there."""
    import hashlib

    h = hashlib.sha256()
    for f in type(packed)._fields:
        update_tensor_digest(h, f, getattr(packed, f))
    return h.hexdigest()


def pad_pow2(n: int) -> int:
    """Pad delta sections to power-of-two lengths so the donated
    scatter programs compile O(log(max churn)) times, not per tick —
    shared by the in-process planner's device cache and the planner
    service's batched tenant scatter."""
    return 8 if n <= 8 else 1 << (n - 1).bit_length()


def pad_packed_delta(
    delta: PackedDelta,
    C: int,
    S: int,
    *,
    lane_rows: int = 0,
    cand_rows: int = 0,
    spot_rows: int = 0,
    K: int = 0,
) -> PackedDelta:
    """Pad each delta section to a power-of-two length (or the given
    explicit row counts — the service pads a whole batch's deltas to
    one shared shape); index pads point one past the axis end and are
    dropped by the ``mode="drop"`` scatters. ``K`` > the slab width
    additionally zero-pads the lane slabs' slot axis — a delta shipped
    at the agent's K scatters into a bucket-padded cached state whose
    pad slot columns are zeros, and zero-padding the slab writes the
    exact same zeros there."""

    def idx(a, oob, rows):
        out = np.full(rows or pad_pow2(len(a)), oob, np.int32)
        out[: len(a)] = a
        return out

    def data(a, rows):
        out = np.zeros(
            (rows or pad_pow2(a.shape[0]),) + a.shape[1:], a.dtype
        )
        out[: a.shape[0]] = a
        return out

    def slab(a, rows):
        out = np.zeros(
            (rows or pad_pow2(a.shape[0]), max(K, a.shape[1]))
            + a.shape[2:],
            a.dtype,
        )
        out[: a.shape[0], : a.shape[1]] = a
        return out

    return PackedDelta(
        lanes=idx(delta.lanes, C, lane_rows),
        lane_slot_req=slab(delta.lane_slot_req, lane_rows),
        lane_slot_valid=slab(delta.lane_slot_valid, lane_rows),
        lane_slot_tol=slab(delta.lane_slot_tol, lane_rows),
        lane_slot_aff=slab(delta.lane_slot_aff, lane_rows),
        cand_rows=idx(delta.cand_rows, C, cand_rows),
        cand_valid=data(delta.cand_valid, cand_rows),
        spot_rows=idx(delta.spot_rows, S, spot_rows),
        spot_free=data(delta.spot_free, spot_rows),
        spot_count=data(delta.spot_count, spot_rows),
        spot_max_pods=data(delta.spot_max_pods, spot_rows),
        spot_taints=data(delta.spot_taints, spot_rows),
        spot_ok=data(delta.spot_ok, spot_rows),
        spot_aff=data(delta.spot_aff, spot_rows),
    )


def empty_packed_delta(packed_or_delta) -> PackedDelta:
    """An all-empty delta at another pack/delta's trailing dims — the
    inert scatter a full-pack tenant rides in a mixed batch (every
    index section pads to out-of-bounds no-ops)."""
    src = packed_or_delta
    if isinstance(src, PackedDelta):
        K, R = src.lane_slot_req.shape[1:3]
        W = src.lane_slot_tol.shape[2]
        A = src.lane_slot_aff.shape[2]
    else:
        _, K, R = src.slot_req.shape
        W = src.spot_taints.shape[1]
        A = src.spot_aff.shape[1]
    return PackedDelta(
        lanes=np.zeros(0, np.int32),
        lane_slot_req=np.zeros((0, K, R), np.float32),
        lane_slot_valid=np.zeros((0, K), bool),
        lane_slot_tol=np.zeros((0, K, W), np.uint32),
        lane_slot_aff=np.zeros((0, K, A), np.uint32),
        cand_rows=np.zeros(0, np.int32),
        cand_valid=np.zeros(0, bool),
        spot_rows=np.zeros(0, np.int32),
        spot_free=np.zeros((0, R), np.float32),
        spot_count=np.zeros(0, np.int32),
        spot_max_pods=np.zeros(0, np.int32),
        spot_taints=np.zeros((0, W), np.uint32),
        spot_ok=np.zeros(0, bool),
        spot_aff=np.zeros((0, A), np.uint32),
    )
