"""k8s_spot_rescheduler_tpu — a TPU-native spot-rescheduling framework.

A from-scratch reimplementation of the capabilities of
``coveord/k8s-spot-rescheduler`` (reference: /root/reference, a pure-Go
Kubernetes controller) with the per-tick drain *plan* reformulated as a
batched, vectorized bin-packing problem solved on TPU via JAX/XLA/Pallas.

Architecture (see SURVEY.md for the reference layer map this mirrors):

- ``utils/``      — config dataclass, k8s quantity parsing, label matching,
                    leveled logging, injectable clocks.
- ``models/``     — the host-side cluster model (PodSpec/NodeSpec/NodeInfo,
                    node-map builder, evictability filter) and the dense
                    tensor packing (``PackedCluster``).
- ``predicates/`` — vectorized scheduler-predicate masks (resource fit,
                    taints/tolerations, readiness) replacing the reference's
                    per-(pod,node) ``PredicateChecker.CheckPredicates`` probe
                    (reference rescheduler.go:344).
- ``solver/``     — the drain-plan solvers: a NumPy oracle faithful to the
                    reference's serial first-fit (rescheduler.go:334-370) and
                    a batched JAX FFD solver (scan over pod slots, vmap over
                    candidate on-demand nodes).
- ``ops/``        — Pallas TPU kernels for the solver hot loop.
- ``parallel/``   — device-mesh sharding of the solver (shard_map over
                    candidate and spot-node axes, XLA collectives).
- ``planner/``    — the ``Planner`` interface: ``plan(state) -> DrainPlan``.
- ``actuator/``   — host-side eviction/drain state machine with retries,
                    timeouts and taint bookkeeping (reference scaler/).
- ``loop/``       — the housekeeping control loop with its gates
                    (reference rescheduler.go:144-293).
- ``io/``         — the ClusterClient boundary: in-memory fake cluster,
                    synthetic cluster generators, interruption replay.
- ``metrics/``    — Prometheus series matching the reference's
                    (metrics/metrics.go) plus solver timing.
- ``cli/``        — process entry point with the reference's flag surface.
"""

__version__ = "0.1.0"

VERSION = __version__
