"""Device-health watchdog: detect the slow-degrading accelerator.

BENCH_r01/r05 were CPU-fallback artifacts of the tunneled chip's "sick
phases" (docs/RESULTS.md) — a real failure mode where the accelerator
neither crashes nor disappears, it just gets slow, and every latency
number and fleet plan it touches silently degrades. Nothing in the
stack detected it: XLA errors are contained (PR 4/8), but a device that
merely *answers slowly* looks healthy to every existing guard.

This module is the detector. The planner service times every batched
device solve on its injected clock and feeds the watchdog:

- a **calibrated baseline**: an EMA over the first ``CALIBRATION_BATCHES``
  solves (and, while healthy, every later solve). Slowness is judged
  RELATIVE to this baseline — a solver that is uniformly slow from boot
  is a slow solver, not a sick device, and never flips the watchdog.
- **sick detection**: ``device_sick_threshold`` CONSECUTIVE batches
  slower than ``SLOW_RATIO x baseline`` (with an absolute floor so a
  zero-ish virtual-clock baseline cannot make noise look sick), OR any
  device-solve exception, OR a canary solve past its timeout, flips the
  watchdog to ``sick``.
- **while sick** the service serves every batch from its numpy-oracle
  host path (the same ``solver/numpy_oracle`` union the CI path runs),
  so a fleet keeps getting *correct* plans at host speed instead of
  poisoned latency — and ``/healthz`` says ``device: "sick"``, the
  ``service_device_sick`` gauge reads 1, and the flight recorder holds a
  ``device-sick`` degradation event, all driven by the same edge.
- **hysteresis-gated recovery**: every ``PROBE_INTERVAL_S`` a batch is
  routed through the device path as a probe; only ``RECOVERY_PROBES``
  consecutive healthy probes flip the watchdog back (a device limping in
  and out of its sick phase must not flap the fleet's solve path).
- a **canary**: while the service is idle (no batches to time), the
  scheduler loop periodically runs a tiny all-invalid solve through the
  device path so a wedging device is noticed before the next real
  request pays for the discovery. A canary that raises or overruns
  ``CANARY_TIMEOUT_S`` is a sick edge like any other. (A canary that
  never *returns* cannot be preempted in-process — that terminal wedge
  surfaces as /healthz batch-cadence age, not here.)

The watchdog is pure bookkeeping over an injected clock: no device
access of its own, fully deterministic under ``FakeClock`` — which is
how ``make fleet-chaos-smoke`` scripts a sick phase and pins the
detection/recovery edges.
"""

from __future__ import annotations

from typing import Optional

from k8s_spot_rescheduler_tpu.utils.clock import Clock


class DeviceHealthWatchdog:
    """Latency-EMA + canary sick-device detector with hysteresis.

    State machine: ``calibrating`` -> ``ok`` <-> ``sick``. Edges are
    returned from the ``note_*`` methods ("sick" / "recovered" / None)
    so the caller (service/server.py) fires the gauge, the flight event
    and the log line from ONE place per edge.
    """

    # a batch counts "slow" past this multiple of the calibrated baseline
    SLOW_RATIO = 4.0
    # absolute slowness floor: protects a near-zero baseline (virtual
    # clocks, sub-ms CPU stubs) from flagging measurement noise — and is
    # itself the slow bar when the baseline is that small
    MIN_SLOW_S = 0.05
    # healthy solves that seed the baseline before slowness is judged
    CALIBRATION_BATCHES = 5
    # EMA weight of the newest healthy sample
    EMA_ALPHA = 0.3
    # consecutive healthy probes required to leave ``sick`` (hysteresis)
    RECOVERY_PROBES = 2
    # minimum spacing of recovery probes while sick
    PROBE_INTERVAL_S = 2.0
    # idle-canary cadence while healthy, and its hard latency budget
    CANARY_INTERVAL_S = 10.0
    CANARY_TIMEOUT_S = 5.0

    def __init__(self, clock: Clock, threshold: int):
        self.clock = clock
        # consecutive slow batches that flip sick (config
        # ``device_sick_threshold``; callers gate construction on > 0)
        self.threshold = max(1, int(threshold))
        self.sick = False
        self.sick_reason = ""
        self.sick_since: Optional[float] = None
        self.sick_total = 0  # lifetime sick transitions
        self.detect_streak = 0  # streak length at the last sick flip
        self._baseline: Optional[float] = None
        self._samples = 0
        self._slow_streak = 0
        self._healthy_probes = 0
        self._last_probe = float("-inf")
        self._last_activity = clock.now()

    # ------------------------------------------------------------------
    # healthy-path accounting

    def _is_slow(self, dur_s: float) -> bool:
        if self._samples < self.CALIBRATION_BATCHES or self._baseline is None:
            return False
        return dur_s > max(self.SLOW_RATIO * self._baseline, self.MIN_SLOW_S)

    def note_batch(self, dur_s: float) -> Optional[str]:
        """One timed healthy-path device solve; returns "sick" on the
        detection edge (the slow result itself is still valid — latency
        is the symptom, not corruption)."""
        self._last_activity = self.clock.now()
        if self.sick:
            return None
        if self._is_slow(dur_s):
            self._slow_streak += 1
            if self._slow_streak >= self.threshold:
                return self._flip_sick(
                    "latency",
                    f"{self._slow_streak} consecutive batches past "
                    f"{self.SLOW_RATIO:g}x the {self._baseline * 1e3:.1f} ms "
                    "baseline",
                )
            return None
        self._slow_streak = 0
        self._samples += 1
        self._baseline = (
            dur_s
            if self._baseline is None
            else (1 - self.EMA_ALPHA) * self._baseline + self.EMA_ALPHA * dur_s
        )
        return None

    def note_error(self, err: BaseException) -> Optional[str]:
        """A device solve raised (XLA error class): immediate sick edge."""
        self._last_activity = self.clock.now()
        if self.sick:
            return None
        return self._flip_sick("solve-error", f"device solve raised: {err}")

    # ------------------------------------------------------------------
    # recovery probes (while sick)

    def should_probe(self) -> bool:
        """While sick: is it time to route one batch through the device
        path as a recovery probe? Stamps the probe clock when it says
        yes — callers must then report via ``note_probe``."""
        if not self.sick:
            return False
        now = self.clock.now()
        if now - self._last_probe < self.PROBE_INTERVAL_S:
            return False
        self._last_probe = now
        return True

    def note_probe(self, dur_s: float, ok: bool) -> Optional[str]:
        """One recovery-probe outcome; returns "recovered" only after
        ``RECOVERY_PROBES`` consecutive healthy probes (hysteresis)."""
        self._last_activity = self.clock.now()
        if not self.sick:
            return None
        if ok and not self._is_slow(dur_s):
            self._healthy_probes += 1
            if self._healthy_probes >= self.RECOVERY_PROBES:
                return self._recover()
        else:
            self._healthy_probes = 0
        return None

    # ------------------------------------------------------------------
    # idle canary (while healthy)

    def should_canary(self) -> bool:
        """While healthy and idle: is the device overdue a tiny canary
        solve? (Sick-state probing is ``should_probe``'s job.)"""
        if self.sick:
            return False
        return (
            self.clock.now() - self._last_activity >= self.CANARY_INTERVAL_S
        )

    def note_canary(self, dur_s: float, ok: bool) -> Optional[str]:
        self._last_activity = self.clock.now()
        if self.sick:
            return None
        if not ok:
            return self._flip_sick("canary-error", "canary solve raised")
        if dur_s > self.CANARY_TIMEOUT_S:
            return self._flip_sick(
                "canary-timeout",
                f"canary solve took {dur_s:.2f}s "
                f"(budget {self.CANARY_TIMEOUT_S:g}s)",
            )
        # a healthy canary is a liveness sample, not a baseline one (its
        # problem shape is not the fleet's)
        return None

    # ------------------------------------------------------------------

    def _flip_sick(self, reason: str, detail: str) -> str:
        self.sick = True
        self.sick_reason = f"{reason}: {detail}"
        self.sick_since = self.clock.now()
        self.sick_total += 1
        self.detect_streak = self._slow_streak
        self._healthy_probes = 0
        self._last_probe = float("-inf")
        return "sick"

    def _recover(self) -> str:
        self.sick = False
        self.sick_reason = ""
        self.sick_since = None
        self._slow_streak = 0
        self._healthy_probes = 0
        return "recovered"

    def snapshot(self) -> dict:
        """The /healthz half: ``device`` plus the numbers an operator
        needs to trust (or distrust) it."""
        state = "sick" if self.sick else (
            "calibrating"
            if self._samples < self.CALIBRATION_BATCHES
            else "ok"
        )
        out = {
            "device": state,
            "device_baseline_ms": (
                None
                if self._baseline is None
                else round(self._baseline * 1e3, 3)
            ),
            "device_slow_streak": self._slow_streak,
            "device_sick_total": self.sick_total,
        }
        if self.sick:
            out["device_sick_reason"] = self.sick_reason
            out["device_sick_age_s"] = round(
                max(0.0, self.clock.now() - (self.sick_since or 0.0)), 3
            )
        return out
