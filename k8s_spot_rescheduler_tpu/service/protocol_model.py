"""Checked protocol model: the wire/breaker/resync automata as data.

Everything the service protocol promises — wire version negotiation,
the delta/fingerprint/RESYNC ladder, the per-endpoint breaker, the
resync-ingest admission class — is declared here twice over:

1. **Declarative tables** (``KINDS``, ``SHED_REASONS``,
   ``BREAKER_TABLE``, ``BREAKER_CONSTANTS``, ``ADMISSION_*``,
   ``LADDER_TABLE``): the protocol surface as plain data, each entry
   bound to a live code site (``"service/agent.py::RemotePlanner.
   _note_failure"``). The proto-tier ``protocol-contract`` pass
   (tools/analysis/proto/contract.py) holds these tables and the
   implementation in lockstep in BOTH directions — a ``KIND_*``
   constant, ``_note_shed`` reason, breaker constant or admission
   counter added to the code without a model entry turns ``make
   verify-protocol`` red, and so does a model entry whose code site
   was deleted. The model cannot drift the way a design doc would.

2. **An executable product automaton** (``build_systems``): N agents x
   M replicas with per-agent request/reply channels (loss and
   retry-after-lost-reply duplication), replica restart events, churn,
   and the admission token bucket + byte ledger, explored EXHAUSTIVELY
   by the proto-tier checker (tools/analysis/proto/model_check.py).
   The checker proves, over every reachable state:

   - safety: no double full-pack admission per (tenant,
     restart-epoch); no delta applied over a mismatched fingerprint;
     admission inflight <= cap; no frame decoded below its minimum
     wire version (version-mix run);
   - liveness: from EVERY reachable state the drained goal state (all
     tenants cached + acked, all breakers closed, channels quiet) is
     reachable, and no non-goal state is terminal — under weak
     fairness on admission releases and breaker-backoff expiry the
     storm therefore drains, and no breaker livelocks against a
     healthy replica.

Deliberately dependency-free: this module imports NOTHING from
``service/wire.py`` / ``service/agent.py`` / ``service/server.py``.
If it did, the contract checks would be vacuously true; because it
does not, every mirrored constant below is a falsifiable claim.

Modeling notes (docs/ANALYSIS.md "Protocol tier"):

- Time is abstracted away: backoff/Retry-After horizons become
  nondeterministic ``expire`` events; the 30 s Retry-After cap and the
  jitter factors are carried as symbolic intervals
  (``RETRY_AFTER_INTERVAL_S``, ``RESYNC_RETRY_DELAY_INTERVAL_S``) and
  contract-checked against the live constants, not explored.
- Channels are request/reply slots (one outstanding request per
  agent, as the real single-threaded-per-agent HTTP RPC guarantees);
  reorder is interleaving across agents, duplication is the real
  form it takes over TCP — an agent retrying after a LOST REPLY
  re-delivers a request the server already processed.
- The byte ledger is modeled in abstract units (``pack_units``); the
  idle floor (a lone over-budget tenant is admitted when the class is
  idle) is exercised by giving one agent a pack larger than the whole
  budget.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

# =====================================================================
# 1. Declarative tables — the contract-checked protocol surface
# =====================================================================

# --- wire versions (service/wire.py) ---------------------------------

VERSIONS = (1, 2, 3, 4)  # == wire.SUPPORTED_VERSIONS
WIRE_VERSION = 4  # == wire.WIRE_VERSION; replies mirror the REQUEST's
#                   version (never the server's newer one)


@dataclasses.dataclass(frozen=True)
class FrameKind:
    """One wire frame kind: its constant value, the minimum version
    whose decoder accepts it (pre-vN frames are REFUSED at decode,
    wire.WireVersionError), and the live encode site."""

    value: int
    min_version: int
    direction: str  # "agent->server" | "server->agent"
    site: str  # "path::qualname" of the encoding function


KINDS = {
    "KIND_PLAN_REQUEST": FrameKind(
        1, 1, "agent->server", "service/wire.py::encode_plan_request"),
    "KIND_PLAN_REPLY": FrameKind(
        2, 1, "server->agent", "service/wire.py::encode_plan_reply"),
    "KIND_PACKED_DELTA": FrameKind(
        3, 4, "agent->server", "service/wire.py::encode_packed_delta"),
    "KIND_ERROR": FrameKind(
        4, 1, "server->agent", "service/wire.py::encode_error"),
    "KIND_PLAN_SCHEDULE": FrameKind(
        5, 3, "server->agent",
        "service/wire.py::encode_plan_schedule_reply"),
    "KIND_RESYNC": FrameKind(
        6, 4, "server->agent", "service/wire.py::encode_resync"),
}

# --- admission-shed reasons (service/server.py _note_shed funnel) ----


@dataclasses.dataclass(frozen=True)
class ShedReason:
    """One labeled 503 reason: the flight-recorder kind it pairs with
    (one site per reason, flight delta == metric delta) and the live
    ``_note_shed`` call site."""

    flight_kind: str  # "service-shed" | "resync-shed"
    site: str


SHED_REASONS = {
    "deadline": ShedReason(
        "service-shed",
        "service/server.py::PlannerService._finish_wait"),
    "queue-timeout": ShedReason(
        "service-shed",
        "service/server.py::PlannerService._finish_wait"),
    "drain-evict": ShedReason(
        "service-shed",
        "service/server.py::PlannerService.drain_pending"),
    "drain-refuse": ShedReason(
        "service-shed",
        "service/server.py::ServiceServer.__init__.Handler._read_body"),
    "max-inflight": ShedReason(
        "service-shed",
        "service/server.py::ServiceServer.__init__.Handler._read_body"),
    "resync-storm": ShedReason(
        "resync-shed",
        "service/server.py::ServiceServer.__init__.Handler._post_wire"),
}

# --- per-endpoint breaker (service/agent.py RemotePlanner) -----------

BREAKER_STATES = ("closed", "open", "half-open")


@dataclasses.dataclass(frozen=True)
class BreakerEdge:
    src: str
    dst: str
    event: str
    site: str


BREAKER_TABLE = (
    BreakerEdge("closed", "closed", "failure-below-threshold",
                "service/agent.py::RemotePlanner._note_failure"),
    BreakerEdge("closed", "open", "failure-at-threshold",
                "service/agent.py::RemotePlanner._note_failure"),
    BreakerEdge("closed", "closed", "success",
                "service/agent.py::RemotePlanner._note_success"),
    BreakerEdge("open", "half-open", "backoff-expired",
                "service/agent.py::RemotePlanner._ladder_call"),
    BreakerEdge("half-open", "closed", "probe-success",
                "service/agent.py::RemotePlanner._note_success"),
    BreakerEdge("half-open", "open", "probe-failure",
                "service/agent.py::RemotePlanner._note_failure"),
)

# Mirrors of RemotePlanner's numeric class constants — every UPPERCASE
# numeric attribute on the class must appear here with this exact
# value, and vice versa (protocol-contract, both directions).
BREAKER_CONSTANTS = {
    "FAIL_THRESHOLD": 2,
    "BACKOFF_BASE": 5.0,
    "BACKOFF_MAX": 120.0,
    "RETRY_AFTER_CAP_S": 30.0,
    "RETRY_JITTER_FRAC": 0.5,
    "RESYNC_JITTER_S": 2.0,
}

# == agent._Endpoint.__slots__ — the whole per-endpoint state the
# breaker/ladder automaton runs on; a new field means a new model
# dimension and must land here first.
ENDPOINT_FIELDS = ("url", "consecutive_failures", "skip_until",
                   "acked_fp")

# Symbolic jitter intervals (NOT explored — time is abstract; the
# contract pins the endpoints to the live constants):
# a 503's suggested horizon is clamped to [0, RETRY_AFTER_CAP_S] then
# scaled by uniform[1, 1 + RETRY_JITTER_FRAC)
RETRY_AFTER_INTERVAL_S = (0.0, 30.0 * (1.0 + 0.5))
# the one full-pack resync retry waits uniform[0, RESYNC_JITTER_S]
# (clamped to half the remaining deadline)
RESYNC_RETRY_DELAY_INTERVAL_S = (0.0, 2.0)

# --- resync-ingest admission (service/server.py ServiceServer) -------

ADMISSION_CAP_ATTR = "resync_ingest_cap"
ADMISSION_LOCK_ATTR = "_resync_lock"
ADMISSION_COUNTERS = (
    "_resync_inflight", "_resync_ledger_bytes", "_resync_pressure",
)
ADMISSION_SITES = {
    "admit": "service/server.py::ServiceServer.admit_resync_ingest",
    "release": "service/server.py::ServiceServer.release_resync_ingest",
}

# --- the delta/fingerprint/RESYNC ladder (events -> live sites) ------


@dataclasses.dataclass(frozen=True)
class LadderEvent:
    event: str
    site: str


LADDER_TABLE = (
    # ship a delta only to an endpoint whose acked_fp matches the base
    LadderEvent("send-delta",
                "service/agent.py::RemotePlanner._ladder_call"),
    LadderEvent("send-full-pack",
                "service/agent.py::RemotePlanner._ladder_call"),
    # server refuses the delta base (uncached / restarted / mismatch)
    LadderEvent("resync-demand",
                "service/server.py::PlannerService.note_resync"),
    # the agent's one jittered full-pack retry on the SAME endpoint
    LadderEvent("full-pack-retry",
                "service/agent.py::RemotePlanner._resync_retry_delay"),
    # success advances the endpoint's acked_fp to the shipped pack
    LadderEvent("ack-fingerprint",
                "service/agent.py::RemotePlanner._ladder_call"),
    # a replica restart is observed as a cache mismatch server-side
    LadderEvent("replica-restart",
                "service/server.py::PlannerService._cache_mismatch_locked"),
    # every endpoint dead/skipped -> the local numpy oracle
    LadderEvent("fallback-local",
                "service/agent.py::RemotePlanner._plan_fallback"),
)


# =====================================================================
# 2. The executable product automaton
# =====================================================================

# agent phase tags
_IDLE = "idle"
_WAIT = "wait"  # request in flight / processing / reply in flight
_RESYNC = "resync"  # RESYNC received; full-pack retry pending

# request kinds in the explored subset
_DELTA = "delta"
_FULL = "full"

# channel stages for a _WAIT phase
_ST_REQ = "req"  # request frame in flight toward the replica
_ST_PROC = "proc"  # admitted resync-class ingest being processed
_ST_PLAN = "plan"  # PLAN_REPLY in flight back
_ST_RESYNC = "rsync"  # KIND_RESYNC demand in flight back
_ST_SHED = "shed"  # typed 503 (resync-storm) in flight back
_ST_LOST = "lost"  # frame dropped; the agent will time out

_CLOSED, _OPEN, _HALF = "closed", "open", "half-open"

_NO_FP = -1  # "no fingerprint": nothing acked / nothing cached


@dataclasses.dataclass(frozen=True)
class ModelBounds:
    """Exploration bounds for one product-automaton run. The defaults
    are the declared proof bounds from ISSUE/docs: >= 2 agents x 2
    replicas with a restart event."""

    name: str = "storm"
    n_agents: int = 2
    n_replicas: int = 2
    # wire version each agent negotiates (replies mirror it)
    versions: Tuple[int, ...] = (4, 4)
    # churn events (pack-fingerprint bumps) available per agent
    churn_budget: Tuple[int, ...] = (1, 0)
    # abstract byte-ledger units per agent's full pack
    pack_units: Tuple[int, ...] = (1, 3)
    loss_budget: int = 1
    restart_budget: int = 1
    # admission class: token bucket + byte ledger (abstract units)
    ingest_cap: int = 2
    ingest_budget_units: int = 2
    pressure_max: int = 1


# The two checked configurations: the resync-storm run (both agents on
# the current wire, churn + restart + loss) and the version-mix run
# (a v4 agent beside a v3 agent; proves no frame is ever decoded below
# its minimum version while the fleet is mixed).
CHECK_BOUNDS = (
    ModelBounds(),
    ModelBounds(
        name="version-mix",
        versions=(4, 3),
        churn_budget=(1, 0),
        pack_units=(1, 1),
        ingest_cap=1,
        ingest_budget_units=2,
    ),
)


def _initial_agent(bounds: ModelBounds) -> tuple:
    eps = tuple((_NO_FP, 0, _CLOSED) for _ in range(bounds.n_replicas))
    return ((_IDLE,), 0, eps)


def _initial_replica(bounds: ModelBounds) -> tuple:
    cached = tuple(_NO_FP for _ in range(bounds.n_agents))
    bits = tuple(0 for _ in range(bounds.n_agents))
    return (0, cached, bits, (), 0)


class ProtocolSystem:
    """One bounded product automaton over the tables above.

    State (all nested tuples, hashable):
      ``(agents, replicas, budgets)``
      agent   = (phase, fp, endpoints)
                phase = ("idle",) | ("wait", r, kind, stage)
                      | ("resync", r)
                endpoints[r] = (acked_fp, failures, breaker_state)
      replica = (epoch, cached_by_agent, fullpack_bits, proc, pressure)
      budgets = (churn_by_agent, loss, restarts)
    """

    def __init__(self, bounds: ModelBounds):
        self.bounds = bounds
        self.name = bounds.name

    # -- construction --------------------------------------------------

    def initial(self) -> tuple:
        b = self.bounds
        agents = tuple(_initial_agent(b) for _ in range(b.n_agents))
        replicas = tuple(
            _initial_replica(b) for _ in range(b.n_replicas)
        )
        budgets = (tuple(b.churn_budget), b.loss_budget,
                   b.restart_budget)
        return (agents, replicas, budgets)

    # -- small pure helpers -------------------------------------------

    @staticmethod
    def _with_agent(state, a, agent):
        agents, replicas, budgets = state
        agents = agents[:a] + (agent,) + agents[a + 1:]
        return (agents, replicas, budgets)

    @staticmethod
    def _with_replica(state, r, replica):
        agents, replicas, budgets = state
        replicas = replicas[:r] + (replica,) + replicas[r + 1:]
        return (agents, replicas, budgets)

    @staticmethod
    def _with_budgets(state, budgets):
        agents, replicas, _ = state
        return (agents, replicas, budgets)

    def _note_failure(self, ep: tuple) -> tuple:
        """BREAKER_TABLE: failure-below-threshold / failure-at-threshold
        / probe-failure."""
        acked, fails, brk = ep
        fails = min(fails + 1, BREAKER_CONSTANTS["FAIL_THRESHOLD"])
        if fails >= BREAKER_CONSTANTS["FAIL_THRESHOLD"]:
            return (acked, fails, _OPEN)
        return (acked, fails, _CLOSED)

    @staticmethod
    def _note_success(ep: tuple, acked_fp: Optional[int]) -> tuple:
        """BREAKER_TABLE: success / probe-success; LADDER_TABLE:
        ack-fingerprint (acked_fp advances only when the reply carried
        a fingerprint — v4)."""
        acked, _, _ = ep
        if acked_fp is not None:
            acked = acked_fp
        return (acked, 0, _CLOSED)

    def _ladder_target(self, eps: tuple) -> Optional[int]:
        """The real ladder walks endpoints in order, skipping open
        breakers; half-open endpoints take a probe."""
        for r, (_, _, brk) in enumerate(eps):
            if brk != _OPEN:
                return r
        return None

    # -- transition relation ------------------------------------------

    def successors(
        self, state: tuple
    ) -> Iterator[Tuple[str, dict, tuple]]:
        """Yield (label, info, next_state). ``info`` feeds the safety
        checks (model_check) and is never part of the state."""
        b = self.bounds
        agents, replicas, budgets = state
        churn, loss, restarts = budgets

        for a, agent in enumerate(agents):
            phase, fp, eps = agent
            version = b.versions[a]

            if phase[0] == _IDLE:
                # churn: the tenant's pack fingerprint advances
                if churn[a] > 0:
                    nb = (
                        churn[:a] + (churn[a] - 1,) + churn[a + 1:],
                        loss, restarts,
                    )
                    yield (
                        f"churn[a{a}]", {},
                        self._with_budgets(
                            self._with_agent(
                                state, a, (phase, fp + 1, eps)
                            ),
                            nb,
                        ),
                    )
                # tick: send through the endpoint ladder
                r = self._ladder_target(eps)
                if r is not None:
                    acked = eps[r][0]
                    if version >= 4 and acked != _NO_FP:
                        kind = _DELTA  # LADDER: send-delta
                    else:
                        kind = _FULL  # LADDER: send-full-pack
                    nphase = (_WAIT, r, kind, _ST_REQ)
                    yield (
                        f"send-{kind}[a{a}->r{r}]",
                        {"event": "send", "agent": a, "version": version,
                         "kind": ("KIND_PACKED_DELTA" if kind == _DELTA
                                  else "KIND_PLAN_REQUEST")},
                        self._with_agent(state, a, (nphase, fp, eps)),
                    )
                continue

            if phase[0] == _RESYNC:
                # LADDER: full-pack-retry on the SAME endpoint, no
                # breaker penalty for the demand itself
                r = phase[1]
                nphase = (_WAIT, r, _FULL, _ST_REQ)
                yield (
                    f"full-pack-retry[a{a}->r{r}]",
                    {"event": "send", "agent": a, "version": version,
                     "kind": "KIND_PLAN_REQUEST"},
                    self._with_agent(state, a, (nphase, fp, eps)),
                )
                continue

            _, r, kind, stage = phase
            replica = replicas[r]
            epoch, cached, bits, proc, pressure = replica

            if stage == _ST_REQ:
                if loss > 0:
                    yield (
                        f"lose-req[a{a}]", {},
                        self._with_budgets(
                            self._with_agent(
                                state, a,
                                ((_WAIT, r, kind, _ST_LOST), fp, eps),
                            ),
                            (churn, loss - 1, restarts),
                        ),
                    )
                yield from self._deliver(state, a, r)

            elif stage == _ST_PROC:
                # admitted resync-class ingest completes: cache seeded,
                # admission charge released, pressure relaxes
                ncached = cached[:a] + (fp,) + cached[a + 1:]
                nproc = tuple(x for x in proc if x != a)
                nrep = (epoch, ncached, bits, nproc,
                        max(0, pressure - 1))
                yield (
                    f"ingest-complete[a{a}@r{r}]",
                    {"event": "reply", "agent": a,
                     "version": self.bounds.versions[a],
                     "kind": "KIND_PLAN_REPLY"},
                    self._with_replica(
                        self._with_agent(
                            state, a,
                            ((_WAIT, r, kind, _ST_PLAN), fp, eps),
                        ),
                        r, nrep,
                    ),
                )

            elif stage in (_ST_PLAN, _ST_RESYNC, _ST_SHED):
                if loss > 0:
                    yield (
                        f"lose-reply[a{a}]", {},
                        self._with_budgets(
                            self._with_agent(
                                state, a,
                                ((_WAIT, r, kind, _ST_LOST), fp, eps),
                            ),
                            (churn, loss - 1, restarts),
                        ),
                    )
                yield from self._receive(state, a, r, stage)

            elif stage == _ST_LOST:
                # the agent's deadline fires: breaker notes a failure
                nep = self._note_failure(eps[r])
                neps = eps[:r] + (nep,) + eps[r + 1:]
                yield (
                    f"timeout[a{a}@r{r}]", {},
                    self._with_agent(state, a, ((_IDLE,), fp, neps)),
                )

        # breaker backoff expiry: open -> half-open (untimed)
        for a, agent in enumerate(agents):
            phase, fp, eps = agent
            for r, ep in enumerate(eps):
                if ep[2] == _OPEN:
                    nep = (ep[0], ep[1], _HALF)
                    neps = eps[:r] + (nep,) + eps[r + 1:]
                    yield (
                        f"backoff-expired[a{a}@r{r}]", {},
                        self._with_agent(state, a, (phase, fp, neps)),
                    )

        # replica restart: warm restart wipes the tenant cache and the
        # admission class; in-flight exchanges with it die
        if restarts > 0:
            for r in range(b.n_replicas):
                epoch = replicas[r][0]
                nrep = (
                    epoch + 1,
                    tuple(_NO_FP for _ in range(b.n_agents)),
                    tuple(0 for _ in range(b.n_agents)),
                    (), 0,
                )
                nstate = self._with_replica(state, r, nrep)
                for a, agent in enumerate(agents):
                    phase, fp, eps = agent
                    if phase[0] == _WAIT and phase[1] == r:
                        nphase = (_WAIT, r, phase[2], _ST_LOST)
                        nstate = self._with_agent(
                            nstate, a, (nphase, fp, eps)
                        )
                nstate = self._with_budgets(
                    nstate, (churn, loss, restarts - 1)
                )
                yield (f"restart[r{r}]", {"event": "restart",
                                          "replica": r}, nstate)

    def _deliver(
        self, state: tuple, a: int, r: int
    ) -> Iterator[Tuple[str, dict, tuple]]:
        """The replica processes agent ``a``'s in-flight request."""
        b = self.bounds
        agents, replicas, _ = state
        phase, fp, eps = agents[a]
        _, _, kind, _ = phase
        epoch, cached, bits, proc, pressure = replicas[r]
        version = b.versions[a]
        acked = eps[r][0]

        if kind == _DELTA:
            # base fingerprint the delta was computed against == the
            # endpoint's acked_fp at send time (unchanged while waiting)
            base = acked
            if cached[a] == base and base != _NO_FP:
                ncached = cached[:a] + (fp,) + cached[a + 1:]
                nrep = (epoch, ncached, bits, proc, pressure)
                yield (
                    f"apply-delta[a{a}@r{r}]",
                    {"event": "apply-delta", "agent": a, "replica": r,
                     "base": base, "cached": cached[a],
                     "version": version, "kind": "KIND_PLAN_REPLY"},
                    self._with_replica(
                        self._with_agent(
                            state, a,
                            ((_WAIT, r, _DELTA, _ST_PLAN), fp, eps),
                        ),
                        r, nrep,
                    ),
                )
            else:
                # LADDER: resync-demand (uncached / restart / mismatch)
                yield (
                    f"resync-demand[a{a}@r{r}]",
                    {"event": "reply", "agent": a, "version": version,
                     "kind": "KIND_RESYNC"},
                    self._with_agent(
                        state, a,
                        ((_WAIT, r, _DELTA, _ST_RESYNC), fp, eps),
                    ),
                )
            return

        # full pack
        if version < 4:
            # unfingerprinted pack: served statelessly, never cached,
            # never admission-gated; the reply mirrors the old version
            yield (
                f"plan-v{version}[a{a}@r{r}]",
                {"event": "reply", "agent": a, "version": version,
                 "kind": "KIND_PLAN_REPLY"},
                self._with_agent(
                    state, a, ((_WAIT, r, _FULL, _ST_PLAN), fp, eps)
                ),
            )
            return

        if cached[a] != _NO_FP:
            # warm tenant re-uploading (e.g. duplicate after a lost
            # reply, or a fingerprint-mismatch retry): idempotent
            # re-cache, NOT a resync-class ingest
            ncached = cached[:a] + (fp,) + cached[a + 1:]
            nrep = (epoch, ncached, bits, proc, pressure)
            yield (
                f"recache[a{a}@r{r}]",
                {"event": "reply", "agent": a, "version": version,
                 "kind": "KIND_PLAN_REPLY"},
                self._with_replica(
                    self._with_agent(
                        state, a,
                        ((_WAIT, r, _FULL, _ST_PLAN), fp, eps),
                    ),
                    r, nrep,
                ),
            )
            return

        # uncached + fingerprinted: the resync-storm admission class
        # (ADMISSION_SITES["admit"])
        ledger = sum(b.pack_units[x] for x in proc)
        per = b.pack_units[a]
        over_cap = len(proc) >= b.ingest_cap
        over_budget = (
            len(proc) > 0 and ledger + per > b.ingest_budget_units
        )  # idle floor: a lone over-budget tenant is admitted
        if over_cap or over_budget:
            yield (
                f"shed-resync[a{a}@r{r}]",
                {"event": "reply", "agent": a, "version": version,
                 "kind": "KIND_ERROR", "shed_reason": "resync-storm"},
                self._with_replica(
                    self._with_agent(
                        state, a,
                        ((_WAIT, r, _FULL, _ST_SHED), fp, eps),
                    ),
                    r,
                    (epoch, cached, bits, proc,
                     min(pressure + 1, b.pressure_max)),
                ),
            )
            return
        nbits = bits[:a] + (1,) + bits[a + 1:]
        nproc = tuple(sorted(proc + (a,)))
        yield (
            f"admit-full-pack[a{a}@r{r}]",
            {"event": "admit-full-pack", "agent": a, "replica": r,
             "epoch": epoch, "bit": bits[a]},
            self._with_replica(
                self._with_agent(
                    state, a, ((_WAIT, r, _FULL, _ST_PROC), fp, eps)
                ),
                r, (epoch, cached, nbits, nproc, pressure),
            ),
        )

    def _receive(
        self, state: tuple, a: int, r: int, stage: str
    ) -> Iterator[Tuple[str, dict, tuple]]:
        """The agent consumes the in-flight reply."""
        agents, _, _ = state
        phase, fp, eps = agents[a]
        version = self.bounds.versions[a]

        if stage == _ST_PLAN:
            # v4 replies ack the shipped pack's fingerprint; pre-v4
            # replies carry none (acked_fp stays empty)
            acked_fp = fp if version >= 4 else None
            nep = self._note_success(eps[r], acked_fp)
            neps = eps[:r] + (nep,) + eps[r + 1:]
            yield (
                f"recv-plan[a{a}]", {},
                self._with_agent(state, a, ((_IDLE,), fp, neps)),
            )
        elif stage == _ST_RESYNC:
            # RESYNC demand: the acked fingerprint is void; retry a
            # full pack on the same endpoint (no breaker penalty)
            nep = (_NO_FP, eps[r][1], eps[r][2])
            neps = eps[:r] + (nep,) + eps[r + 1:]
            yield (
                f"recv-resync[a{a}]", {},
                self._with_agent(state, a, ((_RESYNC, r), fp, neps)),
            )
        else:  # _ST_SHED — typed 503, Retry-After honored via breaker
            nep = self._note_failure(eps[r])
            neps = eps[:r] + (nep,) + eps[r + 1:]
            yield (
                f"recv-shed[a{a}]", {},
                self._with_agent(state, a, ((_IDLE,), fp, neps)),
            )

    # -- properties ----------------------------------------------------

    def check(
        self, state: tuple, label: str, info: dict, nxt: tuple
    ) -> List[str]:
        """Safety violations for one transition (empty when clean).
        Deliberately INDEPENDENT re-derivations — they validate the
        transition relation above, so an edit that breaks the protocol
        tables is caught by exploration, not hidden by shared code."""
        out: List[str] = []
        b = self.bounds
        event = info.get("event", "")

        # (3) admission inflight <= cap in every reachable state
        for r, (_, _, _, proc, _) in enumerate(nxt[1]):
            if len(proc) > b.ingest_cap:
                out.append(
                    "admission-cap: replica r%d holds %d concurrent "
                    "resync ingests (cap %d) after %s"
                    % (r, len(proc), b.ingest_cap, label)
                )

        # (1) no double full-pack admission per (tenant, restart-epoch)
        if event == "admit-full-pack" and info["bit"]:
            out.append(
                "double-full-pack: tenant a%d admitted twice at "
                "replica r%d within restart epoch %d (%s)"
                % (info["agent"], info["replica"], info["epoch"], label)
            )

        # (2) no delta applied over a mismatched fingerprint
        if event == "apply-delta" and (
            info["cached"] != info["base"] or info["base"] == _NO_FP
        ):
            out.append(
                "delta-fingerprint: delta from a%d applied at r%d over "
                "cached fp %s != base fp %s (%s)"
                % (info["agent"], info["replica"], info["cached"],
                   info["base"], label)
            )

        # (4) version-mix never carries a frame the negotiated version
        # forbids (replies mirror the REQUEST version)
        kind = info.get("kind")
        if kind is not None:
            if KINDS[kind].min_version > info["version"]:
                out.append(
                    "version-gate: %s carried to/from a v%d agent "
                    "(min version %d) on %s"
                    % (kind, info["version"], KINDS[kind].min_version,
                       label)
                )
        return out

    def is_goal(self, state: tuple) -> bool:
        """The drained state: everyone idle, no breaker stuck open,
        and every tenant served through a closed-breaker endpoint —
        current-wire tenants cached + acked there. A HALF-OPEN breaker
        on an unused backup endpoint is part of the drained steady
        state (the ladder rightly never probes past a healthy
        primary); an OPEN one is not, but can always expire, so goal
        reachability proves no breaker livelocks against a healthy
        replica."""
        agents, replicas, _ = state
        for a, (phase, fp, eps) in enumerate(agents):
            if phase[0] != _IDLE:
                return False
            if any(brk == _OPEN for _, _, brk in eps):
                return False
            if self.bounds.versions[a] >= 4:
                if not any(
                    eps[r][0] == fp and replicas[r][1][a] == fp
                    and eps[r][2] == _CLOSED
                    for r in range(self.bounds.n_replicas)
                ):
                    return False
            else:
                if not any(brk == _CLOSED for _, _, brk in eps):
                    return False
        return True


def build_systems() -> List[ProtocolSystem]:
    """The product automata ``make verify-protocol`` explores."""
    return [ProtocolSystem(bounds) for bounds in CHECK_BOUNDS]
