"""The per-cluster agent: a Planner whose solver lives across the wire.

``RemotePlanner`` implements the same ``Planner`` surface the control
loop already speaks (plan / plan_async), so the agent topology changes
NOTHING above the planner boundary: observe, pack and actuate stay
local and chaos-hardened (PR 4's retrying kube reads, crash containment,
orphan-taint recovery all apply unchanged). What moves is only the
solve: the locally-packed ``PackedCluster`` ships to the shared planner
service (service/server.py) over the binary wire protocol
(service/wire.py), and the tiny selection vector comes back — the same
few-hundred-byte boundary the in-process device fetch uses, so a fleet
of agents costs the service O(tenants x packed bytes) ingress and
near-zero egress.

Degradation is the agent's job, not the loop's: a service that is
unreachable, times out, overloads (503) or answers out of protocol
degrades THIS tick to the local numpy-oracle fallback planner — the
same containment the loop applies to a crashing in-process planner —
counted in ``remote_planner_fallback_total``. Repeated failures open a
circuit breaker that skips the service entirely for a doubling backoff
window (bounded), so a dead service costs each tick one fallback solve,
not one connect timeout; the first healthy reply closes the breaker.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

import numpy as np

from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import PDBSpec
from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster
from k8s_spot_rescheduler_tpu.planner.base import PlanReport
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


class RemotePlanner:
    """Planner over a remote multi-tenant planner service."""

    accepts_columnar = True

    # breaker: consecutive failures before the service is skipped, and
    # the doubling skip window (seconds) that failure cadence buys
    FAIL_THRESHOLD = 2
    BACKOFF_BASE = 5.0
    BACKOFF_MAX = 120.0

    def __init__(
        self,
        config: ReschedulerConfig,
        url: str = "",
        *,
        tenant: Optional[str] = None,
        timeout: Optional[float] = None,
    ):
        self.config = config
        self.url = (url or config.planner_url).rstrip("/")
        if not self.url:
            raise ValueError("RemotePlanner needs a planner service url")
        import socket

        self.tenant = tenant or socket.gethostname()
        self.timeout = float(
            timeout if timeout is not None else config.planner_timeout
        )
        self._pad_c = 0
        self._pad_s = 0
        self._pad_k = config.max_pods_per_node_hint
        self._fallback = None  # lazy local numpy-oracle planner
        self._consecutive_failures = 0
        self._skip_until = 0.0  # monotonic; breaker-open horizon
        self.last_solver = "remote"
        # the trace the last plan recorded into: the controller's tick
        # trace when one is ambient, else a standalone trace (direct
        # callers like bench.serve_smoke read the grafted span tree off
        # this); None with tracing disabled
        self.last_trace = None

    # ------------------------------------------------------------------

    def _fallback_planner(self):
        if self._fallback is None:
            from k8s_spot_rescheduler_tpu.planner.solver_planner import (
                SolverPlanner,
            )

            self._fallback = SolverPlanner(
                dataclasses.replace(
                    self.config, solver="numpy", planner_url=""
                )
            )
        return self._fallback

    def _note_failure(self, why: str, retry_after: float = 0.0) -> None:
        self._consecutive_failures += 1
        if self._consecutive_failures >= self.FAIL_THRESHOLD:
            n = self._consecutive_failures - self.FAIL_THRESHOLD
            backoff = min(
                self.BACKOFF_BASE * (2.0 ** n), self.BACKOFF_MAX
            )
            backoff = max(backoff, retry_after)
            self._skip_until = time.monotonic() + backoff
            log.error(
                "planner service unusable (%s; %d consecutive failures); "
                "skipping it for %.1fs — local fallback plans until then",
                why, self._consecutive_failures, backoff,
            )
        elif retry_after > 0:
            # a single 503 already names its horizon: honor it without
            # waiting for the threshold
            self._skip_until = time.monotonic() + retry_after
            log.warning(
                "planner service overloaded (%s); retrying after %.1fs",
                why, retry_after,
            )
        else:
            log.warning("planner service call failed: %s", why)

    def _note_success(self) -> None:
        if self._consecutive_failures:
            log.info(
                "planner service healthy again after %d failed call(s)",
                self._consecutive_failures,
            )
        self._consecutive_failures = 0
        self._skip_until = 0.0

    def _post(self, body: bytes, trace_id: str = "") -> wire.PlanReply:
        headers = {
            "Content-Type": "application/octet-stream",
            # declare our own deadline so the service evicts (and
            # frees the slot of) a request we will have abandoned
            "X-Planner-Deadline": f"{self.timeout:.3f}",
        }
        if trace_id:
            # belt to the wire frame: proxies/logs see the correlation
            # id even when the binary body is opaque to them
            headers["X-Trace-Id"] = trace_id
        req = urllib.request.Request(
            f"{self.url}/v2/plan",
            data=body,
            headers=headers,
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return wire.decode_plan_reply(resp.read())
        except urllib.error.HTTPError as err:
            retry_after = 0.0
            if err.code == 503:
                try:
                    retry_after = float(err.headers.get("Retry-After", 0))
                except (TypeError, ValueError):
                    retry_after = 0.0
            detail = ""
            try:
                wire.decode_plan_reply(err.read())
            except wire.WireError as werr:
                detail = str(werr)
            raise _RemoteError(
                f"HTTP {err.code}{': ' + detail if detail else ''}",
                retry_after,
            ) from err

    # ------------------------------------------------------------------
    # Planner surface

    def plan(self, observation, pdbs: Sequence[PDBSpec]) -> PlanReport:
        return self.plan_async(observation, pdbs)()

    def plan_async(self, observation, pdbs: Sequence[PDBSpec]):
        """Pack locally, dispatch the service call on a worker thread
        (the loop's metrics pass overlaps the network round trip exactly
        as it overlaps the in-process device solve), and return the
        blocking ``finish`` callable.

        Tracing: the pack and the wire round trip record into the
        controller's ambient tick trace (or a standalone trace for
        direct callers); the tick's trace ID ships with the request
        (wire v2 frame + ``X-Trace-Id``) and the server's own spans come
        back in the reply and are grafted under ``wire.request`` — one
        tree separates queue, solve and wire time per tick. The worker
        thread only stores raw timestamps; all trace mutation happens on
        the caller's thread at ``finish`` (traces are single-threaded)."""
        t0 = time.perf_counter()
        cfg = self.config
        trace = tracing.current_trace()
        if trace is None and cfg.trace_enabled:
            trace = tracing.Trace()
        self.last_trace = trace

        def _sp(name, **attrs):
            return (
                trace.span(name, **attrs)
                if trace is not None
                else contextlib.nullcontext()
            )

        with _sp("plan.pack"):
            if hasattr(observation, "pack"):  # ColumnarStore
                packed, meta = observation.pack(
                    pdbs,
                    priority_threshold=cfg.priority_threshold,
                    delete_non_replicated=cfg.delete_non_replicated_pods,
                    pad_candidates=self._pad_c,
                    pad_spot=self._pad_s,
                    pad_slots=self._pad_k,
                )
            else:
                packed, meta = pack_cluster(
                    observation,
                    pdbs,
                    resources=cfg.resources,
                    delete_non_replicated=cfg.delete_non_replicated_pods,
                    pad_candidates=self._pad_c,
                    pad_spot=self._pad_s,
                    pad_slots=self._pad_k,
                )
        # high-water pads: stable shapes keep the whole fleet in few
        # service-side buckets (and the service in few compiles)
        self._pad_c = max(self._pad_c, packed.slot_req.shape[0])
        self._pad_k = max(self._pad_k, packed.slot_req.shape[1])
        self._pad_s = max(self._pad_s, packed.spot_free.shape[0])
        self.last_packed = packed

        for blocked in meta.blocking_pods():
            log.info("BlockingPod: %s (%s)", blocked.pod.uid, blocked.reason)

        breaker_open = time.monotonic() < self._skip_until
        box: dict = {}
        worker: Optional[threading.Thread] = None
        if not breaker_open:
            trace_id = trace.trace_id if trace is not None else ""
            body = wire.encode_plan_request(
                self.tenant, packed, trace_id=trace_id
            )

            def call():
                box["t_send"] = time.perf_counter()
                try:
                    box["reply"] = self._post(body, trace_id=trace_id)
                except _RemoteError as err:
                    box["error"] = err
                except Exception as err:  # noqa: BLE001 — transport/proto
                    box["error"] = _RemoteError(str(err), 0.0)
                finally:
                    box["t_recv"] = time.perf_counter()

            worker = threading.Thread(target=call, daemon=True)
            worker.start()

        def finish() -> PlanReport:
            if worker is not None:
                worker.join()
            reply = box.get("reply")
            if reply is None:
                err = box.get("error")
                if err is not None:
                    self._note_failure(str(err), err.retry_after)
                return self._plan_fallback(
                    observation, pdbs,
                    cause=str(box.get("error", "breaker open")),
                )
            self._note_success()
            self.last_solver = "remote"
            if trace is not None:
                # graft the server's span block under the measured round
                # trip; the residual (rtt minus server-side work) is the
                # wire itself — tunnel, TLS, serialization on the path
                rtt_ms = max(
                    0.0, (box["t_recv"] - box["t_send"]) * 1e3
                )
                server_ms = sum(d for _, _, d in reply.spans)
                trace.graft(
                    tracing.make_span("wire.request", 0.0, rtt_ms),
                    children=reply.spans,
                    attrs={
                        "batch_lanes": reply.batch_lanes,
                        "batch_tenants": reply.batch_tenants,
                    },
                )
                trace.graft(
                    tracing.make_span(
                        "wire.transfer", 0.0,
                        max(0.0, rtt_ms - server_ms),
                    )
                )
            plan = None
            if reply.found and reply.index < meta.n_candidates:
                plan = meta.build_plan(
                    reply.index, np.asarray(reply.row)
                )
            return PlanReport(
                plan=plan,
                n_candidates=meta.n_candidates,
                n_feasible=reply.n_feasible,
                solve_seconds=time.perf_counter() - t0,
                solver="remote",
                feasible_candidates=[plan] if plan else [],
            )

        return finish

    def _plan_fallback(self, observation, pdbs, cause: str = "") -> PlanReport:
        """This tick plans locally (numpy oracle) — the service is down,
        slow, overloaded or out of protocol. Counted (metric + flight
        event, same site); the loop keeps running at full fidelity minus
        device speed."""
        metrics.update_remote_planner_fallback()
        flight.note_event(
            "remote-planner-fallback",
            cause=cause or "planner service unusable",
            trace_id=tracing.current_trace_id() or (
                self.last_trace.trace_id if self.last_trace else ""
            ),
        )
        report = self._fallback_planner().plan(observation, pdbs)
        self.last_solver = "remote-fallback"
        return dataclasses.replace(report, solver="remote-fallback")


class _RemoteError(Exception):
    def __init__(self, message: str, retry_after: float):
        super().__init__(message)
        self.retry_after = float(retry_after)
