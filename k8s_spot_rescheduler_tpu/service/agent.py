"""The per-cluster agent: a Planner whose solver lives across the wire.

``RemotePlanner`` implements the same ``Planner`` surface the control
loop already speaks (plan / plan_async), so the agent topology changes
NOTHING above the planner boundary: observe, pack and actuate stay
local and chaos-hardened (PR 4's retrying kube reads, crash containment,
orphan-taint recovery all apply unchanged). What moves is only the
solve: the locally-packed ``PackedCluster`` ships to the shared planner
service (service/server.py) over the binary wire protocol
(service/wire.py), and the tiny selection vector comes back — the same
few-hundred-byte boundary the in-process device fetch uses, so a fleet
of agents costs the service O(tenants x packed bytes) ingress and
near-zero egress.

Degradation is the agent's job, not the loop's, and it is a LADDER, not
a cliff:

1. **failover** — the agent accepts an ordered list of planner
   endpoints (``planner_urls`` / a comma list in ``planner_url``). Each
   endpoint carries its OWN consecutive-failure breaker; a tick walks
   the list in order, skipping breaker-open endpoints and failing over
   past an endpoint that resets, times out, 5xxs, or answers out of
   protocol. A reply from any endpoint is a full-fidelity remote plan —
   a dead primary replica costs the fleet one connect failure per
   breaker window, not a fallback. Served-after-failover ticks are
   counted (``remote_planner_failover_total``) and evented (flight kind
   ``failover``), both from the same site.
2. **local fallback** — only when EVERY endpoint is dead or breaker-open
   does the tick degrade to the in-process numpy-oracle fallback planner
   (``remote_planner_fallback_total``, flight ``remote-planner-fallback``)
   — the same containment the loop applies to a crashing in-process
   planner. The first healthy reply closes that endpoint's breaker.

A 503's ``Retry-After`` is honored below the breaker threshold as the
skip window; at/above the threshold the skip window is
``max(doubling backoff, Retry-After)`` with the server-suggested value
capped at ``RETRY_AFTER_CAP_S`` — one bad LB header must not park an
agent on its fallback for hours (the same 30 s cap the kube read path
applies, docs/ROBUSTNESS.md).

The transport is a seam (``self.transport``): ``service/chaos.py``
wraps it to inject wire faults in ``make fleet-chaos-smoke``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

import numpy as np

from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import PDBSpec
from k8s_spot_rescheduler_tpu.planner.base import PlanReport, pack_observation
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


class RemoteCallError(Exception):
    """A planner-service call failed at the HTTP layer (typed so the
    503 Retry-After can ride along to the breaker)."""

    def __init__(self, message: str, retry_after: float):
        super().__init__(message)
        self.retry_after = float(retry_after)


# historical name (pre-failover); tests and chaos wrappers may hold it
_RemoteError = RemoteCallError


class _Endpoint:
    """Per-endpoint breaker state: failures at replica A must not make
    the agent skip replica B. ``acked_fp`` is the fingerprint of the
    last pack THIS endpoint acknowledged (full upload or applied
    delta) — the delta wire ships churn only to an endpoint whose
    acknowledged state IS the delta's base, so a failover target (or a
    repointed url) gets a full pack by construction, without waiting
    for the server's resync demand."""

    __slots__ = ("url", "consecutive_failures", "skip_until", "acked_fp")

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.consecutive_failures = 0
        self.skip_until = 0.0  # on the agent's clock (monotonic)
        self.acked_fp = ""  # last pack fingerprint this replica holds


# public name: the fleet twin (service/twin.py) reuses the per-endpoint
# breaker state object rather than growing a parallel one
Endpoint = _Endpoint


class RemotePlanner:
    """Planner over a remote multi-tenant planner service (or an
    ordered failover list of its replicas)."""

    accepts_columnar = True

    # breaker: consecutive failures before an endpoint is skipped, and
    # the doubling skip window (seconds) that failure cadence buys
    FAIL_THRESHOLD = 2
    BACKOFF_BASE = 5.0
    BACKOFF_MAX = 120.0
    # cap on the SERVER-suggested Retry-After contribution to the skip
    # window (a misconfigured LB header must not stall failback for
    # hours; outages past this belong to the doubling backoff)
    RETRY_AFTER_CAP_S = 30.0

    def __init__(
        self,
        config: ReschedulerConfig,
        url: str = "",
        *,
        tenant: Optional[str] = None,
        timeout: Optional[float] = None,
        clock: Optional[Clock] = None,
    ):
        self.config = config
        raw = url or config.planner_urls or config.planner_url
        self._endpoints: List[_Endpoint] = [
            _Endpoint(u.strip()) for u in raw.split(",") if u.strip()
        ]
        if not self._endpoints:
            raise ValueError("RemotePlanner needs a planner service url")
        import socket

        self.tenant = tenant or socket.gethostname()
        self.timeout = float(
            timeout if timeout is not None else config.planner_timeout
        )
        self.clock = clock or RealClock()
        # seam: (url, body, headers, timeout) -> reply bytes; raises
        # RemoteCallError for HTTP errors. service/chaos.py wraps it.
        self.transport = self._transport_urllib
        if config.service_chaos_profile not in ("", "off", "none"):
            from k8s_spot_rescheduler_tpu.service.chaos import (
                ChaosAgentTransport,
                ServiceFaultPlan,
            )

            log.info(
                "CHAOS: service-path fault injection on the agent "
                "transport (profile=%s seed=%d) — testing mode",
                config.service_chaos_profile, config.service_chaos_seed,
            )
            self.transport = ChaosAgentTransport(
                self.transport,
                ServiceFaultPlan.profile(
                    config.service_chaos_profile,
                    config.service_chaos_seed,
                ),
                clock=self.clock,
            )
        self._pad_c = 0
        self._pad_s = 0
        self._pad_k = config.max_pods_per_node_hint
        self._fallback = None  # lazy local numpy-oracle planner
        # delta wire (v4): the previous tick's pack + its fingerprint —
        # what this tick's churn delta is diffed against (the agent's
        # half of the anti-entropy pair; the service holds the other)
        self._prev_packed = None
        self._prev_fp = ""
        self.last_solver = "remote"
        self.last_endpoint = ""
        # the trace the last plan recorded into: the controller's tick
        # trace when one is ambient, else a standalone trace (direct
        # callers like bench.serve_smoke read the grafted span tree off
        # this); None with tracing disabled
        self.last_trace = None

    # ------------------------------------------------------------------
    # single-endpoint compatibility surface (tests, serve_smoke)

    @property
    def url(self) -> str:
        return self._endpoints[0].url

    @url.setter
    def url(self, value: str) -> None:
        # repointing resets that endpoint's breaker (a NEW replica owes
        # nothing to the old one's failure streak)
        self._endpoints[0] = _Endpoint(value)

    @property
    def urls(self) -> List[str]:
        return [ep.url for ep in self._endpoints]

    @property
    def _consecutive_failures(self) -> int:
        return self._endpoints[0].consecutive_failures

    @property
    def _skip_until(self) -> float:
        return self._endpoints[0].skip_until

    @_skip_until.setter
    def _skip_until(self, value: float) -> None:
        self._endpoints[0].skip_until = float(value)

    # ------------------------------------------------------------------

    def _fallback_planner(self):
        if self._fallback is None:
            from k8s_spot_rescheduler_tpu.planner.solver_planner import (
                SolverPlanner,
            )

            self._fallback = SolverPlanner(
                dataclasses.replace(
                    self.config, solver="numpy",
                    planner_url="", planner_urls="",
                )
            )
        return self._fallback

    def _note_failure(
        self, ep: _Endpoint, why: str, retry_after: float = 0.0
    ) -> None:
        ep.consecutive_failures += 1
        # one bad LB header must not stall failback for hours: the
        # server-suggested horizon is capped wherever it feeds the skip
        # window (regression-tested; docs/ROBUSTNESS.md)
        suggested = min(max(retry_after, 0.0), self.RETRY_AFTER_CAP_S)
        if ep.consecutive_failures >= self.FAIL_THRESHOLD:
            n = ep.consecutive_failures - self.FAIL_THRESHOLD
            backoff = min(
                self.BACKOFF_BASE * (2.0 ** n), self.BACKOFF_MAX
            )
            # a LONGER server-suggested Retry-After beats the schedule
            # (the server knows its queue) — capped above
            backoff = max(backoff, suggested)
            ep.skip_until = self.clock.now() + backoff
            log.error(
                "planner endpoint %s unusable (%s; %d consecutive "
                "failures); skipping it for %.1fs",
                ep.url, why, ep.consecutive_failures, backoff,
            )
        elif suggested > 0:
            # a single 503 already names its horizon: honor it without
            # waiting for the threshold
            ep.skip_until = self.clock.now() + suggested
            log.warning(
                "planner endpoint %s overloaded (%s); retrying after %.1fs",
                ep.url, why, suggested,
            )
        else:
            log.warning(
                "planner endpoint %s call failed: %s", ep.url, why
            )

    def _note_success(self, ep: _Endpoint) -> None:
        if ep.consecutive_failures:
            log.info(
                "planner endpoint %s healthy again after %d failed call(s)",
                ep.url, ep.consecutive_failures,
            )
        ep.consecutive_failures = 0
        ep.skip_until = 0.0

    def _transport_urllib(
        self, url: str, body: bytes, headers: dict, timeout: float
    ) -> bytes:
        """The default transport: one POST, reply bytes back.
        HTTP error statuses become :class:`RemoteCallError` carrying any
        503 Retry-After; everything else propagates as-is."""
        req = urllib.request.Request(
            url, data=body, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as err:
            retry_after = 0.0
            if err.code == 503:
                try:
                    retry_after = float(err.headers.get("Retry-After", 0))
                except (TypeError, ValueError):
                    retry_after = 0.0
            detail = ""
            try:
                wire.decode_plan_reply(err.read())
            except wire.WireError as werr:
                detail = str(werr)
            raise RemoteCallError(
                f"HTTP {err.code}{': ' + detail if detail else ''}",
                retry_after,
            ) from err

    def _pack_observation(self, observation, pdbs):
        """The shared pack path (planner/base.pack_observation) with
        the agent's high-water pads — stable shapes keep the whole
        fleet in few service-side buckets; shared by plan_async,
        plan_schedule, and the drain-schedule execution handle."""
        return pack_observation(self, observation, pdbs)

    def _ladder_call(self, path: str, body: bytes, headers: dict,
                     decode, box: dict, delta_body: bytes = None,
                     base_fp: str = "", new_fp: str = "") -> None:
        """Walk the ordered endpoint list under ONE deadline budget:
        the tick's documented planner_timeout bounds the whole call,
        not each endpoint — three blackholed replicas must not stall
        the loop 3x the deadline. Fills ``box`` with the decoded reply
        + serving endpoint (or just the attempts on total failure).

        Delta wire: with ``delta_body`` given, an endpoint whose
        acknowledged fingerprint equals ``base_fp`` is sent the churn
        delta instead of the full pack; a KIND_RESYNC answer retries
        the full pack on the SAME endpoint within the same budget (a
        resync is protocol, not a failure — no breaker, no failover).
        A serving endpoint's ``acked_fp`` advances to ``new_fp``, so
        failover targets get a full pack by construction."""
        box["t_send"] = time.perf_counter()
        deadline = box["t_send"] + self.timeout
        skipped = 0
        for ep in self._endpoints:
            if self.clock.now() < ep.skip_until:
                # counts toward failover only if it precedes the
                # endpoint that eventually serves
                skipped += 1
                continue
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                box["attempts"].append((
                    ep.url,
                    "plan deadline exhausted before this "
                    "endpoint was tried",
                    0.0,
                ))
                # not an endpoint failure: its breaker is
                # untouched — we simply ran out of budget
                continue
            use_delta = delta_body is not None and ep.acked_fp == base_fp
            t_ep = time.perf_counter()
            try:
                raw = self.transport(
                    f"{ep.url}{path}",
                    delta_body if use_delta else body,
                    headers,
                    max(0.05, remaining),
                )
                reply = (
                    wire.decode_plan_or_resync(raw)
                    if use_delta
                    else decode(raw)
                )
                if isinstance(reply, wire.ResyncDemand):
                    # the service cannot honor the delta's base
                    # (restart, eviction, mismatch, corruption): one
                    # full pack to the SAME endpoint, same budget
                    box["resyncs"] = box.get("resyncs", 0) + 1
                    log.info(
                        "planner endpoint %s demanded a full-pack "
                        "resync: %s", ep.url, reply.cause,
                    )
                    remaining = deadline - time.perf_counter()
                    raw = self.transport(
                        f"{ep.url}{path}", body, headers,
                        max(0.05, remaining),
                    )
                    reply = decode(raw)
            except RemoteCallError as err:
                self._note_failure(ep, str(err), err.retry_after)
                box["attempts"].append((
                    ep.url, str(err),
                    (time.perf_counter() - t_ep) * 1e3,
                ))
                continue
            except Exception as err:  # noqa: BLE001, exception-discipline — transport/protocol failure of ONE endpoint: recorded as a failover attempt and the ladder continues; the terminal all-dead case is counted+evented by the caller
                self._note_failure(ep, str(err), 0.0)
                box["attempts"].append((
                    ep.url, str(err),
                    (time.perf_counter() - t_ep) * 1e3,
                ))
                continue
            self._note_success(ep)
            if new_fp:
                # this replica now holds exactly the new pack (full
                # upload, or delta applied over an acknowledged base)
                ep.acked_fp = new_fp
            box["reply"] = reply
            box["endpoint"] = ep.url
            box["skipped_before"] = skipped
            break
        box["t_recv"] = time.perf_counter()

    def _note_wire_outcome(self, trace, box, spans, attrs=None) -> None:
        """The shared post-ladder accounting: graft each FAILED
        endpoint attempt, fire the failover metric + flight event when
        the serving endpoint was not first choice (same site, so the
        two surfaces always agree), and graft the server's span block
        under the measured round trip."""
        attempts = box["attempts"]
        if trace is not None:
            for ep_url, why, dur_ms in attempts:
                trace.graft(
                    tracing.make_span("wire.failover", 0.0, dur_ms),
                    attrs={"endpoint": ep_url, "error": True},
                )
        if box.get("reply") is None:
            return
        skipped_before = box.get("skipped_before", 0)
        if attempts or skipped_before:
            # served, but only after at least one EARLIER endpoint
            # failed or was breaker-open: a failover tick. (A
            # breaker-open endpoint LATER in the list is irrelevant —
            # the primary serving is healthy.)
            metrics.update_remote_planner_failover()
            flight.note_event(
                "failover",
                cause=(
                    f"{len(attempts)} endpoint(s) failed, "
                    f"{skipped_before} breaker-open; served by "
                    f"{box.get('endpoint', '?')}"
                ),
                trace_id=(
                    trace.trace_id if trace is not None else ""
                ),
                endpoints_tried=len(attempts) + skipped_before + 1,
            )
        if trace is not None:
            if box.get("resyncs"):
                # surface a served-after-resync tick on the trace tree
                attrs = dict(attrs or {})
                attrs["delta_resyncs"] = box["resyncs"]
            # graft the server's span block under the measured round
            # trip; the residual (rtt minus server-side work) is the
            # wire itself — tunnel, TLS, serialization on the path
            rtt_ms = max(0.0, (box["t_recv"] - box["t_send"]) * 1e3)
            server_ms = sum(d for _, _, d in spans)
            trace.graft(
                tracing.make_span("wire.request", 0.0, rtt_ms),
                children=spans,
                attrs=attrs,
            )
            trace.graft(
                tracing.make_span(
                    "wire.transfer", 0.0, max(0.0, rtt_ms - server_ms)
                )
            )

    # ------------------------------------------------------------------
    # Planner surface

    def plan(self, observation, pdbs: Sequence[PDBSpec]) -> PlanReport:
        return self.plan_async(observation, pdbs)()

    def plan_async(self, observation, pdbs: Sequence[PDBSpec]):
        """Pack locally, walk the endpoint ladder on a worker thread
        (the loop's metrics pass overlaps the network round trips
        exactly as it overlaps the in-process device solve), and return
        the blocking ``finish`` callable.

        Tracing: the pack and the wire round trip record into the
        controller's ambient tick trace (or a standalone trace for
        direct callers); the tick's trace ID ships with the request
        (wire v2 frame + ``X-Trace-Id``) and the serving endpoint's
        spans come back in the reply and are grafted under
        ``wire.request``; each FAILED endpoint attempt grafts a
        ``wire.failover`` span. The worker thread only stores raw
        timestamps and outcomes; all trace mutation happens on the
        caller's thread at ``finish`` (traces are single-threaded)."""
        t0 = time.perf_counter()
        cfg = self.config
        trace = tracing.current_trace()
        if trace is None and cfg.trace_enabled:
            trace = tracing.Trace()
        self.last_trace = trace

        def _sp(name, **attrs):
            return (
                trace.span(name, **attrs)
                if trace is not None
                else contextlib.nullcontext()
            )

        with _sp("plan.pack"):
            packed, meta = self._pack_observation(observation, pdbs)

        for blocked in meta.blocking_pods():
            log.info("BlockingPod: %s (%s)", blocked.pod.uid, blocked.reason)

        live = [
            ep for ep in self._endpoints
            if self.clock.now() >= ep.skip_until
        ]
        box: dict = {"attempts": [], "skipped_before": 0}
        worker: Optional[threading.Thread] = None
        # delta wire (v4): fingerprint this pack, diff it against the
        # previous tick's, and remember it as the next tick's base —
        # regardless of how THIS tick ends (fallback included), since
        # the per-endpoint acked fingerprints are what gate shipping
        fp = ""
        delta = None
        base_fp = ""
        if cfg.delta_wire_enabled:
            from k8s_spot_rescheduler_tpu.models.columnar import (
                emit_packed_delta,
                pack_fingerprint,
            )

            with _sp("plan.fingerprint"):
                fp = pack_fingerprint(packed)
            if self._prev_packed is not None:
                with _sp("plan.delta-emit"):
                    # None on shape growth past the high-water pads:
                    # this tick ships the full pack (and re-seeds)
                    delta = emit_packed_delta(self._prev_packed, packed)
                base_fp = self._prev_fp
            self._prev_packed = packed
            self._prev_fp = fp
        if live:
            trace_id = trace.trace_id if trace is not None else ""
            body = wire.encode_plan_request(
                self.tenant, packed, trace_id=trace_id,
                pack_fingerprint=fp,
            )
            delta_body = None
            if delta is not None and any(
                ep.acked_fp == base_fp for ep in live
            ):
                delta_body = wire.encode_packed_delta(
                    self.tenant, delta,
                    base_fingerprint=base_fp, new_fingerprint=fp,
                    trace_id=trace_id,
                )
            headers = {
                "Content-Type": "application/octet-stream",
                # declare our own deadline so the service evicts (and
                # frees the slot of) a request we will have abandoned
                "X-Planner-Deadline": f"{self.timeout:.3f}",
            }
            if trace_id:
                # belt to the wire frame: proxies/logs see the
                # correlation id even when the binary body is opaque
                headers["X-Trace-Id"] = trace_id

            def call():
                self._ladder_call(
                    "/v2/plan", body, headers, wire.decode_plan_reply,
                    box, delta_body=delta_body, base_fp=base_fp,
                    new_fp=fp,
                )

            worker = threading.Thread(target=call, daemon=True)
            worker.start()

        def finish() -> PlanReport:
            if worker is not None:
                worker.join()
            reply = box.get("reply")
            if reply is None:
                self._note_wire_outcome(trace, box, ())
                causes = "; ".join(why for _, why, _ in box["attempts"])
                return self._plan_fallback(
                    observation, pdbs,
                    cause=causes or "breaker open on every endpoint",
                )
            self.last_solver = "remote"
            self.last_endpoint = box.get("endpoint", "")
            self._note_wire_outcome(
                trace, box, reply.spans,
                attrs={
                    "batch_lanes": reply.batch_lanes,
                    "batch_tenants": reply.batch_tenants,
                },
            )
            plan = None
            if reply.found and reply.index < meta.n_candidates:
                plan = meta.build_plan(
                    reply.index, np.asarray(reply.row)
                )
            return PlanReport(
                plan=plan,
                n_candidates=meta.n_candidates,
                n_feasible=reply.n_feasible,
                solve_seconds=time.perf_counter() - t0,
                solver="remote",
                feasible_candidates=[plan] if plan else [],
            )

        return finish

    def plan_schedule(self, observation, pdbs: Sequence[PDBSpec]):
        """Fetch a whole drain schedule over the wire (wire v3
        ``schedule_horizon`` frame -> KIND_PLAN_SCHEDULE reply): pack
        locally, walk the SAME endpoint failover ladder synchronously
        (a schedule fetch happens once per ``schedule_horizon`` drains
        — there is no metrics pass to overlap), and return a
        ``planner/schedule.DrainSchedule`` whose per-step validation
        runs entirely locally — executing an in-flight schedule needs
        no wire at all, so a replica dying mid-schedule costs nothing
        until the NEXT cut, which fails over. Returns None when every
        endpoint is unusable; the controller then plans per tick
        (plan_async's own ladder + local-fallback accounting owns the
        degradation)."""
        from k8s_spot_rescheduler_tpu.planner.schedule import DrainSchedule
        from k8s_spot_rescheduler_tpu.solver.schedule import decode_schedule

        cfg = self.config
        horizon = max(1, cfg.schedule_horizon)
        trace = tracing.current_trace()
        if trace is None and cfg.trace_enabled:
            trace = tracing.Trace()
        self.last_trace = trace
        span_cm = (
            trace.span("plan.schedule")
            if trace is not None
            else contextlib.nullcontext()
        )
        with span_cm as sp:
            with (
                trace.span("plan.pack")
                if trace is not None
                else contextlib.nullcontext()
            ):
                packed, meta = self._pack_observation(observation, pdbs)
            live = [
                ep for ep in self._endpoints
                if self.clock.now() >= ep.skip_until
            ]
            if not live:
                return None
            trace_id = trace.trace_id if trace is not None else ""
            body = wire.encode_plan_request(
                self.tenant, packed, trace_id=trace_id,
                schedule_horizon=horizon,
            )
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Planner-Deadline": f"{self.timeout:.3f}",
            }
            if trace_id:
                headers["X-Trace-Id"] = trace_id
            box: dict = {"attempts": [], "skipped_before": 0}
            self._ladder_call(
                "/v2/plan", body, headers,
                wire.decode_plan_schedule_reply, box,
            )
            reply = box.get("reply")
            self._note_wire_outcome(
                trace, box,
                reply.spans if reply is not None else (),
                attrs=(
                    {
                        "batch_lanes": reply.batch_lanes,
                        "batch_tenants": reply.batch_tenants,
                    }
                    if reply is not None
                    else None
                ),
            )
            if reply is None:
                log.warning(
                    "drain-schedule fetch failed on every endpoint "
                    "(%s); the tick plans per-plan instead",
                    "; ".join(why for _, why, _ in box["attempts"])
                    or "breaker open on every endpoint",
                )
                return None
            steps = decode_schedule(reply.steps)
            if sp is not None:
                sp.attrs["steps"] = len(steps)
                sp.attrs["horizon"] = horizon
        metrics.update_plan_schedule_len(len(steps))
        self.last_solver = "remote"
        self.last_endpoint = box.get("endpoint", "")
        return DrainSchedule(
            steps,
            packed,
            meta,
            pack_fn=self._pack_observation,
            solver_label="remote+schedule",
            horizon=horizon,
            base_observation=observation,
        )

    def _plan_fallback(self, observation, pdbs, cause: str = "") -> PlanReport:
        """This tick plans locally (numpy oracle) — every endpoint is
        down, slow, overloaded or out of protocol. Counted (metric +
        flight event, same site); the loop keeps running at full
        fidelity minus device speed."""
        metrics.update_remote_planner_fallback()
        flight.note_event(
            "remote-planner-fallback",
            cause=cause or "planner service unusable",
            trace_id=tracing.current_trace_id() or (
                self.last_trace.trace_id if self.last_trace else ""
            ),
        )
        report = self._fallback_planner().plan(observation, pdbs)
        self.last_solver = "remote-fallback"
        self.last_endpoint = ""
        return dataclasses.replace(report, solver="remote-fallback")
