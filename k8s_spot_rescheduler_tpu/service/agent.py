"""The per-cluster agent: a Planner whose solver lives across the wire.

``RemotePlanner`` implements the same ``Planner`` surface the control
loop already speaks (plan / plan_async), so the agent topology changes
NOTHING above the planner boundary: observe, pack and actuate stay
local and chaos-hardened (PR 4's retrying kube reads, crash containment,
orphan-taint recovery all apply unchanged). What moves is only the
solve: the locally-packed ``PackedCluster`` ships to the shared planner
service (service/server.py) over the binary wire protocol
(service/wire.py), and the tiny selection vector comes back — the same
few-hundred-byte boundary the in-process device fetch uses, so a fleet
of agents costs the service O(tenants x packed bytes) ingress and
near-zero egress.

Degradation is the agent's job, not the loop's, and it is a LADDER, not
a cliff:

1. **failover** — the agent accepts an ordered list of planner
   endpoints (``planner_urls`` / a comma list in ``planner_url``). Each
   endpoint carries its OWN consecutive-failure breaker; a tick walks
   the list in order, skipping breaker-open endpoints and failing over
   past an endpoint that resets, times out, 5xxs, or answers out of
   protocol. A reply from any endpoint is a full-fidelity remote plan —
   a dead primary replica costs the fleet one connect failure per
   breaker window, not a fallback. Served-after-failover ticks are
   counted (``remote_planner_failover_total``) and evented (flight kind
   ``failover``), both from the same site.
2. **local fallback** — only when EVERY endpoint is dead or breaker-open
   does the tick degrade to the in-process numpy-oracle fallback planner
   (``remote_planner_fallback_total``, flight ``remote-planner-fallback``)
   — the same containment the loop applies to a crashing in-process
   planner. The first healthy reply closes that endpoint's breaker.

A 503's ``Retry-After`` is honored below the breaker threshold as the
skip window; at/above the threshold the skip window is
``max(doubling backoff, Retry-After)`` with the server-suggested value
capped at ``RETRY_AFTER_CAP_S`` — one bad LB header must not park an
agent on its fallback for hours (the same 30 s cap the kube read path
applies, docs/ROBUSTNESS.md). The capped horizon is then stretched by
a private urandom-seeded jitter, and a KIND_RESYNC full-pack retry
sleeps a jittered delay first: a fleet-wide restart hands every agent
the same horizon in the same tick, and without per-agent jitter they
would all come back at once — the resync storm docs/ROBUSTNESS.md's
"Resync storms" section bounds.

The transport is a seam (``self.transport``): ``service/chaos.py``
wraps it to inject wire faults in ``make fleet-chaos-smoke``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import http.client
import random
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.cluster import PDBSpec
from k8s_spot_rescheduler_tpu.planner.base import PlanReport, pack_observation
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


class RemoteCallError(Exception):
    """A planner-service call failed at the HTTP layer (typed so the
    503 Retry-After can ride along to the breaker)."""

    def __init__(self, message: str, retry_after: float):
        super().__init__(message)
        self.retry_after = float(retry_after)


# historical name (pre-failover); tests and chaos wrappers may hold it
_RemoteError = RemoteCallError


class _Endpoint:
    """Per-endpoint breaker state: failures at replica A must not make
    the agent skip replica B. ``acked_fp`` is the fingerprint of the
    last pack THIS endpoint acknowledged (full upload or applied
    delta) — the delta wire ships churn only to an endpoint whose
    acknowledged state IS the delta's base, so a failover target (or a
    repointed url) gets a full pack by construction, without waiting
    for the server's resync demand."""

    __slots__ = ("url", "consecutive_failures", "skip_until", "acked_fp")

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.consecutive_failures = 0
        self.skip_until = 0.0  # on the agent's clock (monotonic)
        self.acked_fp = ""  # last pack fingerprint this replica holds


# public name: the fleet twin (service/twin.py) reuses the per-endpoint
# breaker state object rather than growing a parallel one
Endpoint = _Endpoint


# longest HTTP status/header line the pooled reader accepts (matches
# http.client's own _MAXLINE discipline)
_MAX_LINE = 65536


class _WireSocket:
    """One persistent keep-alive connection to a planner endpoint, with
    HTTP/1.1 request pipelining.

    Writes are serialized under a send lock and each request takes a
    FIFO *ticket*; replies are read strictly in ticket order (the
    HTTP/1.1 pipelining contract), so a second request — the overlapped
    metrics-pass upload, a concurrent direct caller — can go on the
    wire while the first reply is still in flight instead of opening a
    second socket. One buffered reader lives for the connection's whole
    life: response parsing can never strand the next reply's bytes in
    a discarded per-response buffer.

    Any send/parse failure marks the connection ``broken``; the pool
    discards it and the transport's stale-retry contract decides
    whether the failure counts (see :class:`PooledWireTransport`)."""

    def __init__(self, host: str, port: int, timeout: float,
                 tls: bool = False):
        t0 = time.perf_counter()
        self.sock = socket.create_connection((host, port), timeout=timeout)
        if tls:
            import ssl

            self.sock = ssl.create_default_context().wrap_socket(
                self.sock, server_hostname=host
            )
        self.connect_ms = (time.perf_counter() - t0) * 1e3
        with contextlib.suppress(OSError):
            self.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        self.rfile = self.sock.makefile("rb")
        self.requests = 0  # requests ever sent on this connection
        self.broken = False
        self._send_lock = threading.Lock()
        self._read_cond = threading.Condition()
        self._next_ticket = 0
        self._next_read = 0

    @property
    def idle(self) -> bool:
        """No reply in flight (every sent request has been read)."""
        return self._next_ticket == self._next_read

    def send(self, data: bytes, timeout: float) -> Tuple[int, bool]:
        """Write one request; returns ``(ticket, reused)`` where
        ``reused`` is True when this connection had already served
        traffic (the reuse-vs-fresh distinction the stale-retry
        contract and the reuse counter both key on)."""
        with self._send_lock:
            if self.broken:
                raise ConnectionError(
                    "pooled connection already marked broken"
                )
            reused = self.requests > 0
            self.requests += 1
            self.sock.settimeout(max(0.05, timeout))
            try:
                # the send lock is HELD across the socket write on
                # purpose: it serializes whole frames onto the shared
                # pipelined connection — two ticks interleaving bytes
                # mid-frame would corrupt the wire
                self.sock.sendall(data)  # noqa: lock-graph
            except BaseException:
                self.broken = True
                raise
            ticket = self._next_ticket
            self._next_ticket += 1
            return ticket, reused

    def read(self, ticket: int, deadline: float):
        """Read the reply for ``ticket`` (FIFO pipeline order); returns
        ``(status, headers, body, keep_alive)``."""
        with self._read_cond:
            while self._next_read != ticket:
                if self.broken:
                    raise ConnectionError(
                        "pooled connection broke ahead in the pipeline"
                    )
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self.broken = True
                    self._read_cond.notify_all()
                    raise TimeoutError(
                        "pipelined reply timed out behind earlier "
                        "requests"
                    )
                self._read_cond.wait(min(remaining, 0.05))
            if self.broken:
                raise ConnectionError(
                    "pooled connection broke ahead in the pipeline"
                )
            try:
                return self._read_response(deadline)
            except BaseException:
                self.broken = True
                raise
            finally:
                self._next_read += 1
                self._read_cond.notify_all()

    def _read_response(self, deadline: float):
        self.sock.settimeout(max(0.05, deadline - time.perf_counter()))
        status_line = self.rfile.readline(_MAX_LINE + 1)
        if not status_line:
            # EOF before any reply byte: the server closed this
            # keep-alive connection while it sat idle — THE stale
            # half-closed case the retry-once contract exists for
            raise ConnectionError(
                "server closed the keep-alive connection"
            )
        try:
            version, code_raw = status_line.split(None, 2)[:2]
            code = int(code_raw)
        except (ValueError, IndexError) as err:
            raise ConnectionError(
                f"malformed HTTP status line {status_line[:64]!r}"
            ) from err
        headers = http.client.parse_headers(self.rfile)
        try:
            length = int(headers.get("Content-Length", 0) or 0)
        except (TypeError, ValueError):
            length = 0
        body = self.rfile.read(length) if length > 0 else b""
        if length > 0 and len(body) < length:
            raise ConnectionError(
                "keep-alive reply truncated mid-body"
            )
        conn_hdr = (headers.get("Connection") or "").lower()
        keep = version.startswith(b"HTTP/1.1") and "close" not in conn_hdr
        return code, headers, body, keep

    def close(self) -> None:
        with self._read_cond:
            self.broken = True
            self._read_cond.notify_all()
        with contextlib.suppress(Exception):
            self.rfile.close()
        with contextlib.suppress(Exception):
            self.sock.close()


class PooledWireTransport:
    """The default agent transport: a persistent keep-alive connection
    pool behind the ``RemotePlanner.transport`` seam (same callable
    shape ``(url, body, headers, timeout) -> bytes``).

    - **One connection per endpoint**, reused across ticks AND across
      the failover ladder: a breaker-expiry failback to the primary
      rides the primary's still-pooled socket, and
      ``MAX_CONNS_PER_ENDPOINT`` bounds the pool by construction —
      concurrent requests share the endpoint's connection via HTTP/1.1
      pipelining (:class:`_WireSocket`) instead of fanning out sockets.
    - **Stale-retry contract** (docs/ROBUSTNESS.md): a send/parse
      failure on a connection that had already served traffic —
      server restart, idle-timeout close, LB reset between ticks — is
      retried exactly ONCE on a fresh connection
      (``remote_wire_reconnects_total``) before it propagates as an
      endpoint failure. Failures on a *fresh* connection, and genuine
      deadline timeouts, propagate immediately (retrying a timeout
      would double the stall).
    - **Accounting**: reuses feed ``remote_wire_connection_reuse_total``;
      a fresh connect's handshake time is handed to the caller's
      thread via :meth:`take_last_call` and grafted as the
      ``wire.connect`` span under ``wire.request`` — socket economics
      are visible per tick, not just in aggregate.

    Thread-safe; trace mutation stays on the caller (RemotePlanner
    reads ``take_last_call`` on the worker thread into the box and
    grafts on the finish thread, the same single-threaded-trace
    discipline as the rest of the wire accounting)."""

    # hard per-endpoint connection bound: requests PIPELINE rather than
    # fan out, so one socket per endpoint is the steady state and the
    # ceiling (tests/test_wire_pool.py hammers this)
    MAX_CONNS_PER_ENDPOINT = 1

    def __init__(self):
        self._lock = threading.Lock()
        self._conns: Dict[Tuple[str, int, bool], _WireSocket] = {}
        self._tls = threading.local()

    # ------------------------------------------------------------------

    @staticmethod
    def _endpoint(url: str) -> Tuple[Tuple[str, int, bool], str, str]:
        parsed = urllib.parse.urlsplit(url)
        tls = parsed.scheme == "https"
        host = parsed.hostname or "localhost"
        port = parsed.port or (443 if tls else 80)
        path = parsed.path or "/"
        if parsed.query:
            path = f"{path}?{parsed.query}"
        return (host, port, tls), host, path

    @staticmethod
    def _request_bytes(
        host: str, port: int, path: str, body: bytes, headers: dict
    ) -> bytes:
        lines = [
            f"POST {path} HTTP/1.1",
            f"Host: {host}:{port}",
            f"Content-Length: {len(body)}",
            "Connection: keep-alive",
        ]
        lines.extend(f"{k}: {v}" for k, v in headers.items())
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body

    def _checkout(self, key, timeout: float) -> _WireSocket:
        """The endpoint's pooled connection, or a fresh one when none
        is live. The pool holds at most MAX_CONNS_PER_ENDPOINT (=1)
        connection per endpoint — ever."""
        with self._lock:
            conn = self._conns.get(key)
            if conn is not None and not conn.broken:
                return conn
            if conn is not None:
                conn.close()
            conn = _WireSocket(key[0], key[1], timeout, tls=key[2])
            self._conns[key] = conn
            return conn

    def _discard(self, key, conn: _WireSocket) -> None:
        with self._lock:
            if self._conns.get(key) is conn:
                del self._conns[key]
        conn.close()

    # ------------------------------------------------------------------

    def __call__(
        self, url: str, body: bytes, headers: dict, timeout: float
    ) -> bytes:
        key, host, path = self._endpoint(url)
        data = self._request_bytes(host, key[1], path, body, headers)
        deadline = time.perf_counter() + timeout
        info = {"connect_ms": 0.0, "reused": False, "reconnected": False}
        self._tls.last_call = info
        for attempt in (0, 1):
            budget = max(0.05, deadline - time.perf_counter())
            conn = self._checkout(key, budget)
            try:
                ticket, reused = conn.send(data, budget)
                code, hdrs, payload, keep = conn.read(ticket, deadline)
            except TimeoutError:
                # a genuine deadline timeout is not staleness: retrying
                # would stall the tick twice. The ladder owns it.
                self._discard(key, conn)
                raise
            except (ConnectionError, OSError):
                self._discard(key, conn)
                if conn.requests > 1 and attempt == 0:
                    # the stale-socket contract: a connection that had
                    # already served traffic may have been half-closed
                    # between ticks — ONE transparent retry on a fresh
                    # socket before this counts as an endpoint failure
                    metrics.update_remote_wire_reconnect()
                    info["reconnected"] = True
                    continue
                raise
            if not reused:
                info["connect_ms"] = conn.connect_ms
            info["reused"] = reused
            if reused:
                metrics.update_remote_wire_reuse()
            if not keep:
                # the server said close (drain-refuse, pre-body reject,
                # HTTP/1.0 peer): honor it — never pool a socket whose
                # next reply would desync
                self._discard(key, conn)
            if code != 200:
                retry_after = 0.0
                if code == 503:
                    try:
                        retry_after = float(hdrs.get("Retry-After", 0))
                    except (TypeError, ValueError):
                        retry_after = 0.0
                detail = ""
                try:
                    wire.decode_plan_reply(payload)
                except wire.WireError as werr:
                    detail = str(werr)
                raise RemoteCallError(
                    f"HTTP {code}{': ' + detail if detail else ''}",
                    retry_after,
                )
            return payload
        raise ConnectionError("unreachable")  # pragma: no cover

    # ------------------------------------------------------------------
    # caller-facing accounting + lifecycle

    def take_last_call(self) -> Optional[dict]:
        """Pop this thread's last call's connection accounting
        (``connect_ms``/``reused``/``reconnected``), or None when no
        pooled call happened on this thread since the last take."""
        info = getattr(self._tls, "last_call", None)
        self._tls.last_call = None
        return info

    def connection_count(self) -> int:
        with self._lock:
            return len(self._conns)

    def connection_for(self, url: str) -> Optional[_WireSocket]:
        """The live pooled connection for ``url``'s endpoint (tests:
        socket-identity assertions across failover return)."""
        key, _, _ = self._endpoint(url)
        with self._lock:
            return self._conns.get(key)

    def break_idle(self) -> int:
        """OS-level half-close of every pooled connection with no reply
        in flight, LEAVING it in the pool — exactly what a server-side
        idle-timeout close between ticks looks like to the agent. The
        chaos half-closed-socket fault (service/chaos.py) calls this;
        the next request must discover the stale socket and retry once
        on a fresh one. Returns the number of connections broken."""
        with self._lock:
            conns = list(self._conns.values())
        broken = 0
        for conn in conns:
            if conn.idle and not conn.broken:
                with contextlib.suppress(OSError):
                    conn.sock.shutdown(socket.SHUT_RDWR)
                broken += 1
        return broken

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            conn.close()


class RemotePlanner:
    """Planner over a remote multi-tenant planner service (or an
    ordered failover list of its replicas)."""

    accepts_columnar = True

    # breaker: consecutive failures before an endpoint is skipped, and
    # the doubling skip window (seconds) that failure cadence buys
    FAIL_THRESHOLD = 2
    BACKOFF_BASE = 5.0
    BACKOFF_MAX = 120.0
    # cap on the SERVER-suggested Retry-After contribution to the skip
    # window (a misconfigured LB header must not stall failback for
    # hours; outages past this belong to the doubling backoff)
    RETRY_AFTER_CAP_S = 30.0
    # decorrelation jitter: the suggested horizon is stretched by a
    # per-agent random factor in [1.0, 1 + this) before it opens the
    # skip window — N agents refused with the SAME Retry-After must
    # not come back in the same instant (the herd the horizon exists
    # to spread)
    RETRY_JITTER_FRAC = 0.5
    # spread (seconds) of the jittered delay before a KIND_RESYNC
    # full-pack retry — a fleet-wide restart demands resyncs from
    # every agent in the same tick; an immediate retry would be a
    # perfectly synchronized full-pack herd by construction. Bounded
    # by the remaining tick deadline budget.
    RESYNC_JITTER_S = 2.0

    def __init__(
        self,
        config: ReschedulerConfig,
        url: str = "",
        *,
        tenant: Optional[str] = None,
        timeout: Optional[float] = None,
        clock: Optional[Clock] = None,
    ):
        self.config = config
        raw = url or config.planner_urls or config.planner_url
        self._endpoints: List[_Endpoint] = [
            _Endpoint(u.strip()) for u in raw.split(",") if u.strip()
        ]
        if not self._endpoints:
            raise ValueError("RemotePlanner needs a planner service url")
        import socket

        self.tenant = tenant or socket.gethostname()
        self.timeout = float(
            timeout if timeout is not None else config.planner_timeout
        )
        self.clock = clock or RealClock()
        # seam: (url, body, headers, timeout) -> reply bytes; raises
        # RemoteCallError for HTTP errors. service/chaos.py wraps it.
        # Default = the persistent keep-alive pool; _transport_urllib
        # stays as the fresh-connection-per-request baseline (bench.py
        # serve_smoke measures the pool's win against it in-run).
        self._wire_pool = PooledWireTransport()
        self.transport = self._wire_pool
        if config.service_chaos_profile not in ("", "off", "none"):
            from k8s_spot_rescheduler_tpu.service.chaos import (
                ChaosAgentTransport,
                ServiceFaultPlan,
            )

            log.info(
                "CHAOS: service-path fault injection on the agent "
                "transport (profile=%s seed=%d) — testing mode",
                config.service_chaos_profile, config.service_chaos_seed,
            )
            self.transport = ChaosAgentTransport(
                self.transport,
                ServiceFaultPlan.profile(
                    config.service_chaos_profile,
                    config.service_chaos_seed,
                ),
                clock=self.clock,
                pool=self._wire_pool,
            )
        self._pad_c = 0
        self._pad_s = 0
        self._pad_k = config.max_pods_per_node_hint
        # private urandom-seeded instance (the kube read path's PR-4
        # lesson): retry jitter must decorrelate agents/restarts — a
        # fixed seed would synchronize the very herd it exists to
        # spread — without perturbing global random state
        self._retry_rng = random.Random()
        self._fallback = None  # lazy local numpy-oracle planner
        # delta wire (v4): the previous tick's pack + its fingerprint —
        # what this tick's churn delta is diffed against (the agent's
        # half of the anti-entropy pair; the service holds the other)
        self._prev_packed = None
        self._prev_fp = ""
        self.last_solver = "remote"
        self.last_endpoint = ""
        # the trace the last plan recorded into: the controller's tick
        # trace when one is ambient, else a standalone trace (direct
        # callers like bench.serve_smoke read the grafted span tree off
        # this); None with tracing disabled
        self.last_trace = None

    # ------------------------------------------------------------------
    # single-endpoint compatibility surface (tests, serve_smoke)

    @property
    def url(self) -> str:
        return self._endpoints[0].url

    @url.setter
    def url(self, value: str) -> None:
        # repointing resets that endpoint's breaker (a NEW replica owes
        # nothing to the old one's failure streak)
        self._endpoints[0] = _Endpoint(value)

    @property
    def urls(self) -> List[str]:
        return [ep.url for ep in self._endpoints]

    @property
    def _consecutive_failures(self) -> int:
        return self._endpoints[0].consecutive_failures

    @property
    def _skip_until(self) -> float:
        return self._endpoints[0].skip_until

    @_skip_until.setter
    def _skip_until(self, value: float) -> None:
        self._endpoints[0].skip_until = float(value)

    # ------------------------------------------------------------------

    def _fallback_planner(self):
        if self._fallback is None:
            from k8s_spot_rescheduler_tpu.planner.solver_planner import (
                SolverPlanner,
            )

            self._fallback = SolverPlanner(
                dataclasses.replace(
                    self.config, solver="numpy",
                    planner_url="", planner_urls="",
                )
            )
        return self._fallback

    def _jittered_horizon(self, suggested: float) -> float:
        """Stretch a (already-capped) server-suggested horizon by this
        agent's private jitter: uniform in [1.0, 1+RETRY_JITTER_FRAC).
        A storm refuses hundreds of agents with near-identical
        Retry-After values; without this they would all come back in
        the same instant and re-form the herd the 503 just shed."""
        return suggested * (
            1.0 + self._retry_rng.random() * self.RETRY_JITTER_FRAC
        )

    def _note_failure(
        self, ep: _Endpoint, why: str, retry_after: float = 0.0
    ) -> None:
        ep.consecutive_failures += 1
        # one bad LB header must not stall failback for hours: the
        # server-suggested horizon is capped wherever it feeds the skip
        # window (regression-tested; docs/ROBUSTNESS.md), then jittered
        # per agent so equal horizons don't re-synchronize the fleet
        suggested = min(max(retry_after, 0.0), self.RETRY_AFTER_CAP_S)
        if suggested > 0:
            suggested = self._jittered_horizon(suggested)
        if ep.consecutive_failures >= self.FAIL_THRESHOLD:
            n = ep.consecutive_failures - self.FAIL_THRESHOLD
            backoff = min(
                self.BACKOFF_BASE * (2.0 ** n), self.BACKOFF_MAX
            )
            # a LONGER server-suggested Retry-After beats the schedule
            # (the server knows its queue) — capped above
            backoff = max(backoff, suggested)
            ep.skip_until = self.clock.now() + backoff
            log.error(
                "planner endpoint %s unusable (%s; %d consecutive "
                "failures); skipping it for %.1fs",
                ep.url, why, ep.consecutive_failures, backoff,
            )
        elif suggested > 0:
            # a single 503 already names its horizon: honor it without
            # waiting for the threshold
            ep.skip_until = self.clock.now() + suggested
            log.warning(
                "planner endpoint %s overloaded (%s); retrying after %.1fs",
                ep.url, why, suggested,
            )
        else:
            log.warning(
                "planner endpoint %s call failed: %s", ep.url, why
            )

    def _note_success(self, ep: _Endpoint) -> None:
        if ep.consecutive_failures:
            log.info(
                "planner endpoint %s healthy again after %d failed call(s)",
                ep.url, ep.consecutive_failures,
            )
        ep.consecutive_failures = 0
        ep.skip_until = 0.0

    def _transport_urllib(
        self, url: str, body: bytes, headers: dict, timeout: float
    ) -> bytes:
        """The default transport: one POST, reply bytes back.
        HTTP error statuses become :class:`RemoteCallError` carrying any
        503 Retry-After; everything else propagates as-is."""
        req = urllib.request.Request(
            url, data=body, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as err:
            retry_after = 0.0
            if err.code == 503:
                try:
                    retry_after = float(err.headers.get("Retry-After", 0))
                except (TypeError, ValueError):
                    retry_after = 0.0
            detail = ""
            try:
                wire.decode_plan_reply(err.read())
            except wire.WireError as werr:
                detail = str(werr)
            raise RemoteCallError(
                f"HTTP {err.code}{': ' + detail if detail else ''}",
                retry_after,
            ) from err

    def _pack_observation(self, observation, pdbs):
        """The shared pack path (planner/base.pack_observation) with
        the agent's high-water pads — stable shapes keep the whole
        fleet in few service-side buckets; shared by plan_async,
        plan_schedule, and the drain-schedule execution handle."""
        return pack_observation(self, observation, pdbs)

    def _resync_retry_delay(self, remaining: float) -> float:
        """Jittered decorrelation delay before the KIND_RESYNC
        full-pack retry: uniform over [0, RESYNC_JITTER_S], clamped to
        at most half the remaining deadline budget (the retry must
        still have room to complete). 0 when the budget is exhausted."""
        spread = min(self.RESYNC_JITTER_S, max(0.0, remaining * 0.5))
        if spread <= 0:
            return 0.0
        return self._retry_rng.uniform(0.0, spread)

    def _ladder_call(self, path: str, body: bytes, headers: dict,
                     decode, box: dict, delta_body: bytes = None,
                     base_fp: str = "", new_fp: str = "") -> None:
        """Walk the ordered endpoint list under ONE deadline budget:
        the tick's documented planner_timeout bounds the whole call,
        not each endpoint — three blackholed replicas must not stall
        the loop 3x the deadline. Fills ``box`` with the decoded reply
        + serving endpoint (or just the attempts on total failure).

        Delta wire: with ``delta_body`` given, an endpoint whose
        acknowledged fingerprint equals ``base_fp`` is sent the churn
        delta instead of the full pack; a KIND_RESYNC answer retries
        the full pack on the SAME endpoint within the same budget (a
        resync is protocol, not a failure — no breaker, no failover).
        A serving endpoint's ``acked_fp`` advances to ``new_fp``, so
        failover targets get a full pack by construction."""
        box["t_send"] = time.perf_counter()
        deadline = box["t_send"] + self.timeout
        skipped = 0
        for ep in self._endpoints:
            if self.clock.now() < ep.skip_until:
                # counts toward failover only if it precedes the
                # endpoint that eventually serves
                skipped += 1
                continue
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                box["attempts"].append((
                    ep.url,
                    "plan deadline exhausted before this "
                    "endpoint was tried",
                    0.0,
                ))
                # not an endpoint failure: its breaker is
                # untouched — we simply ran out of budget
                continue
            use_delta = delta_body is not None and ep.acked_fp == base_fp
            t_ep = time.perf_counter()

            def _call(payload: bytes, budget: float) -> bytes:
                # one transport invocation + the pool's per-call socket
                # accounting (connect time, reuse, stale reconnects)
                # copied into the box on THIS worker thread; the finish
                # thread grafts it (traces are single-threaded)
                raw = self.transport(
                    f"{ep.url}{path}", payload, headers, budget
                )
                pool = self._wire_pool
                if pool is not None:
                    conn_info = pool.take_last_call()
                    if conn_info is not None:
                        box["wire_conn"] = conn_info
                return raw

            try:
                raw = _call(
                    delta_body if use_delta else body,
                    max(0.05, remaining),
                )
                reply = (
                    wire.decode_plan_or_resync(raw)
                    if use_delta
                    else decode(raw)
                )
                if isinstance(reply, wire.ResyncDemand):
                    # the service cannot honor the delta's base
                    # (restart, eviction, mismatch, corruption): one
                    # full pack to the SAME endpoint, same budget.
                    # NOT immediately — a replica restart stales every
                    # agent's fingerprint in the same tick, and a
                    # zero-jitter retry is a perfectly synchronized
                    # full-pack herd by construction. Sleep a private
                    # urandom-jittered delay (bounded so most of the
                    # budget is left for the retry itself) before the
                    # one full pack.
                    box["resyncs"] = box.get("resyncs", 0) + 1
                    log.info(
                        "planner endpoint %s demanded a full-pack "
                        "resync: %s", ep.url, reply.cause,
                    )
                    remaining = deadline - time.perf_counter()
                    delay = self._resync_retry_delay(remaining)
                    if delay > 0:
                        self.clock.sleep(delay)
                        remaining = deadline - time.perf_counter()
                    raw = _call(body, max(0.05, remaining))
                    reply = decode(raw)
            except RemoteCallError as err:
                self._note_failure(ep, str(err), err.retry_after)
                box["attempts"].append((
                    ep.url, str(err),
                    (time.perf_counter() - t_ep) * 1e3,
                ))
                continue
            except Exception as err:  # noqa: BLE001, exception-discipline — transport/protocol failure of ONE endpoint: recorded as a failover attempt and the ladder continues; the terminal all-dead case is counted+evented by the caller
                self._note_failure(ep, str(err), 0.0)
                box["attempts"].append((
                    ep.url, str(err),
                    (time.perf_counter() - t_ep) * 1e3,
                ))
                continue
            self._note_success(ep)
            if new_fp:
                # this replica now holds exactly the new pack (full
                # upload, or delta applied over an acknowledged base)
                ep.acked_fp = new_fp
            box["reply"] = reply
            box["endpoint"] = ep.url
            box["skipped_before"] = skipped
            break
        box["t_recv"] = time.perf_counter()

    def _note_wire_outcome(self, trace, box, spans, attrs=None) -> None:
        """The shared post-ladder accounting: graft each FAILED
        endpoint attempt, fire the failover metric + flight event when
        the serving endpoint was not first choice (same site, so the
        two surfaces always agree), and graft the server's span block
        under the measured round trip."""
        attempts = box["attempts"]
        if trace is not None:
            for ep_url, why, dur_ms in attempts:
                trace.graft(
                    tracing.make_span("wire.failover", 0.0, dur_ms),
                    attrs={"endpoint": ep_url, "error": True},
                )
        if box.get("reply") is None:
            return
        skipped_before = box.get("skipped_before", 0)
        if attempts or skipped_before:
            # served, but only after at least one EARLIER endpoint
            # failed or was breaker-open: a failover tick. (A
            # breaker-open endpoint LATER in the list is irrelevant —
            # the primary serving is healthy.)
            metrics.update_remote_planner_failover()
            flight.note_event(
                "failover",
                cause=(
                    f"{len(attempts)} endpoint(s) failed, "
                    f"{skipped_before} breaker-open; served by "
                    f"{box.get('endpoint', '?')}"
                ),
                trace_id=(
                    trace.trace_id if trace is not None else ""
                ),
                endpoints_tried=len(attempts) + skipped_before + 1,
            )
        if trace is not None:
            if box.get("resyncs"):
                # surface a served-after-resync tick on the trace tree
                attrs = dict(attrs or {})
                attrs["delta_resyncs"] = box["resyncs"]
            # graft the server's span block under the measured round
            # trip; the residual (rtt minus server-side work) is the
            # wire itself — tunnel, TLS, serialization on the path
            rtt_ms = max(0.0, (box["t_recv"] - box["t_send"]) * 1e3)
            server_ms = sum(d for _, _, d in spans)
            children = list(spans)
            conn_info = box.get("wire_conn")
            if conn_info is not None:
                attrs = dict(attrs or {})
                attrs["wire_reused"] = bool(conn_info.get("reused"))
                if conn_info.get("reconnected"):
                    attrs["wire_reconnected"] = True
                if conn_info.get("connect_ms"):
                    # a fresh TCP connect happened inside this round
                    # trip (first tick, failback, stale replacement);
                    # on a reused socket the span is absent — its
                    # absence IS the sub-RTT win
                    children.append(
                        tracing.make_span(
                            "wire.connect", 0.0,
                            float(conn_info["connect_ms"]),
                        )
                    )
            trace.graft(
                tracing.make_span("wire.request", 0.0, rtt_ms),
                children=children,
                attrs=attrs,
            )
            trace.graft(
                tracing.make_span(
                    "wire.transfer", 0.0, max(0.0, rtt_ms - server_ms)
                )
            )

    # ------------------------------------------------------------------
    # Planner surface

    def plan(self, observation, pdbs: Sequence[PDBSpec]) -> PlanReport:
        return self.plan_async(observation, pdbs)()

    def plan_async(self, observation, pdbs: Sequence[PDBSpec]):
        """Pack locally, walk the endpoint ladder on a worker thread
        (the loop's metrics pass overlaps the network round trips
        exactly as it overlaps the in-process device solve), and return
        the blocking ``finish`` callable.

        Tracing: the pack and the wire round trip record into the
        controller's ambient tick trace (or a standalone trace for
        direct callers); the tick's trace ID ships with the request
        (wire v2 frame + ``X-Trace-Id``) and the serving endpoint's
        spans come back in the reply and are grafted under
        ``wire.request``; each FAILED endpoint attempt grafts a
        ``wire.failover`` span. The worker thread only stores raw
        timestamps and outcomes; all trace mutation happens on the
        caller's thread at ``finish`` (traces are single-threaded)."""
        t0 = time.perf_counter()
        cfg = self.config
        trace = tracing.current_trace()
        if trace is None and cfg.trace_enabled:
            trace = tracing.Trace()
        self.last_trace = trace

        def _sp(name, **attrs):
            return (
                trace.span(name, **attrs)
                if trace is not None
                else contextlib.nullcontext()
            )

        with _sp("plan.pack"):
            packed, meta = self._pack_observation(observation, pdbs)

        for blocked in meta.blocking_pods():
            log.info("BlockingPod: %s (%s)", blocked.pod.uid, blocked.reason)

        live = [
            ep for ep in self._endpoints
            if self.clock.now() >= ep.skip_until
        ]
        box: dict = {"attempts": [], "skipped_before": 0}
        worker: Optional[threading.Thread] = None
        # delta wire (v4): fingerprint this pack, diff it against the
        # previous tick's, and remember it as the next tick's base —
        # regardless of how THIS tick ends (fallback included), since
        # the per-endpoint acked fingerprints are what gate shipping
        fp = ""
        delta = None
        base_fp = ""
        if cfg.delta_wire_enabled:
            from k8s_spot_rescheduler_tpu.models.columnar import (
                emit_packed_delta,
                pack_fingerprint,
            )

            with _sp("plan.fingerprint"):
                fp = pack_fingerprint(packed)
            if self._prev_packed is not None:
                with _sp("plan.delta-emit"):
                    # None on shape growth past the high-water pads:
                    # this tick ships the full pack (and re-seeds)
                    delta = emit_packed_delta(self._prev_packed, packed)
                base_fp = self._prev_fp
            self._prev_packed = packed
            self._prev_fp = fp
        if live:
            trace_id = trace.trace_id if trace is not None else ""
            body = wire.encode_plan_request(
                self.tenant, packed, trace_id=trace_id,
                pack_fingerprint=fp,
            )
            delta_body = None
            if delta is not None and any(
                ep.acked_fp == base_fp for ep in live
            ):
                delta_body = wire.encode_packed_delta(
                    self.tenant, delta,
                    base_fingerprint=base_fp, new_fingerprint=fp,
                    trace_id=trace_id,
                )
            headers = {
                "Content-Type": "application/octet-stream",
                # declare our own deadline so the service evicts (and
                # frees the slot of) a request we will have abandoned
                "X-Planner-Deadline": f"{self.timeout:.3f}",
            }
            if trace_id:
                # belt to the wire frame: proxies/logs see the
                # correlation id even when the binary body is opaque
                headers["X-Trace-Id"] = trace_id

            def call():
                self._ladder_call(
                    "/v2/plan", body, headers, wire.decode_plan_reply,
                    box, delta_body=delta_body, base_fp=base_fp,
                    new_fp=fp,
                )

            worker = threading.Thread(target=call, daemon=True)
            worker.start()

        def finish() -> PlanReport:
            if worker is not None:
                worker.join()
            reply = box.get("reply")
            if reply is None:
                self._note_wire_outcome(trace, box, ())
                causes = "; ".join(why for _, why, _ in box["attempts"])
                return self._plan_fallback(
                    observation, pdbs,
                    cause=causes or "breaker open on every endpoint",
                )
            self.last_solver = "remote"
            self.last_endpoint = box.get("endpoint", "")
            self._note_wire_outcome(
                trace, box, reply.spans,
                attrs={
                    "batch_lanes": reply.batch_lanes,
                    "batch_tenants": reply.batch_tenants,
                },
            )
            plan = None
            if reply.found and reply.index < meta.n_candidates:
                plan = meta.build_plan(
                    reply.index, np.asarray(reply.row)
                )
            return PlanReport(
                plan=plan,
                n_candidates=meta.n_candidates,
                n_feasible=reply.n_feasible,
                solve_seconds=time.perf_counter() - t0,
                solver="remote",
                feasible_candidates=[plan] if plan else [],
            )

        return finish

    def plan_schedule(self, observation, pdbs: Sequence[PDBSpec]):
        """Fetch a whole drain schedule over the wire (wire v3
        ``schedule_horizon`` frame -> KIND_PLAN_SCHEDULE reply): pack
        locally, walk the SAME endpoint failover ladder synchronously
        (a schedule fetch happens once per ``schedule_horizon`` drains
        — there is no metrics pass to overlap), and return a
        ``planner/schedule.DrainSchedule`` whose per-step validation
        runs entirely locally — executing an in-flight schedule needs
        no wire at all, so a replica dying mid-schedule costs nothing
        until the NEXT cut, which fails over. Returns None when every
        endpoint is unusable; the controller then plans per tick
        (plan_async's own ladder + local-fallback accounting owns the
        degradation)."""
        from k8s_spot_rescheduler_tpu.planner.schedule import DrainSchedule
        from k8s_spot_rescheduler_tpu.solver.schedule import decode_schedule

        cfg = self.config
        horizon = max(1, cfg.schedule_horizon)
        trace = tracing.current_trace()
        if trace is None and cfg.trace_enabled:
            trace = tracing.Trace()
        self.last_trace = trace
        span_cm = (
            trace.span("plan.schedule")
            if trace is not None
            else contextlib.nullcontext()
        )
        with span_cm as sp:
            with (
                trace.span("plan.pack")
                if trace is not None
                else contextlib.nullcontext()
            ):
                packed, meta = self._pack_observation(observation, pdbs)
            live = [
                ep for ep in self._endpoints
                if self.clock.now() >= ep.skip_until
            ]
            if not live:
                return None
            trace_id = trace.trace_id if trace is not None else ""
            body = wire.encode_plan_request(
                self.tenant, packed, trace_id=trace_id,
                schedule_horizon=horizon,
            )
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Planner-Deadline": f"{self.timeout:.3f}",
            }
            if trace_id:
                headers["X-Trace-Id"] = trace_id
            box: dict = {"attempts": [], "skipped_before": 0}
            self._ladder_call(
                "/v2/plan", body, headers,
                wire.decode_plan_schedule_reply, box,
            )
            reply = box.get("reply")
            self._note_wire_outcome(
                trace, box,
                reply.spans if reply is not None else (),
                attrs=(
                    {
                        "batch_lanes": reply.batch_lanes,
                        "batch_tenants": reply.batch_tenants,
                    }
                    if reply is not None
                    else None
                ),
            )
            if reply is None:
                log.warning(
                    "drain-schedule fetch failed on every endpoint "
                    "(%s); the tick plans per-plan instead",
                    "; ".join(why for _, why, _ in box["attempts"])
                    or "breaker open on every endpoint",
                )
                return None
            steps = decode_schedule(reply.steps)
            if sp is not None:
                sp.attrs["steps"] = len(steps)
                sp.attrs["horizon"] = horizon
        metrics.update_plan_schedule_len(len(steps))
        self.last_solver = "remote"
        self.last_endpoint = box.get("endpoint", "")
        return DrainSchedule(
            steps,
            packed,
            meta,
            pack_fn=self._pack_observation,
            solver_label="remote+schedule",
            horizon=horizon,
            base_observation=observation,
        )

    def _plan_fallback(self, observation, pdbs, cause: str = "") -> PlanReport:
        """This tick plans locally (numpy oracle) — every endpoint is
        down, slow, overloaded or out of protocol. Counted (metric +
        flight event, same site); the loop keeps running at full
        fidelity minus device speed."""
        metrics.update_remote_planner_fallback()
        flight.note_event(
            "remote-planner-fallback",
            cause=cause or "planner service unusable",
            trace_id=tracing.current_trace_id() or (
                self.last_trace.trace_id if self.last_trace else ""
            ),
        )
        report = self._fallback_planner().plan(observation, pdbs)
        self.last_solver = "remote-fallback"
        self.last_endpoint = ""
        return dataclasses.replace(report, solver="remote-fallback")
