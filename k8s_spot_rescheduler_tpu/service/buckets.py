"""Shape buckets: how unrelated tenants come to share one compile.

A jitted solver program is specialized to its tensor shapes, so a fleet
of clusters whose packed problems all differ by a few lanes would each
pay a cold XLA compile and could never share a batch. The service
therefore rounds every incoming problem UP to a shape *bucket* — each of
C (candidate lanes), K (pod slots) and S (spot nodes) to the next power
of two (floored at the TPU sublane width) — and pads the problem into
it. Two consequences, both load-bearing:

- tenants in the same bucket stack into ONE batched solve under ONE
  compiled program (parallel/tenant_batch.py), with per-tenant lane
  blocks along the leading axis;
- the number of distinct compiles is O(log C · log K · log S) for the
  whole fleet, the same recompile-bounding discipline as the delta
  scatter's power-of-two pads (planner/solver_planner._pad_pow2).

Padding is semantics-free by the same invariant the in-process
high-water padding relies on: padded candidate lanes have
``cand_valid=False`` (never feasible, never selected), padded pod slots
have ``slot_valid=False`` (place nothing), and padded spot rows have
``spot_ok=False`` with zero capacity (fit nowhere). A tenant's selection
out of the padded problem is therefore identical to its unpadded solve —
``make serve-smoke`` pins this bit-for-bit against solo in-process plans.

Batch sizing is an HBM question, answered by the same estimator the
auto-shard dispatch trusts (solver/memory.estimate_union_hbm_breakdown):
one tenant's program at the bucket shapes costs ``per_tenant_bytes``;
the batch caps at ``budget // per_tenant_bytes`` tenants so a full batch
provably fits the device before anything is compiled or stacked.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.solver import memory

# Floors match the packer's _pad_dim minimum (multiples of 8 below the
# 128-lane width) so a tiny tenant's bucket is not pathologically small.
MIN_DIM = 8


def _pow2_at_least(n: int, floor: int = MIN_DIM) -> int:
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


class Bucket(NamedTuple):
    """One shared-compile shape class. R/W/A are carried unrounded:
    they come from the config's resource axes and the constraint
    interning and are already tiny and stable."""

    C: int
    K: int
    S: int
    R: int
    W: int
    A: int

    @property
    def key(self) -> str:
        return f"C{self.C}xK{self.K}xS{self.S}xR{self.R}xW{self.W}xA{self.A}"


def bucket_for(packed: PackedCluster) -> Bucket:
    C, K, S, R, W, A = memory.packed_shapes(packed)
    return Bucket(
        C=_pow2_at_least(C), K=_pow2_at_least(K), S=_pow2_at_least(S),
        R=R, W=W, A=A,
    )


def _pad_leading(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad axis 0 to length n with zeros (False for bool)."""
    if arr.shape[0] == n:
        return arr
    out = np.zeros((n,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def pad_to_bucket(packed: PackedCluster, b: Bucket) -> PackedCluster:
    """Pad a problem into its bucket. Pads are inert by construction:
    invalid lanes, empty slots, not-ok zero-capacity spots."""
    C, K, S, R, W, A = memory.packed_shapes(packed)
    if (R, W, A) != (b.R, b.W, b.A):
        raise ValueError(
            f"packed (R={R}, W={W}, A={A}) does not belong to bucket {b.key}"
        )
    if C > b.C or K > b.K or S > b.S:
        raise ValueError(
            f"packed (C={C}, K={K}, S={S}) exceeds bucket {b.key}"
        )

    def pad_slots(arr):
        # [C, K, ...] -> [b.C, b.K, ...]: K pads first (middle axis),
        # then lanes
        if arr.shape[1] != b.K:
            out = np.zeros((arr.shape[0], b.K) + arr.shape[2:], arr.dtype)
            out[:, : arr.shape[1]] = arr
            arr = out
        return _pad_leading(arr, b.C)

    return PackedCluster(
        slot_req=pad_slots(packed.slot_req),
        slot_valid=pad_slots(packed.slot_valid),
        slot_tol=pad_slots(packed.slot_tol),
        slot_aff=pad_slots(packed.slot_aff),
        cand_valid=_pad_leading(packed.cand_valid, b.C),
        spot_free=_pad_leading(packed.spot_free, b.S),
        spot_count=_pad_leading(packed.spot_count, b.S),
        spot_max_pods=_pad_leading(packed.spot_max_pods, b.S),
        spot_taints=_pad_leading(packed.spot_taints, b.S),
        spot_ok=_pad_leading(packed.spot_ok, b.S),
        spot_aff=_pad_leading(packed.spot_aff, b.S),
    )


def stack_bucket(problems: List[PackedCluster], b: Bucket) -> PackedCluster:
    """Stack already-padded problems along a new leading tenant axis —
    the [T, ...] pytree parallel/tenant_batch.plan_tenants_batched
    consumes."""
    return PackedCluster(
        *(
            np.stack([getattr(p, f) for p in problems])
            for f in PackedCluster._fields
        )
    )


def per_tenant_hbm_bytes(
    b: Bucket, *, repair_spot_chunks: int = 1
) -> int:
    """One tenant's estimated solver footprint at the bucket shapes
    (solver/memory's union-program model — the batch dimension
    multiplies it linearly; lanes across tenants share nothing)."""
    return memory.estimate_union_hbm_bytes(
        b.C, b.K, b.S, b.R, b.W, b.A, repair_spot_chunks=repair_spot_chunks
    )


def max_batch_tenants(
    b: Bucket,
    *,
    budget_bytes: int = 0,
    repair_spot_chunks: int = 1,
    cap: int = 64,
) -> int:
    """How many tenants may share one batched solve at these shapes:
    ``budget // per-tenant estimate``, floored at 1 (a single tenant
    that alone exceeds the budget is the auto-shard tiers' problem, not
    the batcher's), capped to keep worst-case batch latency bounded."""
    budget = budget_bytes or memory.device_hbm_budget()
    per = per_tenant_hbm_bytes(b, repair_spot_chunks=repair_spot_chunks)
    return max(1, min(int(cap), budget // max(per, 1)))
