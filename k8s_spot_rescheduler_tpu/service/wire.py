"""The planner service wire protocol: versioned, framed, binary.

The multi-tenant planner service (service/server.py) receives whole
``PackedCluster`` problems from per-cluster agents and returns the tiny
selection vector — tensors in both directions, never Kubernetes JSON
(the agent already packed; re-encoding 30 MB of objects would put the
decode cost the columnar path removed back on every tick). This module
is that boundary's byte format, shared by agent and server and pinned
byte-for-byte by tests/test_wire_fixtures.py.

Layout (all integers little-endian)::

    header   = MAGIC "KSRW" | u8 version | u8 kind | u16 frame_count
    frame    = u16 name_len | name utf-8 | u8 dtype_code | u8 ndim
             | u32 dim * ndim | u64 payload_len | payload (C-order)

Frames are dtype/shape-tagged numpy buffers; strings (tenant ids, error
text) travel as uint8 frames of utf-8 bytes. There is deliberately NO
pickle, NO schema negotiation and NO self-describing container format:
the decoder admits exactly the dtype table below and the message kinds
below, and anything else is a typed :class:`WireError` — a planner
service is a write-capable network surface and must not grow an
arbitrary-deserialization hole.

Version bump policy
-------------------
``WIRE_VERSION`` is a single byte covering the whole message layout.
Bump it when (and only when) an already-shipped frame changes meaning:
field renamed, dtype changed, header reshaped, kind renumbered. ADDING
a new frame name or a new message kind is backward compatible (decoders
ignore unknown frame names; unknown KINDS are an error) and must NOT
bump the version. A decoder seeing a version it does not speak raises
:class:`WireVersionError` — a typed error the server answers with a
clean 400, never a crash — so a mixed-version fleet fails request by
request, loudly, instead of corrupting tensors. Every bump must update
the byte-golden fixtures in tests/test_wire_fixtures.py in the same
commit; the goldens exist precisely so this file cannot drift silently.

Version history
---------------
- **1** — the original PLAN_REQUEST / PLAN_REPLY / PACKED_DELTA / ERROR
  layout. Still fully decodable (``SUPPORTED_VERSIONS``): a version-1
  payload from an un-upgraded agent plans exactly as before, and the
  service answers it in version 1 (the reply mirrors the request's
  version), so a mixed-version fleet interoperates without flag days.
- **2** — tick tracing (docs/OBSERVABILITY.md): PLAN_REQUEST may carry
  an optional ``trace_id`` frame (the agent's tick trace ID, also sent
  as ``X-Trace-Id``), and PLAN_REPLY may carry three optional span
  frames (``span_names``/``span_t0_ms``/``span_dur_ms``) returning the
  server-side spans — queue-wait, batch assembly, solve, ... — the
  agent grafts into its tick trace. All trace frames are optional:
  their absence is a valid version-2 message. The bump (rather than
  frame addition alone) marks the reply-mirroring contract: a v2-aware
  peer may rely on span frames surviving the round trip.
- **3** — drain schedules (solver/schedule.py): PLAN_REQUEST may carry
  an optional ``schedule_horizon`` frame asking the service to answer
  with a whole drain-to-exhaustion schedule, and a NEW reply kind
  ``KIND_PLAN_SCHEDULE`` carries it (one ``steps`` int32
  ``[horizon, 3+K]`` matrix — the same layout the in-process device
  fetch returns — plus the PLAN_REPLY batch telemetry and optional v2
  span frames). Per the policy above, the new kind and frame alone
  would not bump the version; the bump marks the REPLY-KIND contract:
  only a version-3 request may be answered with KIND_PLAN_SCHEDULE
  (the reply mirrors the request's version, so v1/v2 agents can never
  receive a kind they do not decode), and a v3-aware peer may rely on
  the service honoring ``schedule_horizon``.
- **4** — the delta wire (docs/ROBUSTNESS.md "Wire anti-entropy"):
  ``KIND_PACKED_DELTA`` — shipped by nothing before this version —
  becomes a REAL plan request: it must carry ``base_fingerprint`` (the
  pack the delta diffs from), ``new_fingerprint`` (the pack it
  produces) and ``delta_digest`` (sha256 over both fingerprints and
  every delta tensor — verified at decode, so a corrupted-in-flight
  delta is a typed error, never wrong tensors), and may carry the v2
  ``trace_id``. PLAN_REQUEST may carry an optional
  ``pack_fingerprint`` frame seeding the service's tenant cache. A NEW
  reply kind ``KIND_RESYNC`` answers a delta whose base the service
  cannot honor (restart, eviction, fingerprint mismatch, any
  decode/apply anomaly): a ``cause`` string demanding one full-pack
  resync. The bump marks the reply-kind contract once more: only a
  version-4 delta request may be answered with KIND_RESYNC, and a
  pre-v4 KIND_PACKED_DELTA (which nothing ever sent) is refused at
  decode — it carries no fingerprints, so it can neither be verified
  nor answered with a resync the sender would decode.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

MAGIC = b"KSRW"
WIRE_VERSION = 4
SUPPORTED_VERSIONS = (1, 2, 3, 4)

# message kinds (u8). New kinds append; renumbering is a version bump.
KIND_PLAN_REQUEST = 1  # agent -> service: tenant + PackedCluster
KIND_PLAN_REPLY = 2  # service -> agent: selection + batch telemetry
KIND_PACKED_DELTA = 3  # agent -> service: tenant + PackedDelta (v4)
KIND_ERROR = 4  # service -> agent: typed error text
KIND_PLAN_SCHEDULE = 5  # service -> agent: whole drain schedule (v3)
KIND_RESYNC = 6  # service -> agent: delta base unusable; full pack (v4)

# dtype table (u8 code <-> numpy dtype). Append-only; reordering is a
# version bump. bool travels as its own code (1 byte/element) so the
# decoder can hand back real bool arrays, not u8 lookalikes.
_DTYPE_CODES: Tuple[np.dtype, ...] = tuple(
    np.dtype(d) for d in ("<f4", "<i4", "<i8", "<u4", "u1", "?")
)
_CODE_OF: Dict[np.dtype, int] = {d: i for i, d in enumerate(_DTYPE_CODES)}

_HEADER = struct.Struct("<4sBBH")
_FRAME_HEAD = struct.Struct("<H")
_FRAME_TAG = struct.Struct("<BB")
_DIM = struct.Struct("<I")
_PAYLEN = struct.Struct("<Q")

# hard ceilings a hostile or corrupt message cannot talk past: the
# decoder rejects before allocating (ndim is bounded by the tensor
# model; 255 frames is far above any real message's dozen)
MAX_NDIM = 8
MAX_FRAMES = 255


class WireError(ValueError):
    """Malformed or out-of-contract wire bytes (typed; never a crash)."""


class WireVersionError(WireError):
    """The message speaks a protocol version this decoder does not."""


def _encode_frame(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    if arr.dtype.byteorder == ">":
        # actually swap a big-endian input to the wire order — mapping
        # the dtype code alone would tag byte-reversed payloads as
        # little-endian, silent corruption on the far side
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    code = _CODE_OF.get(arr.dtype)
    if code is None:
        raise WireError(f"dtype {arr.dtype} has no wire code (frame {name!r})")
    payload = np.ascontiguousarray(arr).tobytes()
    nb = name.encode("utf-8")
    parts = [
        _FRAME_HEAD.pack(len(nb)),
        nb,
        _FRAME_TAG.pack(code, arr.ndim),
    ]
    parts.extend(_DIM.pack(d) for d in arr.shape)
    parts.append(_PAYLEN.pack(len(payload)))
    parts.append(payload)
    return b"".join(parts)


def encode_frames(
    kind: int,
    frames: List[Tuple[str, np.ndarray]],
    version: Optional[int] = None,
) -> bytes:
    """One wire message: header + the given (name, array) frames, in
    the given order (the order is part of the byte-golden contract).
    ``version`` defaults to ``WIRE_VERSION``; the server passes the
    REQUEST's version so an un-upgraded agent can decode its reply."""
    version = WIRE_VERSION if version is None else int(version)
    if version not in SUPPORTED_VERSIONS:
        raise WireError(f"cannot encode unsupported wire version {version}")
    if len(frames) > MAX_FRAMES:
        raise WireError(f"{len(frames)} frames exceeds the {MAX_FRAMES} cap")
    out = [_HEADER.pack(MAGIC, version, kind, len(frames))]
    out.extend(_encode_frame(n, a) for n, a in frames)
    return b"".join(out)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int, what: str) -> bytes:
        if self.pos + n > len(self.data):
            raise WireError(
                f"truncated message: {what} needs {n} bytes, "
                f"{len(self.data) - self.pos} remain"
            )
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out


def decode_frames(data: bytes) -> Tuple[int, Dict[str, np.ndarray]]:
    """(kind, {name: array}) or a typed WireError; see
    :func:`decode_frames_v` for the variant that also reports the
    message's protocol version."""
    _, kind, frames = decode_frames_v(data)
    return kind, frames


def decode_frames_v(data: bytes) -> Tuple[int, int, Dict[str, np.ndarray]]:
    """(version, kind, {name: array}) or a typed WireError. Arrays are
    zero-copy views into ``data`` (read-only) — the solve path only
    reads them. Every version in ``SUPPORTED_VERSIONS`` decodes (a
    version-1 payload from an un-upgraded agent simply carries no trace
    frames); anything else is a clean :class:`WireVersionError`."""
    r = _Reader(bytes(data) if isinstance(data, (bytearray, memoryview)) else data)
    magic, version, kind, n_frames = _HEADER.unpack(r.take(_HEADER.size, "header"))
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (not a planner wire message)")
    if version not in SUPPORTED_VERSIONS:
        raise WireVersionError(
            f"wire version {version} not supported (this build speaks "
            f"{sorted(SUPPORTED_VERSIONS)}; see the version bump policy "
            "in service/wire.py)"
        )
    if kind not in (
        KIND_PLAN_REQUEST, KIND_PLAN_REPLY, KIND_PACKED_DELTA, KIND_ERROR,
        KIND_PLAN_SCHEDULE, KIND_RESYNC,
    ):
        raise WireError(f"unknown message kind {kind}")
    if n_frames > MAX_FRAMES:
        raise WireError(f"{n_frames} frames exceeds the {MAX_FRAMES} cap")
    frames: Dict[str, np.ndarray] = {}
    for _ in range(n_frames):
        (name_len,) = _FRAME_HEAD.unpack(r.take(_FRAME_HEAD.size, "frame name length"))
        try:
            name = r.take(name_len, "frame name").decode("utf-8")
        except UnicodeDecodeError as err:
            # found by the fuzz corpus: a corrupted name byte must be a
            # typed WireError (clean 400), not a raw UnicodeDecodeError
            raise WireError(f"frame name is not valid utf-8: {err}") from err
        if name in frames:
            raise WireError(f"duplicate frame {name!r}")
        code, ndim = _FRAME_TAG.unpack(r.take(_FRAME_TAG.size, "frame tag"))
        if code >= len(_DTYPE_CODES):
            raise WireError(f"unknown dtype code {code} (frame {name!r})")
        if ndim > MAX_NDIM:
            raise WireError(f"frame {name!r} rank {ndim} exceeds {MAX_NDIM}")
        shape = tuple(
            _DIM.unpack(r.take(_DIM.size, f"{name} dim"))[0] for _ in range(ndim)
        )
        (paylen,) = _PAYLEN.unpack(r.take(_PAYLEN.size, "payload length"))
        dtype = _DTYPE_CODES[code]
        # exact Python-int arithmetic: an np.prod here would wrap on
        # crafted u32 dims and let paylen=0 sail past the check
        want = dtype.itemsize
        for d in shape:
            want *= int(d)
        if paylen != want:
            raise WireError(
                f"frame {name!r}: payload {paylen} bytes != shape "
                f"{shape} x {dtype} = {want}"
            )
        payload = r.take(paylen, f"{name} payload")
        frames[name] = np.frombuffer(payload, dtype).reshape(shape)
    if r.pos != len(r.data):
        raise WireError(f"{len(r.data) - r.pos} trailing bytes after last frame")
    return version, kind, frames


# ---------------------------------------------------------------------------
# PackedCluster / PackedDelta messages

# the wire dtype contract per tensor field — the same pack contract the
# PackedCluster docstring pins; the decoder REJECTS a frame whose dtype
# disagrees instead of silently casting (a u8-cast bool mask would solve
# the wrong problem without erroring anywhere downstream)
_PACKED_DTYPES = {
    "slot_req": np.dtype("<f4"),
    "slot_valid": np.dtype("?"),
    "slot_tol": np.dtype("<u4"),
    "slot_aff": np.dtype("<u4"),
    "cand_valid": np.dtype("?"),
    "spot_free": np.dtype("<f4"),
    "spot_count": np.dtype("<i4"),
    "spot_max_pods": np.dtype("<i4"),
    "spot_taints": np.dtype("<u4"),
    "spot_ok": np.dtype("?"),
    "spot_aff": np.dtype("<u4"),
}

_DELTA_DTYPES = {
    "lanes": np.dtype("<i4"),
    "lane_slot_req": np.dtype("<f4"),
    "lane_slot_valid": np.dtype("?"),
    "lane_slot_tol": np.dtype("<u4"),
    "lane_slot_aff": np.dtype("<u4"),
    "cand_rows": np.dtype("<i4"),
    "cand_valid": np.dtype("?"),
    "spot_rows": np.dtype("<i4"),
    "spot_free": np.dtype("<f4"),
    "spot_count": np.dtype("<i4"),
    "spot_max_pods": np.dtype("<i4"),
    "spot_taints": np.dtype("<u4"),
    "spot_ok": np.dtype("?"),
    "spot_aff": np.dtype("<u4"),
}

_PACKED_RANKS = {
    "slot_req": 3, "slot_valid": 2, "slot_tol": 3, "slot_aff": 3,
    "cand_valid": 1, "spot_free": 2, "spot_count": 1, "spot_max_pods": 1,
    "spot_taints": 2, "spot_ok": 1, "spot_aff": 2,
}


def _str_frame(s: str) -> np.ndarray:
    return np.frombuffer(s.encode("utf-8"), np.uint8)


def _frame_str(arr: np.ndarray, what: str) -> str:
    try:
        return bytes(np.asarray(arr, np.uint8)).decode("utf-8")
    except UnicodeDecodeError as err:
        raise WireError(f"{what} is not valid utf-8: {err}") from err


def encode_plan_request(
    tenant: str,
    packed,
    trace_id: str = "",
    version: Optional[int] = None,
    schedule_horizon: int = 0,
    pack_fingerprint: str = "",
) -> bytes:
    """Agent -> service: one tenant's full packed problem, optionally
    stamped with the agent's tick trace ID (wire v2; omitted when empty
    or when encoding a version-1 message for an old server), an
    optional ``schedule_horizon`` (wire v3: ask for a whole drain
    schedule back — KIND_PLAN_SCHEDULE — instead of a single plan),
    and an optional ``pack_fingerprint`` (wire v4: seed the service's
    tenant cache so the NEXT tick may ship a delta)."""
    version = WIRE_VERSION if version is None else int(version)
    frames: List[Tuple[str, np.ndarray]] = [("tenant", _str_frame(tenant))]
    frames.extend((f, getattr(packed, f)) for f in type(packed)._fields)
    if trace_id and version >= 2:
        frames.append(("trace_id", _str_frame(trace_id)))
    if schedule_horizon > 0 and version >= 3:
        frames.append(
            ("schedule_horizon", np.array([schedule_horizon], "<i4"))
        )
    if pack_fingerprint and version >= 4:
        frames.append(("pack_fingerprint", _str_frame(pack_fingerprint)))
    return encode_frames(KIND_PLAN_REQUEST, frames, version=version)


def _check_tensor_fields(frames, dtypes, ranks, what):
    out = {}
    for name, dtype in dtypes.items():
        arr = frames.get(name)
        if arr is None:
            raise WireError(f"{what} missing tensor frame {name!r}")
        if arr.dtype != dtype:
            raise WireError(
                f"{what} frame {name!r}: dtype {arr.dtype} != contract {dtype}"
            )
        rank = ranks.get(name)
        if rank is not None and arr.ndim != rank:
            raise WireError(
                f"{what} frame {name!r}: rank {arr.ndim} != contract {rank}"
            )
        out[name] = arr
    return out


class PlanRequest(NamedTuple):
    """A fully-decoded plan request: its protocol version (the reply
    mirrors it), tenant, problem tensors, the optional trace ID, the
    optional drain-schedule horizon (0 = an ordinary single-plan
    request; > 0 = answer with KIND_PLAN_SCHEDULE, wire v3), and the
    optional pack fingerprint (wire v4: seed the tenant cache; empty =
    the agent does not speak the delta wire)."""

    version: int
    tenant: str
    packed: object  # PackedCluster
    trace_id: str
    schedule_horizon: int = 0
    pack_fingerprint: str = ""


def decode_plan_request(data: bytes):
    """(tenant, PackedCluster) from KIND_PLAN_REQUEST bytes; see
    :func:`decode_plan_request_ex` for version + trace metadata."""
    req = decode_plan_request_ex(data)
    return req.tenant, req.packed


def decode_plan_request_ex(data: bytes) -> PlanRequest:
    """Full decode of KIND_PLAN_REQUEST bytes; every tensor's dtype and
    rank is checked against the pack contract, and the cross-field
    shape consistency (shared C/K/S/R/W/A dims) is verified — a request
    that decodes is safe to pad, stack and solve. The ``trace_id`` is
    empty for version-1 payloads (or when the agent sent none)."""
    from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster

    version, kind, frames = decode_frames_v(data)
    if kind != KIND_PLAN_REQUEST:
        raise WireError(f"expected PLAN_REQUEST, got kind {kind}")
    tenant = _frame_str(frames.get("tenant", np.zeros(0, np.uint8)), "tenant id")
    if not tenant:
        raise WireError("plan request carries no tenant id")
    trace_id = ""
    if "trace_id" in frames:
        trace_id = _frame_str(frames["trace_id"], "trace id")
    schedule_horizon = 0
    if "schedule_horizon" in frames:
        if version < 3:
            # reject at DECODE (clean 400), not after a batch solve:
            # only a v3 request may be answered with KIND_PLAN_SCHEDULE
            # (the version-bump contract above), so a pre-v3 request
            # carrying the frame is out of contract, and honoring it
            # would burn a whole schedule solve only to fail at encode
            raise WireError(
                f"schedule_horizon frame requires wire version >= 3 "
                f"(request is version {version})"
            )
        schedule_horizon = int(
            _scalar(frames, "schedule_horizon", "<i4", "plan request")
        )
        if schedule_horizon < 1:
            raise WireError(
                f"plan request schedule_horizon {schedule_horizon} "
                "must be >= 1 when present"
            )
    pack_fingerprint = ""
    if "pack_fingerprint" in frames:
        if version < 4:
            # same contract as schedule_horizon above: the frame's
            # meaning (cache seeding + the KIND_RESYNC answer path) is
            # a v4 contract; a pre-v4 request carrying it is out of
            # contract and refused at decode (clean 400)
            raise WireError(
                f"pack_fingerprint frame requires wire version >= 4 "
                f"(request is version {version})"
            )
        pack_fingerprint = _frame_str(
            frames["pack_fingerprint"], "pack fingerprint"
        )
    t = _check_tensor_fields(frames, _PACKED_DTYPES, _PACKED_RANKS, "plan request")
    C, K, R = t["slot_req"].shape
    S = t["spot_free"].shape[0]
    W = t["spot_taints"].shape[1]
    A = t["spot_aff"].shape[1]
    expect = {
        "slot_valid": (C, K), "slot_tol": (C, K, W), "slot_aff": (C, K, A),
        "cand_valid": (C,), "spot_free": (S, R), "spot_count": (S,),
        "spot_max_pods": (S,), "spot_taints": (S, W), "spot_ok": (S,),
        "spot_aff": (S, A),
    }
    for name, shape in expect.items():
        if t[name].shape != shape:
            raise WireError(
                f"plan request frame {name!r}: shape {t[name].shape} "
                f"inconsistent with (C={C}, K={K}, S={S}, R={R}, W={W}, "
                f"A={A}) — expected {shape}"
            )
    return PlanRequest(
        version, tenant, PackedCluster(**t), trace_id, schedule_horizon,
        pack_fingerprint,
    )


def delta_digest(base_fingerprint: str, new_fingerprint: str, delta) -> str:
    """Integrity digest of one delta message: sha256 over both
    fingerprints and every delta tensor's shape + little-endian bytes.
    Computed by the encoder and REVERIFIED at decode — a bit flipped
    anywhere in the fingerprints or the churn payload is a typed
    :class:`WireError` (the service answers with a resync demand),
    never silently-wrong tensors scattered into a tenant's cached
    state. O(churn) to compute, like the delta itself. The per-tensor
    hash step is models/columnar.update_tensor_digest — the SAME
    routine behind pack_fingerprint, so the two sides of the
    anti-entropy protocol can never drift apart."""
    from k8s_spot_rescheduler_tpu.models.columnar import (
        update_tensor_digest,
    )

    h = hashlib.sha256()
    h.update(base_fingerprint.encode("utf-8"))
    h.update(new_fingerprint.encode("utf-8"))
    for f in type(delta)._fields:
        update_tensor_digest(h, f, getattr(delta, f))
    return h.hexdigest()


def encode_packed_delta(
    tenant: str,
    delta,
    version: Optional[int] = None,
    *,
    base_fingerprint: str = "",
    new_fingerprint: str = "",
    trace_id: str = "",
) -> bytes:
    """Agent -> service: a churn-proportional PackedDelta — since wire
    v4 a real plan request carrying the base/new pack fingerprints and
    an integrity digest (see :func:`delta_digest`). Encoding for a
    pre-v4 version drops the fingerprint/digest/trace frames (the
    additive-bump proof: pre-v4 bytes stay exactly what those builds
    shipped); encoding v4 REQUIRES both fingerprints — a v4 delta
    without them could be neither verified nor safely applied."""
    version = WIRE_VERSION if version is None else int(version)
    frames: List[Tuple[str, np.ndarray]] = [("tenant", _str_frame(tenant))]
    frames.extend((f, getattr(delta, f)) for f in type(delta)._fields)
    if version >= 4:
        if not base_fingerprint or not new_fingerprint:
            raise WireError(
                "a version-4 packed delta requires base_fingerprint "
                "and new_fingerprint"
            )
        frames.append(("base_fingerprint", _str_frame(base_fingerprint)))
        frames.append(("new_fingerprint", _str_frame(new_fingerprint)))
        frames.append((
            "delta_digest",
            _str_frame(
                delta_digest(base_fingerprint, new_fingerprint, delta)
            ),
        ))
        if trace_id:
            frames.append(("trace_id", _str_frame(trace_id)))
    return encode_frames(KIND_PACKED_DELTA, frames, version=version)


class DeltaRequest(NamedTuple):
    """A fully-decoded (and digest-verified) delta plan request."""

    version: int
    tenant: str
    delta: object  # PackedDelta
    base_fingerprint: str
    new_fingerprint: str
    trace_id: str = ""


def decode_packed_delta(data: bytes):
    """(tenant, PackedDelta) from KIND_PACKED_DELTA bytes; see
    :func:`decode_packed_delta_ex` for the fingerprints."""
    req = decode_packed_delta_ex(data)
    return req.tenant, req.delta


def decode_packed_delta_ex(data: bytes) -> DeltaRequest:
    """Full decode of KIND_PACKED_DELTA bytes. Requires wire version
    >= 4 (nothing ever sent the kind before v4, and a pre-v4 delta
    carries no fingerprints — unverifiable, and its sender could not
    decode the KIND_RESYNC answer); verifies the delta digest, so a
    message that decodes is bit-exact as sent."""
    from k8s_spot_rescheduler_tpu.models.columnar import PackedDelta

    version, kind, frames = decode_frames_v(data)
    if kind != KIND_PACKED_DELTA:
        raise WireError(f"expected PACKED_DELTA, got kind {kind}")
    if version < 4:
        raise WireError(
            f"packed delta over the wire requires version >= 4 "
            f"(request is version {version}; pre-v4 builds never sent "
            "this kind)"
        )
    tenant = _frame_str(frames.get("tenant", np.zeros(0, np.uint8)), "tenant id")
    if not tenant:
        raise WireError("packed delta carries no tenant id")
    base_fp = _frame_str(
        frames.get("base_fingerprint", np.zeros(0, np.uint8)),
        "base fingerprint",
    )
    new_fp = _frame_str(
        frames.get("new_fingerprint", np.zeros(0, np.uint8)),
        "new fingerprint",
    )
    digest = _frame_str(
        frames.get("delta_digest", np.zeros(0, np.uint8)), "delta digest"
    )
    if not base_fp or not new_fp or not digest:
        raise WireError(
            "packed delta missing base_fingerprint / new_fingerprint / "
            "delta_digest frame(s)"
        )
    trace_id = ""
    if "trace_id" in frames:
        trace_id = _frame_str(frames["trace_id"], "trace id")
    t = _check_tensor_fields(frames, _DELTA_DTYPES, {}, "packed delta")
    for sec in (
        ("lanes", "lane_slot_req", "lane_slot_valid", "lane_slot_tol",
         "lane_slot_aff"),
        ("cand_rows", "cand_valid"),
        ("spot_rows", "spot_free", "spot_count", "spot_max_pods",
         "spot_taints", "spot_ok", "spot_aff"),
    ):
        n = t[sec[0]].shape[0]
        for name in sec[1:]:
            if t[name].shape[0] != n:
                raise WireError(
                    f"packed delta frame {name!r}: leading dim "
                    f"{t[name].shape[0]} != section length {n}"
                )
    delta = PackedDelta(**t)
    want = delta_digest(base_fp, new_fp, delta)
    if digest != want:
        raise WireError(
            "packed delta digest mismatch (message corrupted in "
            "flight); a full-pack resync is required"
        )
    return DeltaRequest(version, tenant, delta, base_fp, new_fp, trace_id)


class ResyncDemand(NamedTuple):
    """Service -> agent (KIND_RESYNC, v4): the delta's base state is
    unusable server-side — restart, cache eviction, fingerprint
    mismatch, or a decode/apply anomaly. ``cause`` says which; the
    agent answers with exactly one full-pack request."""

    cause: str


def encode_resync(cause: str, version: Optional[int] = None) -> bytes:
    version = WIRE_VERSION if version is None else int(version)
    if version < 4:
        raise WireError(
            f"KIND_RESYNC requires wire version >= 4, got {version} "
            "(a pre-v4 peer never sent a delta)"
        )
    return encode_frames(
        KIND_RESYNC, [("cause", _str_frame(cause))], version=version
    )


def decode_resync(data: bytes) -> ResyncDemand:
    kind, frames = decode_frames(data)
    if kind != KIND_RESYNC:
        raise WireError(f"expected RESYNC, got kind {kind}")
    return ResyncDemand(
        _frame_str(frames.get("cause", np.zeros(0, np.uint8)), "resync cause")
    )


def decode_plan_or_resync(data: bytes):
    """The decoder a delta-shipping agent applies to a delta request's
    answer: a :class:`PlanReply` (the delta applied and rode a batch)
    or a :class:`ResyncDemand` (send one full pack). Anything else is
    a typed WireError like every other out-of-contract reply."""
    kind, frames = decode_frames(data)
    if kind == KIND_RESYNC:
        return ResyncDemand(
            _frame_str(
                frames.get("cause", np.zeros(0, np.uint8)), "resync cause"
            )
        )
    return decode_plan_reply(data)


# ---------------------------------------------------------------------------
# plan reply

class PlanReply(NamedTuple):
    """The selection + batch telemetry one plan request gets back —
    deliberately the same few hundred bytes the in-process device
    boundary fetches (solver/select.Selection), plus what the agent's
    metrics need to see about the batch it rode in. ``spans`` (wire v2)
    carries the server-side trace spans as flat
    ``(name, t0_ms, dur_ms)`` tuples the agent grafts into its tick
    trace; empty on version-1 replies."""

    found: bool
    index: int
    n_feasible: int
    row: np.ndarray  # int32 [K]
    solve_ms: float  # the batched device solve, amortized share
    queue_wait_ms: float  # this request's time in the tenant queue
    batch_lanes: int  # candidate lanes in the batch it rode in
    batch_tenants: int  # tenant lane-blocks sharing that batch
    spans: Tuple[Tuple[str, float, float], ...] = ()


def encode_plan_reply(reply: PlanReply, version: Optional[int] = None) -> bytes:
    version = WIRE_VERSION if version is None else int(version)
    frames = [
        ("found", np.array([reply.found], np.uint8)),
        ("index", np.array([reply.index], "<i4")),
        ("n_feasible", np.array([reply.n_feasible], "<i4")),
        ("row", np.ascontiguousarray(np.asarray(reply.row, "<i4"))),
        ("solve_ms", np.array([reply.solve_ms], "<f4")),
        ("queue_wait_ms", np.array([reply.queue_wait_ms], "<f4")),
        ("batch_lanes", np.array([reply.batch_lanes], "<i4")),
        ("batch_tenants", np.array([reply.batch_tenants], "<i4")),
    ]
    if reply.spans and version >= 2:
        # the compact server-span block: newline-joined names + two
        # parallel f4 vectors. Names come from utils/tracing.SPAN_NAMES
        # (never cluster-derived strings) so the frame stays both small
        # and redaction-clean.
        names = [s[0] for s in reply.spans]
        if any("\n" in n for n in names):
            raise WireError("span names must not contain newlines")
        frames.append(("span_names", _str_frame("\n".join(names))))
        frames.append(
            ("span_t0_ms", np.asarray([s[1] for s in reply.spans], "<f4"))
        )
        frames.append(
            ("span_dur_ms", np.asarray([s[2] for s in reply.spans], "<f4"))
        )
    return encode_frames(KIND_PLAN_REPLY, frames, version=version)


def _scalar(frames, name, dtype, what):
    arr = frames.get(name)
    if arr is None or arr.dtype != np.dtype(dtype) or arr.size != 1:
        raise WireError(f"{what} frame {name!r} missing or malformed")
    return arr.reshape(())[()]


def _decode_reply_spans(frames) -> Tuple[Tuple[str, float, float], ...]:
    """The optional server-span block of a v2 reply; () when absent.
    Malformed span frames are a WireError like any other frame — a
    reply that claims spans must carry a coherent block."""
    names_frame = frames.get("span_names")
    if names_frame is None:
        return ()
    names = _frame_str(names_frame, "span names").split("\n")
    t0 = frames.get("span_t0_ms")
    dur = frames.get("span_dur_ms")
    for name, arr in (("span_t0_ms", t0), ("span_dur_ms", dur)):
        if arr is None or arr.dtype != np.dtype("<f4") or arr.ndim != 1 \
                or arr.size != len(names):
            raise WireError(f"plan reply frame {name!r} missing or malformed")
    return tuple(
        (names[i], float(t0[i]), float(dur[i])) for i in range(len(names))
    )


def decode_plan_reply(data: bytes) -> PlanReply:
    kind, frames = decode_frames(data)
    if kind == KIND_ERROR:
        raise WireError(
            "service error: "
            + _frame_str(frames.get("message", np.zeros(0, np.uint8)), "error")
        )
    if kind != KIND_PLAN_REPLY:
        raise WireError(f"expected PLAN_REPLY, got kind {kind}")
    row = frames.get("row")
    if row is None or row.dtype != np.dtype("<i4") or row.ndim != 1:
        raise WireError("plan reply frame 'row' missing or malformed")
    return PlanReply(
        found=bool(_scalar(frames, "found", "u1", "plan reply")),
        index=int(_scalar(frames, "index", "<i4", "plan reply")),
        n_feasible=int(_scalar(frames, "n_feasible", "<i4", "plan reply")),
        row=row,
        solve_ms=float(_scalar(frames, "solve_ms", "<f4", "plan reply")),
        queue_wait_ms=float(
            _scalar(frames, "queue_wait_ms", "<f4", "plan reply")
        ),
        batch_lanes=int(_scalar(frames, "batch_lanes", "<i4", "plan reply")),
        batch_tenants=int(
            _scalar(frames, "batch_tenants", "<i4", "plan reply")
        ),
        spans=_decode_reply_spans(frames),
    )


# ---------------------------------------------------------------------------
# drain-schedule reply (wire v3)

class PlanScheduleReply(NamedTuple):
    """A whole drain schedule for one tenant (KIND_PLAN_SCHEDULE):
    ``steps`` is the int32 ``[horizon, 3 + K]`` matrix the in-process
    device fetch returns (per step ``idx | found | n_feasible | row``;
    decode with ``solver/schedule.decode_schedule``), plus the same
    batch telemetry and optional server-span block a PLAN_REPLY
    carries. Only ever sent in answer to a version-3 request that
    asked via ``schedule_horizon`` (the version-bump contract)."""

    steps: np.ndarray  # int32 [H, 3 + K]
    solve_ms: float
    queue_wait_ms: float
    batch_lanes: int
    batch_tenants: int
    spans: Tuple[Tuple[str, float, float], ...] = ()


def encode_plan_schedule_reply(
    reply: PlanScheduleReply, version: Optional[int] = None
) -> bytes:
    version = WIRE_VERSION if version is None else int(version)
    if version < 3:
        raise WireError(
            f"KIND_PLAN_SCHEDULE requires wire version >= 3, got {version} "
            "(a pre-v3 peer never asked for a schedule)"
        )
    steps = np.ascontiguousarray(np.asarray(reply.steps, "<i4"))
    if steps.ndim != 2 or steps.shape[1] < 3:
        raise WireError(
            f"schedule steps matrix must be [H, 3+K], got {steps.shape}"
        )
    frames = [
        ("steps", steps),
        ("solve_ms", np.array([reply.solve_ms], "<f4")),
        ("queue_wait_ms", np.array([reply.queue_wait_ms], "<f4")),
        ("batch_lanes", np.array([reply.batch_lanes], "<i4")),
        ("batch_tenants", np.array([reply.batch_tenants], "<i4")),
    ]
    if reply.spans:
        names = [s[0] for s in reply.spans]
        if any("\n" in n for n in names):
            raise WireError("span names must not contain newlines")
        frames.append(("span_names", _str_frame("\n".join(names))))
        frames.append(
            ("span_t0_ms", np.asarray([s[1] for s in reply.spans], "<f4"))
        )
        frames.append(
            ("span_dur_ms", np.asarray([s[2] for s in reply.spans], "<f4"))
        )
    return encode_frames(KIND_PLAN_SCHEDULE, frames, version=version)


def decode_plan_schedule_reply(data: bytes) -> PlanScheduleReply:
    kind, frames = decode_frames(data)
    if kind == KIND_ERROR:
        raise WireError(
            "service error: "
            + _frame_str(frames.get("message", np.zeros(0, np.uint8)), "error")
        )
    if kind != KIND_PLAN_SCHEDULE:
        raise WireError(f"expected PLAN_SCHEDULE, got kind {kind}")
    steps = frames.get("steps")
    if (
        steps is None
        or steps.dtype != np.dtype("<i4")
        or steps.ndim != 2
        or steps.shape[1] < 3
    ):
        raise WireError(
            "plan schedule frame 'steps' missing or malformed"
        )
    return PlanScheduleReply(
        steps=steps,
        solve_ms=float(_scalar(frames, "solve_ms", "<f4", "plan schedule")),
        queue_wait_ms=float(
            _scalar(frames, "queue_wait_ms", "<f4", "plan schedule")
        ),
        batch_lanes=int(
            _scalar(frames, "batch_lanes", "<i4", "plan schedule")
        ),
        batch_tenants=int(
            _scalar(frames, "batch_tenants", "<i4", "plan schedule")
        ),
        spans=_decode_reply_spans(frames),
    )


def encode_error(message: str, version: Optional[int] = None) -> bytes:
    """In-protocol error body (rides under an HTTP error status so
    binary clients never have to sniff JSON out of an octet stream).
    ``version`` mirrors the request's when known; version 1 is the safe
    answer to a request whose version could not be read (both old and
    new decoders accept it)."""
    return encode_frames(
        KIND_ERROR, [("message", _str_frame(message))], version=version
    )
