"""The multi-tenant planner service: one TPU planning for a fleet.

The single-tenant sidecar (sidecar/server.py) guarded a one-solve-at-a-
time lock: correct for one cluster, but it leaves the device idle
between ticks — the device-only solve is ~1 ms while a housekeeping
tick is seconds (docs/RESULTS.md), so a whole accelerator per cluster
is ~99.9% idle. This module replaces the lock with a *batching
scheduler*:

- per-cluster agents (service/agent.py) POST packed problems over the
  binary wire protocol (service/wire.py) to ``/v2/plan``;
- concurrent requests are padded into shape buckets
  (service/buckets.py) — tenants in one bucket share one jit compile —
  and stacked into ONE batched device solve with per-tenant lane blocks
  (parallel/tenant_batch.py), batch size capped by the HBM estimate so
  a full batch provably fits the device;
- a deficit-round-robin queue gives per-tenant fairness: each batch
  round offers every waiting tenant one lane-block's worth of quantum,
  so a tenant flooding the queue delays only itself — another tenant's
  head request rides the very next batch;
- the wait is bounded: a request still queued past the queue timeout is
  evicted with 503 + ``Retry-After`` derived from the *measured* batch
  cadence (how long until a batch slot actually frees), not a static
  guess;
- the legacy JSON ``/v1/plan`` survives as a thin decode→pack adapter
  over the same queue, so there is exactly one solve path;
- the sidecar's edge bounds carry over unchanged: ``max_body_bytes``
  caps any request body (413), ``max_inflight`` caps handler depth with
  rejects issued BEFORE the body is read (memory-bounded bursts).

``GET /healthz`` reports queue depth, per-bucket occupancy, per-tenant
last-plan age and the measured cadence alongside the control-loop
health snapshot, so a probe can see a starving tenant without Prometheus.

Fleet failure containment (docs/ROBUSTNESS.md "Fleet failure domains"):

- a **device-health watchdog** (service/devhealth.py) times every
  batched device solve against a calibrated baseline and runs idle
  canaries; a sick device (consecutive slow batches, canary timeout, or
  an XLA error) flips the service to its numpy-oracle host path —
  ``/healthz`` says ``device: "sick"``, the ``service_device_sick``
  gauge reads 1 and the flight recorder holds a ``device-sick`` event —
  and only hysteresis-gated recovery probes flip it back;
- **graceful drain**: SIGTERM (``ServiceServer.graceful_shutdown``)
  stops admitting (503 + Retry-After), finishes queued batches within
  ``service_drain_grace``, persists the warm state, then exits;
- **warm restart**: per-tenant last-pack fingerprints and the
  recently-used bucket list persist to ``service_state_dir``; a
  restarted replica pre-warms those bucket compiles on boot so N
  reconnecting agents do not land on a compile storm;
- **chaos hooks** (service/chaos.py): a seeded ``ServiceFaultPlan`` can
  corrupt incoming requests ahead of the decode and inject scripted
  batch-solve failures / sick-phase latency inside the timed solve
  window — how ``make fleet-chaos-smoke`` proves all of the above.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import socket
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster
from k8s_spot_rescheduler_tpu.service import buckets as bucketing
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.buckets import Bucket
from k8s_spot_rescheduler_tpu.service.devhealth import DeviceHealthWatchdog
from k8s_spot_rescheduler_tpu.solver import memory
from k8s_spot_rescheduler_tpu.utils.clock import Clock, RealClock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils import logging as log
from k8s_spot_rescheduler_tpu.utils import tracing


class ServiceBusy(Exception):
    """The queue refused or expired a request; retry after ``retry_after``
    seconds (the measured batch cadence, ceil'd)."""

    def __init__(self, message: str, retry_after: int):
        super().__init__(message)
        self.retry_after = int(retry_after)


class ResyncRequired(Exception):
    """A delta request's base state is unusable (restart, eviction,
    fingerprint mismatch, decode/apply anomaly): the agent must answer
    with exactly one full pack. Typed so the HTTP layer encodes it as
    wire ``KIND_RESYNC`` (HTTP 200 — a resync is protocol, not an
    endpoint failure; a 4xx/5xx would trip the agent's breaker and
    read as a dead replica)."""


# per-tenant bookkeeping bounds: tenant ids are CLIENT-supplied (wire
# frame / X-Tenant header), so every keyed structure must be pruned or a
# churning fleet (fresh hostname per agent restart) grows the long-lived
# service without bound
TENANT_STATE_TTL_S = 3600.0
TENANT_STATE_MAX = 4096

# warm-restart state (service_state_dir): file name, save cadence, and
# how many recently-used buckets a restarted replica pre-warms
STATE_FILE = "planner_warm_state.json"
STATE_SAVE_INTERVAL_S = 60.0
WARM_MAX_BUCKETS = 8
SEEN_BUCKETS_MAX = 64

# delta-wire tenant cache (wire v4): per-tenant packed state is a whole
# bucket-padded tensor set — far heavier than the bookkeeping maps — so
# it carries its own, tighter hard cap (eviction is cheap for the
# evictee: one full-pack resync on its next delta)
TENANT_CACHE_MAX = 512


class _TenantEntry:
    """One tenant's cached packed state for the delta wire: the host
    mirror (bucket-padded, owned writable arrays — deltas scatter into
    it in place), the device-resident twin on the accelerator path
    (populated after the tenant's first batched scatter; None on the
    numpy path and after a device error), and the content fingerprint
    the next delta's base must name."""

    __slots__ = ("fp", "host", "device", "bucket", "K", "lanes",
                 "last_used")

    def __init__(self, fp, host, bucket, K, lanes, last_used):
        self.fp = fp
        self.host = host  # PackedCluster of writable numpy arrays
        self.device = None  # PackedCluster of device arrays, or None
        self.bucket = bucket
        self.K = int(K)  # the agent's own K (reply row trim)
        self.lanes = int(lanes)  # valid lanes (DRR cost of a delta req)
        self.last_used = float(last_used)


class _Request:
    __slots__ = (
        "tenant", "packed", "bucket", "lanes", "enqueued", "event",
        "reply", "error", "trace_id", "horizon", "fingerprint", "K",
        "delta", "base_fp", "new_fp", "resync",
    )

    def __init__(self, tenant: str, packed: Optional[PackedCluster],
                 bucket: Bucket, enqueued: float, trace_id: str = "",
                 horizon: int = 0, fingerprint: str = "", lanes: int = 0,
                 K: int = 0):
        self.tenant = tenant
        self.packed = packed
        self.bucket = bucket
        # drain-schedule horizon (wire v3): 0 = ordinary single plan;
        # > 0 = answer with a whole [horizon, 3+K] schedule. Requests
        # only batch with same-horizon peers (one program per batch).
        self.horizon = int(horizon)
        # DRR cost: the lanes this problem actually solves (valid lanes,
        # not pad) — a tenant shipping big problems drains its deficit
        # faster than one shipping small ones. Delta requests (packed
        # None) have the caller compute it from the cached state.
        if packed is not None:
            self.lanes = int(np.asarray(packed.cand_valid).sum())
            self.K = packed.slot_req.shape[1]
        else:
            self.lanes = int(lanes)
            self.K = int(K)
        self.enqueued = enqueued
        self.event = threading.Event()
        self.reply: Optional[wire.PlanReply] = None
        self.error: Optional[ServiceBusy] = None
        # the agent's tick trace ID (wire v2 / X-Trace-Id): server-side
        # spans are keyed by it so the reply's span block grafts into
        # the right tick tree on the far side
        self.trace_id = trace_id
        # delta wire (v4): the pack fingerprint a full-pack request
        # carries (seeds the tenant cache), or the churn payload +
        # base/new fingerprints of a delta-backed request; ``resync``
        # carries the demand's cause when the batch path refused the
        # delta after it was queued
        self.fingerprint = fingerprint
        self.delta = None
        self.base_fp = ""
        self.new_fp = ""
        self.resync: Optional[str] = None


class PlannerService:
    """The queue + batcher + solver. HTTP lives in :class:`ServiceServer`;
    this class is directly drivable by tests (virtual clock, no threads:
    ``submit_nowait`` + ``drain_once``)."""

    def __init__(
        self,
        config: ReschedulerConfig,
        *,
        queue_timeout_s: Optional[float] = None,
        batch_window_s: Optional[float] = None,
        max_batch_tenants: int = 0,
        clock: Optional[Clock] = None,
    ):
        self.config = config
        self.clock = clock or RealClock()
        self.queue_timeout_s = float(
            queue_timeout_s
            if queue_timeout_s is not None
            else config.service_queue_timeout
        )
        self.batch_window_s = float(
            batch_window_s
            if batch_window_s is not None
            else config.service_batch_window
        )
        # 0 = derive per bucket from the HBM budget
        self.max_batch_tenants = int(max_batch_tenants)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: Dict[str, deque] = {}  # tenant -> FIFO of _Request
        self._ring: List[str] = []  # DRR ring, activation order
        self._rr_pos = 0
        self._deficit: Dict[str, int] = {}
        self._last_plan_wall: Dict[str, float] = {}
        self._batch_cap: Dict[Bucket, int] = {}  # HBM cap memo per bucket
        self._cadence_s: Optional[float] = None  # EMA of batch intervals
        self._last_batch_mono: Optional[float] = None
        self._batched = None  # lazy jitted tenant-batch program
        self._sched_programs: Dict[int, object] = {}  # horizon -> jit
        self._mesh = None
        self._mesh_ready = False
        # delta wire (v4): per-tenant fingerprinted packed state +
        # the lazily-jitted batched tenant scatter; _warm_fps holds the
        # RESTART-persisted fingerprints (content is gone — they only
        # name the resync cause precisely)
        self._tenant_cache: Dict[str, _TenantEntry] = {}
        self._warm_fps: Dict[str, str] = {}
        self._delta_applier = None
        self._stop = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        # test seam: solve_hook(stacked, reqs) -> int32 [T, 3+K]. When
        # set it IS the device path: the watchdog times it and the sick
        # flip routes around it, exactly as for the real device solve.
        self.solve_hook = None
        # device-health watchdog (lazy; None while device_sick_threshold
        # is 0) + the server-side chaos hook (None outside chaos runs)
        self._devhealth: Optional[DeviceHealthWatchdog] = None
        self.chaos = None
        if config.service_chaos_profile not in ("", "off", "none"):
            from k8s_spot_rescheduler_tpu.service.chaos import (
                ServiceChaos,
                ServiceFaultPlan,
            )

            self.chaos = ServiceChaos(
                ServiceFaultPlan.profile(
                    config.service_chaos_profile,
                    config.service_chaos_seed,
                ),
                clock=self.clock,
            )
        # warm-restart bookkeeping: recently-used bucket shapes (dims ->
        # last-used wall) and per-tenant last-pack fingerprints, both
        # bounded, persisted to service_state_dir
        self._seen_buckets: Dict[tuple, float] = {}
        self._tenant_bucket: Dict[str, str] = {}
        self._last_state_save: Optional[float] = None
        self.warmed_buckets: List[str] = []
        # stacked shapes whose program has already run once: the FIRST
        # solve of a shape includes its XLA compile and must not be
        # judged (or baselined) as device latency by the watchdog — a
        # fleet ramp-up's compiles are not a sick accelerator
        self._timed_shapes: set = set()
        # compile-sharing accounting, independent of the watchdog's
        # _timed_shapes (which deliberately does NOT advance on the
        # sick/host path): every batch counts a hit or a miss against
        # the shapes THIS process has solved, whatever path served it
        self._compile_seen: set = set()

    # ------------------------------------------------------------------
    # queue

    def submit_nowait(
        self,
        tenant: str,
        packed: PackedCluster,
        trace_id: str = "",
        schedule_horizon: int = 0,
        pack_fingerprint: str = "",
    ) -> _Request:
        """Enqueue one problem; returns the pending request (its
        ``event`` fires when a batch delivered ``reply`` or ``error``)."""
        req = _Request(
            tenant, packed, bucketing.bucket_for(packed), self.clock.now(),
            trace_id=trace_id, horizon=schedule_horizon,
            fingerprint=pack_fingerprint,
        )
        self._enqueue(req)
        return req

    def _enqueue(self, req: _Request) -> None:
        with self._work:
            if self._draining:
                # graceful drain: stop admitting; the Retry-After horizon
                # is the drain grace (by then this replica is gone and a
                # failover endpoint or a fresh replica answers)
                raise ServiceBusy(
                    "service draining (graceful shutdown); retry another "
                    "replica",
                    self.drain_retry_after(),
                )
            q = self._queues.get(req.tenant)
            if q is None:
                q = self._queues[req.tenant] = deque()
            if req.tenant not in self._deficit:
                self._ring.append(req.tenant)
                self._deficit[req.tenant] = 0
            q.append(req)
            self._work.notify_all()

    def submit(
        self,
        tenant: str,
        packed: PackedCluster,
        timeout_s: Optional[float] = None,
        trace_id: str = "",
        schedule_horizon: int = 0,
        pack_fingerprint: str = "",
    ):
        """Enqueue and wait for the batch that carries this request.
        Raises :class:`ServiceBusy` when the bounded wait expires — the
        request is evicted from the queue so an abandoned caller cannot
        occupy a batch slot. ``timeout_s`` is the CLIENT's declared
        deadline (agents send it as ``X-Planner-Deadline``): waiting any
        longer than the caller will would solve — and hold an inflight
        slot for — a request nobody is listening to anymore. Returns a
        :class:`wire.PlanReply`, or a :class:`wire.PlanScheduleReply`
        when ``schedule_horizon`` > 0 asked for a drain schedule."""
        wait_s, capped = self._bounded_wait(timeout_s)
        req = self.submit_nowait(
            tenant, packed, trace_id=trace_id,
            schedule_horizon=schedule_horizon,
            pack_fingerprint=pack_fingerprint,
        )
        return self._finish_wait(req, wait_s, deadline_capped=capped)

    def _bounded_wait(self, timeout_s: Optional[float]):
        """(wait_s, deadline_capped): the queue timeout, shortened to
        the client's declared deadline when that is tighter — the flag
        names which bound an eventual eviction was shed under."""
        wait_s = self.queue_timeout_s
        if timeout_s is not None and 0 < float(timeout_s) < wait_s:
            return max(0.05, float(timeout_s)), True
        return wait_s, False

    def _note_shed(
        self, reason: str, cause: str, tenant: str = "", trace_id: str = "",
        kind: str = "service-shed",
    ) -> None:
        """ONE request shed at an admission edge: fire the labeled
        ``service_admission_shed_total`` counter and the flight shed
        event (same reason attr) from this single funnel, one call site
        per reason, so the two surfaces can be asserted equal per
        reason (fleet-twin-smoke does). ``kind`` defaults to
        ``service-shed``; the resync-storm admission edge fires its
        dedicated ``resync-shed`` flight kind through the same
        funnel."""
        metrics.update_service_admission_shed(reason)
        attrs = {"reason": reason}
        if tenant:
            attrs["tenant"] = tenant
        flight.note_event(kind, cause=cause, trace_id=trace_id, **attrs)

    def _finish_wait(
        self, req: _Request, wait_s: float, deadline_capped: bool = False
    ):
        """The shared bounded wait behind :meth:`submit` and
        :meth:`submit_delta`: inline drain for scheduler-less callers,
        eviction past the deadline, and the typed outcomes.
        ``deadline_capped`` names which bound an eviction sheds under —
        the client's declared deadline vs the service queue timeout."""
        if self._thread is None:
            # no scheduler thread (an in-process caller — e.g.
            # PlannerSidecar.plan without start_background): drain the
            # queue on the caller's thread so the historical synchronous
            # contract holds instead of timing out against nobody
            while not req.event.is_set() and self.drain_once():
                pass
        if not req.event.wait(wait_s):
            if self._evict(req):
                metrics.update_service_request("expired")
                metrics.update_service_tenant_eviction(req.tenant)
                if deadline_capped:
                    self._note_shed(
                        "deadline",
                        "plan request outlived the client's %.1fs "
                        "declared deadline" % wait_s,
                        tenant=req.tenant, trace_id=req.trace_id,
                    )
                else:
                    self._note_shed(
                        "queue-timeout",
                        "plan request waited past the %.1fs queue "
                        "timeout" % wait_s,
                        tenant=req.tenant, trace_id=req.trace_id,
                    )
                raise ServiceBusy(
                    "plan request waited past the %.1fs queue timeout"
                    % wait_s,
                    self.retry_after(),
                )
            # already popped into an in-flight batch: the solve is not
            # interruptible (an XLA dispatch cannot be cancelled), so
            # ride it out — same contract as the old sidecar lock
            req.event.wait()
        if req.resync is not None:
            raise ResyncRequired(req.resync)
        if req.error is not None:
            raise req.error
        if req.reply is None:
            raise RuntimeError("request completed without reply or error")
        return req.reply

    def _evict(self, req: _Request) -> bool:
        with self._work:
            q = self._queues.get(req.tenant)
            if q is not None and req in q:
                q.remove(req)
                return True
        return False

    # ------------------------------------------------------------------
    # delta wire (v4): fingerprinted tenant cache + resync demands

    def note_resync(self, tenant: str, cause: str, trace_id: str = "") -> None:
        """ONE resync demanded: fire the metric and the flight event
        from this single site so ``service_delta_requests_total``
        {outcome=resync} and the flight ``delta-resync`` count can
        never disagree (fleet-chaos-smoke asserts equality)."""
        metrics.update_service_delta("resync")
        flight.note_event(
            "delta-resync", cause=cause, trace_id=trace_id, tenant=tenant,
        )
        log.warning(
            "delta resync demanded for tenant %s: %s",
            flight.redact_text(tenant) if tenant else "<undecoded>", cause,
        )

    def _cache_mismatch_locked(
        self, tenant: str, entry: Optional[_TenantEntry], base_fp: str
    ) -> Optional[str]:
        """Why this delta cannot apply (None = it can). Caller holds
        the lock."""
        if entry is None:
            if self._warm_fps.get(tenant) == base_fp:
                return (
                    "server restart lost the cached tenant state (the "
                    "persisted warm fingerprint matches the delta base)"
                )
            return "no cached state for tenant (first contact or evicted)"
        if entry.fp != base_fp:
            return (
                f"fingerprint mismatch (cache holds {entry.fp[:12]}..., "
                f"delta base names {base_fp[:12]}...)"
            )
        return None

    @staticmethod
    def _validate_delta(delta, bucket: Bucket) -> Optional[str]:
        """Range-check a decoded delta against the cached bucket shape
        (the wire digest already proves the bytes are as sent; this
        guards a buggy agent — numpy would silently WRAP a negative
        index where the device scatter drops it, so refuse both)."""
        if delta.lane_slot_req.shape[1] > bucket.K:
            return (
                f"delta lane slabs carry K={delta.lane_slot_req.shape[1]} "
                f"> cached bucket K={bucket.K}"
            )
        for name, idx, n in (
            ("lanes", delta.lanes, bucket.C),
            ("cand_rows", delta.cand_rows, bucket.C),
            ("spot_rows", delta.spot_rows, bucket.S),
        ):
            if len(idx) and (
                int(idx.min()) < 0 or int(idx.max()) >= n
            ):
                return f"delta {name} index out of range [0, {n})"
        return None

    def submit_delta(
        self,
        tenant: str,
        delta,
        base_fp: str,
        new_fp: str,
        timeout_s: Optional[float] = None,
        trace_id: str = "",
    ):
        """Enqueue one delta-backed plan request and wait for the batch
        that carries it. Raises :class:`ResyncRequired` when the cached
        base state cannot honor the delta (fast-path check here; the
        authoritative re-check happens at batch assembly, since an
        earlier queued delta may advance the cache first), or
        :class:`ServiceBusy` exactly like :meth:`submit`. Returns a
        :class:`wire.PlanReply` — the selection is computed from the
        cached state with this delta scattered in, bit-identical to the
        same tenant shipping its full pack."""
        with self._work:
            entry = self._tenant_cache.get(tenant)
            cause = self._cache_mismatch_locked(tenant, entry, base_fp)
            if cause is None:
                cause = self._validate_delta(delta, entry.bucket)
            if cause is None:
                # DRR lane cost of the resulting state, computed from
                # the delta alone: cached lanes minus the flips the
                # cand_valid section reverts, plus the ones it sets
                old = np.asarray(
                    entry.host.cand_valid[np.asarray(delta.cand_rows)]
                )
                lanes = (
                    entry.lanes
                    - int(old.sum())
                    + int(np.asarray(delta.cand_valid).sum())
                )
                req = _Request(
                    tenant, None, entry.bucket, self.clock.now(),
                    trace_id=trace_id, lanes=lanes, K=entry.K,
                )
                req.delta = delta
                req.base_fp = base_fp
                req.new_fp = new_fp
        if cause is not None:
            self.note_resync(tenant, cause, trace_id)
            raise ResyncRequired(cause)
        self._enqueue(req)
        wait_s, capped = self._bounded_wait(timeout_s)
        return self._finish_wait(req, wait_s, deadline_capped=capped)

    def tenant_cached(self, tenant: str) -> bool:
        """Whether this tenant currently has delta-wire state cached —
        the resync admission class keys on it: a fingerprinted full
        pack from an UNCACHED tenant is a cache-seeding resync ingest
        (first contact or post-restart re-seed); cached tenants and
        delta traffic bypass the resync gate entirely."""
        with self._work:
            return tenant in self._tenant_cache

    def invalidate_tenant_cache(self, tenant: Optional[str] = None) -> int:
        """Drop one tenant's (or every) cached packed state; their next
        delta is answered with a resync demand. The forced-resync seam
        serve-smoke drives; eviction/TTL pruning reuses it."""
        with self._work:
            if tenant is not None:
                n = 1 if self._tenant_cache.pop(tenant, None) else 0
            else:
                n = len(self._tenant_cache)
                self._tenant_cache.clear()
            metrics.update_service_tenant_cache(len(self._tenant_cache))
        return n

    def retry_after(self) -> int:
        """Seconds until a batch slot plausibly frees: the measured
        batch cadence (EMA over completed batches), ceil'd; 1 before
        any batch has completed."""
        cadence = self._cadence_s
        if cadence is None or cadence <= 0:
            return 1
        return max(1, int(math.ceil(cadence)))

    def queue_depth(self) -> int:
        with self._work:
            return sum(len(q) for q in self._queues.values())

    def healthz_snapshot(self) -> dict:
        """Queue depth, per-bucket occupancy, per-tenant last-plan age,
        the measured cadence, the drain flag and the device-health
        verdict — the service half of /healthz."""
        wd = self._watchdog()  # takes (and releases) the lock itself
        with self._work:
            depth = 0
            by_bucket: Dict[str, int] = {}
            for q in self._queues.values():
                depth += len(q)
                for req in q:
                    key = req.bucket.key
                    by_bucket[key] = by_bucket.get(key, 0) + 1
            wall = self.clock.wall()
            tenants = {
                t: round(max(0.0, wall - w), 3)
                for t, w in self._last_plan_wall.items()
            }
            cadence = self._cadence_s
            draining = self._draining
            cache_entries = len(self._tenant_cache)
        out = {
            "queue_depth": depth,
            "bucket_occupancy": by_bucket,
            "tenant_last_plan_age_s": tenants,
            "batch_cadence_s": (
                None if cadence is None else round(cadence, 3)
            ),
            "batch_window_s": self.batch_window_s,
            "draining": draining,
            "tenant_cache_entries": cache_entries,
            # windowed queue-wait percentiles (pooled + the worst
            # tenants' tails): a probe sees a starving tenant NOW, not
            # its worst-ever (metrics/registry.py bounded rings)
            "queue_wait_ms": metrics.service_queue_wait_summary(),
        }
        if wd is not None:
            out.update(wd.snapshot())
        else:
            out["device"] = "unwatched"  # device_sick_threshold = 0
        return out

    # ------------------------------------------------------------------
    # batching

    def _pop_batch_locked(self):
        """One deficit-round-robin pass: pick the bucket of the oldest
        waiting request (bounded wait beats throughput), then walk the
        tenant ring giving each tenant one quantum (a full lane-block,
        ``bucket.C`` lanes) and popping head requests of that bucket
        while its deficit covers their lane cost. Caller holds the lock."""
        oldest: Optional[_Request] = None
        for q in self._queues.values():
            if q and (oldest is None or q[0].enqueued < oldest.enqueued):
                oldest = q[0]
        if oldest is None:
            return []
        bucket = oldest.bucket
        # schedule requests (horizon > 0) solve a different program per
        # horizon: a batch only ever mixes same-(bucket, horizon) peers
        horizon = oldest.horizon
        cap = self.max_batch_tenants or self._batch_cap.get(bucket, 0)
        if not cap:
            # memoized per bucket: the estimate is constant in (bucket,
            # config), and with solver_hbm_budget=0 it queries backend
            # memory stats — not something to repeat per pop under the
            # queue lock
            cap = bucketing.max_batch_tenants(
                bucket,
                budget_bytes=self.config.solver_hbm_budget,
                repair_spot_chunks=(
                    1
                    if self.config.fallback_best_fit
                    and self.config.repair_rounds > 0
                    else 0
                ),
            )
            self._batch_cap[bucket] = cap
        batch: List[_Request] = []
        # refill each waiting tenant's deficit ONCE per batch: one full
        # lane-block of quantum. quantum >= any request's lane cost, so
        # every tenant is guaranteed a slot in the very next batch — the
        # bounded-wait fairness claim — while lane accounting still lets
        # small-problem tenants pack denser than big-problem ones.
        refilled: set = set()
        while len(batch) < cap:
            popped = False
            # one full ring rotation, ONE pop per tenant per pass:
            # interleaving is what keeps a flooding tenant from filling
            # the batch before the rotation reaches anyone else
            for _ in range(len(self._ring)):
                if len(batch) >= cap or not self._ring:
                    break
                self._rr_pos %= len(self._ring)
                tenant = self._ring[self._rr_pos]
                q = self._queues.get(tenant)
                if not q:
                    # empty queue leaves the ring AND the queue map;
                    # deficit resets (classic DRR: credit must not
                    # accrue while idle) and a churned tenant id leaves
                    # no residue behind
                    self._ring.pop(self._rr_pos)
                    self._deficit.pop(tenant, None)
                    self._queues.pop(tenant, None)
                    continue
                if q[0].bucket == bucket and q[0].horizon == horizon:
                    if tenant not in refilled:
                        refilled.add(tenant)
                        # clamp: credit saved while batches were full
                        # must not compound into a later burst
                        self._deficit[tenant] = min(
                            self._deficit.get(tenant, 0) + bucket.C,
                            2 * bucket.C,
                        )
                    if self._deficit[tenant] >= max(q[0].lanes, 1):
                        req = q.popleft()
                        self._deficit[tenant] -= max(req.lanes, 1)
                        batch.append(req)
                        popped = True
                self._rr_pos += 1
            if not popped:
                break
        return batch

    def drain_once(self) -> bool:
        """Form and solve ONE batch; returns True if a batch dispatched.
        The scheduler thread loops this; tests call it directly under a
        virtual clock."""
        with self._work:
            batch = self._pop_batch_locked()
        if not batch:
            return False
        bucket = batch[0].bucket
        t0 = self.clock.now()
        try:
            batch, stacked = self._assemble_batch(batch, bucket)
            if not batch:
                # every member resynced away (already answered typed)
                return True
            now = self.clock.now()
            waits_ms = [max(0.0, now - r.enqueued) * 1e3 for r in batch]
            t_solve = self.clock.now()
            out = self._solve_batch(stacked, batch)
        except Exception as err:  # noqa: BLE001 — contain: fail the batch,
            # not the service (the agents fall back to their local oracle);
            # counted via update_service_request("error") below
            log.error("batched solve failed: %s", err)
            for req in batch:
                if req.event.is_set():
                    continue  # already answered (a typed resync)
                req.error = ServiceBusy(f"solve failed: {err}", 0)
                metrics.update_service_request("error")
                req.event.set()
            return True
        batch_ms = (t_solve - t0) * 1e3
        solve_wall_ms = (self.clock.now() - t_solve) * 1e3
        solve_ms = (self.clock.now() - t0) * 1e3
        lanes = sum(r.lanes for r in batch)
        tenants = len({r.tenant for r in batch})
        cap = self.max_batch_tenants or self._batch_cap.get(bucket, 0)
        metrics.update_service_batch(
            lanes, tenants,
            [(r.tenant, w) for r, w in zip(batch, waits_ms)],
            occupancy=(len(batch) / cap if cap else None),
        )
        wall = self.clock.wall()
        end = self.clock.now()
        with self._work:
            # bookkeeping a concurrent /healthz iterates — same lock
            for req in batch:
                self._last_plan_wall[req.tenant] = wall
                # warm-restart fingerprint: the bucket this tenant's
                # last pack landed in (persisted to service_state_dir)
                self._tenant_bucket[req.tenant] = bucket.key
            self._seen_buckets[tuple(bucket)] = wall
            if len(self._seen_buckets) > SEEN_BUCKETS_MAX:
                oldest = min(self._seen_buckets, key=self._seen_buckets.get)
                del self._seen_buckets[oldest]
            # bounded: tenant ids are client-supplied, so the age map
            # drops entries past the TTL and hard-caps at the newest
            # TENANT_STATE_MAX (a churning fleet must not grow the
            # service or its /healthz response without bound)
            cutoff = wall - TENANT_STATE_TTL_S
            stale = [
                t for t, w in self._last_plan_wall.items() if w < cutoff
            ]
            for t in stale:
                del self._last_plan_wall[t]
            if len(self._last_plan_wall) > TENANT_STATE_MAX:
                newest = sorted(
                    self._last_plan_wall.items(),
                    key=lambda kv: kv[1],
                    reverse=True,
                )[:TENANT_STATE_MAX]
                self._last_plan_wall = dict(newest)
            if len(self._tenant_bucket) > len(self._last_plan_wall):
                self._tenant_bucket = {
                    t: b
                    for t, b in self._tenant_bucket.items()
                    if t in self._last_plan_wall
                }
            # the delta-wire tenant cache rides the same lifecycle —
            # TTL'd tenants lose their cached packed state, and a
            # tighter hard cap evicts the least-recently-used entries
            # (packed state is far heavier than the bookkeeping maps);
            # an evicted tenant's next delta costs one full-pack resync
            if self._tenant_cache:
                for t in [
                    t for t in self._tenant_cache
                    if t not in self._last_plan_wall
                ]:
                    del self._tenant_cache[t]
                if len(self._tenant_cache) > TENANT_CACHE_MAX:
                    newest = sorted(
                        self._tenant_cache.items(),
                        key=lambda kv: kv[1].last_used,
                        reverse=True,
                    )[:TENANT_CACHE_MAX]
                    self._tenant_cache = dict(newest)
                metrics.update_service_tenant_cache(
                    len(self._tenant_cache)
                )
            if self._last_batch_mono is not None:
                interval = max(1e-9, end - self._last_batch_mono)
                self._cadence_s = (
                    interval
                    if self._cadence_s is None
                    else 0.7 * self._cadence_s + 0.3 * interval
                )
            self._last_batch_mono = end
        for i, req in enumerate(batch):
            K = req.K
            vec = out[i]
            # server-side spans, offset from THIS request's enqueue:
            # how its wall time split between the tenant queue, the
            # bucket pad/stack, and the shared solve. The HTTP layer
            # prepends admit/decode and appends encode; the agent
            # grafts the whole block under its wire.request span.
            spans = (
                tracing.make_span("service.queue-wait", 0.0, waits_ms[i]),
                tracing.make_span("service.batch", waits_ms[i], batch_ms),
                tracing.make_span(
                    "service.solve", waits_ms[i] + batch_ms, solve_wall_ms
                ),
            )
            if req.horizon > 0:
                # a whole drain schedule (wire v3): trim the bucket's K
                # pad per step — the slot columns beyond the tenant's
                # own K are pad rows, exactly as for a single plan
                req.reply = wire.PlanScheduleReply(
                    steps=np.ascontiguousarray(
                        np.concatenate(
                            [vec[:, :3], vec[:, 3 : 3 + K]], axis=1
                        ).astype(np.int32)
                    ),
                    solve_ms=float(solve_ms / max(len(batch), 1)),
                    queue_wait_ms=float(waits_ms[i]),
                    batch_lanes=lanes,
                    batch_tenants=tenants,
                    spans=spans,
                )
            else:
                req.reply = wire.PlanReply(
                    found=bool(vec[1]),
                    index=int(vec[0]),
                    n_feasible=int(vec[2]),
                    # trim the bucket's K pad back to the tenant's K:
                    # slot indices beyond the tenant's own slots are pad
                    row=np.asarray(vec[3 : 3 + K], np.int32),
                    solve_ms=float(solve_ms / max(len(batch), 1)),
                    queue_wait_ms=float(waits_ms[i]),
                    batch_lanes=lanes,
                    batch_tenants=tenants,
                    spans=spans,
                )
            metrics.update_service_request("ok")
            if req.delta is not None:
                # the applied half of the delta accounting (the resync
                # half fires in note_resync — one site each)
                metrics.update_service_delta("applied")
            req.event.set()
        if self._state_path() and (
            self._last_state_save is None
            or wall - self._last_state_save >= STATE_SAVE_INTERVAL_S
        ):
            # opportunistic warm-state save: a kill -9 at most loses one
            # interval of fingerprints, never availability
            self._last_state_save = wall
            self.save_state()
        return True

    # ------------------------------------------------------------------
    # batch assembly (full packs + delta scatter)

    @staticmethod
    def _apply_delta_host(host: PackedCluster, delta) -> None:
        """Scatter one wire delta into a cached host mirror IN PLACE —
        the same update models/columnar.apply_packed_delta defines,
        sliced to the delta's own slab width (the cached state is
        bucket-padded; columns past the agent's K are zeros on both
        sides by the pad invariant, so the narrower write is exact)."""
        k = delta.lane_slot_req.shape[1]
        host.slot_req[delta.lanes, :k] = delta.lane_slot_req
        host.slot_valid[delta.lanes, :k] = delta.lane_slot_valid
        host.slot_tol[delta.lanes, :k] = delta.lane_slot_tol
        host.slot_aff[delta.lanes, :k] = delta.lane_slot_aff
        host.cand_valid[delta.cand_rows] = delta.cand_valid
        host.spot_free[delta.spot_rows] = delta.spot_free
        host.spot_count[delta.spot_rows] = delta.spot_count
        host.spot_max_pods[delta.spot_rows] = delta.spot_max_pods
        host.spot_taints[delta.spot_rows] = delta.spot_taints
        host.spot_ok[delta.spot_rows] = delta.spot_ok
        host.spot_aff[delta.spot_rows] = delta.spot_aff

    def _assemble_batch(self, batch, bucket: Bucket):
        """Resolve a popped batch to its solve-input state: full packs
        pad into the bucket (and seed the tenant cache when they carry
        a v4 fingerprint); delta requests re-verify against the cache —
        the authoritative check, an earlier queued delta may have
        advanced it since submit — update the host mirror in place,
        and on the accelerator path ride ONE batched donated scatter
        (parallel/tenant_batch.apply_tenant_deltas) applying every
        tenant's churn on device before the batch solve, whose output
        slices become the per-tenant device-resident state. A delta
        the cache cannot honor (or whose apply raises) is answered
        with a typed resync demand and dropped — never a wrong plan.
        Returns (live_batch, stacked_states)."""
        from k8s_spot_rescheduler_tpu.models.columnar import (
            empty_packed_delta,
            pad_packed_delta,
            pad_pow2,
        )

        wd = self._devhealth
        any_delta = any(r.delta is not None for r in batch)
        use_device = (
            any_delta
            and self.config.solver != "numpy"
            and batch[0].horizon == 0
            and (wd is None or not wd.sick)
        )
        live: List[_Request] = []
        states: List[PackedCluster] = []
        deltas: List[Optional[object]] = []
        resynced: List[_Request] = []
        wall = self.clock.wall()
        with self._work:
            for req in batch:
                if req.delta is None:
                    padded = bucketing.pad_to_bucket(req.packed, bucket)
                    if req.fingerprint:
                        # owned writable copies: decoded wire tensors
                        # are read-only views into the request body,
                        # and future deltas scatter into these in place
                        host = PackedCluster(
                            *(np.array(f) for f in padded)
                        )
                        self._tenant_cache[req.tenant] = _TenantEntry(
                            req.fingerprint, host, bucket, req.K,
                            req.lanes, wall,
                        )
                        states.append(host)
                    else:
                        states.append(padded)
                    deltas.append(None)
                    live.append(req)
                    continue
                entry = self._tenant_cache.get(req.tenant)
                cause = self._cache_mismatch_locked(
                    req.tenant, entry, req.base_fp
                )
                if cause is None and entry.bucket != bucket:
                    # a stale queued delta racing a full repack into
                    # another shape family — resync, never mis-scatter
                    cause = "cached state moved to another shape bucket"
                if cause is None:
                    cause = self._validate_delta(req.delta, bucket)
                if cause is None:
                    # base for the device scatter, captured before the
                    # host mirror mutates. When it IS the host mirror
                    # (no device twin yet) the stack below may read the
                    # post-apply arrays — harmless: the scatter is a
                    # pure SET, so re-applying the same delta is
                    # idempotent bit-for-bit.
                    base = (
                        entry.device
                        if entry.device is not None
                        else entry.host
                    )
                    try:
                        self._apply_delta_host(entry.host, req.delta)
                    except Exception as err:  # noqa: BLE001, exception-discipline — ANY apply anomaly demands a typed resync (counted + flight-evented below); the entry is dropped so a partial scatter can never serve a later delta
                        self._tenant_cache.pop(req.tenant, None)
                        cause = f"delta apply failed: {err}"
                if cause is not None:
                    req.resync = cause
                    resynced.append(req)
                    continue
                entry.fp = req.new_fp
                entry.lanes = req.lanes
                entry.last_used = wall
                if not use_device:
                    # the twin was NOT part of this apply (host-only
                    # path: sick watchdog, or a schedule/numpy batch):
                    # drop it, or a post-recovery device scatter would
                    # build on a base missing this batch's churn
                    entry.device = None
                states.append(base if use_device else entry.host)
                deltas.append(req.delta)
                live.append(req)
            metrics.update_service_tenant_cache(len(self._tenant_cache))
            stacked = None
            if live and not use_device:
                # host path: the mirrors already hold the post-delta
                # state; stack INSIDE the lock so no concurrent batch's
                # apply can slip between mirror and copy
                stacked = bucketing.stack_bucket(states, bucket)
        for req in resynced:
            self.note_resync(req.tenant, req.resync, req.trace_id)
            req.event.set()
        if not live:
            return [], None
        if not use_device:
            return live, stacked
        try:
            import jax.numpy as jnp

            stacked_base = PackedCluster(
                *(
                    jnp.stack([getattr(s, f) for s in states])
                    for f in PackedCluster._fields
                )
            )
            rows = {
                sec: pad_pow2(max(
                    (
                        len(getattr(d, sec))
                        for d in deltas
                        if d is not None
                    ),
                    default=0,
                ))
                for sec in ("lanes", "cand_rows", "spot_rows")
            }
            padded_deltas = [
                pad_packed_delta(
                    d if d is not None else empty_packed_delta(states[i]),
                    bucket.C,
                    bucket.S,
                    lane_rows=rows["lanes"],
                    cand_rows=rows["cand_rows"],
                    spot_rows=rows["spot_rows"],
                    K=bucket.K,
                )
                for i, d in enumerate(deltas)
            ]
            delta_t = type(padded_deltas[0])
            stacked_delta = delta_t(
                *(
                    np.stack([getattr(d, f) for d in padded_deltas])
                    for f in delta_t._fields
                )
            )
            if self._delta_applier is None:
                from k8s_spot_rescheduler_tpu.parallel.tenant_batch import (
                    make_tenant_delta_applier,
                )

                self._delta_applier = make_tenant_delta_applier()
            out_state = self._delta_applier(*stacked_base, stacked_delta)
            with self._work:
                for i, req in enumerate(live):
                    entry = self._tenant_cache.get(req.tenant)
                    if entry is not None and entry.bucket == bucket:
                        # the device-resident per-tenant state: next
                        # tick's scatter stacks these device-to-device
                        entry.device = PackedCluster(
                            *(f[i] for f in out_state)
                        )
            return live, out_state
        except Exception as err:  # noqa: BLE001, exception-discipline — a device-side scatter failure is contained to the HOST path (the post-apply host mirrors are authoritative and bit-identical); the device twins are dropped and rebuilt by the next batch
            log.error(
                "batched delta scatter failed on device (%s); serving "
                "this batch from the host mirrors", err,
            )
            with self._work:
                host_states = []
                for i, req in enumerate(live):
                    entry = self._tenant_cache.get(req.tenant)
                    if entry is not None:
                        entry.device = None
                    if req.delta is not None and entry is not None:
                        host_states.append(entry.host)
                    else:
                        host_states.append(states[i])
                stacked = bucketing.stack_bucket(host_states, bucket)
            return live, stacked

    # ------------------------------------------------------------------
    # device health + solve routing

    def _watchdog(self) -> Optional[DeviceHealthWatchdog]:
        if self.config.device_sick_threshold <= 0:
            return None
        with self._work:
            # lazy-create under the lock: a /healthz probe racing the
            # first batch must not replace the instance the solve path
            # just flipped sick (the gauge/flight/healthz agreement
            # depends on there being exactly ONE watchdog)
            if self._devhealth is None:
                self._devhealth = DeviceHealthWatchdog(
                    self.clock, self.config.device_sick_threshold
                )
            return self._devhealth

    def _first_compile(self, stacked: PackedCluster) -> bool:
        """True exactly once per stacked shape family: that solve pays
        the jit compile, which the watchdog must not read as latency."""
        key = (
            stacked.slot_req.shape, stacked.spot_free.shape,
            stacked.spot_taints.shape, stacked.spot_aff.shape,
        )
        if key in self._timed_shapes:
            return False
        if len(self._timed_shapes) > 4096:
            self._timed_shapes.clear()
        self._timed_shapes.add(key)
        return True

    def _note_bucket_compile(
        self, stacked: PackedCluster, horizon: int, count: bool = True
    ) -> bool:
        """Compile-sharing accounting: True exactly once per stacked
        shape family + schedule horizon (that solve pays the jit
        compile on a device backend); with ``count`` the hit/miss
        counters fire (warm_start marks its pre-warmed shapes seen
        WITHOUT counting — a boot-time pre-warm is the compile the
        first reconnecting agent then gets a hit against)."""
        key = (
            stacked.slot_req.shape, stacked.spot_free.shape,
            stacked.spot_taints.shape, stacked.spot_aff.shape,
            int(horizon),
        )
        first = key not in self._compile_seen
        if first:
            if len(self._compile_seen) > 4096:
                self._compile_seen.clear()
            self._compile_seen.add(key)
        if count:
            metrics.update_service_bucket_compile(first)
        return first

    def _device_solve_timed(self, stacked: PackedCluster, batch):
        """One device-path solve (the solve_hook seam included), timed
        on the service clock, with the server-side chaos hook inside the
        timing window (injected sick-phase latency must be SEEN)."""
        t = self.clock.now()
        try:
            if self.chaos is not None:
                self.chaos.on_batch()
            if self.solve_hook is not None:
                out = np.asarray(self.solve_hook(stacked, batch))
            else:
                out = self._solve(stacked)
            return np.asarray(out), self.clock.now() - t, None
        except Exception as err:  # noqa: BLE001, exception-discipline — the error is RETURNED for classification: every caller either re-raises it or flips the watchdog, which fires the device-sick metric + flight event
            return None, self.clock.now() - t, err

    def _note_device_edge(self, edge: Optional[str]) -> None:
        """Fire the gauge, the flight event and the log line for one
        watchdog edge — ONE site per edge so /healthz, the
        ``service_device_sick`` gauge and the flight recorder always
        agree."""
        if edge is None:
            return
        wd = self._devhealth
        if edge == "sick":
            metrics.update_service_device_sick(True)
            flight.note_event(
                "device-sick",
                cause=wd.sick_reason or "device health watchdog fired",
            )
            log.error(
                "device sick (%s) — serving the numpy-oracle host path "
                "until hysteresis probes pass",
                wd.sick_reason,
            )
        elif edge == "recovered":
            metrics.update_service_device_sick(False)
            flight.note_event(
                "device-recovered",
                cause=f"{wd.RECOVERY_PROBES} consecutive healthy probes",
            )
            log.info(
                "device recovered after hysteresis probes; the device "
                "solve path resumes"
            )

    def _solve_batch(self, stacked: PackedCluster, batch) -> np.ndarray:
        """Route one stacked batch through the failure-domain ladder:
        the device path while healthy (timed into the watchdog), the
        numpy-oracle host path while sick (except hysteresis probes).
        A device exception flips the watchdog and is contained to the
        host path for the batch; host-path exceptions propagate to
        drain_once's per-batch containment."""
        self._note_bucket_compile(
            stacked, batch[0].horizon if batch else 0
        )
        if batch and batch[0].horizon > 0:
            return self._solve_schedule_batch(stacked, batch[0].horizon)
        wd = self._watchdog()
        if wd is None:
            out, _dur, err = self._device_solve_timed(stacked, batch)
            if err is not None:
                raise err
            return out
        if not wd.sick:
            first = self._first_compile(stacked)
            out, dur, err = self._device_solve_timed(stacked, batch)
            if err is not None:
                self._note_device_edge(wd.note_error(err))
                # the batch still fails typed (drain_once contains it):
                # the agents' local fallback owns THIS tick, the host
                # path owns the next — no silently-different result from
                # the batch that exposed the error
                raise err
            if not first:
                # a shape's first solve carries its compile: neither a
                # slowness verdict nor a baseline sample
                self._note_device_edge(wd.note_batch(dur))
            # a slow result is still a correct result
            return out
        if wd.should_probe():
            first = self._first_compile(stacked)
            out, dur, err = self._device_solve_timed(stacked, batch)
            if err is not None:
                self._note_device_edge(wd.note_probe(dur, ok=False))
                return self._solve_host(stacked)
            if not first:
                self._note_device_edge(wd.note_probe(dur, ok=True))
            return out
        return self._solve_host(stacked)

    def _solve_schedule_batch(self, stacked: PackedCluster, horizon: int):
        """One batched drain-SCHEDULE solve (wire v3): int32
        [T, horizon, 3+K]. Routed like the single-plan solve — host
        oracle for solver=numpy and while the watchdog holds the device
        sick — but deliberately NOT fed into the watchdog's latency
        baseline: a schedule is ~horizon single solves by construction,
        and sampling it would poison the EMA a single-plan batch is
        judged against (a device ERROR still flips the watchdog)."""
        wd = self._watchdog()
        if self.config.solver == "numpy" or (wd is not None and wd.sick):
            return self._solve_schedule_host(stacked, horizon)
        if horizon not in self._sched_programs:
            from k8s_spot_rescheduler_tpu.parallel.tenant_batch import (
                make_tenant_schedule_planner,
            )

            cfg = self.config
            self._sched_programs[horizon] = make_tenant_schedule_planner(
                self._ensure_mesh(),
                horizon=horizon,
                rounds=(cfg.repair_rounds if cfg.fallback_best_fit else 0),
                best_fit_fallback=cfg.fallback_best_fit,
            )
        try:
            if self.chaos is not None:
                self.chaos.on_batch()
            # the schedule batch shards over the tenant mesh exactly
            # like the single-plan batch: pad the tenant axis to a
            # device multiple with all-invalid problems, trim after
            T = stacked.slot_req.shape[0]
            return np.asarray(
                self._sched_programs[horizon](
                    self._pad_tenant_axis(stacked)
                )
            )[:T]
        except Exception as err:  # noqa: BLE001, exception-discipline — a device failure on the schedule program flips the SAME watchdog edge (gauge + flight) as a single-plan batch, then drain_once's per-batch containment answers the tenants typed
            if wd is not None:
                self._note_device_edge(wd.note_error(err))
            raise

    def _solve_schedule_host(
        self, stacked: PackedCluster, horizon: int
    ) -> np.ndarray:
        """Per-tenant host drain schedules via the SAME oracle loop
        SolverPlanner's numpy branch runs (solver/schedule.
        plan_schedule_oracle) — one host implementation, no drift."""
        from k8s_spot_rescheduler_tpu.solver.schedule import (
            plan_schedule_oracle,
        )

        cfg = self.config
        T = stacked.slot_req.shape[0]
        K = stacked.slot_req.shape[2]
        out = np.full((T, horizon, 3 + K), -1, np.int32)
        for t in range(T):
            packed = PackedCluster(
                *(np.asarray(getattr(stacked, f)[t]) for f in stacked._fields)
            )
            out[t] = plan_schedule_oracle(
                packed,
                horizon,
                best_fit_fallback=cfg.fallback_best_fit,
                repair_rounds=cfg.repair_rounds,
            )
        return out

    def run_canary(self) -> None:
        """Idle liveness canary (called from the scheduler loop): a tiny
        all-invalid solve through the device path, timed into the
        watchdog, so a wedging device is noticed before the next real
        request pays for the discovery."""
        wd = self._watchdog()
        if wd is None or not wd.should_canary():
            return
        bucket = self._canary_bucket()
        if bucket is None:
            return  # nothing has solved yet: no R/W/A dims to build with
        stacked = self._all_invalid_stack(bucket)
        first = self._first_compile(stacked)
        out, dur, err = self._device_solve_timed(stacked, [])
        if err is None and first:
            # the canary shape's first run pays its own compile — a
            # liveness proof, not a latency sample
            return
        self._note_device_edge(wd.note_canary(dur, ok=err is None))

    def _canary_bucket(self) -> Optional[Bucket]:
        """The smallest bucket in the fleet's R/W/A shape family — tiny
        by construction, so the canary costs one small compile and a
        trivial solve."""
        with self._work:
            if not self._seen_buckets:
                return None
            dims = max(self._seen_buckets, key=self._seen_buckets.get)
        b = Bucket(*dims)
        return Bucket(
            C=bucketing.MIN_DIM, K=bucketing.MIN_DIM, S=bucketing.MIN_DIM,
            R=b.R, W=b.W, A=b.A,
        )

    @staticmethod
    def _all_invalid_stack(b: Bucket) -> PackedCluster:
        """A T=1 stacked problem of pure pad at the bucket's shape:
        invalid lanes, empty slots, not-ok zero-capacity spots — solves
        to found=False rows, compiles the real program."""
        p = PackedCluster(
            slot_req=np.zeros((b.C, b.K, b.R), np.float32),
            slot_valid=np.zeros((b.C, b.K), bool),
            slot_tol=np.zeros((b.C, b.K, b.W), np.uint32),
            slot_aff=np.zeros((b.C, b.K, b.A), np.uint32),
            cand_valid=np.zeros(b.C, bool),
            spot_free=np.zeros((b.S, b.R), np.float32),
            spot_count=np.zeros(b.S, np.int32),
            spot_max_pods=np.zeros(b.S, np.int32),
            spot_taints=np.zeros((b.S, b.W), np.uint32),
            spot_ok=np.zeros(b.S, bool),
            spot_aff=np.zeros((b.S, b.A), np.uint32),
        )
        return bucketing.stack_bucket([p], b)

    # ------------------------------------------------------------------
    # graceful drain + warm restart

    @property
    def draining(self) -> bool:
        return self._draining

    def drain_retry_after(self) -> int:
        """The ONE Retry-After horizon every drain-refusal surface
        quotes (typed ServiceBusy, HTTP header, log line): the grace —
        by then this replica is gone and another answers."""
        return max(1, int(math.ceil(self.config.service_drain_grace)))

    def begin_drain(self) -> None:
        """Stop admitting (new submissions get 503 + Retry-After); the
        already-queued work still solves, bounded by
        ``drain_pending``."""
        with self._work:
            if self._draining:
                return
            self._draining = True
            self._work.notify_all()
        log.info(
            "planner service draining: refusing new plan requests "
            "(Retry-After %ds); finishing queued batches",
            self.drain_retry_after(),
        )

    def drain_pending(self) -> None:
        """Finish queued batches within ``service_drain_grace``; evict
        whatever remains past the grace with a typed 503 so no agent
        blocks on a dying replica."""
        grace = self.config.service_drain_grace
        deadline = self.clock.now() + grace
        while self.clock.now() < deadline:
            if not self.drain_once():
                break
        with self._work:
            leftover = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
        for req in leftover:
            req.error = ServiceBusy(
                "service draining (graceful shutdown); retry another "
                "replica",
                self.drain_retry_after(),
            )
            metrics.update_service_request("expired")
            metrics.update_service_tenant_eviction(req.tenant)
            self._note_shed(
                "drain-evict",
                "queued plan request evicted by graceful drain",
                tenant=req.tenant, trace_id=req.trace_id,
            )
            req.event.set()

    def _state_path(self) -> str:
        d = self.config.service_state_dir
        return os.path.join(d, STATE_FILE) if d else ""

    def save_state(self) -> Optional[str]:
        """Persist the warm-restart state (atomic rename): per-tenant
        last-pack bucket fingerprints + the recently-used bucket list a
        restarted replica pre-warms."""
        path = self._state_path()
        if not path:
            return None
        with self._work:
            buckets = sorted(
                self._seen_buckets,
                key=self._seen_buckets.get,
                reverse=True,
            )
            payload = {
                "version": 2,
                "tenants": dict(self._tenant_bucket),
                "buckets": [list(dims) for dims in buckets],
                # delta-wire pack fingerprints: the cached CONTENT does
                # not survive a restart, but the fingerprints do — a
                # reconnecting agent's first delta then gets a resync
                # demand that NAMES the restart as its cause, and the
                # anti-entropy accounting stays exact
                "fingerprints": {
                    t: e.fp for t, e in self._tenant_cache.items()
                },
            }
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError as err:
            # a full/readonly state volume must not take the service
            # down; the only cost is a colder next restart
            log.error("planner warm-state save failed: %s", err)
            return None

    def warm_start(self) -> List[str]:
        """Pre-warm the persisted buckets' compiles on boot so a
        restarted replica doesn't eat a compile storm from N
        reconnecting agents; returns the warmed bucket keys."""
        path = self._state_path()
        if not path or not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                payload = json.load(f)
            bucket_dims = list(payload.get("buckets", ()))
            tenants = payload.get("tenants", {})
            fingerprints = payload.get("fingerprints", {})
        except (OSError, ValueError, TypeError, AttributeError) as err:
            # valid JSON of the wrong SHAPE (a list, "buckets": 5) must
            # cost a cold start, never the boot — same contract as an
            # unreadable file
            log.error("planner warm state unreadable (%s); cold start", err)
            return []
        warmed: List[str] = []
        wall = self.clock.wall()
        for dims in bucket_dims[:WARM_MAX_BUCKETS]:
            try:
                b = Bucket(*(int(d) for d in dims))
            except (TypeError, ValueError):
                continue
            try:
                stacked = self._all_invalid_stack(b)
                self._solve(stacked)
                self._note_bucket_compile(stacked, 0, count=False)
            except Exception as err:  # noqa: BLE001, exception-discipline — a failed pre-warm costs one later cold compile, never availability; boot continues and the failure is logged
                log.error("bucket %s pre-warm failed: %s", b.key, err)
                continue
            warmed.append(b.key)
            with self._work:
                self._seen_buckets[tuple(b)] = wall
        if isinstance(tenants, dict):
            with self._work:
                self._tenant_bucket.update(
                    {str(t): str(k) for t, k in tenants.items()}
                )
        if isinstance(fingerprints, dict):
            with self._work:
                self._warm_fps.update(
                    {str(t): str(fp) for t, fp in fingerprints.items()}
                )
        if warmed:
            log.info(
                "warm restart: pre-warmed %d bucket compile(s): %s",
                len(warmed), ", ".join(warmed),
            )
        self.warmed_buckets = warmed
        return warmed

    # ------------------------------------------------------------------
    # solving

    def batch_program(self) -> str:
        """What actually solves batches (surfaced on /healthz so a
        configured solver name can never silently misreport)."""
        return (
            "numpy-oracle"
            if self.config.solver == "numpy"
            else "tenant-batch(jax union)"
        )

    def _ensure_mesh(self):
        """The tenant mesh, probed once: None on a single-device (or
        backend-less) host, shared by the batch, schedule and delta-
        scatter programs."""
        if self._mesh_ready:
            return self._mesh
        try:
            import jax

            if len(jax.devices()) > 1:
                from k8s_spot_rescheduler_tpu.parallel.mesh import (
                    make_tenant_mesh,
                )

                self._mesh = make_tenant_mesh()
        except Exception:  # noqa: BLE001, exception-discipline — no backend info: stay 1-chip, the single-device vmap program is the documented degradation and /healthz batch_program names it
            self._mesh = None
        self._mesh_ready = True
        return self._mesh

    def _pad_tenant_axis(self, stacked: PackedCluster) -> PackedCluster:
        """Pad the tenant axis to a device multiple so the batch
        SHARDS instead of falling to one-device vmap; pad tenants are
        all-invalid problems (found=False rows, trimmed by callers)."""
        if self._mesh is None:
            return stacked
        T = stacked.slot_req.shape[0]
        n = int(self._mesh.devices.size)
        pad = (-T) % n
        if not pad:
            return stacked
        return PackedCluster(
            *(
                np.concatenate(
                    [
                        np.asarray(f),
                        np.zeros((pad,) + f.shape[1:], f.dtype),
                    ]
                )
                for f in stacked
            )
        )

    def _solve(self, stacked: PackedCluster) -> np.ndarray:
        if self.config.solver == "numpy":
            return self._solve_host(stacked)
        if self._batched is None:
            from k8s_spot_rescheduler_tpu.parallel.tenant_batch import (
                make_tenant_batch_planner,
            )

            self._ensure_mesh()
            cfg = self.config
            if cfg.solver not in ("jax",):
                # pallas/sharded are per-tenant SINGLE-problem kernel
                # choices; the service's scale story is the tenant
                # batch, which composes the jax union program. Say so
                # instead of silently no-opping the flag.
                log.info(
                    "planner service batches tenants with the jax union "
                    "program (configured solver %r selects in-process "
                    "kernels; /healthz reports batch_program)",
                    cfg.solver,
                )
            self._batched = make_tenant_batch_planner(
                self._mesh,
                rounds=(
                    cfg.repair_rounds if cfg.fallback_best_fit else 0
                ),
                best_fit_fallback=cfg.fallback_best_fit,
            )
        T = stacked.slot_req.shape[0]
        return np.asarray(self._batched(self._pad_tenant_axis(stacked)))[:T]

    def _solve_host(self, stacked: PackedCluster) -> np.ndarray:
        """The numpy-oracle batch path (CI / --solver numpy): the SAME
        union helper SolverPlanner's host branch calls
        (solver/numpy_oracle.plan_union_oracle), per tenant — one host
        union, so the two paths cannot drift."""
        from k8s_spot_rescheduler_tpu.solver.numpy_oracle import (
            plan_union_oracle,
        )

        cfg = self.config
        T = stacked.slot_req.shape[0]
        K = stacked.slot_req.shape[2]
        out = np.zeros((T, 3 + K), np.int32)
        for t in range(T):
            packed = PackedCluster(
                *(np.asarray(getattr(stacked, f)[t]) for f in stacked._fields)
            )
            result = plan_union_oracle(
                packed,
                best_fit_fallback=cfg.fallback_best_fit,
                repair_rounds=cfg.repair_rounds,
            )
            feasible = np.asarray(result.feasible)
            idx = int(np.argmax(feasible)) if feasible.size else 0
            out[t, 0] = idx
            out[t, 1] = int(bool(feasible.any()))
            out[t, 2] = int(feasible.sum())
            if feasible.size:
                out[t, 3:] = np.asarray(result.assignment[idx], np.int32)
        return out

    # ------------------------------------------------------------------
    # scheduler thread

    def start_scheduler(self) -> None:
        if self._thread is not None:
            return
        with self._work:
            self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop_scheduler(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._work:
                has_work = any(self._queues.get(t) for t in self._queues)
                if not has_work and not self._stop and not self._draining:
                    self._work.wait(timeout=1.0)
                    has_work = any(
                        self._queues.get(t) for t in self._queues
                    )
                if self._stop:
                    return
                if self._draining and not has_work:
                    # graceful drain finished its queue; drain_pending
                    # owns the bounded tail, nothing left to schedule
                    return
            if not has_work:
                # idle: give the device-health watchdog its canary
                # window (no-op unless overdue)
                self.run_canary()
                continue
            # coalescing window: concurrent tenants land in one batch
            # (skipped while draining — latency no longer buys batching)
            if self.batch_window_s > 0 and not self._draining:
                self.clock.sleep(self.batch_window_s)
            while self.drain_once():
                pass


# ---------------------------------------------------------------------------
# HTTP surface


class ServiceServer:
    """HTTP front of a :class:`PlannerService`: ``/v2/plan`` (binary
    wire), ``/v1/plan`` (legacy JSON adapter over the same queue) and
    ``/healthz``. Edge bounds are the sidecar's, unchanged: body cap
    (413), handler depth cap with pre-body-read rejection (503)."""

    def __init__(
        self,
        config: ReschedulerConfig,
        address: str = "127.0.0.1:8642",
        *,
        max_body_bytes: int = 128 << 20,
        queue_timeout_s: Optional[float] = None,
        # fleet-facing default: comfortably above the HBM-derived batch
        # caps so concurrently-ticking agents are queued (and batched),
        # not shed; the single-tenant sidecar surface keeps its
        # historical 4
        max_inflight: int = 16,
        batch_window_s: Optional[float] = None,
        max_batch_tenants: int = 0,
        clock: Optional[Clock] = None,
    ):
        self.config = config
        self.service = PlannerService(
            config,
            queue_timeout_s=queue_timeout_s,
            batch_window_s=batch_window_s,
            max_batch_tenants=max_batch_tenants,
            clock=clock,
        )
        self.max_body_bytes = int(max_body_bytes)
        self.max_inflight = int(max_inflight)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # Resync-storm admission class (docs/ROBUSTNESS.md "Resync
        # storms"): full-pack resync ingests — a fingerprinted full
        # pack from a tenant with NO cached state (first contact or
        # post-restart re-seed) — get their own bounded admission:
        # a concurrent-ingest token bucket plus a byte ledger charging
        # each ingest its estimated per-tenant HBM footprint (the same
        # model the batch cap uses). A replica restart under a large
        # fleet stales every tenant's fingerprint at once; this class
        # sheds the excess (503 + load-derived Retry-After, reason
        # resync-storm) so delta traffic and cached tenants keep their
        # queue-wait SLO instead of the queue collapsing.
        self.resync_ingest_cap = int(config.service_resync_ingest_cap)
        self._resync_lock = threading.Lock()
        self._resync_inflight = 0
        self._resync_ledger_bytes = 0
        # refusals not yet drained by a completed ingest — the load
        # term that makes Retry-After grow with the storm instead of
        # answering every refused tenant the same static horizon
        self._resync_pressure = 0
        # flight recorder knobs ride the same config the control loop
        # uses; in service-only mode this process records request-level
        # degradation events (sheds, solve failures) instead of ticks
        flight.configure(
            ring_size=config.flight_ring_size,
            dump_dir=config.flight_dump_dir,
        )
        # the last few requests' server-side span blocks, keyed by the
        # agent trace ID that sent them (/debug/trace on a service that
        # has no tick of its own)
        self._recent_lock = threading.Lock()
        self._recent: deque = deque(maxlen=32)
        # live accepted sockets: with keep-alive a handler thread stays
        # parked in readline() between requests, so closing the listener
        # alone would leave pooled agent connections happily served by a
        # "stopped" replica — close() must hard-close these too
        self._conn_lock = threading.Lock()
        self._open_conns: set = set()
        host, _, port = address.rpartition(":")
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive for the persistent agent wire (service/agent.py
            # PooledWireTransport): HTTP/1.1 + the Content-Length
            # discipline _send_bytes already enforces lets one socket
            # carry every tick. The default HTTP/1.0 answered one
            # request per connection — the per-tick TCP+HTTP setup tax
            # the pool exists to amortize. Pre-body rejects still close
            # (_reject_unread), and an idle connection is reaped after
            # ``timeout`` so drained agents don't pin handler threads.
            protocol_version = "HTTP/1.1"
            timeout = 120.0
            # on a keep-alive connection the reply goes out as two
            # writes (buffered headers, then body): with Nagle on, the
            # body segment sits behind the client's delayed ACK —
            # a ~40ms stall per tick that dwarfs the round trip the
            # pool exists to shrink. (A closing connection never showed
            # it: the FIN flushed the tail.)
            disable_nagle_algorithm = True

            def setup(self):
                super().setup()
                with server._conn_lock:
                    server._open_conns.add(self.connection)

            def finish(self):
                try:
                    super().finish()
                finally:
                    with server._conn_lock:
                        server._open_conns.discard(self.connection)

            def log_message(self, *a):
                pass

            def _send_json(self, obj, code=200, headers=()):
                data = json.dumps(obj).encode()
                self._send_bytes(data, "application/json", code, headers)

            def _send_bytes(self, data, ctype, code=200, headers=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    from k8s_spot_rescheduler_tpu.loop import health

                    out = {
                        "ok": True,
                        "solver": server.config.solver,
                        "batch_program": server.service.batch_program(),
                    }
                    out.update(server.service.healthz_snapshot())
                    out.update(health.snapshot())
                    return self._send_json(out)
                if self.path.startswith("/debug/"):
                    return self._debug_get()
                return self._send_json({"error": "not found"}, 404)

            def _debug_get(self):
                """/debug/trace (last tick tree + recent server span
                blocks) and /debug/flight (ring summary; ?dump=1 writes
                a postmortem). Gated OFF by default — 404, not 403, so
                a disabled surface is indistinguishable from an absent
                one."""
                if not server.config.debug_endpoints:
                    return self._send_json({"error": "not found"}, 404)
                path, _, query = self.path.partition("?")
                if path == "/debug/trace":
                    return self._send_json({
                        "last_tick": flight.last_tick(),
                        "recent_requests": server.recent_request_traces(),
                    })
                if path == "/debug/flight":
                    out = flight.snapshot()
                    if "dump=1" in query.split("&"):
                        out["dumped"] = flight.dump("debug-endpoint")
                    return self._send_json(out)
                return self._send_json({"error": "not found"}, 404)

            def _reject_unread(self, obj, code, headers=()):
                """A response sent BEFORE the body was read must close
                the connection: under keep-alive the unconsumed body
                bytes would desync the next request on this socket.
                Applies to every pre-read reject — 400/404/413/503."""
                self.close_connection = True
                return self._send_json(
                    obj, code,
                    headers=tuple(headers) + (("Connection", "close"),),
                )

            def _read_body(self):
                """Content-Length checks + the body read, or None if a
                reject was already sent."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    self._reject_unread({"error": "bad Content-Length"}, 400)
                    return None
                if length < 0:
                    # must not reach rfile.read(-1): buffer-until-EOF is
                    # the exact exhaustion the size cap prevents
                    self._reject_unread({"error": "bad Content-Length"}, 400)
                    return None
                if length > server.max_body_bytes:
                    self._reject_unread(
                        {
                            "error": "request exceeds %d-byte limit"
                            % server.max_body_bytes
                        },
                        413,
                    )
                    metrics.update_service_request("rejected")
                    return None
                if server.service.draining:
                    # graceful drain: refuse BEFORE the body is read,
                    # naming the horizon a failover replica answers by
                    metrics.update_service_request("rejected")
                    server.service._note_shed(
                        "drain-refuse",
                        "replica draining (graceful shutdown)",
                        trace_id=self.headers.get("X-Trace-Id", "") or "",
                    )
                    self._reject_unread(
                        {"error": "planner draining"},
                        503,
                        headers=[(
                            "Retry-After",
                            str(server.service.drain_retry_after()),
                        )],
                    )
                    return None
                if not server._admit():
                    metrics.update_service_request("rejected")
                    server.service._note_shed(
                        "max-inflight",
                        "planner overloaded (%d requests in flight)"
                        % server.max_inflight,
                        trace_id=self.headers.get("X-Trace-Id", "") or "",
                    )
                    self._reject_unread(
                        {
                            "error": "planner overloaded (%d requests in "
                            "flight)" % server.max_inflight
                        },
                        503,
                        headers=[(
                            "Retry-After",
                            str(server.service.retry_after()),
                        )],
                    )
                    return None
                try:
                    return self.rfile.read(length)
                except Exception:
                    # the slot was admitted above but the caller's
                    # finally-release is only reached once we RETURN a
                    # body — a client aborting mid-upload must not leak
                    # its inflight slot forever
                    server._release()
                    raise

            def do_POST(self):
                if self.path == "/v2/plan":
                    return self._post_wire()
                if self.path == "/v1/plan":
                    return self._post_json()
                return self._reject_unread({"error": "not found"}, 404)

            def _post_wire(self):
                t_req = time.perf_counter()
                body = self._read_body()
                if body is None:
                    return
                # ingest-bandwidth accounting (the ceiling the delta
                # wire lowers): every /v2/plan body, pack or delta
                metrics.update_service_wire_ingest(len(body))
                chaos = server.service.chaos
                if chaos is not None:
                    # the decode chaos hook: a corrupted request must
                    # come back as a clean typed 400, never a crash
                    corrupted = chaos.corrupt_request(body)
                    if corrupted is not None:
                        body = corrupted
                # the reply speaks the REQUEST's protocol version so an
                # un-upgraded v1 agent keeps decoding; before a
                # successful decode the raw header byte is the best
                # guess (falling back to v1, which every decoder speaks)
                raw_version = body[4] if len(body) > 4 else 0
                reply_version = (
                    raw_version
                    if raw_version in wire.SUPPORTED_VERSIONS
                    else 1
                )
                if (
                    len(body) > 5
                    and body[5] == wire.KIND_PACKED_DELTA
                    and reply_version >= 4
                ):
                    # the delta wire (v4): same endpoint, its own
                    # decode/answer contract (resync-on-anything)
                    return self._post_wire_delta(body, t_req)
                # ledger charge held by THIS request when it was
                # admitted as a resync-class ingest (-1 = not one);
                # released in the finally below
                resync_charge = -1
                try:
                    admit_ms = (time.perf_counter() - t_req) * 1e3
                    try:
                        t_dec = time.perf_counter()
                        req = wire.decode_plan_request_ex(body)
                        decode_ms = (time.perf_counter() - t_dec) * 1e3
                    except wire.WireError as err:
                        metrics.update_service_request("error")
                        return self._send_bytes(
                            wire.encode_error(
                                str(err), version=reply_version
                            ),
                            "application/octet-stream", 400,
                        )
                    trace_id = req.trace_id or (
                        self.headers.get("X-Trace-Id", "") or ""
                    )
                    # Resync-storm admission: a fingerprinted full pack
                    # for a tenant with no cached state is a
                    # cache-seeding resync ingest (first contact or the
                    # post-restart re-upload every tenant fires at
                    # once). It must clear the bounded resync class
                    # BEFORE entering the queue — delta traffic and
                    # cached tenants never touch this gate.
                    if req.pack_fingerprint and not (
                        server.service.tenant_cached(req.tenant)
                    ):
                        ok, retry, charge = server.admit_resync_ingest(
                            req.packed
                        )
                        if not ok:
                            metrics.update_service_request("rejected")
                            server.service._note_shed(
                                "resync-storm",
                                "full-pack resync ingest refused: "
                                "concurrent-ingest cap or byte ledger "
                                "exhausted",
                                tenant=req.tenant, trace_id=trace_id,
                                kind="resync-shed",
                            )
                            return self._send_bytes(
                                wire.encode_error(
                                    "resync ingest shed (storm "
                                    "admission); retry after the "
                                    "suggested horizon",
                                    version=reply_version,
                                ),
                                "application/octet-stream", 503,
                                headers=[("Retry-After", str(retry))],
                            )
                        resync_charge = charge
                    try:
                        # the agent declares its own HTTP deadline:
                        # waiting longer server-side would batch-solve
                        # (and hold an inflight slot for) a request the
                        # caller already abandoned
                        try:
                            deadline = float(
                                self.headers.get("X-Planner-Deadline", 0)
                                or 0
                            )
                        except (TypeError, ValueError):
                            deadline = 0.0
                        reply = server.service.submit(
                            req.tenant, req.packed,
                            timeout_s=deadline or None,
                            trace_id=trace_id,
                            schedule_horizon=req.schedule_horizon,
                            pack_fingerprint=req.pack_fingerprint,
                        )
                    except ServiceBusy as err:
                        return self._send_bytes(
                            wire.encode_error(
                                str(err), version=reply_version
                            ),
                            "application/octet-stream", 503,
                            headers=[("Retry-After", str(err.retry_after))],
                        )
                    # complete the server-side span block: admit (slot
                    # + body read) and decode ahead of the queue spans,
                    # encode measured on a first encode and shipped via
                    # a second (the reply is a few hundred bytes; the
                    # re-encode costs less than leaving the span out)
                    spans = (
                        tracing.make_span("service.admit", 0.0, admit_ms),
                        tracing.make_span(
                            "service.decode", admit_ms, decode_ms
                        ),
                    ) + reply.spans
                    # schedule requests (wire v3) answer in the
                    # schedule kind; the encode dance is identical
                    encode = (
                        wire.encode_plan_schedule_reply
                        if isinstance(reply, wire.PlanScheduleReply)
                        else wire.encode_plan_reply
                    )
                    t_enc = time.perf_counter()
                    encode(
                        reply._replace(spans=spans), version=req.version
                    )
                    encode_ms = (time.perf_counter() - t_enc) * 1e3
                    spans = spans + (
                        tracing.make_span("service.encode", 0.0, encode_ms),
                    )
                    server.note_request_trace(trace_id, req.tenant, spans)
                    return self._send_bytes(
                        encode(
                            reply._replace(spans=spans),
                            version=req.version,
                        ),
                        "application/octet-stream",
                    )
                except Exception as err:  # noqa: BLE001 — handler survives
                    log.error("service /v2/plan failed: %s", err)
                    metrics.update_service_request("error")
                    return self._send_bytes(
                        wire.encode_error(str(err), version=reply_version),
                        "application/octet-stream", 500,
                    )
                finally:
                    if resync_charge >= 0:
                        server.release_resync_ingest(resync_charge)
                    server._release()

            def _post_wire_delta(self, body: bytes, t_req: float):
                """One delta-backed plan request (wire v4). The answer
                ladder is resync-on-anything: a decode anomaly, an
                unknown/mismatched base, or an apply failure all come
                back as HTTP 200 + KIND_RESYNC (a 4xx would read as an
                endpoint failure and trip the agent's breaker — a
                resync is protocol, not an outage); only queue
                pressure (503) and handler bugs (500) answer as for
                full packs. The caller already released no state: the
                inflight slot is freed in the finally as usual."""
                try:
                    admit_ms = (time.perf_counter() - t_req) * 1e3
                    header_trace = self.headers.get("X-Trace-Id", "") or ""
                    try:
                        t_dec = time.perf_counter()
                        dreq = wire.decode_packed_delta_ex(body)
                        decode_ms = (time.perf_counter() - t_dec) * 1e3
                    except wire.WireError as err:
                        # ANY decode anomaly (truncation, bit flip —
                        # the digest catches payload corruption) is a
                        # typed resync demand; the agent answers with
                        # one full pack, never a wrong plan
                        cause = f"delta decode failed: {err}"
                        server.service.note_resync(
                            "", cause, header_trace
                        )
                        return self._send_bytes(
                            wire.encode_resync(cause, version=4),
                            "application/octet-stream",
                        )
                    trace_id = dreq.trace_id or header_trace
                    try:
                        deadline = float(
                            self.headers.get("X-Planner-Deadline", 0)
                            or 0
                        )
                    except (TypeError, ValueError):
                        deadline = 0.0
                    try:
                        reply = server.service.submit_delta(
                            dreq.tenant,
                            dreq.delta,
                            dreq.base_fingerprint,
                            dreq.new_fingerprint,
                            timeout_s=deadline or None,
                            trace_id=trace_id,
                        )
                    except ResyncRequired as err:
                        # counted + flight-evented at the demand site
                        return self._send_bytes(
                            wire.encode_resync(str(err), version=4),
                            "application/octet-stream",
                        )
                    except ServiceBusy as err:
                        return self._send_bytes(
                            wire.encode_error(str(err), version=4),
                            "application/octet-stream", 503,
                            headers=[("Retry-After", str(err.retry_after))],
                        )
                    spans = (
                        tracing.make_span("service.admit", 0.0, admit_ms),
                        tracing.make_span(
                            "service.decode", admit_ms, decode_ms
                        ),
                    ) + reply.spans
                    t_enc = time.perf_counter()
                    wire.encode_plan_reply(
                        reply._replace(spans=spans), version=dreq.version
                    )
                    encode_ms = (time.perf_counter() - t_enc) * 1e3
                    spans = spans + (
                        tracing.make_span("service.encode", 0.0, encode_ms),
                    )
                    server.note_request_trace(trace_id, dreq.tenant, spans)
                    return self._send_bytes(
                        wire.encode_plan_reply(
                            reply._replace(spans=spans),
                            version=dreq.version,
                        ),
                        "application/octet-stream",
                    )
                except Exception as err:  # noqa: BLE001 — handler survives
                    log.error("service /v2/plan (delta) failed: %s", err)
                    metrics.update_service_request("error")
                    return self._send_bytes(
                        wire.encode_error(str(err), version=4),
                        "application/octet-stream", 500,
                    )
                finally:
                    server._release()

            def _post_json(self):
                body = self._read_body()
                if body is None:
                    return
                try:
                    try:
                        snapshot = json.loads(body)
                    except ValueError as err:
                        return self._send_json({"error": str(err)}, 400)
                    tenant = self.headers.get("X-Tenant") or "default"
                    try:
                        result = server.plan_json(snapshot, tenant=tenant)
                    except ServiceBusy as err:
                        return self._send_json(
                            {"error": str(err)}, 503,
                            headers=[("Retry-After", str(err.retry_after))],
                        )
                    except (ValueError, KeyError) as err:
                        return self._send_json({"error": str(err)}, 400)
                    return self._send_json(result)
                except Exception as err:  # noqa: BLE001 — handler survives
                    log.error("service /v1/plan failed: %s", err)
                    metrics.update_service_request("error")
                    return self._send_json({"error": str(err)}, 500)
                finally:
                    server._release()

        self.server = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port)), Handler
        )

    def _admit(self) -> bool:
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            return True

    def _release(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    def _resync_ingest_budget(self) -> int:
        """Byte budget for the resync-ingest ledger: the configured
        override, else the solver HBM budget the batch cap sizes
        against, else the device budget."""
        configured = int(self.config.service_resync_ingest_budget)
        if configured > 0:
            return configured
        return int(self.config.solver_hbm_budget) or memory.device_hbm_budget()

    def admit_resync_ingest(self, packed):
        """Gate ONE cache-seeding full-pack resync ingest through the
        bounded admission class. Returns ``(admitted, retry_after_s,
        charge_bytes)``; an admitted ingest holds one token and
        ``charge_bytes`` of ledger until :meth:`release_resync_ingest`.
        Refusals carry a LOAD-derived Retry-After: the measured batch
        cadence scaled by how deep the storm currently is (in-flight
        ingests plus undrained refusals, per cap slot) — the herd is
        answered with staggered horizons, not one synchronized
        comeback time. A lone over-budget tenant is still admitted
        when the class is idle (the batch cap's never-zero floor)."""
        bucket = bucketing.bucket_for(packed)
        per = bucketing.per_tenant_hbm_bytes(bucket)
        budget = self._resync_ingest_budget()
        with self._resync_lock:
            over_cap = self._resync_inflight >= self.resync_ingest_cap
            over_budget = (
                self._resync_inflight > 0
                and self._resync_ledger_bytes + per > budget
            )
            if over_cap or over_budget:
                self._resync_pressure += 1
                cadence = max(1, self.service.retry_after())
                retry = int(math.ceil(
                    cadence
                    * (self._resync_inflight + self._resync_pressure)
                    / max(1, self.resync_ingest_cap)
                ))
                return False, max(1, retry), 0
            self._resync_inflight += 1
            self._resync_ledger_bytes += per
            metrics.update_service_resync_ingest(
                self._resync_inflight, self._resync_ledger_bytes,
                admitted=True,
            )
            return True, 0, per

    def release_resync_ingest(self, charge_bytes: int) -> None:
        """Return one resync-ingest token (and its ledger bytes); each
        completed ingest also drains one unit of refusal pressure so
        Retry-After horizons relax as the storm is worked off."""
        with self._resync_lock:
            self._resync_inflight = max(0, self._resync_inflight - 1)
            self._resync_ledger_bytes = max(
                0, self._resync_ledger_bytes - int(charge_bytes)
            )
            self._resync_pressure = max(0, self._resync_pressure - 1)
            metrics.update_service_resync_ingest(
                self._resync_inflight, self._resync_ledger_bytes
            )

    def note_request_trace(self, trace_id: str, tenant: str, spans) -> None:
        """Remember one request's server-side span block, keyed by the
        agent's trace ID (/debug/trace on the service process). The
        tenant id is client-supplied and /debug responses may leave the
        process, so it rides hashed per the redaction policy."""
        entry = {
            "trace_id": trace_id,
            "tenant": flight.redact_text(tenant),
            "spans": [
                {"name": n, "t0_ms": round(t0, 3), "dur_ms": round(d, 3)}
                for n, t0, d in spans
            ],
        }
        with self._recent_lock:
            self._recent.append(entry)

    def recent_request_traces(self) -> list:
        with self._recent_lock:
            return list(self._recent)

    @property
    def address(self) -> str:
        host, port = self.server.server_address
        return f"{host}:{port}"

    # ------------------------------------------------------------------
    # the legacy JSON adapter: decode -> pack -> the SAME queue

    def plan_json(self, body: dict, *, tenant: str = "default") -> dict:
        """Kubernetes-JSON snapshot in, legacy /v1/plan response out —
        packed host-side and solved through the batching queue exactly
        like a wire-protocol tenant (one solve path)."""
        from k8s_spot_rescheduler_tpu.io.kube import (
            decode_node,
            decode_pdb,
            decode_pod,
        )
        from k8s_spot_rescheduler_tpu.models.cluster import build_node_map
        from k8s_spot_rescheduler_tpu.models.tensors import pack_cluster

        cfg = self.config
        nodes = [decode_node(o) for o in body.get("nodes", [])]
        pods = [decode_pod(o) for o in body.get("pods", [])]
        pdbs = [decode_pdb(o) for o in body.get("pdbs", [])]
        pvc_objs = body.get("pvcs") or []
        pv_objs = body.get("pvs") or []
        if pvc_objs or pv_objs:
            from k8s_spot_rescheduler_tpu.io.kube import (
                decode_volume_snapshots,
            )
            from k8s_spot_rescheduler_tpu.models.volumes import (
                resolve_volume_affinity,
            )

            pvcs, pvs = decode_volume_snapshots(pvc_objs, pv_objs)
            pods = [
                resolve_volume_affinity(p, pvcs, pvs)
                if p.pvc_resolvable
                else p
                for p in pods
            ]
        pods_by_node: dict = {}
        for pod in pods:
            pods_by_node.setdefault(pod.node_name, []).append(pod)
        node_map = build_node_map(
            [n for n in nodes if n.ready],
            pods_by_node,
            on_demand_label=cfg.on_demand_node_label,
            spot_label=cfg.spot_node_label,
            priority_threshold=cfg.priority_threshold,
            # not-ready nodes are presence-only (zone/spread counts) —
            # dropping them would overstate the spread domain-min, the
            # permissive direction (same rule as the control loop)
            unready_nodes=[n for n in nodes if not n.ready],
        )
        packed, meta = pack_cluster(
            node_map,
            pdbs,
            resources=cfg.resources,
            delete_non_replicated=cfg.delete_non_replicated_pods,
            pad_slots=cfg.max_pods_per_node_hint,
        )
        reply = self.service.submit(tenant, packed)
        out = {
            "found": reply.found,
            "nCandidates": meta.n_candidates,
            "nFeasible": reply.n_feasible,
            "solveMs": round(reply.solve_ms, 3),
            "batchLanes": reply.batch_lanes,
            "batchTenants": reply.batch_tenants,
        }
        if reply.found:
            plan = meta.build_plan(reply.index, np.asarray(reply.row))
            out["node"] = plan.node.node.name
            out["pods"] = [p.uid for p in plan.pods]
            out["assignments"] = plan.assignments
        return out

    # ------------------------------------------------------------------
    # lifecycle

    def serve_forever(self) -> None:
        log.info("planner service listening on %s", self.address)
        self.service.warm_start()
        self.service.start_scheduler()
        self._serving = True
        self.server.serve_forever()

    def start_background(self, scheduler: bool = True) -> None:
        """Serve on a daemon thread. ``scheduler=False`` skips the
        batching thread: submissions then drain synchronously on the
        handler thread — the deterministic mode the virtual-clock fleet
        smoke drives (no thread ever sleeps on the shared clock)."""
        self.service.warm_start()
        if scheduler:
            self.service.start_scheduler()
        self._serving = True
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def graceful_shutdown(self) -> None:
        """The SIGTERM contract (docs/ROBUSTNESS.md): stop admitting
        (503 + Retry-After = the drain grace), finish queued batches
        within ``service_drain_grace``, persist the warm-restart state,
        then stop serving."""
        svc = self.service
        svc.begin_drain()
        svc.stop_scheduler()
        svc.drain_pending()
        self.close()  # close() persists the warm state

    def close(self) -> None:
        # shutdown() handshakes with a RUNNING serve_forever loop; with
        # no loop ever started (in-process use) it would block forever
        # on an event only serve_forever sets
        if getattr(self, "_serving", False):
            self.server.shutdown()
        self.server.server_close()
        # hard-close live keep-alive connections: their handler threads
        # are parked in readline() waiting for the agent's next request
        # and would keep answering a "closed" replica otherwise
        with self._conn_lock:
            conns = list(self._open_conns)
            self._open_conns.clear()
        for conn in conns:
            with contextlib.suppress(OSError):
                conn.shutdown(socket.SHUT_RDWR)
        self.service.stop_scheduler()
        self.service.save_state()


def main(argv=None) -> int:
    """``python -m k8s_spot_rescheduler_tpu.service.server`` — the
    standalone multi-tenant planner (also reachable as ``--serve`` on
    the main CLI)."""
    import argparse

    ap = argparse.ArgumentParser(prog="spot-rescheduler-planner-service")
    ap.add_argument("--listen", default="127.0.0.1:8642")
    ap.add_argument("--solver", default="jax",
                    choices=["jax", "numpy", "pallas", "sharded"])
    ap.add_argument("--max-body-mb", type=int, default=128,
                    help="reject request bodies larger than this (413)")
    ap.add_argument("--queue-timeout", type=float, default=30.0,
                    help="seconds a plan request may wait in the tenant "
                         "queue before 503 + measured-cadence Retry-After")
    ap.add_argument("--batch-window", type=float, default=0.02,
                    help="seconds the batcher waits to coalesce "
                         "concurrent tenants into one solve")
    ap.add_argument("--max-inflight", type=int, default=16,
                    help="reject immediately (503) past this many "
                         "concurrent requests — bounds worst-case request "
                         "memory at max-inflight x max-body-mb")
    ap.add_argument("--state-dir", default="",
                    help="persist per-tenant pack fingerprints + the "
                         "bucket warmup list here so a restarted replica "
                         "pre-warms its compiles (warm restart)")
    ap.add_argument("--drain-grace", type=float, default=5.0,
                    help="seconds SIGTERM lets queued batches finish "
                         "before the rest are evicted with 503")
    ap.add_argument("-v", "--verbosity", type=int, default=0)
    args = ap.parse_args(argv)
    log.setup(args.verbosity)
    server = ServiceServer(
        ReschedulerConfig(
            solver=args.solver,
            service_queue_timeout=args.queue_timeout,
            service_batch_window=args.batch_window,
            service_state_dir=args.state_dir,
            service_drain_grace=args.drain_grace,
        ),
        args.listen,
        max_body_bytes=args.max_body_mb << 20,
        max_inflight=args.max_inflight,
    )
    install_sigterm_drain(server)
    server.serve_forever()
    return 0


def install_sigterm_drain(server: ServiceServer) -> bool:
    """Route SIGTERM into the graceful-drain contract (no-op outside
    the main thread — an embedded server's host process owns its own
    signals). Returns whether the handler was installed."""
    import signal

    def _sigterm(*_):
        # off the signal frame: graceful_shutdown blocks up to the
        # drain grace and must not run inside the handler
        threading.Thread(
            target=server.graceful_shutdown, daemon=True
        ).start()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
        return True
    except ValueError:
        return False


if __name__ == "__main__":
    import sys

    sys.exit(main())
