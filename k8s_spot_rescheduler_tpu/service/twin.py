"""Lightweight tenant twins: the fleet simulation's agent layer.

A :class:`TenantTwin` is what a full :class:`service.agent.RemotePlanner`
costs too much to be five hundred times over: one synthetic tenant
cluster (columnar store + PDBs), one wire-protocol POST per tick, and
the agent's endpoint-failover breaker re-implemented on the FLEET's
virtual clock — so a thousand twins can drive a real-HTTP replica set
through hours of simulated time in minutes of wall time
(``bench/fleet_twin.py`` owns the event loop; this module owns one
twin's behavior).

What a twin keeps from the real agent, deliberately:

- the wire bytes are the production ones (``wire.encode_plan_request``
  -> ``/v2/plan`` -> ``wire.decode_plan_reply``) against a real
  ``ServiceServer`` socket — transport, decode contract, 503
  Retry-After, all exercised;
- the per-endpoint breaker state is the agent's own
  (:class:`service.agent.Endpoint`) with the agent's thresholds, only
  timed on the shared virtual clock so a skip window costs simulated
  seconds, not wall seconds;
- a tick served by a non-primary replica fires the SAME failover
  accounting the agent fires (``remote_planner_failover`` + the flight
  ``failover`` event) from one site, so flight-delta == metric-delta
  holds for every failover edge the fleet induces;
- every selection is reconstructible (``meta.build_plan``) and
  spot-checkable bit-identical against a solo in-process
  ``SolverPlanner`` — the serve-smoke correctness contract at fleet
  scale.

- since the resync-storm hardening, the twin speaks the agent's FULL
  protocol ladder: v4 ``KIND_PACKED_DELTA`` with pack fingerprints
  (delta to an endpoint whose ``acked_fp`` matches the base, full pack
  otherwise), KIND_RESYNC handling with a jittered decorrelated
  full-pack retry on the virtual clock (per-twin seeded RNG — distinct
  seeds decorrelate the herd deterministically), and occasional v3
  ``schedule_horizon`` requests — so the 512-twin fleet exercises the
  same anti-entropy contract production agents run, restart storms
  included.

What it drops: local-fallback planning and tracing. A twin that cannot
reach any replica records a shed tick and moves on — the fleet bench
asserts on the ACCOUNTING of that degradation, not on hiding it.
"""

from __future__ import annotations

import dataclasses
import urllib.error
import urllib.request
from typing import List, Optional, Sequence, Tuple

import numpy as np

from k8s_spot_rescheduler_tpu.io.synthetic import CONFIGS, generate_cluster
from k8s_spot_rescheduler_tpu.loop import flight
from k8s_spot_rescheduler_tpu.metrics import registry as metrics
from k8s_spot_rescheduler_tpu.models.columnar import (
    emit_packed_delta,
    pack_fingerprint,
)
from k8s_spot_rescheduler_tpu.service import wire
from k8s_spot_rescheduler_tpu.service.agent import (
    Endpoint,
    RemoteCallError,
    RemotePlanner,
)
from k8s_spot_rescheduler_tpu.utils.clock import Clock
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig
from k8s_spot_rescheduler_tpu.utils.labels import matches_label
from k8s_spot_rescheduler_tpu.utils import logging as log

# real-time HTTP budget per POST: generous — queue waits are VIRTUAL
# under the fleet clock (the handler blocks in real time only for the
# host solves ahead of it), so this only bounds a hung socket
HTTP_TIMEOUT_S = 30.0

# every Nth offered tick asks for a whole drain schedule (wire v3)
# instead of a single plan, when the config has schedules on — the
# fleet exercises the KIND_PLAN_SCHEDULE surface at scale without
# paying the horizon-times solve cost on every tick
SCHEDULE_EVERY = 16

# heterogeneity menu: (n_on_demand, n_spot, n_pods) size tiers chosen
# to land in DIFFERENT power-of-two service buckets, so a mixed fleet
# exercises bucket batching + compile sharing instead of collapsing
# into one stacked shape
SIZE_TIERS: Tuple[Tuple[int, int, int], ...] = (
    (3, 3, 18),
    (4, 4, 30),
    (6, 6, 48),
    (8, 8, 80),
)
CADENCE_TIERS_S: Tuple[float, ...] = (30.0, 60.0, 90.0, 180.0)
CHURN_TIERS: Tuple[float, ...] = (0.0, 0.15, 0.35, 0.6)


@dataclasses.dataclass(frozen=True)
class TwinSpec:
    """One twin's identity: cluster shape, tick cadence, churn
    appetite, failure-correlation zone, and RNG seed. ``deadline_s``
    > 0 makes the twin declare a client deadline on every request
    (``X-Planner-Deadline``) — the deadline-cap shed path's tenant."""

    name: str
    n_on_demand: int
    n_spot: int
    n_pods: int
    cadence_s: float
    churn_prob: float
    zone: int
    seed: int
    deadline_s: float = 0.0


def fleet_specs(
    n: int, seed: int = 0, zones: int = 4, deadline_frac: float = 0.0
) -> List[TwinSpec]:
    """A deterministic heterogeneous fleet: sizes, cadences and churn
    rates drawn from the tier menus, zones assigned round-robin so a
    zone-correlated storm hits a seeded, reproducible subset."""
    rng = np.random.default_rng(seed)
    specs = []
    for i in range(n):
        od, spot, pods = SIZE_TIERS[int(rng.integers(len(SIZE_TIERS)))]
        specs.append(TwinSpec(
            name=f"twin-{i:04d}",
            n_on_demand=od,
            n_spot=spot,
            n_pods=pods,
            cadence_s=float(
                CADENCE_TIERS_S[int(rng.integers(len(CADENCE_TIERS_S)))]
            ),
            churn_prob=float(
                CHURN_TIERS[int(rng.integers(len(CHURN_TIERS)))]
            ),
            zone=i % max(1, zones),
            seed=seed * 100_003 + i,
            deadline_s=(
                2.0 if deadline_frac > 0 and rng.random() < deadline_frac
                else 0.0
            ),
        ))
    return specs


def post_plan(
    url: str, body: bytes, headers: dict, timeout: float = HTTP_TIMEOUT_S
) -> bytes:
    """One wire POST, reply bytes back — the twin-sized cut of the
    agent transport: HTTP error statuses become
    :class:`RemoteCallError` carrying any 503 Retry-After (the breaker
    honors it in virtual time); connection-level failures propagate as
    ``URLError``/``OSError`` for the caller's failure accounting."""
    req = urllib.request.Request(
        url, data=body, headers=headers, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as err:
        retry_after = 0.0
        if err.code == 503:
            try:
                retry_after = float(err.headers.get("Retry-After", 0))
            except (TypeError, ValueError):
                retry_after = 0.0
        raise RemoteCallError(f"HTTP {err.code}", retry_after) from err


def selection(found: bool, meta, index: int, row) -> tuple:
    """The comparable selection triple (found, drained node,
    assignments) — the same shape serve-smoke diffs, so the twin's
    bit-identity check and the original single-tenant one can never
    drift apart in what "identical" means."""
    if not found or index >= meta.n_candidates:
        return (False, None, None)
    plan = meta.build_plan(index, np.asarray(row))
    return (True, plan.node.node.name, dict(plan.assignments))


class TenantTwin:
    """One simulated tenant: synthetic cluster, churn, spot storms,
    and a breaker-guarded wire client. Driven strictly sequentially by
    the fleet event loop — ``tick`` may run on a worker thread, but
    never concurrently with this twin's ``churn``/``spot_interrupt``
    mutations (the loop joins dispatches before mutating)."""

    def __init__(
        self,
        spec: TwinSpec,
        cfg: ReschedulerConfig,
        clock: Clock,
        urls: Sequence[str],
    ):
        self.spec = spec
        self.cfg = cfg
        self.clock = clock
        # the twin's OWN breaker state per replica, in ITS preference
        # order (the fleet splits primary order across twins so load
        # spreads without a balancer)
        self.endpoints: List[Endpoint] = [Endpoint(u) for u in urls]
        sspec = dataclasses.replace(
            CONFIGS[2],
            name=spec.name,
            n_on_demand=spec.n_on_demand,
            n_spot=spec.n_spot,
            n_pods=spec.n_pods,
        )
        client = generate_cluster(sspec, spec.seed, clock=clock)
        self.store = client.columnar_store(
            cfg.resources,
            on_demand_label=cfg.on_demand_node_label,
            spot_label=cfg.spot_node_label,
        )
        self.pdbs = client.list_pdbs()
        self.rng = np.random.default_rng(spec.seed ^ 0x5EED)
        self.next_due = 0.0
        # accounting the fleet aggregates (offered vs served feeds the
        # Jain fairness over demand-normalized shares)
        self.offered = 0
        self.served = 0
        self.shed_ticks = 0
        self.crashes = 0
        self.failovers = 0
        self.wait_samples_ms: List[float] = []
        # enqueue timestamp (virtual clock) per sample, parallel to
        # wait_samples_ms: the fleet bench classifies waits by WHEN THE
        # REQUEST ENTERED the system, so a request queued during an
        # outage counts against the outage even if served after restart
        self.wait_sample_t: List[float] = []
        self.last_reply: Optional[wire.PlanReply] = None
        self.last_meta = None
        self.last_error = ""
        self._parked_pod = None
        self._storm_nodes: List[object] = []  # NodeSpec parked by a storm
        # delta wire (v4): the last PLAN tick's pack + fingerprint —
        # the base the next tick's delta diffs against (per-endpoint
        # acked_fp gates actually shipping it, exactly as in the agent)
        self._prev_packed = None
        self._prev_fp = ""
        # a KIND_RESYNC demand is pending its one full-pack answer;
        # while set, delta emission is suppressed (the retry is a full
        # pack by construction, and acked_fp stays stale until served)
        self._need_full = False
        # jittered early re-tick the fleet loop honors instead of the
        # cadence (the virtual-clock form of the agent's jittered
        # in-budget resync retry): 0 = no early retry scheduled
        self.retry_due = 0.0
        # protocol accounting the storm bench aggregates
        self.resyncs = 0          # KIND_RESYNC demands observed
        self.full_posts = 0       # full-pack bodies POSTed
        self.full_served = 0      # full packs acknowledged by a replica
        self.delta_posts = 0      # delta bodies POSTed
        self.schedule_ticks = 0   # v3 schedule requests served
        self.wire_bytes_sent = 0  # request-body bytes, pack and delta

    # ------------------------------------------------------------------
    # wire client

    def _note_endpoint_failure(self, ep: Endpoint, why: str,
                               retry_after: float = 0.0) -> None:
        """The agent's breaker arithmetic (same thresholds, same
        Retry-After cap) on the fleet's VIRTUAL clock: a skipped
        replica costs the twin simulated seconds, and a storm's worth
        of 503s opens breakers that expire while the fleet sleeps."""
        ep.consecutive_failures += 1
        suggested = min(
            max(retry_after, 0.0), RemotePlanner.RETRY_AFTER_CAP_S
        )
        if suggested > 0:
            # the agent's decorrelation stretch, from the twin's OWN
            # seeded RNG: distinct per-twin seeds spread equal server
            # horizons across the fleet deterministically
            suggested *= (
                1.0
                + float(self.rng.random()) * RemotePlanner.RETRY_JITTER_FRAC
            )
        if ep.consecutive_failures >= RemotePlanner.FAIL_THRESHOLD:
            n = ep.consecutive_failures - RemotePlanner.FAIL_THRESHOLD
            backoff = min(
                RemotePlanner.BACKOFF_BASE * (2.0 ** n),
                RemotePlanner.BACKOFF_MAX,
            )
            ep.skip_until = self.clock.now() + max(backoff, suggested)
        elif suggested > 0:
            ep.skip_until = self.clock.now() + suggested

    def tick(self) -> Optional[wire.PlanReply]:
        """One planning tick on the agent's full protocol ladder: pack
        (memoized O(1) on a quiet tick), fingerprint + delta against
        the previous plan tick's pack, then POST down the
        breaker-ordered endpoint list — the churn delta to an endpoint
        whose ``acked_fp`` matches the base, the fingerprinted full
        pack otherwise; every ``SCHEDULE_EVERY``-th tick asks for a v3
        drain schedule instead. A KIND_RESYNC answer defers ONE full
        pack to a jittered ``retry_due`` (decorrelation on the virtual
        clock — the agent sleeps the same jitter in real time).
        Returns the reply, or None when unserved this tick."""
        self.offered += 1
        self.last_reply = None
        self.retry_due = 0.0
        schedule_tick = (
            self.cfg.schedule_horizon > 0
            and not self._need_full
            and self.served > 0
            and self.offered % SCHEDULE_EVERY == 0
        )
        try:
            packed, meta = self.store.pack(self.pdbs)
            if schedule_tick:
                # a schedule request ships the full pack WITHOUT a
                # fingerprint (the agent's plan_schedule contract): it
                # neither seeds the tenant cache nor advances the
                # delta base
                body = wire.encode_plan_request(
                    self.spec.name, packed,
                    schedule_horizon=int(self.cfg.schedule_horizon),
                )
                fp = ""
                delta_body = None
                base_fp = ""
            else:
                fp = pack_fingerprint(packed)
                delta = None
                base_fp = ""
                if self._prev_packed is not None and not self._need_full:
                    # None on shape growth past the high-water pads:
                    # this tick ships the full pack (and re-seeds)
                    delta = emit_packed_delta(self._prev_packed, packed)
                    base_fp = self._prev_fp
                body = wire.encode_plan_request(
                    self.spec.name, packed, pack_fingerprint=fp,
                )
                delta_body = (
                    wire.encode_packed_delta(
                        self.spec.name, delta,
                        base_fingerprint=base_fp, new_fingerprint=fp,
                    )
                    if delta is not None
                    and any(ep.acked_fp == base_fp for ep in self.endpoints)
                    else None
                )
                # the next tick diffs against THIS pack regardless of
                # how the tick ends — the per-endpoint acked
                # fingerprints gate shipping, exactly as in the agent
                self._prev_packed = packed
                self._prev_fp = fp
        except Exception as err:  # noqa: BLE001 — a twin must never
            # take the fleet loop down; counted + flight-recorded and
            # asserted ZERO by the fleet bench
            self.crashes += 1
            self.last_error = f"pack/encode: {err}"
            flight.note_event(
                "twin-crash", cause=f"pack/encode failed: {err}",
            )
            return None
        headers = {"Content-Type": "application/octet-stream"}
        if self.spec.deadline_s > 0:
            headers["X-Planner-Deadline"] = str(self.spec.deadline_s)
        now = self.clock.now()
        reply = None
        served_by = -1
        sent_delta = False
        for slot, ep in enumerate(self.endpoints):
            if ep.skip_until > now:
                continue
            use_delta = (
                delta_body is not None and ep.acked_fp == base_fp
            )
            payload = delta_body if use_delta else body
            try:
                raw = post_plan(f"{ep.url}/v2/plan", payload, headers)
                self.wire_bytes_sent += len(payload)
                if use_delta:
                    self.delta_posts += 1
                    decoded = wire.decode_plan_or_resync(raw)
                elif schedule_tick:
                    self.full_posts += 1
                    decoded = wire.decode_plan_schedule_reply(raw)
                else:
                    self.full_posts += 1
                    decoded = wire.decode_plan_reply(raw)
            except RemoteCallError as err:
                self.last_error = str(err)
                self._note_endpoint_failure(
                    ep, str(err), retry_after=err.retry_after
                )
                continue
            except (urllib.error.URLError, OSError, wire.WireError) as err:
                self.last_error = str(err)
                self._note_endpoint_failure(ep, str(err))
                continue
            except Exception as err:  # noqa: BLE001 — contain: an
                # unexpected client-side failure is a twin crash, not a
                # fleet crash; counted + flight-recorded, asserted zero
                self.crashes += 1
                self.last_error = f"tick: {err}"
                flight.note_event(
                    "twin-crash", cause=f"tick failed: {err}",
                )
                return None
            if isinstance(decoded, wire.ResyncDemand):
                # protocol, not failure: no breaker, no failover walk.
                # The one full-pack answer is DEFERRED a jittered
                # moment (per-twin seeded RNG) — 256 tenants staled by
                # one restart must not re-upload in the same instant.
                # This replica does NOT hold the base it acked (that is
                # what it just said): drop the stale fingerprint, or a
                # quiet tenant's unchanged fp would "match" again after
                # a restart and demand a second resync
                ep.acked_fp = ""
                self.resyncs += 1
                self._need_full = True
                self.last_error = f"resync: {decoded.cause}"
                # spread the full-pack answers over up to half a
                # cadence (capped at the agent's 30s retry ceiling):
                # a restart stales a whole replica's tenants at once,
                # and a 2s herd of full packs is the storm the server
                # would then have to shed
                spread = max(
                    RemotePlanner.RESYNC_JITTER_S,
                    min(self.spec.cadence_s * 0.5,
                        RemotePlanner.RETRY_AFTER_CAP_S),
                )
                self.retry_due = now + float(
                    self.rng.uniform(0.05, spread)
                )
                return None
            reply = decoded
            sent_delta = use_delta
            ep.consecutive_failures = 0
            ep.skip_until = 0.0
            if fp:
                # this replica now holds exactly this pack (full
                # upload, or delta applied over an acknowledged base)
                ep.acked_fp = fp
            served_by = slot
            break
        if reply is None:
            self.shed_ticks += 1
            if self._need_full:
                # a storm-refused resync retry: come back when the
                # soonest breaker window opens, plus jitter — the
                # load-derived Retry-After horizons (different per
                # refusal) stagger the fleet's convergence
                soonest = min(
                    (ep.skip_until for ep in self.endpoints), default=now
                )
                self.retry_due = max(soonest, now) + float(
                    self.rng.uniform(0.1, RemotePlanner.RESYNC_JITTER_S)
                )
            return None
        if served_by > 0:
            # ONE fire site for the twin's failover edge: the metric
            # and the flight event can then be asserted equal
            self.failovers += 1
            metrics.update_remote_planner_failover()
            flight.note_event(
                "failover",
                cause="primary replica unusable; served by fallback",
                reason=f"slot-{served_by}",
            )
        self.served += 1
        if fp and not sent_delta:
            self.full_served += 1
            self._need_full = False
        if schedule_tick:
            self.schedule_ticks += 1
        self.wait_samples_ms.append(float(reply.queue_wait_ms))
        self.wait_sample_t.append(now)
        if not schedule_tick:
            self.last_reply = reply
            self.last_meta = meta
        return reply if not schedule_tick else None

    # ------------------------------------------------------------------
    # correctness spot check

    def verify(self, solo) -> Optional[dict]:
        """Bit-identity spot check: rebuild the served selection from
        the wire reply and diff it against a solo in-process plan over
        the SAME store state (None = identical; a dict names the
        drift). Call between a tick and the next mutation."""
        if self.last_reply is None or self.last_meta is None:
            return None
        got = selection(
            self.last_reply.found, self.last_meta,
            self.last_reply.index, self.last_reply.row,
        )
        report = solo.plan(self.store, self.pdbs)
        if report.plan is None:
            want = (False, None, None)
        else:
            want = (
                True,
                report.plan.node.node.name,
                dict(report.plan.assignments),
            )
        if got != want:
            return {"twin": self.spec.name, "served": got[:2],
                    "solo": want[:2]}
        return None

    # ------------------------------------------------------------------
    # scenario mutations (driver thread only; never concurrent with tick)

    def churn(self) -> bool:
        """One churn roll: with probability ``churn_prob``, toggle a
        pod out of (or back into) the cluster — the steady workload
        drift that keeps re-packs honest without shrinking the twin
        monotonically."""
        if self.spec.churn_prob <= 0:
            return False
        if float(self.rng.random()) >= self.spec.churn_prob:
            return False
        store = self.store
        if self._parked_pod is not None:
            pod = self._parked_pod
            if pod.node_name in store._node_row:
                store.add_pod(pod)
                self._parked_pod = None
                return True
            return False  # its node is storm-parked; retry later
        if not store._pod_row:
            return False
        uid = next(iter(store._pod_row))
        self._parked_pod = store.pod_objs[store._pod_row[uid]]
        store.remove_pod(uid)
        return True

    def live_spot_nodes(self) -> List[object]:
        return [
            n for n in self.store.node_objs
            if n is not None and n.name in self.store._node_row
            and matches_label(n.labels, self.store.spot_label)
        ]

    def spot_interrupt(self, frac: float) -> int:
        """A correlated spot storm hits this twin: reclaim ``frac`` of
        its live spot nodes (at least one). The columnar store parks
        the victims' pods as orphans keyed by node name, so
        ``spot_restore`` re-adding the SAME NodeSpec gets them back —
        the kubelet re-registration semantics the store already
        models."""
        live = self.live_spot_nodes()
        if not live:
            return 0
        take = max(1, int(round(len(live) * frac)))
        victims = live[:take]
        for node in victims:
            self.store.remove_node(node.name)
            self._storm_nodes.append(node)
        log.vlog(
            2, "twin %s: spot storm reclaimed %d/%d spot nodes",
            self.spec.name, len(victims), len(live),
        )
        return len(victims)

    def spot_restore(self) -> int:
        """The storm passes: re-register every parked spot node (its
        orphaned pods come back with it)."""
        n = len(self._storm_nodes)
        for node in self._storm_nodes:
            self.store.add_node(node)
        self._storm_nodes.clear()
        return n

    # ------------------------------------------------------------------

    def bucket_signature(self) -> tuple:
        """The twin's current packed shape (its service-bucket
        identity) — the fleet's join/leave test asserts membership
        churn changes the fleet's bucket MAP without resync storms."""
        packed, _ = self.store.pack(self.pdbs)
        return tuple(packed.slot_req.shape) + tuple(packed.spot_free.shape)
