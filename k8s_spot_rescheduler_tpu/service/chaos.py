"""Seeded fault injection for the planner-service wire/HTTP path.

PR 4's ``io/chaos.py`` hardened the kube control plane by making every
apiserver failure reproducible; the service stack (agent transport,
wire protocol, batch solver, device) had no equivalent — its failure
behavior was asserted by unit tests one fault at a time, never soaked.
This module is the service-side twin: a seeded :class:`ServiceFaultPlan`
replayed deterministically by

- :class:`ChaosAgentTransport` — wraps a ``RemotePlanner``'s transport
  callable agent-side and injects everything a network can do to an
  HTTP client: connection resets before any byte moves, slow-loris
  uploads that eat the whole deadline, replies truncated or bit-flipped
  mid-frame (the wire decoder must answer with a typed ``WireError``,
  never an unhandled exception), scripted 503 storms with Retry-After,
  random 5xx, and reply delays past the agent's declared deadline;
- :class:`ServiceChaos` — the server-side solve/decode hook a
  ``PlannerService`` consults per batch: scripted batch-solve
  exceptions, a request-corruption rate ahead of the wire decode, and a
  scripted **sick-device phase** (extra per-batch solve latency between
  two batch indices, slept on the service's injected clock) — exactly
  the slow-degrading-accelerator mode the device-health watchdog
  (service/devhealth.py) exists to catch.

Layering mirrors io/chaos.py: agent faults sit ABOVE the real transport
(every injected failure exercises the agent's real failover/breaker/
fallback ladder), server faults sit INSIDE the batch window (the
watchdog times what the chaos clock sleeps). All draws come from one
``random.Random(plan.seed)`` stream per injector, so a fixed (plan,
call sequence) is bit-reproducible — the property ``make
fleet-chaos-smoke`` builds its acceptance on.
"""

from __future__ import annotations

import collections
import dataclasses
import random
from typing import Mapping, Optional, Tuple


class ServiceChaosError(ConnectionError):
    """An injected transport/solve failure (connection-reset class)."""


@dataclasses.dataclass(frozen=True)
class ServiceFaultPlan:
    """What to break on the service path, how often — one seeded stream.

    Agent-side (transport) knobs:

    - ``connect_reset_rate`` — probability a POST dies with a connection
      reset before any reply byte arrives.
    - ``slow_loris_rate`` — probability the upload stalls: the injected
      clock sleeps out the caller's deadline, then the timeout the
      socket would raise is raised.
    - ``reply_truncate_rate`` / ``reply_corrupt_rate`` — probability the
      reply bytes come back cut mid-frame / with one bit flipped
      (decoder must yield a typed ``WireError``).
    - ``reply_delay_s`` + ``reply_delay_rate`` — the reply is delayed
      this long; past the caller's deadline that IS a timeout.
    - ``http_503_script`` — 1-based request indices answered with a 503
      + ``http_503_retry_after`` (a scripted shed storm).
    - ``http_5xx_rate`` — probability of a plain 500.
    - ``half_close_script`` — 1-based request indices BEFORE which every
      idle pooled keep-alive connection is half-closed at the OS level
      (``PooledWireTransport.break_idle``) — the server-restarts/
      idle-timeout-between-ticks case. The agent must retry ONCE on a
      fresh socket (``remote_wire_reconnects_total``) with ZERO
      fallback/failover counted; needs the transport pool handed to
      :class:`ChaosAgentTransport` (no-op otherwise).

    Server-side (PlannerService hook) knobs:

    - ``solve_error_script`` — 1-based batch indices whose device solve
      raises (contained per batch; flips the watchdog).
    - ``sick_phase`` — ``(first_batch, last_batch, extra_latency_s)``:
      batches in the inclusive 1-based index range pay the extra solve
      latency on the service clock — the scripted sick-device phase.
    - ``request_corrupt_rate`` — probability an incoming /v2/plan body
      is bit-flipped ahead of the decode (must 400, never crash).
    """

    seed: int = 0
    # agent side
    connect_reset_rate: float = 0.0
    slow_loris_rate: float = 0.0
    reply_truncate_rate: float = 0.0
    reply_corrupt_rate: float = 0.0
    reply_delay_rate: float = 0.0
    reply_delay_s: float = 0.0
    http_503_script: Tuple[int, ...] = ()
    http_503_retry_after: float = 2.0
    http_5xx_rate: float = 0.0
    half_close_script: Tuple[int, ...] = ()
    # server side
    solve_error_script: Tuple[int, ...] = ()
    sick_phase: Tuple[float, ...] = ()
    request_corrupt_rate: float = 0.0
    extra: Mapping[str, float] = dataclasses.field(default_factory=dict)

    # single source for --service-chaos-profile choices (cli/main.py)
    PROFILES = ("", "off", "none", "light", "heavy")

    @classmethod
    def profile(cls, name: str, seed: int = 0) -> "ServiceFaultPlan":
        if name in ("", "off", "none"):
            return cls(seed=seed)
        if name == "light":
            return cls(
                seed=seed,
                connect_reset_rate=0.05,
                reply_truncate_rate=0.02,
                http_5xx_rate=0.03,
            )
        if name == "heavy":
            return cls(
                seed=seed,
                connect_reset_rate=0.10,
                slow_loris_rate=0.03,
                reply_truncate_rate=0.05,
                reply_corrupt_rate=0.05,
                http_5xx_rate=0.05,
                request_corrupt_rate=0.02,
            )
        raise ValueError(
            f"unknown service chaos profile {name!r} (known: light, heavy)"
        )


class ChaosAgentTransport:
    """Transport decorator for ``RemotePlanner``: same callable shape
    ``(url, body, headers, timeout) -> reply bytes``, faults injected
    per the plan before/after the wrapped transport runs. ``enabled``
    quiesces every fault at once (scripted counters keep their state)."""

    def __init__(self, inner, plan: ServiceFaultPlan, *, clock=None,
                 pool=None):
        self.inner = inner
        self.plan = plan
        self.clock = clock
        # the agent's PooledWireTransport (or anything with a
        # ``break_idle()``): the half-closed-keep-alive-socket fault
        # needs to reach UNDER the transport callable and kill the
        # pooled sockets at the OS level — a fault raised above the
        # pool would exercise the failover ladder, not the stale-retry
        # contract this fault exists to prove
        self.pool = pool
        self.enabled = True
        self.rng = random.Random(plan.seed)
        self.stats: collections.Counter = collections.Counter()
        self._requests = 0

    def __call__(self, url: str, body: bytes, headers, timeout: float):
        # the agent's typed HTTP error lives beside RemotePlanner; import
        # here so chaos stays optional on the agent's own import path
        from k8s_spot_rescheduler_tpu.service.agent import RemoteCallError

        self._requests += 1
        n = self._requests
        plan = self.plan
        if self.enabled:
            if n in plan.half_close_script and self.pool is not None:
                # the server "restarted"/idle-timed-out between ticks:
                # every idle pooled socket is half-closed under the
                # transport's feet. NOT an injected exception — the
                # request proceeds and the pool itself must discover
                # the stale socket and retry once on a fresh one.
                self.stats["half_close"] += self.pool.break_idle()
            if plan.slow_loris_rate and self.rng.random() < plan.slow_loris_rate:
                # the upload crawls: the caller's whole deadline elapses
                # (instant on a virtual clock), then the socket timeout
                self.stats["slow_loris"] += 1
                if self.clock is not None:
                    self.clock.sleep(timeout)
                raise TimeoutError(
                    "chaos: slow-loris upload stalled past the "
                    f"{timeout:.1f}s deadline"
                )
            if (
                plan.connect_reset_rate
                and self.rng.random() < plan.connect_reset_rate
            ):
                self.stats["connect_reset"] += 1
                raise ServiceChaosError(
                    "chaos: connection reset by peer mid-frame"
                )
            if n in plan.http_503_script:
                self.stats["http_503"] += 1
                raise RemoteCallError(
                    "HTTP 503: chaos scripted shed storm",
                    plan.http_503_retry_after,
                )
            if plan.http_5xx_rate and self.rng.random() < plan.http_5xx_rate:
                self.stats["http_5xx"] += 1
                raise RemoteCallError("HTTP 500: chaos injected", 0.0)
        raw = self.inner(url, body, headers, timeout)
        if not self.enabled:
            return raw
        if (
            plan.reply_delay_rate
            and plan.reply_delay_s > 0
            and self.rng.random() < plan.reply_delay_rate
        ):
            self.stats["reply_delay"] += 1
            if self.clock is not None:
                self.clock.sleep(min(plan.reply_delay_s, timeout))
            if plan.reply_delay_s >= timeout:
                # the bytes would land after the caller stopped waiting
                raise TimeoutError(
                    "chaos: reply delayed past the "
                    f"{timeout:.1f}s deadline"
                )
        if (
            plan.reply_truncate_rate
            and len(raw) > 8
            and self.rng.random() < plan.reply_truncate_rate
        ):
            self.stats["reply_truncate"] += 1
            return raw[: self.rng.randrange(1, len(raw))]
        if (
            plan.reply_corrupt_rate
            and raw
            and self.rng.random() < plan.reply_corrupt_rate
        ):
            self.stats["reply_corrupt"] += 1
            flipped = bytearray(raw)
            i = self.rng.randrange(len(flipped))
            flipped[i] ^= 1 << self.rng.randrange(8)
            return bytes(flipped)
        return raw


class ServiceChaos:
    """Server-side hooks a ``PlannerService`` consults: ``on_batch``
    inside the timed solve window (scripted exceptions + the sick-phase
    latency the watchdog must see), ``corrupt_request`` ahead of the
    wire decode."""

    def __init__(self, plan: ServiceFaultPlan, *, clock=None):
        self.plan = plan
        self.clock = clock
        self.enabled = True
        self.rng = random.Random(plan.seed ^ 0x5EC0_51C5)
        self.stats: collections.Counter = collections.Counter()
        self._batches = 0

    def on_batch(self) -> None:
        """Called inside the device-solve timing window, once per batch
        (probes and canaries included — chaos does not know the
        difference, which is the point)."""
        self._batches += 1
        if not self.enabled:
            return
        n = self._batches
        phase = self.plan.sick_phase
        if len(phase) == 3 and phase[0] <= n <= phase[1]:
            self.stats["sick_latency"] += 1
            if self.clock is not None:
                self.clock.sleep(float(phase[2]))
        if n in self.plan.solve_error_script:
            self.stats["solve_error"] += 1
            raise ServiceChaosError(
                f"chaos: scripted batch-solve failure (batch {n})"
            )

    def sick_phase_active(self) -> bool:
        phase = self.plan.sick_phase
        return (
            self.enabled
            and len(phase) == 3
            and phase[0] <= self._batches + 1 <= phase[1]
        )

    def corrupt_request(self, body: bytes) -> Optional[bytes]:
        """A bit-flipped copy of ``body`` (the decode hook), or None to
        leave the request alone."""
        if (
            not self.enabled
            or not body
            or not self.plan.request_corrupt_rate
            or self.rng.random() >= self.plan.request_corrupt_rate
        ):
            return None
        self.stats["request_corrupt"] += 1
        flipped = bytearray(body)
        i = self.rng.randrange(len(flipped))
        flipped[i] ^= 1 << self.rng.randrange(8)
        return bytes(flipped)
