"""Solution quality: greedy sequential drains vs an ILP oracle.

BASELINE.md's quality target: the framework must free ≥95% as many
on-demand nodes as an ILP oracle. The oracle solves the *simultaneous*
drain-selection problem exactly (maximize drained candidates subject to
every moved pod fitting some spot node within capacity) — an upper bound
no sequential first-fit controller can beat. The framework's number comes
from ``drain_to_exhaustion``: run real housekeeping ticks (cooldown
zeroed) until no further node can be drained, exactly how the live
controller consolidates a cluster over time.

The ILP is host-side scipy (HiGHS via ``scipy.optimize.milp``) and only
tractable at small scale; quality is asserted on down-scaled clusters,
latency on the full-scale ones.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp
from scipy.optimize import LinearConstraint, milp

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster


def ilp_max_drains(
    packed: PackedCluster, *, time_limit: float = 120.0
) -> Optional[int]:
    """Max number of candidate nodes drainable *simultaneously*.

    Variables: y_c (drain candidate c), x_{(c,k),s} (slot (c,k) placed on
    spot s, only for statically-admissible pairs). Constraints:
    sum_s x = y_c per valid slot; per-spot resource capacity; per-spot pod
    count. Anti-affinity is not modeled — use affinity-free clusters for
    quality runs. Returns None if the solver fails.
    """
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]

    cands = [c for c in range(C) if packed.cand_valid[c]]
    slots = [(c, k) for c in cands for k in range(K) if packed.slot_valid[c, k]]
    if not cands:
        return 0

    # static admissibility per (slot, spot): taints + node_ok
    taint_ok = np.all(
        (packed.spot_taints[None, None] & ~packed.slot_tol[:, :, None]) == 0,
        axis=-1,
    )  # [C,K,S]
    ok_spots = packed.spot_ok[None, None] & taint_ok

    # variable layout: y for each cand, then x for admissible pairs
    y_index = {c: i for i, c in enumerate(cands)}
    x_pairs = []
    for (c, k) in slots:
        for s in range(S):
            if ok_spots[c, k, s]:
                x_pairs.append((c, k, s))
    n_y, n_x = len(cands), len(x_pairs)
    n = n_y + n_x

    rows, cols, vals = [], [], []
    lb, ub = [], []
    row = 0

    # per-slot assignment: sum_s x_{cks} - y_c = 0
    slot_rows = {sl: None for sl in slots}
    for i, sl in enumerate(slots):
        slot_rows[sl] = row
        c, _ = sl
        rows.append(row), cols.append(y_index[c]), vals.append(-1.0)
        lb.append(0.0), ub.append(0.0)
        row += 1
    for j, (c, k, s) in enumerate(x_pairs):
        r = slot_rows[(c, k)]
        rows.append(r), cols.append(n_y + j), vals.append(1.0)

    # per-spot capacity per resource
    for s in range(S):
        if not packed.spot_ok[s]:
            continue
        for r_ in range(R):
            rows_before = len(rows)
            for j, (c, k, s2) in enumerate(x_pairs):
                if s2 == s and packed.slot_req[c, k, r_] > 0:
                    rows.append(row), cols.append(n_y + j)
                    vals.append(float(packed.slot_req[c, k, r_]))
            if len(rows) > rows_before:
                lb.append(-np.inf)
                ub.append(float(packed.spot_free[s, r_]))
                row += 1
        # pod-count capacity
        rows_before = len(rows)
        for j, (c, k, s2) in enumerate(x_pairs):
            if s2 == s:
                rows.append(row), cols.append(n_y + j), vals.append(1.0)
        if len(rows) > rows_before:
            lb.append(-np.inf)
            ub.append(float(packed.spot_max_pods[s] - packed.spot_count[s]))
            row += 1

    A = sp.csr_matrix((vals, (rows, cols)), shape=(row, n))
    c_obj = np.zeros(n)
    c_obj[:n_y] = -1.0  # maximize sum y
    res = milp(
        c=c_obj,
        constraints=LinearConstraint(A, np.array(lb), np.array(ub)),
        integrality=np.ones(n),
        bounds=(0, 1),
        options={"time_limit": time_limit},
    )
    if res.status not in (0, 1) or res.x is None:  # 0=optimal, 1=iter/time
        return None
    return int(round(-res.fun))


def drain_to_exhaustion(client, config, *, max_ticks: int = 10_000) -> int:
    """Run the real control loop (zero cooldown) until no drain happens;
    returns the number of nodes drained — the framework's quality number."""
    import dataclasses

    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner

    config = dataclasses.replace(config, node_drain_delay=0.0)
    r = Rescheduler(
        client, SolverPlanner(config), config, clock=client.clock, recorder=client
    )
    freed = 0
    stuck = 0
    for _ in range(max_ticks):
        client.clock.advance(config.housekeeping_interval)
        result = r.tick()
        if result.skipped == "unschedulable":
            # let evicted pods land; a permanently-pending pod ends the run
            stuck += 1
            if stuck > 50:
                break
            continue
        stuck = 0
        if not result.drained and not result.drain_failed:
            break
        freed += len(result.drained)
    return freed
