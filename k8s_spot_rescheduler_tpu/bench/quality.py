"""Solution quality: greedy sequential drains vs an ILP oracle.

BASELINE.md's quality target: the framework must free ≥95% as many
on-demand nodes as an ILP oracle. The oracle solves the *simultaneous*
drain-selection problem exactly (maximize drained candidates subject to
every moved pod fitting some spot node within capacity) — an upper bound
no sequential first-fit controller can beat. The framework's number comes
from ``drain_to_exhaustion``: run real housekeeping ticks (cooldown
zeroed) until no further node can be drained, exactly how the live
controller consolidates a cluster over time.

The ILP is host-side scipy (HiGHS via ``scipy.optimize.milp``) and only
tractable at small scale; quality is asserted on down-scaled clusters,
latency on the full-scale ones.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.sparse as sp
from scipy.optimize import LinearConstraint, milp

from k8s_spot_rescheduler_tpu.models.tensors import PackedCluster


def ilp_max_drains(
    packed: PackedCluster, *, time_limit: float = 120.0
) -> Optional[int]:
    """Max number of candidate nodes drainable *simultaneously*.

    Variables: y_c (drain candidate c), x_{(c,k),s} (slot (c,k) placed on
    spot s, only for statically-admissible pairs). Constraints:
    sum_s x = y_c per valid slot; per-spot resource capacity; per-spot pod
    count; hostname anti-affinity as (a) static exclusion of spots whose
    RESIDENT bits conflict with the slot and (b) pairwise
    ``x_i,s + x_j,s <= 1`` for slot pairs with overlapping affinity
    words. The bit-overlap rule is exact for the self-selecting group
    pattern the quality configs use (each group's pods carry and are
    matched by one distinct selector, so overlap ⇔ a genuine scheduler
    conflict); for arbitrary selector soups the overlap over-approximates
    conflicts (masks.py's safe direction), which would TIGHTEN this
    oracle below the true optimum — keep quality clusters to the
    self-selecting shape. Zone-family bits get the same per-node pair
    rule, which is weaker than the real zone-wide constraint — weaker
    only ever loosens the oracle, so the bound stays valid.

    Hard topologySpreadConstraints (round 5) enter through the SAME
    static admissibility: the packers intern each carrier's
    refused-domain verdict as SpreadBit words in ``slot_tol`` /
    ``spot_taints``, so the taint check above enforces them with no
    extra rows. The verdict is exact — and with it this oracle — when
    one mover per spread identity is in flight and no other pod matched
    by its selector moves (the quality-config scope, same contract as
    the affinity rule; ``SpreadQualitySpec`` is built to it). A config
    with interacting spread movers would have the lane guard TIGHTEN
    the masks below the true optimum — keep quality clusters to the
    single-carrier shape. Returns None if the solver fails.
    """
    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]

    cands = [c for c in range(C) if packed.cand_valid[c]]
    slots = [(c, k) for c in cands for k in range(K) if packed.slot_valid[c, k]]
    if not cands:
        return 0

    # static admissibility per (slot, spot): taints + node_ok + resident
    # anti-affinity bits
    taint_ok = np.all(
        (packed.spot_taints[None, None] & ~packed.slot_tol[:, :, None]) == 0,
        axis=-1,
    )  # [C,K,S]
    aff_ok = np.all(
        (packed.spot_aff[None, None] & packed.slot_aff[:, :, None]) == 0,
        axis=-1,
    )  # [C,K,S]
    ok_spots = packed.spot_ok[None, None] & taint_ok & aff_ok

    # variable layout: y for each cand, then x for admissible pairs
    y_index = {c: i for i, c in enumerate(cands)}
    x_pairs = []
    for (c, k) in slots:
        for s in range(S):
            if ok_spots[c, k, s]:
                x_pairs.append((c, k, s))
    n_y, n_x = len(cands), len(x_pairs)
    n = n_y + n_x

    rows, cols, vals = [], [], []
    lb, ub = [], []
    row = 0

    # per-slot assignment: sum_s x_{cks} - y_c = 0
    slot_rows = {sl: None for sl in slots}
    for i, sl in enumerate(slots):
        slot_rows[sl] = row
        c, _ = sl
        rows.append(row), cols.append(y_index[c]), vals.append(-1.0)
        lb.append(0.0), ub.append(0.0)
        row += 1
    for j, (c, k, s) in enumerate(x_pairs):
        r = slot_rows[(c, k)]
        rows.append(r), cols.append(n_y + j), vals.append(1.0)

    # per-spot capacity per resource
    for s in range(S):
        if not packed.spot_ok[s]:
            continue
        for r_ in range(R):
            rows_before = len(rows)
            for j, (c, k, s2) in enumerate(x_pairs):
                if s2 == s and packed.slot_req[c, k, r_] > 0:
                    rows.append(row), cols.append(n_y + j)
                    vals.append(float(packed.slot_req[c, k, r_]))
            if len(rows) > rows_before:
                lb.append(-np.inf)
                ub.append(float(packed.spot_free[s, r_]))
                row += 1
        # pod-count capacity
        rows_before = len(rows)
        for j, (c, k, s2) in enumerate(x_pairs):
            if s2 == s:
                rows.append(row), cols.append(n_y + j), vals.append(1.0)
        if len(rows) > rows_before:
            lb.append(-np.inf)
            ub.append(float(packed.spot_max_pods[s] - packed.spot_count[s]))
            row += 1

    # pairwise anti-affinity: two moved slots with overlapping affinity
    # words may not share one spot node (same or different lanes)
    x_index = {(c, k, s): j for j, (c, k, s) in enumerate(x_pairs)}
    aff_slots = [sl for sl in slots if packed.slot_aff[sl[0], sl[1]].any()]
    for a in range(len(aff_slots)):
        c1, k1 = aff_slots[a]
        w1 = packed.slot_aff[c1, k1]
        for b in range(a + 1, len(aff_slots)):
            c2, k2 = aff_slots[b]
            if not np.any(w1 & packed.slot_aff[c2, k2]):
                continue
            for s in range(S):
                j1 = x_index.get((c1, k1, s))
                j2 = x_index.get((c2, k2, s))
                if j1 is None or j2 is None:
                    continue
                rows.append(row), cols.append(n_y + j1), vals.append(1.0)
                rows.append(row), cols.append(n_y + j2), vals.append(1.0)
                lb.append(-np.inf), ub.append(1.0)
                row += 1

    A = sp.csr_matrix((vals, (rows, cols)), shape=(row, n))
    c_obj = np.zeros(n)
    c_obj[:n_y] = -1.0  # maximize sum y
    res = milp(
        c=c_obj,
        constraints=LinearConstraint(A, np.array(lb), np.array(ub)),
        integrality=np.ones(n),
        bounds=(0, 1),
        options={"time_limit": time_limit},
    )
    if res.status not in (0, 1) or res.x is None:  # 0=optimal, 1=iter/time
        return None
    return int(round(-res.fun))


def pack_quality(spec, seed: int) -> PackedCluster:
    """Pack a quality-config cluster through the production columnar
    observe path."""
    from k8s_spot_rescheduler_tpu.io.synthetic import generate_quality_cluster
    from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig

    cfg = ReschedulerConfig(resources=spec.resources)
    client = generate_quality_cluster(spec, seed)
    store = client.columnar_store(
        cfg.resources,
        on_demand_label=cfg.on_demand_node_label,
        spot_label=cfg.spot_node_label,
    )
    packed, _ = store.pack(
        client.list_pdbs(), priority_threshold=cfg.priority_threshold
    )
    return packed


def lp_upper_bound(packed: PackedCluster, *, max_sigs: int = 8) -> Optional[int]:
    """Tractable upper bound on simultaneously-drainable candidates at
    full (config 3/4) scale, where ``ilp_max_drains``'s per-(slot, spot)
    variables are intractable.

    The LP relaxes the exact ILP two ways: drain indicators ``y_c`` become
    fractional, and per-spot-node bins are aggregated into *admissibility
    signature* groups (distinct taint/pseudo-taint word rows over the spot
    pool). Validity is a Hall/transportation condition: any integral drain
    set places each moved pod on a node whose signature the pod tolerates,
    so for EVERY subset T of signatures, the demand of chosen pods
    admissible only within T cannot exceed T's aggregate capacity (each
    resource, plus the pod-count axis). Anti-affinity and per-node
    fragmentation are relaxed away — the bound only ever loosens, so
    achieved/bound understates true quality, never flatters it.

    Signatures beyond ``max_sigs`` are merged into a universally-admissible
    group (again only loosening). Returns None if the LP fails.
    """
    from scipy.optimize import linprog

    C, K, R = packed.slot_req.shape
    S = packed.spot_free.shape[0]
    ok = np.asarray(packed.spot_ok, bool)
    if not ok.any() or not np.asarray(packed.cand_valid).any():
        return 0

    # distinct taint-word signatures over usable spot nodes
    words = np.asarray(packed.spot_taints)[ok]  # [S_ok, W]
    sig_rows, sig_of = np.unique(words, axis=0, return_inverse=True)
    G = sig_rows.shape[0]
    if G > max_sigs:
        # keep the most common signatures; merge the rest into taint-free
        # (admissible to everyone -> capacity over-approximated, bound valid)
        counts = np.bincount(sig_of, minlength=G)
        keep = np.argsort(-counts)[: max_sigs - 1]
        remap = np.full(G, -1)
        for new, old in enumerate(keep):
            remap[old] = new
        merged = max_sigs - 1
        sig_of = np.where(remap[sig_of] >= 0, remap[sig_of], merged)
        new_rows = np.zeros((max_sigs, sig_rows.shape[1]), sig_rows.dtype)
        new_rows[:merged] = sig_rows[keep]
        sig_rows, G = new_rows, max_sigs

    # per-signature aggregate capacity: resources + pod-count axis. An
    # overcommitted node (free < 0) must contribute 0, not subtract from
    # its group — the bound must only ever loosen vs the per-bin truth.
    cap_sig = np.zeros((G, R + 1))
    free_ok = np.asarray(packed.spot_free, float)[ok].clip(min=0.0)
    count_room = (
        np.asarray(packed.spot_max_pods, float) - np.asarray(packed.spot_count, float)
    )[ok].clip(min=0.0)
    for g in range(G):
        rows = sig_of == g
        cap_sig[g, :R] = free_ok[rows].sum(axis=0)
        cap_sig[g, R] = count_room[rows].sum()

    # admissible-signature bitmask per valid slot: tol covers sig's taints
    tol = np.asarray(packed.slot_tol)  # [C, K, W]
    admissible = np.all(
        (sig_rows[None, None] & ~tol[:, :, None]) == 0, axis=-1
    )  # [C, K, G]
    slot_valid = np.asarray(packed.slot_valid, bool)
    cand_valid = np.asarray(packed.cand_valid, bool).copy()
    # a valid slot admissible nowhere pins its candidate to y=0
    nowhere = slot_valid & ~admissible.any(axis=-1)
    cand_valid &= ~nowhere.any(axis=-1)

    masks = admissible.astype(np.int64) @ (1 << np.arange(G))  # [C, K]
    req = np.asarray(packed.slot_req, float)  # [C, K, R]
    demand = np.concatenate([req, np.ones((C, K, 1))], axis=-1)  # [C,K,R+1]
    demand = np.where(slot_valid[:, :, None], demand, 0.0)

    # bucket demand by exact mask, then subset-sum (zeta transform)
    n_masks = 1 << G
    bucket = np.zeros((C, n_masks, R + 1))
    for c in np.flatnonzero(cand_valid):
        np.add.at(bucket[c], masks[c][slot_valid[c]], demand[c][slot_valid[c]])
    zeta = bucket
    for b in range(G):
        bit = 1 << b
        has = (np.arange(n_masks) & bit) != 0
        zeta[:, has] += zeta[:, ~has]

    # constraint rows: for every non-empty signature subset T and axis r:
    #   sum_c y_c * zeta[c, T, r] <= cap(T, r)
    T_idx = np.arange(1, n_masks)
    sig_in_T = (T_idx[:, None] >> np.arange(G)) & 1  # [T, G]
    cap_T = sig_in_T @ cap_sig  # [T, R+1]
    A_ub = zeta[:, T_idx].reshape(C, -1).T  # [(T*(R+1)), C]
    b_ub = cap_T.reshape(-1)
    # drop trivial all-zero rows
    live = A_ub.any(axis=1)
    A_ub, b_ub = A_ub[live], b_ub[live]

    c_obj = -cand_valid.astype(float)
    bounds = [(0.0, 1.0 if v else 0.0) for v in cand_valid]
    res = linprog(c_obj, A_ub=A_ub, b_ub=b_ub, bounds=bounds, method="highs")
    if not res.success:
        return None
    return int(np.floor(-res.fun + 1e-6))


class _HintingPlanner:
    """Delegates to SolverPlanner, recording each approved plan's proven
    placements as the fake scheduler's routing hints (DrainPlan carries
    ``assignments`` for exactly this). The quality number then measures
    *planner* quality — not the toy first-fit scheduler's — in the tight
    regimes where arbitrary re-placement would strand a proven-placeable
    pod."""

    def __init__(self, inner, client):
        self.inner = inner
        self.client = client

    def __getattr__(self, name):
        # transparent wrapper: the control loop probes planner traits
        # (notably accepts_columnar — losing it would silently drop the
        # columnar observe fast path for every quality benchmark)
        return getattr(self.inner, name)

    def _record(self, report):
        hints = getattr(self.client, "placement_hints", None)
        if hints is not None and report.plan is not None:
            hints.clear()
            hints.update(report.plan.assignments)
        return report

    def plan(self, node_map, pdbs):
        return self._record(self.inner.plan(node_map, pdbs))

    def plan_async(self, node_map, pdbs):
        # the control loop prefers the pipelined entry point, and
        # __getattr__ would hand it the INNER planner's — which skips the
        # hint recording — so it must be wrapped explicitly
        finish = self.inner.plan_async(node_map, pdbs)
        return lambda: self._record(finish())

    def plan_schedule(self, node_map, pdbs):
        # same lesson as plan_async: __getattr__ would hand the loop
        # the inner planner's plan_schedule, whose served steps would
        # skip hint recording — the handle's on_step hook exists for
        # exactly this (each executed step's proven placements become
        # the fake scheduler's routing hints before its drain runs)
        plan_schedule = getattr(self.inner, "plan_schedule", None)
        if plan_schedule is None:
            return None
        handle = plan_schedule(node_map, pdbs)
        if handle is not None:
            handle.on_step = self._record
        return handle


def drain_to_exhaustion(
    client, config, *, max_ticks: int = 10_000, on_packed=None,
    planner_stats=None,
) -> int:
    """Run the real control loop (zero cooldown) until no drain happens;
    returns the number of nodes drained — the framework's quality
    number. ``on_packed`` (optional) receives each tick's packed problem
    after planning — the chain-depth analyzer's tap
    (bench/chain_depth.py; it id-deduplicates skipped ticks).
    ``planner_stats`` (optional dict) is filled with the planner's
    fetch accounting — ``fetches_total`` and per-cut ``schedule_lens``
    — the measured artifact behind the O(1)-fetch claim when
    ``plan_schedule_enabled`` is on."""
    import dataclasses

    from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
    from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner

    config = dataclasses.replace(config, node_drain_delay=0.0)
    inner = SolverPlanner(config)
    r = Rescheduler(
        client,
        _HintingPlanner(inner, client),
        config,
        clock=client.clock,
        recorder=client,
    )
    freed = 0
    stuck = 0
    for _ in range(max_ticks):
        client.clock.advance(config.housekeeping_interval)
        result = r.tick()
        if on_packed is not None:
            on_packed(getattr(inner, "last_packed", None))
        if result.skipped == "unschedulable":
            # let evicted pods land; a permanently-pending pod ends the run
            stuck += 1
            if stuck > 50:
                break
            continue
        stuck = 0
        if not result.drained and not result.drain_failed:
            break
        freed += len(result.drained)
    if planner_stats is not None:
        planner_stats["fetches_total"] = inner.fetches_total
        planner_stats["schedule_lens"] = list(inner.schedule_lens)
    return freed
