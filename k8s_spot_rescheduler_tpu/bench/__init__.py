"""Benchmark harnesses: solution-quality oracle and streaming replay."""

from k8s_spot_rescheduler_tpu.bench.quality import (
    drain_to_exhaustion,
    ilp_max_drains,
)
from k8s_spot_rescheduler_tpu.bench.replay import run_replay

__all__ = ["drain_to_exhaustion", "ilp_max_drains", "run_replay"]
