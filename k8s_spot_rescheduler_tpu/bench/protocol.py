"""The bench's device-only estimation protocol, pinned.

This TPU is reached through a network tunnel whose round trip (~65 ms
observed, 60-117 ms variance) dwarfs the actual solve, so ``bench.py``'s
headline solve+fetch median is RTT-pinned. The device-only figure — what
a locally attached chip would see per tick — comes from an amortization
protocol:

1. chain ``N_CHAIN`` *dependent* solves into one jitted program (each
   iteration's input perturbed by the previous result so XLA cannot
   collapse the loop), fetch one scalar;
2. time a minimal fetch of the same shape (one scalar reduction) as the
   round-trip floor;
3. estimate = (median(chain) - median(rtt)) / N_CHAIN.

The arithmetic and the chain construction live HERE, unit-tested against
stubbed solvers (tests/test_bench_protocol.py), so the methodology cannot
silently drift between rounds; bench.py emits the raw inputs
(chain length, raw chain/rtt medians) into BENCH_r*.json for audit.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

# Pinned chain length: long enough that per-iteration solve time dominates
# the single fetch, short enough to stay under the driver's watchdog on a
# real chip. Changing this changes the meaning of every recorded
# device-only number — bump deliberately, never silently.
N_CHAIN = 50


def make_chained(fused: Callable, n_chain: int = N_CHAIN) -> Callable:
    """One jittable program running ``n_chain`` data-dependent solves.

    Each iteration folds the running scalar back into the problem
    (``slot_req + acc * 0.0`` — value-preserving but dependence-creating),
    so the compiler must execute the solves serially; the program returns
    a single f32 scalar, so exactly one device->host fetch ends the
    timing. ``fused(p)`` must return an array (the fused planner's packed
    selection row works; any reducible output does).
    """
    import jax

    def chained(p):
        def step(_, acc):
            p2 = p._replace(slot_req=p.slot_req + acc * 0.0)
            return acc + fused(p2).sum().astype(jax.numpy.float32)

        return jax.lax.fori_loop(0, n_chain, step, jax.numpy.float32(0.0))

    return jax.jit(chained)


def device_only_ms(
    chain_times_s: Sequence[float],
    rtt_times_s: Sequence[float],
    n_chain: int = N_CHAIN,
) -> float:
    """The amortized per-solve estimate in milliseconds.

    median(chain walltime) minus median(round-trip floor), divided by the
    chain length; clamped at zero (tunnel variance can make a short chain
    measure faster than the floor — a negative solve time is noise, not
    information).
    """
    if not chain_times_s or not rtt_times_s or n_chain <= 0:
        return float("nan")
    return max(
        0.0,
        (float(np.median(chain_times_s)) - float(np.median(rtt_times_s)))
        / n_chain
        * 1e3,
    )


def run_protocol(fused: Callable, device_packed, reps: int = 5) -> dict:
    """Drive the full pinned protocol against a warmed device problem:
    build the chained program and the RTT probe, warm both, time
    ``reps`` alternating repetitions, and return ``protocol_record``.
    The ONE driver both bench modes share — a protocol change edits
    this function, never a call site."""
    import time

    import jax

    chained_jit = make_chained(fused)
    rtt_jit = jax.jit(lambda p: p.cand_valid.sum())
    np.asarray(chained_jit(device_packed))
    np.asarray(rtt_jit(device_packed))
    chain_t, rtt_t = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(chained_jit(device_packed))
        chain_t.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(rtt_jit(device_packed))
        rtt_t.append(time.perf_counter() - t0)
    return protocol_record(chain_t, rtt_t)


def protocol_record(
    chain_times_s: Sequence[float],
    rtt_times_s: Sequence[float],
    n_chain: int = N_CHAIN,
) -> dict:
    """The audit trail bench.py embeds in its JSON line: the raw inputs
    of the device-only claim, so a recorded number can be re-derived."""
    return {
        "chain_len": int(n_chain),
        "chain_ms": round(float(np.median(chain_times_s)) * 1e3, 3),
        "rtt_ms": round(float(np.median(rtt_times_s)) * 1e3, 3),
        "device_only_ms": round(
            device_only_ms(chain_times_s, rtt_times_s, n_chain), 3
        ),
    }
