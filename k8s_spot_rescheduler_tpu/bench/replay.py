"""Streaming spot-interruption replay (BASELINE.md config 5).

Replays a timed stream of spot add/remove events against the fake cluster
while the housekeeping loop keeps re-planning on its 10 s cadence — the
reference's level-triggered design under churn (its recovery story is
"every tick recomputes from observed cluster state", SURVEY.md §5.3).
Measures rolling re-plan latency and drain activity; displaced pods from
interrupted nodes re-enter as unschedulable and gate the loop exactly as
the reference's unschedulable-pods gate does (rescheduler.go:172-181).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

import numpy as np

from k8s_spot_rescheduler_tpu.io.synthetic import (
    CONFIGS,
    REPLAY_CONSTRAINED,
    generate_replay,
)
from k8s_spot_rescheduler_tpu.loop.controller import Rescheduler
from k8s_spot_rescheduler_tpu.planner.solver_planner import SolverPlanner
from k8s_spot_rescheduler_tpu.utils.config import ReschedulerConfig


def run_replay(
    config: ReschedulerConfig,
    *,
    config_id: int = 5,
    n_events: int = 1000,
    seed: int = 0,
    constrained: bool = False,
    on_packed=None,
) -> Dict[str, float]:
    """Returns summary stats of a full replay run.

    ``constrained`` swaps in the REPLAY_CONSTRAINED spec — config-5
    churn with the full predicate surface loaded on (taints,
    anti-affinity groups, PDBs, sparse hostname/zone hard spread) — and
    additionally tracks the safety invariant: a pod evicted by OUR
    drain that fails to re-place immediately is a STRANDING (the plan
    proved its placement); pods displaced by spot interruptions may
    legitimately pend (capacity vanished). Conservatism gauge values
    (metrics/registry.py) ride along in the stats."""
    spec = REPLAY_CONSTRAINED if constrained else CONFIGS[config_id]
    client, events = generate_replay(spec, n_events, seed)
    # drains every cooldown-free tick so churn keeps being consolidated.
    # schedule_horizon=0 (the documented opt-out): this benchmark's
    # metric IS per-tick replan latency under event-stream churn — the
    # regime where the controller's churn hysteresis parks schedules
    # anyway — so the harness pins the per-tick path the metric names
    config = dataclasses.replace(
        config, node_drain_delay=0.0, schedule_horizon=0
    )
    planner = SolverPlanner(config)
    r = Rescheduler(
        client, planner, config, clock=client.clock, recorder=client
    )

    plan_ms: List[float] = []
    drained = 0
    displaced = 0
    interruptions = 0
    stranded_by_drain = 0
    i = 0
    t_end = events[-1].at if events else 0.0
    now = 0.0
    while now < t_end:
        now += config.housekeeping_interval
        while i < len(events) and events[i].at <= now:
            ev = events[i]
            if ev.kind == "remove_spot":
                gone = client.remove_node(ev.node_name)
                displaced += len(gone)
                interruptions += 1
                for pod in gone:
                    # interrupted pods come back as pending reschedules
                    client.pending.append(dataclasses.replace(pod, node_name=""))
                client.retry_pending()
            else:
                client.add_node(ev.node)
            i += 1
        client.clock.advance(config.housekeeping_interval)
        evictions_before = len(client.evictions)
        result = r.tick()
        if on_packed is not None:
            # chain-depth analyzer tap (id-deduplicates skipped ticks)
            on_packed(getattr(planner, "last_packed", None))
        if result.report is not None:
            plan_ms.append(result.report.solve_seconds * 1e3)
        drained += len(result.drained)
        if result.drained:
            # the proven-placement invariant: none of THIS tick's drain
            # evictions may end the tick pending
            tick_evicted = set(client.evictions[evictions_before:])
            stranded_by_drain += sum(
                1 for p in client.pending if p.uid in tick_evicted
            )

    stats = {
        "ticks": len(plan_ms),
        "events": float(len(events)),
        "interruptions": float(interruptions),
        "displaced_pods": float(displaced),
        "drained_nodes": float(drained),
        "replan_ms_p50": float(np.median(plan_ms)) if plan_ms else 0.0,
        "replan_ms_p99": (
            float(np.percentile(plan_ms, 99)) if plan_ms else 0.0
        ),
        "pending_at_end": float(len(client.pending)),
        "stranded_by_drain": float(stranded_by_drain),
    }
    if constrained:
        from k8s_spot_rescheduler_tpu.metrics import registry as metrics

        snap = metrics.conservatism_snapshot()
        stats["unplaceable_pods_gauge"] = float(snap["unplaceable_pods"])
        stats["blocked_unmodeled_gauge"] = float(
            snap["blocked"].get("unmodeled", 0.0)
        )
    return stats
